"""Deterministic fault-drill matrix — ``python bench.py --faults``
(docs/FAULT_TOLERANCE.md "Drills").

Each drill injects exactly one fault from the taxonomy through the REAL
production path (GraphDataLoader → TrainingDriver scan/per-batch epochs, or
run_training under the supervisor) and checks that the designated mechanism —
guard skip, rollback, quarantine, transfer retry, supervised restart —
survived it: training completes, the final loss lands in the clean run's
ballpark, and the mechanism's counter incremented. Everything is seeded: the
same spec string produces the same drill, run to run.

Also measures what the guard COSTS: steady-epoch time with the guard enabled
(no faults) vs disabled on the same compiled-workload, plus a bit-inertness
check (guard-on clean params must equal guard-off params exactly).

Emits the ``FAULTS_rNN.json`` block consumed by bench.py's ``--faults`` mode.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Final-loss ballpark gate vs the clean run: a drill changes the trajectory
# (skipped steps, dropped samples, a rollback), not the problem — the loss
# must stay the same order of magnitude, not bit-match.
BALLPARK = (0.2, 5.0)

HEADS = {
    "graph": {
        "num_sharedlayers": 1,
        "dim_sharedlayers": 4,
        "num_headlayers": 1,
        "dim_headlayers": [8],
    },
}


def _dataset(seed=0, count=48, lo=4, hi=12):
    from hydragnn_tpu.graphs import GraphSample

    rng = np.random.default_rng(seed)
    graphs = []
    for _ in range(count):
        n = int(rng.integers(lo, hi))
        x = rng.normal(size=(n, 1)).astype(np.float32)
        ei = np.stack([np.arange(n), (np.arange(n) + 1) % n]).astype(np.int32)
        graphs.append(
            GraphSample(
                x=x,
                pos=np.zeros((n, 3), np.float32),
                y=np.array([x.sum()], np.float32),
                y_loc=np.array([[0, 1]], np.int64),
                edge_index=ei,
            )
        )
    return graphs


def _loader(graphs, **kw):
    from hydragnn_tpu.preprocess.dataloader import GraphDataLoader

    kw.setdefault("batch_size", 8)
    kw.setdefault("shuffle", False)
    loader = GraphDataLoader(graphs, **kw)
    loader.set_head_spec(("graph",), (1,))
    return loader


def _driver(loader, fault_tolerance=None, fault_plan=None, hidden=8, layers=2):
    from hydragnn_tpu.models import create_model, init_model_variables
    from hydragnn_tpu.train.train_validate_test import TrainingDriver
    from hydragnn_tpu.train.trainer import create_train_state
    from hydragnn_tpu.utils.optimizer import select_optimizer

    model = create_model("SAGE", 1, hidden, (1,), ("graph",), HEADS, [1.0], layers)
    variables = init_model_variables(model, next(iter(loader)))
    opt = select_optimizer("AdamW", 5e-3)
    state = create_train_state(model, variables, opt)
    return TrainingDriver(
        model, opt, state, fault_tolerance=fault_tolerance, fault_plan=fault_plan
    )


def _train(driver, loader, epochs=3):
    loss = None
    for epoch in range(epochs):
        loader.set_epoch(epoch)
        loss, _ = driver.train_epoch(loader)
    return loss


def _params_finite(driver):
    import jax

    return all(
        np.isfinite(np.asarray(leaf)).all()
        for leaf in jax.tree_util.tree_leaves(driver.state.params)
    )


def _params_equal(a, b):
    import jax

    return all(
        (np.asarray(x) == np.asarray(y)).all()
        for x, y in zip(
            jax.tree_util.tree_leaves(a.state.params),
            jax.tree_util.tree_leaves(b.state.params),
        )
    )


def _in_ballpark(loss, clean):
    return (
        np.isfinite(loss)
        and BALLPARK[0] * clean <= loss <= BALLPARK[1] * clean
    )


def _guard_overhead_pct(windows=6, batch=64, steps=8):
    """min-of steady scan-window time, guard on vs off, on the PR-2-baseline-
    shaped workload (flagship PNA, hidden 64, QM9-like graphs): the guard's
    in-jit cost is O(params) per step — isfinite over grads plus the
    state-sized keep-selects — so it must be measured against a step whose
    batch work dominates, like the production batch-256 workload, not the
    drill matrix's micro-epochs (where a fixed ~100 µs/step reads as double-
    digit percent). Windows are INTERLEAVED off/on and min-taken, the
    standard shared-host noise estimator (bench.py's WINDOWS rationale)."""
    import jax

    from __graft_entry__ import DIMS, TYPES, _build_model, _make_graphs
    from hydragnn_tpu.graphs import collate_graphs
    from hydragnn_tpu.models import init_model_variables
    from hydragnn_tpu.train.trainer import (
        create_train_state,
        make_train_epoch_scan,
        stack_batches,
    )
    from hydragnn_tpu.utils.optimizer import select_optimizer

    runs = {}
    for key, guard in (("off", False), ("on", True)):
        rng = np.random.default_rng(0)
        graphs = _make_graphs(batch, rng, n_lo=12, n_hi=26)
        b = collate_graphs(graphs, TYPES, DIMS, edge_dim=1)
        stacked = stack_batches([b] * steps, steps)
        model = _build_model(hidden=64, layers=3)
        variables = init_model_variables(model, b)
        opt = select_optimizer("AdamW", 1e-3)
        state = create_train_state(model, variables, opt)
        compiled = (
            make_train_epoch_scan(model, opt, guard=guard)
            .lower(state, stacked, jax.random.PRNGKey(0))
            .compile()
        )
        state, m = compiled(state, stacked, jax.random.PRNGKey(0))  # warmup
        jax.block_until_ready(m["loss"])
        runs[key] = (compiled, state, stacked)
    times = {"off": [], "on": []}
    for _ in range(windows):
        for key in ("off", "on"):
            compiled, state, stacked = runs[key]
            t0 = time.perf_counter()
            state, m = compiled(state, stacked, jax.random.PRNGKey(0))
            jax.block_until_ready(m["loss"])
            times[key].append(time.perf_counter() - t0)
            runs[key] = (compiled, state, stacked)
    best = {k: min(v) for k, v in times.items()}
    return round(100.0 * (best["on"] / best["off"] - 1.0), 2), best


def _ckpt_fallback_drill(kind: str) -> dict:
    """corrupt_ckpt / truncate_ckpt: train with keep_last_k retention, let the
    plan's post-save hook damage the LAST save (which also damages its
    hard-linked retained twin), then load through the verified chain — the
    newest intact retained entry must come back, with the fallback recorded
    in FaultCounters and the run's supervisor.json."""
    import tempfile

    from hydragnn_tpu.checkpoint import load_existing_model, save_model, set_post_save_hook
    from hydragnn_tpu.faults import FaultCounters, FaultPlan

    graphs = _dataset(seed=0)
    loader = _loader(list(graphs))
    d = _driver(loader)
    # Save indices 0..2; the drill hits the last one (epoch-3 state).
    plan = FaultPlan(f"seed=5,{kind}@2")
    before = FaultCounters.get("ckpt_fallback_loads")
    with tempfile.TemporaryDirectory() as tmp:
        path = tmp + "/"
        set_post_save_hook(plan.on_checkpoint_saved)
        try:
            for epoch in (1, 2, 3):
                loader.set_epoch(epoch)
                d.train_epoch(loader)
                save_model(
                    {"params": d.state.params, "batch_stats": d.state.batch_stats},
                    d.state.opt_state,
                    "drill",
                    path=path,
                    meta={"epoch": epoch},
                    keep_last_k=3,
                )
        finally:
            set_post_save_hook(None)
        variables = {"params": d.state.params, "batch_stats": d.state.batch_stats}
        _, _, meta = load_existing_model(variables, "drill", path=path, return_meta=True)
        with open(os.path.join(tmp, "drill", "supervisor.json")) as f:
            recorded = json.load(f).get("checkpoint_fallbacks", [])
    return {
        "survived": meta.get("epoch") == 2
        and FaultCounters.get("ckpt_fallback_loads") == before + 1
        and bool(recorded),
        "mechanism": "ckpt_fallback_chain",
        "recovered_epoch": meta.get("epoch"),
        "fallback_recorded": bool(recorded),
    }


def _ckpt_kill_save_drill(num_epoch: int = 3) -> dict:
    """corrupt_ckpt + kill@save under run_training(supervise=True), end to
    end: incarnation 0 saves epoch 1 cleanly, then its epoch-2 save is
    bit-flipped and the process SIGKILLed right after. The restart's resume
    hits the corrupt latest, falls back to the epoch-1 retained entry, and
    completes — restart metadata AND the fallback record land in the same
    supervisor.json."""
    import subprocess
    import sys as _sys
    import tempfile

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with tempfile.TemporaryDirectory() as tmp:
        script = f"""
import json, os, sys
os.chdir({tmp!r})
os.environ["SERIALIZED_DATA_PATH"] = {tmp!r}
os.environ["HYDRAGNN_FAULTS"] = "seed=5,corrupt_ckpt@1,kill@save1"
sys.path.insert(0, {repo!r})
sys.path.insert(0, os.path.join({repo!r}, "tests"))
import jax
jax.config.update("jax_platforms", "cpu")
from deterministic_graph_data import deterministic_graph_data
import hydragnn_tpu
from hydragnn_tpu.utils.config_utils import get_log_name_config
from hydragnn_tpu.utils.model import load_checkpoint_meta
with open(os.path.join({repo!r}, "tests/inputs/ci.json")) as f:
    config = json.load(f)
config["Visualization"] = {{"create_plots": False}}
tr = config["NeuralNetwork"]["Training"]
tr["num_epoch"] = {num_epoch}
tr["periodic_checkpoint_every"] = 1
tr["checkpoint_keep_last_k"] = 3
for split, cnt in {{"train": 24, "test": 8, "validate": 8}}.items():
    p = f"dataset/unit_test_singlehead_{{split}}"
    os.makedirs(p, exist_ok=True)
    deterministic_graph_data(p, number_configurations=cnt)
    config["Dataset"]["path"][split] = p
meta = hydragnn_tpu.run_training(config, supervise=True, max_restarts=2)
log_name = get_log_name_config(config)
meta["final_epoch"] = load_checkpoint_meta(log_name).get("epoch")
print("SUPERVISOR_META " + json.dumps(meta))
"""
        proc = subprocess.run(
            [_sys.executable, "-c", script],
            capture_output=True,
            text=True,
            timeout=900,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )
        line = next(
            (
                l
                for l in proc.stdout.splitlines()
                if l.startswith("SUPERVISOR_META ")
            ),
            None,
        )
        if line is None:
            return {
                "survived": False,
                "mechanism": "supervised_restart+ckpt_fallback",
                "error": (proc.stderr or proc.stdout)[-400:],
            }
        meta = json.loads(line[len("SUPERVISOR_META ") :])
        fallbacks = meta.get("checkpoint_fallbacks", [])
        return {
            "survived": bool(meta.get("completed"))
            and meta.get("restarts", 0) >= 1
            and bool(fallbacks)
            and meta.get("final_epoch") == num_epoch,
            "mechanism": "supervised_restart+ckpt_fallback",
            "restarts": meta.get("restarts"),
            "fallback_recorded": bool(fallbacks),
            "final_epoch": meta.get("final_epoch"),
        }


def _ckpt_save_stall(reps: int = 5) -> dict:
    """Train-thread stall per checkpoint, sync vs async, min-of-reps (the
    shared-host noise estimator): a sync save holds the thread through
    serialize+fsync+rename; the async path only through the device->host
    snapshot + enqueue. ``ckpt_save_stall_ms`` in FAULTS_rNN.json."""
    import tempfile

    from hydragnn_tpu.checkpoint import AsyncCheckpointer, save_model

    graphs = _dataset(seed=0)
    loader = _loader(graphs)
    d = _driver(loader, hidden=128, layers=3)  # big enough to serialize measurably
    variables = {"params": d.state.params, "batch_stats": d.state.batch_stats}
    sync_s, async_s = [], []
    with tempfile.TemporaryDirectory() as tmp:
        path = tmp + "/"
        for i in range(reps):
            t0 = time.perf_counter()
            save_model(variables, d.state.opt_state, "sync", path=path,
                       meta={"epoch": i})
            sync_s.append(time.perf_counter() - t0)
        ac = AsyncCheckpointer()
        for i in range(reps):
            ac.wait()  # measure the save() stall alone, not the prior write
            async_s.append(
                ac.save(variables, d.state.opt_state, "async", path=path,
                        meta={"epoch": i})
            )
        ac.close()
        identical = (
            open(os.path.join(tmp, "sync", "sync.pk"), "rb").read()
            == open(os.path.join(tmp, "async", "async.pk"), "rb").read()
        )
    return {
        "sync_ms": round(min(sync_s) * 1e3, 3),
        "async_ms": round(min(async_s) * 1e3, 3),
        "payload_bit_identical": identical,
    }


def _supervisor_drill(kill_step: int = 2, num_epoch: int = 4) -> dict:
    """kill@K under run_training(supervise=True): the child dies by SIGKILL
    mid-run, the supervisor restarts it, Training.resume picks up the last
    periodic checkpoint, and the run completes with restart metadata. The
    drill config feeds ONE train batch per epoch (24 samples, batch 32), so
    kill@2 fires in epoch 2 — after the epoch-1 and epoch-2 checkpoints."""
    import subprocess
    import sys as _sys
    import tempfile

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with tempfile.TemporaryDirectory() as tmp:
        # Subprocess so the drill controls cwd/env without mutating ours.
        script = f"""
import json, os, sys
os.chdir({tmp!r})
os.environ["SERIALIZED_DATA_PATH"] = {tmp!r}
os.environ["HYDRAGNN_FAULTS"] = "kill@{kill_step}"
sys.path.insert(0, {repo!r})
sys.path.insert(0, os.path.join({repo!r}, "tests"))
import jax
jax.config.update("jax_platforms", "cpu")
from deterministic_graph_data import deterministic_graph_data
import hydragnn_tpu
with open(os.path.join({repo!r}, "tests/inputs/ci.json")) as f:
    config = json.load(f)
config["Visualization"] = {{"create_plots": False}}
tr = config["NeuralNetwork"]["Training"]
tr["num_epoch"] = {num_epoch}
tr["periodic_checkpoint_every"] = 1
for split, cnt in {{"train": 24, "test": 8, "validate": 8}}.items():
    p = f"dataset/unit_test_singlehead_{{split}}"
    os.makedirs(p, exist_ok=True)
    deterministic_graph_data(p, number_configurations=cnt)
    config["Dataset"]["path"][split] = p
meta = hydragnn_tpu.run_training(config, supervise=True, max_restarts=2)
print("SUPERVISOR_META " + json.dumps(meta))
"""
        proc = subprocess.run(
            [_sys.executable, "-c", script],
            capture_output=True,
            text=True,
            timeout=900,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )
        line = next(
            (
                l
                for l in proc.stdout.splitlines()
                if l.startswith("SUPERVISOR_META ")
            ),
            None,
        )
        if line is None:
            return {
                "survived": False,
                "mechanism": "supervised_restart",
                "error": (proc.stderr or proc.stdout)[-400:],
            }
        meta = json.loads(line[len("SUPERVISOR_META ") :])
        return {
            "survived": bool(meta.get("completed"))
            and meta.get("restarts", 0) >= 1,
            "mechanism": "supervised_restart",
            "restarts": meta.get("restarts"),
            "attempts": len(meta.get("attempts", [])),
        }


def _flywheel_promote_rollback_drill() -> dict:
    """Fast promote-and-rollback smoke for the continuous-learning flywheel
    (CI ``--flywheel`` subset; the full gauntlet lives in
    benchmarks/flywheel_soak.py). One replica, one genuine candidate
    auto-promoted through the shadow gate, one wrecked candidate refused
    and quarantined, then an operator ``rollback()`` restoring the
    pre-flywheel live — all against the real registry/router/engine
    stack, no subprocesses."""
    import glob
    import tempfile

    from benchmarks.serve_load import (
        _host_variables,
        _perturb,
        _swap_fixture,
        build_serving_engine,
    )
    from hydragnn_tpu.checkpoint.io import save_model
    from hydragnn_tpu.flywheel import Flywheel, FlywheelConfig
    from hydragnn_tpu.lifecycle import LifecycleManager
    from hydragnn_tpu.route import InProcessReplica, Router

    with tempfile.TemporaryDirectory() as tmp:
        registry, engines, graphs, run_dir, vars0 = _swap_fixture(
            tmp, n_replicas=1
        )
        engine = engines[0]
        shadow, _ = build_serving_engine(model_version="shadow")
        router = Router(
            [InProcessReplica("fw-smoke", engine)],
            health_interval_s=0.1,
            jitter_seed=0,
        )
        fly = None
        try:
            initial = registry.live.short
            manager = LifecycleManager(registry, [engine], router=router)
            fly = Flywheel(
                registry,
                manager,
                router,
                shadow,
                [(g.num_nodes, g.num_edges, 1) for g in graphs],
                config=FlywheelConfig(
                    shadow_fraction=1.0,
                    shadow_tolerance=0.5,
                    shadow_min_samples=2,
                    gate_window_s=0.0,
                    gate_patience_s=60.0,
                    refit_interval_s=3600.0,
                ),
                run_dir=run_dir,
            )
            fly.attach()

            def drive(want_state):
                state = None
                for i in range(128):
                    router.predict(
                        [graphs[i % len(graphs)]], request_id=f"fw-{i}"
                    )
                    state = fly.tick()["weights"].get("state")
                    if state == want_state:
                        return True
                return state == want_state

            # Genuine candidate (diff ~1e-2, an order under the 0.5 bound):
            # the gate must go green and auto-promote.
            save_model(
                _perturb(vars0, 1e-3, seed=21), None, registry.name,
                path=tmp, meta={"epoch": 1}, keep_last_k=3,
            )
            promoted = drive("promoted")
            live_after_promote = registry.live.short
            # Wrecked candidate (diff orders above the bound): refused and
            # quarantined, live untouched.
            save_model(
                _perturb(vars0, 5.0, seed=22), None, registry.name,
                path=tmp, meta={"epoch": 2}, keep_last_k=3,
            )
            rejected = drive("rejected")
            live_after_reject = registry.live.short
            dumps = glob.glob(
                os.path.join(run_dir, "flightrec_*_flywheel_reject.json")
            )
            quarantined = glob.glob(os.path.join(run_dir, "quarantine", "*"))
            # Operator rollback: previous (= the pre-flywheel live) returns.
            manager.rollback()
            counters = fly.report()["counters"]
            survived = (
                promoted
                and rejected
                and live_after_promote != initial
                and live_after_reject == live_after_promote
                and registry.live.short == initial
                and counters["promotions"] == 1
                and counters["rejections"] == 1
                and len(dumps) >= 1
                and len(quarantined) >= 1
            )
            return {
                "survived": bool(survived),
                "mechanism": "shadow_gate",
                "initial": initial,
                "promoted_to": live_after_promote,
                "live_after_reject": live_after_reject,
                "live_after_rollback": registry.live.short,
                "reject_flight_dumps": len(dumps),
                "quarantined": len(quarantined),
                "counters": counters,
            }
        finally:
            if fly is not None:
                fly.stop()
            router.close()
            engine.close()
            shadow.close()


def run_fault_drills(include_supervisor: bool = True, only: "str | None" = None) -> dict:
    from hydragnn_tpu.faults import FaultCounters, FaultPlan

    FaultCounters.reset()
    if only == "flywheel":
        # The CI smoke (static-analysis workflow --flywheel): one in-process
        # promote-and-rollback pass through the real shadow gate — no soak,
        # no subprocess kills (benchmarks/flywheel_soak.py owns those).
        drills = {
            "flywheel_promote_rollback": _flywheel_promote_rollback_drill(),
        }
        passed = sum(1 for v in drills.values() if v["survived"])
        return {
            "metric": "fault_drills",
            "value": round(passed / len(drills), 4),
            "unit": "drills_passed_frac",
            "subset": "flywheel",
            "drills_passed": passed,
            "drills_total": len(drills),
            "drills": drills,
            "counters": FaultCounters.snapshot(),
        }
    if only == "checkpoint":
        # The CI subset (static-analysis workflow): the two local checkpoint
        # drills plus the stall/byte-identity split — no subprocess
        # supervisor runs, no guard-overhead windows. Byte identity GATES
        # the subset: an async/sync payload divergence must fail CI here,
        # not only in tier-1.
        stall = _ckpt_save_stall()
        drills = {
            "corrupt_ckpt_fallback": _ckpt_fallback_drill("corrupt_ckpt"),
            "truncate_ckpt_fallback": _ckpt_fallback_drill("truncate_ckpt"),
            "async_sync_byte_identity": {
                "survived": bool(stall["payload_bit_identical"]),
                "mechanism": "single_serializer",
                **stall,
            },
        }
        passed = sum(1 for v in drills.values() if v["survived"])
        return {
            "metric": "fault_drills",
            "value": round(passed / len(drills), 4),
            "unit": "drills_passed_frac",
            "subset": "checkpoint",
            "drills_passed": passed,
            "drills_total": len(drills),
            "drills": drills,
            "ckpt_save_stall_ms": stall,
            "counters": FaultCounters.snapshot(),
        }
    graphs = _dataset(seed=0)
    drills = {}

    # ---- clean reference (guard off) -------------------------------------
    clean_loader = _loader(list(graphs))
    clean = _driver(clean_loader)
    clean_loss = _train(clean, clean_loader)

    # ---- guard on, no faults: bit-inert ----------------------------------
    inert_loader = _loader(list(graphs))
    inert = _driver(inert_loader, fault_tolerance={"enabled": True})
    inert_loss = _train(inert, inert_loader)
    guard_bit_inert = (inert_loss == clean_loss) and _params_equal(clean, inert)

    # ---- nan_grad: guard skips the poisoned step -------------------------
    loader = _loader(list(graphs))
    d = _driver(
        loader,
        fault_tolerance={"enabled": True, "max_bad_steps": 8},
        fault_plan=FaultPlan("nan_grad@3"),
    )
    loss = _train(d, loader)
    drills["nan_grad_skip"] = {
        "survived": _in_ballpark(loss, clean_loss)
        and _params_finite(d)
        and d.guard.bad_steps == 1,
        "mechanism": "guard_skip",
        "bad_steps": d.guard.bad_steps,
        "final_loss": round(float(loss), 6),
    }

    # ---- nan_grad burst: rollback to last-good + LR backoff --------------
    loader = _loader(list(graphs))
    d = _driver(
        loader,
        fault_tolerance={"enabled": True, "max_bad_steps": 2, "lr_backoff": 0.5},
        fault_plan=FaultPlan("nan_grad@6-11"),
    )
    loss = _train(d, loader)
    drills["nan_grad_rollback"] = {
        "survived": _in_ballpark(loss, clean_loss)
        and _params_finite(d)
        and d.guard.rollbacks >= 1,
        "mechanism": "rollback",
        "rollbacks": d.guard.rollbacks,
        "final_loss": round(float(loss), 6),
    }

    # ---- corrupt samples: quarantined at loader construction -------------
    loader = _loader(
        list(graphs),
        skip_budget=4,
        fault_plan=FaultPlan("seed=3,corrupt_sample:count=2"),
    )
    d = _driver(loader)
    loss = _train(d, loader)
    drills["corrupt_sample_quarantine"] = {
        "survived": _in_ballpark(loss, clean_loss)
        and len(loader.quarantined) == 2,
        "mechanism": "quarantine",
        "quarantined": len(loader.quarantined),
        "final_loss": round(float(loss), 6),
    }

    # ---- slow host collate: pipeline absorbs the stall -------------------
    loader = _loader(list(graphs))
    d = _driver(loader, fault_plan=FaultPlan("slow_collate@2:ms=30"))
    loss = _train(d, loader)
    drills["slow_collate"] = {
        "survived": loss == clean_loss,  # a stall must not change results
        "mechanism": "async_pipeline",
        "final_loss": round(float(loss), 6),
    }

    # ---- transient transfer crash: retried with backoff ------------------
    loader = _loader(list(graphs))
    d = _driver(loader, fault_plan=FaultPlan("transfer_crash@0"))
    loss = _train(d, loader)
    drills["transfer_crash_retry"] = {
        "survived": loss == clean_loss
        and FaultCounters.get("transfer_retries") >= 1,
        "mechanism": "transfer_retry",
        "retries": FaultCounters.get("transfer_retries"),
        "final_loss": round(float(loss), 6),
    }

    # ---- checkpoint corruption: verified-load fallback chain -------------
    drills["corrupt_ckpt_fallback"] = _ckpt_fallback_drill("corrupt_ckpt")
    drills["truncate_ckpt_fallback"] = _ckpt_fallback_drill("truncate_ckpt")

    # ---- process kill: supervised restart + crash resume -----------------
    if include_supervisor:
        drills["kill_supervised_restart"] = _supervisor_drill()
        # kill@save + corrupt_ckpt end to end: restart resumes THROUGH the
        # fallback chain (docs/CHECKPOINTING.md "Fallback semantics").
        drills["kill_at_save_ckpt_fallback"] = _ckpt_kill_save_drill()

    # Async/sync payload byte identity gates the matrix like any drill.
    stall = _ckpt_save_stall()
    drills["async_sync_byte_identity"] = {
        "survived": bool(stall["payload_bit_identical"]),
        "mechanism": "single_serializer",
        **stall,
    }

    overhead_pct, times = _guard_overhead_pct()
    passed = sum(1 for v in drills.values() if v["survived"])
    return {
        "metric": "fault_drills",
        "value": round(passed / len(drills), 4),
        "unit": "drills_passed_frac",
        "drills_passed": passed,
        "drills_total": len(drills),
        "drills": drills,
        "guard_bit_inert": guard_bit_inert,
        "guard_overhead_pct": overhead_pct,
        "guard_epoch_s": {k: round(v, 5) for k, v in times.items()},
        "ckpt_save_stall_ms": stall,
        "clean_final_loss": round(float(clean_loss), 6),
        "counters": FaultCounters.snapshot(),
    }


if __name__ == "__main__":
    only = (
        "checkpoint"
        if "--checkpoint" in sys.argv
        else "flywheel" if "--flywheel" in sys.argv else None
    )
    result = run_fault_drills(
        include_supervisor="--no-supervisor" not in sys.argv, only=only
    )
    print(json.dumps(result))
    sys.exit(0 if result["value"] == 1.0 else 1)
