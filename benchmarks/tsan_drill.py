"""Deterministic tsan drill over the serve + route + lifecycle +
async-checkpoint paths.

Runs the two concurrency-heavy subsystems with graftrace's runtime
sanitizer enabled (analysis/tsan.py): every registered lock records its
acquisition order, every registered shared-state site records which threads
touched it under which locks, and the annotated yield points perturb thread
interleavings under a SEEDED schedule — the same ``--seed`` replays the
same perturbations, so a drill that exposes a race is a repro, not an
anecdote.

The drill then cross-checks what actually happened against the STATIC
lock-order graph (``python -m hydragnn_tpu.analysis trace``): a dynamic
acquisition order the static model missed, a dynamic inversion, or an
unregistered cross-thread access all fail the run (exit 1). Since graftproto
(ISSUE 19) the drill also runs the SPMD/barrier lockstep pass and the
crash-consistency model checker's smoke sweep — a proto violation or a
recovery-invariant failure fails the run the same way.

    HYDRAGNN_TSAN is forced on BEFORE any hydragnn import, so module-level
    locks created at import time (graftel._lock — the registry behind
    Timer/FaultCounters since the telemetry PR) are instrumented too —
    running this module IS the HYDRAGNN_TSAN=1 drill.

    python benchmarks/tsan_drill.py [--seed N] [--json]

Used by tests/test_concurrency_lint.py (same-seed determinism + clean-run
assertions), .github/workflows/static-analysis.yml (short schedule-fuzz
smoke over two seeds), and ``bench.py --analyze`` (drill outcome embedded
in ANALYSIS_rNN.json).
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import tempfile
import time


def _preparse(flag: str, argv, default: str) -> str:
    for i, a in enumerate(argv):
        if a == flag and i + 1 < len(argv):
            return argv[i + 1]
        if a.startswith(flag + "="):
            return a.split("=", 1)[1]
    return default


_SEED = int(_preparse("--seed", sys.argv[1:], "0") or 0)

# BEFORE any hydragnn/jax import: the tsan module reads these at import, and
# import-time locks (graftel._lock — the shared registry behind Timer and
# FaultCounters since the telemetry PR) wrap only if the flag is up when
# their defining modules load.
os.environ["HYDRAGNN_TSAN"] = "1"
os.environ["HYDRAGNN_TSAN_SEED"] = str(_SEED)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from hydragnn_tpu.analysis import (  # noqa: E402
    model_check,
    proto_paths,
    trace_paths,
    tsan,
)

# Yield sites whose visit counts are workload-determined (not race-
# determined), so their recorded decision streams must be bit-identical
# across same-seed runs — the determinism witness the tests compare.
_DETERMINISTIC_SITES = (
    "ckpt.save.pre_enqueue",
    "serve.submit.pre_enqueue",
    "stream.ring.pre_put",
)

_CKPT_SAVES = 3
_SERVE_REQUESTS = 8


def _checkpoint_drill(tmpdir: str) -> None:
    """Async-checkpoint path: N saves racing the daemon writer, a wait
    barrier, close — the PR-5 lifecycle under schedule perturbation."""
    from hydragnn_tpu.checkpoint.async_writer import AsyncCheckpointer

    rng = np.random.default_rng(0)
    variables = {
        "params": {"w": rng.standard_normal((8, 8)).astype(np.float32)},
        "batch_stats": {},
    }
    ac = AsyncCheckpointer()
    try:
        for k in range(_CKPT_SAVES):
            ac.save(
                variables,
                None,
                name="tsan_drill",
                path=tmpdir,
                meta={"epoch": k},
                keep_last_k=2,
            )
        ac.wait()
    finally:
        ac.close()


def _serve_drill() -> None:
    """Serve path: submit/flush/dispatch/resolve across the batcher,
    transfer, dispatch, and caller threads, then a drain-close."""
    from benchmarks.serve_load import build_serving_engine

    engine, graphs = build_serving_engine(
        hidden=4, layers=1, max_batch_graphs=4, max_delay_ms=5.0,
        pool_size=_SERVE_REQUESTS,
    )
    try:
        futures = [engine.submit(g) for g in graphs[:_SERVE_REQUESTS]]
        for f in futures:
            f.result(timeout=120)
        engine.metrics.render_prometheus()  # the /metrics cross-thread read
    finally:
        engine.close()


def _cache_drill(tmpdir: str) -> None:
    """graftcache path: two registries over ONE store directory (the
    two-replicas-one-store topology), each hammered from its own thread —
    compile+serialize races hydrate races manifest read-modify-write, all
    under the instrumented ExecutableRegistry/ExecutableStore locks
    (docs/COMPILE_CACHE.md; ISSUE 10 requires the store's locks registered
    here from day one)."""
    import threading

    import jax
    import numpy as np

    from hydragnn_tpu.cache import CacheKey, ExecutableRegistry, ExecutableStore

    cache_dir = os.path.join(tmpdir, "graftcache")
    fns = [
        jax.jit(lambda x, k=k: x * (k + 1) + x.sum()) for k in range(2)
    ]
    x = jax.device_put(np.ones((8,), np.float32))

    def worker(wid: int):
        reg = ExecutableRegistry(ExecutableStore(cache_dir), name=f"drill{wid}")
        for k, fn in enumerate(fns):
            key = CacheKey.for_environment(
                program=f"tsan_drill_{k}",
                config_fingerprint="tsan-drill",
                bucket=(8, 0, 0),
            )
            exe, _outcome, _s = reg.lookup_or_compile(
                ("drill", k), key, lambda fn=fn: fn.lower(x)
            )
            exe(x)
            len(reg)

    threads = [
        threading.Thread(
            target=worker, args=(w,), name=f"cache-drill-{w}", daemon=True
        )
        for w in range(2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    ExecutableStore(cache_dir).verify()


def _telemetry_drill(tmpdir: str) -> None:
    """graftel path: concurrent spans/events/counters from worker threads
    racing a flight dump on the main thread — the tracer's single registry
    lock (graftel._lock, instrumented at import under HYDRAGNN_TSAN=1) under
    schedule perturbation. The serve/checkpoint drills already emit through
    graftel implicitly; this section hammers it directly."""
    import threading

    from hydragnn_tpu import telemetry

    telemetry.configure(run_dir=tmpdir, collect=True)
    ctx = telemetry.new_context()

    def worker(wid: int):
        telemetry.attach(ctx)
        for i in range(16):
            with telemetry.span("tsan_drill/span", worker=wid, i=i):
                telemetry.counter("tsan_drill/ops")
            telemetry.event("tsan_drill/event", worker=wid)

    threads = [
        threading.Thread(target=worker, args=(w,), daemon=True)
        for w in range(3)
    ]
    for t in threads:
        t.start()
    telemetry.flight_dump("tsan_drill")
    telemetry.render_prometheus()
    for t in threads:
        t.join(30)
    telemetry.configure(collect=False)


def _route_drill() -> None:
    """graftroute path (ISSUE 12): the router's health loop + caller-thread
    dispatch + a dispatch-observed failure drain, all under instrumentation
    — Router._lock / RouteMetrics._lock / the ring's external-guard contract
    race the engine and telemetry locks exactly as in production. The
    drill's dispatch site (``route.dispatch.pre_send``) perturbs the window
    between target acquisition and the replica call."""
    from benchmarks.serve_load import build_serving_engine
    from hydragnn_tpu.route import InProcessReplica, Router

    engines = []
    replicas = []
    for i in range(2):
        engine, graphs = build_serving_engine(
            hidden=4, layers=1, max_batch_graphs=4, max_delay_ms=5.0,
            pool_size=_SERVE_REQUESTS,
        )
        engines.append(engine)
        replicas.append(InProcessReplica(f"drill-{i}", engine))
    router = Router(
        replicas,
        health_interval_s=0.02,
        jitter_seed=0,
        autostart_health=True,
    )
    try:
        for i in range(_SERVE_REQUESTS):
            router.predict([graphs[i]], request_id=f"route-drill-{i}")
        # Kill one replica mid-fleet: dispatch observes the death, drains it
        # (the health loop racing the same table), and retries elsewhere.
        engines[0].close()
        for i in range(_SERVE_REQUESTS):
            router.predict([graphs[i]], request_id=f"route-drill2-{i}")
        router.poll_health()  # the /healthz cross-thread read
        router.metrics.render_prometheus()  # the /metrics cross-thread read
    finally:
        router.close()
        for engine in engines:
            engine.close()


def _swap_drill(tmpdir: str) -> None:
    """graftswap path (ISSUE 13): hot weight swaps published from a swapper
    thread racing the dispatch thread's per-batch weight read and the
    caller-thread submits — the engine's atomic weight reference under
    `InferenceEngine._lock` (yield site ``serve.swap.pre_publish`` widens
    the publish window), plus the ModelRegistry role table and ShadowGate
    recorders under their own instrumented locks."""
    import threading

    from benchmarks.serve_load import _host_variables, build_serving_engine
    from hydragnn_tpu.checkpoint.io import save_model
    from hydragnn_tpu.lifecycle import ModelRegistry, ShadowGate

    engine, graphs = build_serving_engine(
        hidden=4, layers=1, max_batch_graphs=4, max_delay_ms=5.0,
        pool_size=_SERVE_REQUESTS,
    )
    try:
        host = _host_variables(engine)

        def swapper():
            for k in range(3):
                engine.swap_weights(host, f"drill-v{k + 1}")

        futures = [engine.submit(g) for g in graphs[:_SERVE_REQUESTS]]
        t = threading.Thread(target=swapper, name="swap-drill", daemon=True)
        t.start()
        for f in futures:
            f.result(timeout=120)
        t.join(120)
        engine.metrics.render_prometheus()  # the /metrics cross-thread read
        # Registry role flips + sidecar installs under the instrumented
        # registry lock; the gate's recorders under the gate lock.
        name = "tsan_swap"
        save_model(host, None, name, path=tmpdir, keep_last_k=2)
        registry = ModelRegistry(os.path.join(tmpdir, name), name)
        registry.set_live()
        registry.state()
        gate = ShadowGate(tolerance=1e-3, min_samples=1)
        gate.record({"ok": True, "fwd_err": 0.0}, candidate_version="drill")
        gate.render_prometheus()
    finally:
        engine.close()


def _mesh_drill() -> None:
    """graftmesh path (ISSUE 14): the loopback rendezvous hammered by worker
    threads — the instrumented LoopbackRendezvous._lock races the two-phase
    barrier protocol across exchange/broadcast/barrier rounds, the
    lockstep-divergence detector reads racing tag slots, and an injected
    worker death exercises the abort path (broken barriers must surface as
    LoopbackError, never a hang or a silent thread death)."""
    from hydragnn_tpu.parallel import LoopbackError, run_workers

    def worker(w):
        acc = []
        for i in range(12):
            got = w.exchange((w.rank, i), tag="mesh_drill")
            acc.append(got)
            assert [g[1] for g in got] == [i] * w.world_size
            if i % 3 == 0:
                w.barrier(f"round{i}")
            acc.append(w.broadcast(i if w.rank == 1 else None, src=1))
        return len(acc)

    assert run_workers(4, worker) == [24, 24, 24, 24]

    def dying(w):
        if w.rank == 2:
            raise RuntimeError("mesh drill injected death")
        w.exchange(w.rank)

    try:
        run_workers(3, dying)
    except LoopbackError:
        pass
    else:  # pragma: no cover - drill invariant
        raise AssertionError("loopback abort path did not surface the death")


def _elastic_drill() -> None:
    """graftelastic path (ISSUE 15): the membership tracker hammered by N
    heartbeat threads racing the coordinator's drain/poll loop, the
    rendezvous one-way mailbox post/drain races under the instrumented
    LoopbackRendezvous._lock, and the drill schedule consulted from worker
    and leader sides — MembershipTracker._lock / ElasticSchedule._lock
    registered here from day one per the PR-8 rule. The yield site
    ``elastic.membership.heartbeat`` perturbs the beat-vs-poll window."""
    import threading

    from hydragnn_tpu.parallel import LoopbackRendezvous
    from hydragnn_tpu.parallel.elastic import (
        ElasticEvent,
        ElasticSchedule,
        MembershipTracker,
    )

    tracker = MembershipTracker(heartbeat_s=60.0)
    rdv = LoopbackRendezvous(4)
    sched = ElasticSchedule(
        [
            ElasticEvent(step=5, kind="leave", worker="hb1"),
            ElasticEvent(step=7, kind="join", worker="jx"),
            ElasticEvent(step=9, kind="kill", worker="hb2"),
        ]
    )

    def beat(wid: str, rank: int) -> None:
        tracker.join(wid)
        for i in range(24):
            tracker.heartbeat(wid)
            rdv.post(rank, {"wid": wid}, tag="heartbeat")
            sched.kill_due(wid, i)

    threads = [
        threading.Thread(
            target=beat, args=(f"hb{r}", r),
            name=f"elastic-beat-{r}", daemon=True,
        )
        for r in range(4)
    ]
    for t in threads:
        t.start()
    expected = [f"hb{r}" for r in range(4)]
    for step in range(24):
        tracker.drain(rdv.posts("heartbeat"))
        sched.control_events(step)
        sched.transition_kill_due(step)
        tracker.poll(expected)
    for t in threads:
        t.join(60)
    tracker.drain(rdv.posts("heartbeat"))
    tracker.mark_dead("hb3")
    change = tracker.poll(expected)
    assert "hb3" in change.dead, change


def _stream_drill(tmpdir: str) -> None:
    """graftstream path (ISSUE 16): the shard-prefetch ring's bounded queue
    under schedule perturbation — ShardRing._lock stats updates on the
    "hydragnn-shard-prefetch" thread racing consumer ``stats()`` reads
    (yield site ``stream.ring.pre_put`` widens the decode-to-publish
    window), the Belady replay path (capacity below the epoch's shard set
    keeps the ring live the whole epoch, racing consumer-side eviction),
    and an abandoned-consumer ``close()`` (cancel must wake a producer
    blocked on the full depth-1 queue — never a leaked thread)."""
    from hydragnn_tpu.datasets import shards
    from hydragnn_tpu.datasets.stream import ShardRing, StreamingGraphLoader
    from hydragnn_tpu.graphs.sample import GraphSample

    rng = np.random.default_rng(0)
    samples = []
    for _ in range(24):
        n = int(rng.integers(3, 7))
        e = int(rng.integers(2, 5))
        samples.append(
            GraphSample(
                x=rng.standard_normal((n, 4)).astype(np.float32),
                pos=rng.standard_normal((n, 3)).astype(np.float32),
                edge_index=rng.integers(0, n, size=(2, e)).astype(np.int64),
            )
        )
    corpus = os.path.join(tmpdir, "stream_corpus")
    shards.write_gshd(corpus, samples, shard_size=4, name="tsan_stream")

    loader = StreamingGraphLoader(
        corpus, batch_size=5, shuffle=True, seed=_SEED,
        resident_shards=2, ring_depth=1,
    )
    for epoch in range(2):
        loader.set_epoch(epoch)
        for _ in loader:
            loader.ring_stats()

    ring = ShardRing(list(range(6)), loader._decode_shard, depth=1)
    ring.get()
    ring.stats()
    ring.close()
    assert ring.join(30), "shard-prefetch thread leaked past close()"


def _flywheel_drill(tmpdir: str) -> None:
    """graftloop path (ISSUE 18): the flywheel control state under
    instrumentation — the post-save observer enqueuing from the async
    checkpoint writer thread while ``tick()`` stages/arms/judges on the
    main thread (Flywheel._lock), drift-detector state racing ``report()``
    readers (DriftDetector._lock), and a ladder swap published from a
    swapper thread racing caller submits and the dispatch thread's
    per-flush ladder snapshot (yield site ``serve.ladder.pre_publish``
    widens the warm-to-publish window)."""
    import threading

    from benchmarks.serve_load import (
        _host_variables,
        _perturb,
        build_serving_engine,
    )
    from hydragnn_tpu.checkpoint.async_writer import AsyncCheckpointer
    from hydragnn_tpu.checkpoint.io import save_model
    from hydragnn_tpu.flywheel import Flywheel, FlywheelConfig
    from hydragnn_tpu.lifecycle import LifecycleManager, ModelRegistry
    from hydragnn_tpu.route import InProcessReplica, Router

    engine_kw = dict(
        hidden=4, layers=1, max_batch_graphs=4, max_delay_ms=5.0,
        pool_size=_SERVE_REQUESTS,
    )
    engine, graphs = build_serving_engine(**engine_kw)
    shadow, _ = build_serving_engine(model_version="shadow", **engine_kw)
    router = Router(
        [InProcessReplica("fly-drill", engine)],
        health_interval_s=0.05,
        jitter_seed=0,
    )
    fly = None
    try:
        host = _host_variables(engine)
        name = "tsan_fly"
        save_model(host, None, name, path=tmpdir, keep_last_k=3)
        registry = ModelRegistry(os.path.join(tmpdir, name), name)
        registry.set_live()
        manager = LifecycleManager(registry, [engine], router=router)
        fly = Flywheel(
            registry,
            manager,
            router,
            shadow,
            [(g.num_nodes, g.num_edges, 1) for g in graphs],
            config=FlywheelConfig(
                shadow_tolerance=0.5, shadow_min_samples=1,
                gate_window_s=0.0, gate_patience_s=60.0,
                refit_interval_s=0.01,
            ),
            run_dir=os.path.join(tmpdir, name),
        )
        fly.attach()
        # Candidate observed from the ASYNC writer thread — the post-save
        # hook's cross-thread enqueue is the point.
        ac = AsyncCheckpointer()
        try:
            ac.save(
                _perturb(host, 1e-3, seed=1), None, name=name,
                path=tmpdir, meta={"epoch": 1}, keep_last_k=3,
            )
            ac.wait()
        finally:
            ac.close()

        # report() readers racing the control tick's lock writes.
        def reader():
            for _ in range(16):
                fly.report()
                router.shadow_report()

        rt = threading.Thread(target=reader, name="fly-reader", daemon=True)
        rt.start()
        state = None
        for i in range(64):
            router.predict(
                [graphs[i % len(graphs)]], request_id=f"fly-drill-{i}"
            )
            state = fly.tick()["weights"].get("state")
            if state in ("promoted", "rejected"):
                break
        rt.join(60)
        assert state in ("promoted", "rejected"), state

        # Ladder swap racing live submits (one extra rung keeps the original
        # first-fit bucket, so in-flight batches never take the fallback).
        orig = engine._current_ladder()
        top = orig[-1] if orig else (128, 512)
        grown = orig + [(top[0] * 2, top[1] * 2)]
        futures = [engine.submit(g) for g in graphs[:_SERVE_REQUESTS]]
        st = threading.Thread(
            target=lambda: engine.swap_ladder(grown, warm=True),
            name="ladder-drill",
            daemon=True,
        )
        st.start()
        for f in futures:
            f.result(timeout=120)
        st.join(120)
        engine.metrics.render_prometheus()  # the /metrics cross-thread read
    finally:
        if fly is not None:
            fly.stop()
        router.close()
        engine.close()
        shadow.close()


def _autoscale_drill() -> None:
    """graftpilot path (ISSUE 20): the autopilot tick thread racing
    caller-thread dispatch, the router health loop, and tenant-bulkhead
    charging — Autopilot._lock / PilotMetrics._lock / BrownoutLadder._lock /
    TenantBulkheads._lock against Router._lock and the engine locks exactly
    as in production. One replica dies mid-drill: health ejects it while the
    pilot replaces the corpse and dispatch routes around it. min == max
    replicas pins the reactive arm so the spawn count is deterministic
    (exactly the one replacement)."""
    from benchmarks.serve_load import build_serving_engine
    from hydragnn_tpu.pilot import Autopilot, AutopilotConfig
    from hydragnn_tpu.route import InProcessReplica, Router

    engines = []
    replicas = []
    for i in range(2):
        engine, graphs = build_serving_engine(
            hidden=4, layers=1, max_batch_graphs=4, max_delay_ms=5.0,
            pool_size=_SERVE_REQUESTS,
        )
        engines.append(engine)
        replicas.append(InProcessReplica(f"drill-{i}", engine))
    router = Router(
        replicas,
        health_interval_s=0.02,
        jitter_seed=0,
        autostart_health=True,
    )

    def factory(name):
        engine, _ = build_serving_engine(
            hidden=4, layers=1, max_batch_graphs=4, max_delay_ms=5.0,
            pool_size=_SERVE_REQUESTS,
        )
        engines.append(engine)
        return InProcessReplica(name, engine)

    cfg = AutopilotConfig(
        min_replicas=2,
        max_replicas=2,
        sustain_down=10_000,
        eject_grace_ticks=2,
        tenant_inflight_quota=4,
        global_inflight_limit=64,
        predictive=False,
        tick_interval_s=0.005,
    )
    ap = Autopilot(router, factory, cfg).start()
    try:
        for i in range(_SERVE_REQUESTS):
            router.predict(
                [graphs[i]],
                request_id=f"pilot-drill-{i}",
                tenant=f"t{i % 2}",
            )
        # Kill one replica: health ejects it while the pilot's tick thread
        # replaces it and dispatch keeps routing around the corpse.
        engines[0].close()
        for i in range(_SERVE_REQUESTS):
            router.predict(
                [graphs[i]],
                request_id=f"pilot-drill2-{i}",
                tenant=f"t{i % 2}",
            )
        # The replacement MUST land inside the drill window (a run that
        # exits before the spawn would record a different visit count).
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            states = router.states()
            if any(
                n.startswith("pilot-") and s["state"] == "admitted"
                for n, s in states.items()
            ):
                break
            time.sleep(0.01)
        else:
            raise RuntimeError(f"pilot never replaced the corpse: {states}")
        ap.metrics.render_prometheus()  # the /metrics cross-thread read
        ap.report()  # the /pilotz cross-thread read
    finally:
        ap.stop()
        router.close()
        for engine in engines:
            engine.close()


def _proto_drill(seed: int) -> dict:
    """graftproto path (ISSUE 19): the static SPMD/barrier lockstep pass
    over the package plus the crash-consistency SMOKE sweep (elastic shrink
    + swap promote — the CI subset; the full scenario matrix runs in
    tests/test_proto_lint.py). The checker's seeded schedule digest joins
    the drill's determinism witness: same seed, same injection order."""
    proto = proto_paths([os.path.join(REPO, "hydragnn_tpu")], root=REPO)
    verdict = model_check(seed=seed, smoke=True)
    return {
        "static_violations": len(proto.violations),
        "lockstep_segments": sorted(proto.lockstep_segments),
        "persistence_points": len(proto.persistence_points),
        "modelcheck_ok": verdict["ok"],
        "modelcheck_points": verdict["num_points"],
        "modelcheck_injections": verdict["num_injections"],
        "modelcheck_failures": verdict["failures"],
        "modelcheck_schedule_sha256": verdict["schedule_sha256"],
    }


def run_drill(seed: int) -> dict:
    tsan.enable(seed=seed)
    tsan.reset()
    with tempfile.TemporaryDirectory() as tmpdir:
        _checkpoint_drill(tmpdir)
        _serve_drill()
        _telemetry_drill(tmpdir)
        _cache_drill(tmpdir)
        _route_drill()
        _swap_drill(tmpdir)
        _mesh_drill()
        _elastic_drill()
        _stream_drill(tmpdir)
        _flywheel_drill(tmpdir)
        _autoscale_drill()
    rep = tsan.report()
    static = trace_paths([os.path.join(REPO, "hydragnn_tpu")], root=REPO)
    cross = tsan.cross_check(static.lock_edges)
    proto = _proto_drill(seed)
    det = {s: tsan.schedule(s) for s in _DETERMINISTIC_SITES}
    digest = hashlib.sha256(
        json.dumps(det, sort_keys=True).encode()
    ).hexdigest()
    ok = (
        cross["ok"]
        and not rep["dynamic_inversions"]
        and not rep["unregistered_cross_thread"]
        and not static.lock_cycles
        and not static.violations
        and proto["static_violations"] == 0
        and proto["modelcheck_ok"]
    )
    return {
        "seed": seed,
        "ok": ok,
        "dynamic_inversions": rep["dynamic_inversions"],
        "unregistered_cross_thread": rep["unregistered_cross_thread"],
        "dynamic_lock_edges": rep["lock_edges"],
        "static_lock_edges": len(static.lock_edges),
        "static_violations": len(static.violations),
        "static_lock_cycles": static.lock_cycles,
        "cross_check": cross,
        "shared_sites": rep["shared_sites"],
        "yield_counts": rep["yield_counts"],
        "deterministic_sites": det,
        "schedule_sha256": digest,
        "proto": proto,
    }


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    result = run_drill(args.seed)
    if args.json:
        print(json.dumps(result))
    else:
        print(
            f"tsan drill seed={result['seed']}: "
            f"{len(result['dynamic_lock_edges'])} dynamic lock edge(s), "
            f"{len(result['dynamic_inversions'])} inversion(s), "
            f"{len(result['unregistered_cross_thread'])} unregistered "
            f"cross-thread access(es), merged cycles: "
            f"{result['cross_check']['merged_cycles']}, "
            f"modelcheck {result['proto']['modelcheck_points']} point(s)/"
            f"{result['proto']['modelcheck_injections']} injection(s), "
            f"schedule {result['schedule_sha256'][:12]} — "
            + ("OK" if result["ok"] else "FAIL")
        )
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
