#!/bin/bash
# Hardware-round watchdog (VERDICT r04 item 1): probe the tunneled TPU every
# ~5 min; while it is alive, run the pending hardware steps IN ORDER, each
# writing its artifact immediately. Steps that already succeeded (marker file)
# are skipped, so a 15-minute tunnel window still makes net progress and the
# script survives any number of tunnel deaths. Exits when all steps are done.
cd /root/repo
LOG=/root/repo/hw_watchdog.log
MARK=/root/repo/.hw_done
mkdir -p "$MARK"

probe() {
  # Must be a real TPU: a fast CPU fallback would otherwise mark every
  # hardware step done with CPU artifacts.
  timeout 90 python -c "
import jax, jax.numpy as jnp
kind = jax.devices()[0].device_kind
assert 'tpu' in kind.lower() or jax.default_backend() == 'tpu', kind
(jnp.ones((128,128)) @ jnp.ones((128,128))).block_until_ready()
print('ALIVE', kind)
" >> "$LOG" 2>&1
}

record_probe() {  # $1 = result, $2 = detail
  python - "$1" "$2" <<'EOF'
import json, sys, time
rec = {"ts_unix": time.time(),
       "ts_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
       "round": 5, "probe": "hw_watchdog matmul", "result": sys.argv[1],
       "detail": sys.argv[2]}
open("/root/repo/TPU_PROBES.jsonl", "a").write(json.dumps(rec) + "\n")
EOF
}

step() {  # $1 = marker name, $2... = command
  local name=$1; shift
  [ -f "$MARK/$name" ] && return 0
  echo "=== step $name $(date -u +%FT%TZ) ===" >> "$LOG"
  if "$@" >> "$LOG" 2>&1; then
    touch "$MARK/$name"
    echo "=== step $name OK ===" >> "$LOG"
  else
    echo "=== step $name FAILED rc=$? ===" >> "$LOG"
    return 1
  fi
}

bench_default() {
  timeout 2400 python bench.py > /tmp/bench_r05_default.out
  local rc=$?
  tail -1 /tmp/bench_r05_default.out > BENCH_r05_hw.json
  grep -q '"error"' BENCH_r05_hw.json && return 1
  return $rc
}

bench_pallas() {
  # The kernel arm. SORTED pinned OFF: the sorted path defaults ON for TPU
  # (it would otherwise shadow the kernel in every conv family).
  HYDRAGNN_PALLAS=1 HYDRAGNN_SEGMENT_SORTED=0 timeout 2400 python bench.py > /tmp/bench_r05_pallas.out
  local rc=$?
  tail -1 /tmp/bench_r05_pallas.out > BENCH_r05_pallas.json
  grep -q '"error"' BENCH_r05_pallas.json && return 1
  return $rc
}

bench_xla() {
  # The pre-r05 default (XLA scatter bundle) — the baseline pin's own path,
  # kept measured now that the production default is the sorted path.
  HYDRAGNN_SEGMENT_SORTED=0 timeout 2400 python bench.py > /tmp/bench_r05_xla.out
  local rc=$?
  tail -1 /tmp/bench_r05_xla.out > BENCH_r05_xla.json
  grep -q '"error"' BENCH_r05_xla.json && return 1
  return $rc
}

bench_sorted() {
  # The scatter-free sorted-segment path in the REAL train step (now also
  # the TPU default; kept as an explicit arm for labeling).
  HYDRAGNN_SEGMENT_SORTED=1 timeout 2400 python bench.py > /tmp/bench_r05_sorted.out
  local rc=$?
  tail -1 /tmp/bench_r05_sorted.out > BENCH_r05_sorted.json
  grep -q '"error"' BENCH_r05_sorted.json && return 1
  return $rc
}

certify_full() {
  timeout 1200 python - <<'EOF'
import json
from hydragnn_tpu.ops.pallas_segment import certify_pallas
out = {"contiguous": certify_pallas(contiguous=True),
       "random_ids": certify_pallas(contiguous=False)}
with open("CERTIFY_r05.json", "w") as f:
    json.dump(out, f, indent=2)
print(json.dumps(out))
EOF
}

tune() {
  timeout 7200 python benchmarks/tune_kernel.py --skip both --out TUNE_KERNEL_r05.jsonl
}

profile_axon() {
  # --epochs 2: the measurement is dominated by serial remote compiles
  # through the tunnel (the 2400s/4-epoch variant hit its timeout with no
  # artifact); two steady epochs already separate feed from step at the
  # ~0.5 s epoch times involved.
  timeout 3600 python benchmarks/profile_epoch.py --platform axon --epochs 2 \
    --out PROFILE_r05.json
}

matrix_tpu() {
  # Flagship convergence cell ON HARDWARE (VERDICT r04 item 3's "and, when
  # reachable, TPU" clause): PNA + ci_multihead under the real kernel.
  # Outer timeout > the script's per-child 3600s so its own child-timeout
  # handling (record the cell, write the artifact) can run.
  HYDRAGNN_MATRIX_TPU=1 timeout 3900 python benchmarks/pallas_matrix.py \
    --families PNA --configs ci_multihead.json --arm pallas \
    --out PALLAS_MATRIX_TPU_r05.json
  local rc=$?
  # An artifact whose cells all errored is not a landed measurement.
  grep -q '"rmse"' PALLAS_MATRIX_TPU_r05.json 2>/dev/null || return 1
  return $rc
}

matrix_sorted() {
  # Flagship convergence cell under the NEW production default (sorted).
  HYDRAGNN_MATRIX_TPU=1 timeout 3900 python benchmarks/pallas_matrix.py \
    --families PNA --configs ci_multihead.json --arm sorted \
    --out PALLAS_MATRIX_SORTED_TPU_r05.json
  local rc=$?
  grep -q '"rmse"' PALLAS_MATRIX_SORTED_TPU_r05.json 2>/dev/null || return 1
  return $rc
}

while true; do
  if [ -f "$MARK/bench_default" ] && [ -f "$MARK/bench_pallas" ] \
     && [ -f "$MARK/bench_sorted" ] && [ -f "$MARK/bench_xla" ] \
     && [ -f "$MARK/certify" ] && [ -f "$MARK/tune" ] && [ -f "$MARK/profile" ] \
     && [ -f "$MARK/matrix_tpu" ] && [ -f "$MARK/matrix_sorted" ]; then
    echo "=== all hardware steps complete $(date -u +%FT%TZ) ===" >> "$LOG"
    record_probe "done" "watchdog: all 9 hardware artifacts landed"
    exit 0
  fi
  if probe; then
    FAILS=0
    record_probe "ALIVE" "watchdog probe OK; running pending steps"
    # Steps are independent: one poisoned step must not block the others.
    # Highest-value first; re-probe between steps so a mid-batch tunnel
    # death skips the rest of this cycle quickly.
    step certify certify_full
    probe && step bench_default bench_default
    probe && step bench_pallas bench_pallas
    probe && step bench_sorted bench_sorted
    probe && step bench_xla bench_xla
    probe && step tune tune
    probe && step profile profile_axon
    probe && step matrix_tpu matrix_tpu
    probe && step matrix_sorted matrix_sorted
  else
    # Throttle dead-tunnel records to ~1/hour so the probe log stays readable.
    FAILS=$((FAILS + 1))
    if [ $((FAILS % 12)) -eq 1 ]; then
      record_probe "hang" "watchdog probe timeout (90s); fail #$FAILS"
    fi
  fi
  sleep 290
done
