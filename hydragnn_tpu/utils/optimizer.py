"""Optimizer selection (reference /root/reference/hydragnn/utils/optimizer.py:4-30):
the same name set, mapped to optax. Learning rate is the only exposed knob, like
the reference. The LR is injected as a mutable hyperparameter so the
ReduceLROnPlateau scheduler can update it between epochs without rebuilding
optimizer state. ``freeze_conv`` applies an optax mask (no update at all for
encoder conv/bn params — the functional analog of requires_grad=False,
reference Base._freeze_conv, Base.py:107-111)."""

from __future__ import annotations

from typing import Optional

import optax


class ValueFnTransformation(optax.GradientTransformationExtraArgs):
    """Marker type: ``update()`` needs ``(value, grad, value_fn)`` threaded
    through by the train step (optax's zoom linesearch contract). The step
    builders in train/trainer.py check for this type."""


def _base_optimizer(name: str, learning_rate: float):
    name_l = name.lower()
    table = {
        "sgd": lambda lr: optax.sgd(lr),
        "adam": lambda lr: optax.adam(lr),
        "adadelta": lambda lr: optax.adadelta(lr),
        "adagrad": lambda lr: optax.adagrad(lr),
        "adamax": lambda lr: optax.adamax(lr),
        # torch AdamW's default weight_decay is 0.01 (vs optax's 1e-4); the
        # reference relies on the torch default (optimizer.py:14).
        "adamw": lambda lr: optax.adamw(lr, weight_decay=0.01),
        "rmsprop": lambda lr: optax.rmsprop(lr),
        # torch SparseAdam is Adam with sparse-gradient support; dense here.
        "sparseadam": lambda lr: optax.adam(lr),
    }
    if name_l not in table:
        raise ValueError(f"Purpose of {name} optimizer is not defined.")
    return table[name_l](learning_rate)


def select_optimizer(
    name: str,
    learning_rate: float,
    freeze_conv: bool = False,
) -> optax.GradientTransformation:
    if name.lower() == "lbfgs":
        # Real LBFGS (torch parity, reference optimizer.py:19-20): limited
        # memory + zoom linesearch choosing the step size, so the injected LR
        # is not a knob (get_learning_rate returns None; the plateau
        # scheduler skips it). The train step threads value/grad/value_fn
        # through update() — single-device/scan paths only.
        opt = optax.lbfgs()
        if freeze_conv:
            raise NotImplementedError(
                "freeze_conv_layers with LBFGS is not supported: the "
                "linesearch evaluates the full loss, which conflicts with "
                "masked zero updates."
            )
        return ValueFnTransformation(opt.init, opt.update)
    _base_optimizer(name, learning_rate)  # eager name validation
    opt = optax.inject_hyperparams(
        lambda learning_rate: _base_optimizer(name, learning_rate)
    )(learning_rate=learning_rate)
    if freeze_conv:
        opt = optax.multi_transform(
            {"train": opt, "frozen": optax.set_to_zero()},
            _freeze_partition,
        )
    return opt


def _freeze_partition(params):
    """Label encoder conv/bn params 'frozen', everything else 'train'."""
    import jax

    def label(path, _):
        top = path[0].key if hasattr(path[0], "key") else str(path[0])
        frozen = top.startswith("conv_") or top.startswith("bn_")
        return "frozen" if frozen else "train"

    return jax.tree_util.tree_map_with_path(label, params)


def get_learning_rate(opt_state) -> Optional[float]:
    """Current injected LR (walks multi_transform wrapping if present)."""
    state = opt_state
    if hasattr(state, "inner_states"):  # multi_transform
        state = state.inner_states["train"].inner_state
    if hasattr(state, "hyperparams"):
        return float(state.hyperparams["learning_rate"])
    return None


def set_learning_rate(opt_state, lr: float):
    """Return opt_state with the injected LR replaced (host-side scheduler hook)."""
    import jax.numpy as jnp

    if hasattr(opt_state, "inner_states"):
        inner = opt_state.inner_states["train"]
        new_inner_state = set_learning_rate(inner.inner_state, lr)
        new_inner = inner._replace(inner_state=new_inner_state)
        states = dict(opt_state.inner_states)
        states["train"] = new_inner
        return opt_state._replace(inner_states=states)
    if hasattr(opt_state, "hyperparams"):
        hp = dict(opt_state.hyperparams)
        hp["learning_rate"] = jnp.asarray(lr, dtype=jnp.asarray(hp["learning_rate"]).dtype)
        return opt_state._replace(hyperparams=hp)
    raise ValueError("Optimizer state does not carry an injected learning rate")


class ReduceLROnPlateau:
    """Host-side plateau scheduler with torch's exact decision semantics
    (torch.optim.lr_scheduler.ReduceLROnPlateau is what the reference
    configures, run_training.py:82-84: factor 0.5, patience 5, min_lr 1e-5;
    stepped on validation RMSE every epoch). Matches torch's defaults for the
    parts that change behavior on noisy curves: relative improvement
    threshold (1e-4) and a post-reduction cooldown (0) — verified against
    torch's decision trace in tests/test_optimizers.py."""

    def __init__(
        self,
        factor=0.5,
        patience=5,
        min_lr=1e-5,
        mode="min",
        threshold=1e-4,
        threshold_mode="rel",
        cooldown=0,
    ):
        self.factor = factor
        self.patience = patience
        self.min_lr = min_lr
        self.mode = mode
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.cooldown = cooldown
        self.best = None
        self.num_bad_epochs = 0
        self.cooldown_counter = 0

    def _is_better(self, metric: float) -> bool:
        if self.best is None:
            return True
        if self.threshold_mode == "rel":
            eps = (
                1.0 - self.threshold
                if self.mode == "min"
                else 1.0 + self.threshold
            )
            bar = self.best * eps
        else:  # "abs"
            bar = (
                self.best - self.threshold
                if self.mode == "min"
                else self.best + self.threshold
            )
        return metric < bar if self.mode == "min" else metric > bar

    def step(self, metric: float, current_lr: float) -> float:
        """Returns the (possibly reduced) learning rate."""
        if self._is_better(metric):
            self.best = metric
            self.num_bad_epochs = 0
        else:
            self.num_bad_epochs += 1
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad_epochs = 0
        if self.num_bad_epochs > self.patience:
            self.num_bad_epochs = 0
            self.cooldown_counter = self.cooldown
            return max(current_lr * self.factor, self.min_lr)
        return current_lr

    def state_dict(self) -> dict:
        """Mutable decision state (for checkpoint resume); hyperparameters are
        reconstructed from config, matching torch's state_dict split."""
        return {
            "best": self.best,
            "num_bad_epochs": self.num_bad_epochs,
            "cooldown_counter": self.cooldown_counter,
        }

    def load_state_dict(self, state: dict) -> None:
        self.best = state["best"]
        self.num_bad_epochs = int(state["num_bad_epochs"])
        self.cooldown_counter = int(state["cooldown_counter"])
