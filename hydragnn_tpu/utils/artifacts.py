"""Round-artifact naming convention, shared by benchmarks and tests.

Per-round artifacts (LARGEGRAPH_rNN.json, SERVE_rNN.json, ...) key their
filename on the driver-exported HYDRAGNN_ROUND environment variable; one
helper so the convention (zero-padded, single fallback default) cannot drift
between writers.
"""

from __future__ import annotations

import os

# Bump alongside the repo's round cadence: used only when the driver did not
# export HYDRAGNN_ROUND (e.g. a by-hand test run).
_FALLBACK_ROUND = "06"


def round_tag() -> str:
    """Two-digit round tag for artifact filenames, e.g. "06"."""
    tag = os.environ.get("HYDRAGNN_ROUND", "")
    return tag.zfill(2) if tag.isdigit() else _FALLBACK_ROUND
