"""Step-windowed profiler (reference /root/reference/hydragnn/utils/
profile.py:9-68 wraps torch.profiler with a wait=1/warmup=1/active=3 step
schedule inside a target epoch; here jax.profiler traces to TensorBoard).

Config surface is a superset of the reference's:
``"Profile": {"enable": 1, "target_epoch": N, "wait": 1, "warmup": 1,
"active": 3}`` — within the target epoch, ``wait + warmup`` train steps run
untraced (compile/cache effects settle), then exactly ``active`` steps are
captured. ``active: 0`` falls back to tracing the whole epoch. The trace
lands under ./logs/<name>/profiler_output for TensorBoard / Perfetto.

``annotate(name)`` opens a named span (torch ``record_function`` analog);
the TrainingDriver wraps feed / train_step / eval_step with it."""

from __future__ import annotations

import contextlib
import os
from typing import Optional

import jax


class Profiler:
    def __init__(self, prefix: str = "./logs/profile"):
        self.enabled = False
        self.target_epoch: Optional[int] = None
        self.trace_dir = os.path.join(prefix, "profiler_output")
        # Step schedule within the target epoch (reference profile.py:23).
        self.wait = 1
        self.warmup = 1
        self.active_steps = 3
        self._armed = False  # inside the target epoch
        self._tracing = False  # jax trace window open
        self._step = 0

    def setup(self, config: Optional[dict]) -> None:
        """config = the optional "Profile" block of the run config."""
        if not config:
            return
        self.enabled = bool(config.get("enable", 0))
        self.target_epoch = config.get("target_epoch", 0)
        self.wait = int(config.get("wait", 1))
        self.warmup = int(config.get("warmup", 1))
        self.active_steps = int(config.get("active", 3))

    def set_current_epoch(self, epoch: int) -> None:
        if not self.enabled:
            return
        if epoch == self.target_epoch and not self._armed:
            self._armed = True
            self._step = 0
            # Whole-epoch window, or a schedule with no wait/warmup: the
            # trace must open before the first step runs.
            if self.active_steps <= 0 or self.wait + self.warmup == 0:
                self._start()
        elif self._armed and epoch != self.target_epoch:
            self.stop()

    @property
    def active(self) -> bool:
        """True inside the target epoch (drives the per-step train path —
        scanned epochs would hide step boundaries from the trace)."""
        return self._armed

    def step(self) -> None:
        """Per-train-step hook: advances the wait/warmup/active schedule."""
        if not self._armed or self.active_steps <= 0:
            return
        self._step += 1
        skip = self.wait + self.warmup
        if self._step == skip and not self._tracing:
            self._start()
        elif self._step == skip + self.active_steps and self._tracing:
            self._stop_trace()

    def annotate(self, name: str):
        """Named span (record_function analog) inside the trace."""
        if self._armed:
            return jax.profiler.TraceAnnotation(name)
        return contextlib.nullcontext()

    def _start(self) -> None:
        os.makedirs(self.trace_dir, exist_ok=True)
        jax.profiler.start_trace(self.trace_dir)
        self._tracing = True

    def _stop_trace(self) -> None:
        jax.profiler.stop_trace()
        self._tracing = False

    def stop(self) -> None:
        if self._tracing:
            self._stop_trace()
        self._armed = False
