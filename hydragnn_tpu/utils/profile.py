"""Epoch-targeted profiler window (reference /root/reference/hydragnn/utils/
profile.py:9-68 wraps torch.profiler; here jax.profiler traces to TensorBoard).

Config surface is identical: ``"Profile": {"enable": 1, "target_epoch": N}``; the
trace covers the target epoch's train loop and lands under
./logs/<name>/profiler_output for TensorBoard / Perfetto."""

from __future__ import annotations

import contextlib
import os
from typing import Optional

import jax


class Profiler:
    def __init__(self, prefix: str = "./logs/profile"):
        self.enabled = False
        self.target_epoch: Optional[int] = None
        self.trace_dir = os.path.join(prefix, "profiler_output")
        self._active = False

    def setup(self, config: Optional[dict]) -> None:
        """config = the optional "Profile" block of the run config."""
        if not config:
            return
        self.enabled = bool(config.get("enable", 0))
        self.target_epoch = config.get("target_epoch", 0)

    def set_current_epoch(self, epoch: int) -> None:
        if not self.enabled:
            return
        if epoch == self.target_epoch and not self._active:
            os.makedirs(self.trace_dir, exist_ok=True)
            jax.profiler.start_trace(self.trace_dir)
            self._active = True
        elif self._active and epoch != self.target_epoch:
            self.stop()

    @property
    def active(self) -> bool:
        """True while a trace window is open (drives the per-step train path —
        scanned epochs would hide step boundaries from the trace)."""
        return self._active

    def step(self) -> None:
        """Per-batch hook kept for API parity (jax traces need no step marker)."""

    def annotate(self, name: str):
        """Named span (record_function analog) inside the trace."""
        if self._active:
            return jax.profiler.TraceAnnotation(name)
        return contextlib.nullcontext()

    def stop(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
