"""Named wall-clock timers with cross-process min/max/avg reduction
(reference /root/reference/hydragnn/utils/time_utils.py:22-138).

Timers are host-side (they time host-visible phases: data load, model create,
whole training). Under multi-process JAX the reduction uses a tiny psum'd
all-gather via multihost_utils instead of torch.distributed reduce."""

from __future__ import annotations

import time
from typing import Dict

import jax

from ..telemetry import graftel as telemetry


class Timer:
    """Accumulating named timer; class-level registry like the reference.

    Since the graftel PR the STORAGE lives in the process-wide telemetry
    registry (telemetry/graftel.py, one lock for every metric surface) under
    ``timer/<name>`` keys — written from the main thread (start/stop pairs)
    AND from the pipeline/serve worker threads (``credit`` — the transfer
    thread's H2D wire time, every ``serve_*`` stage). ``Timer`` keeps its
    historical API as the reporting surface (``print_timers``,
    ``reduce_timers``), but it is now a graftel emitter: bench.py, the serve
    ``/metrics`` exposition, and the timer report all read one registry."""

    def __init__(self, name: str):
        self.name = name
        self._start = None

    def start(self):
        if self._start is not None:
            raise RuntimeError(f"Timer {self.name} already started")
        self._start = time.perf_counter()

    def stop(self):
        if self._start is None:
            raise RuntimeError(f"Timer {self.name} not started")
        elapsed = time.perf_counter() - self._start
        telemetry.timer_credit(self.name, elapsed)
        self._start = None
        return elapsed

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    @classmethod
    def credit(cls, name: str, seconds: float) -> None:
        """Credit externally-measured seconds into the registry — for phases
        timed off the main thread (the input pipeline's transfer thread
        measures H2D wire time with its own perf_counter pair and cannot
        hold a start/stop Timer across threads)."""
        if seconds <= 0:
            return
        telemetry.timer_credit(name, seconds)

    @classmethod
    def snapshot(cls) -> Dict[str, float]:
        """Locked copy of the totals — every reader outside the class goes
        through this (reporting must not see a mid-update registry)."""
        return telemetry.timer_totals()

    @classmethod
    def reset(cls):
        telemetry.clear_counters("timer/")


def reduce_timers() -> Dict[str, Dict[str, float]]:
    """Per-timer min/max/avg across processes (rank-0 meaningful)."""
    stats = {}
    nproc = jax.process_count()
    for name, total in Timer.snapshot().items():
        if nproc > 1:
            from jax.experimental import multihost_utils
            import numpy as np

            gathered = multihost_utils.process_allgather(np.float64(total))
            stats[name] = {
                "min": float(gathered.min()),
                "max": float(gathered.max()),
                "avg": float(gathered.mean()),
            }
        else:
            stats[name] = {"min": total, "max": total, "avg": total}
    return stats


def print_timers(verbosity: int = 0):
    """Sorted-by-cost timer report at end of run (time_utils.py:95-138).
    Fault-event counters (faults/counters.py) ride the same report: a run
    that skipped steps, rolled back, retried transfers, or quarantined
    samples says so at the end instead of surviving silently."""
    from .print_utils import print_distributed

    stats = reduce_timers()
    try:
        from ..faults.counters import FaultCounters

        fault_counts = FaultCounters.snapshot()
    except Exception:
        fault_counts = {}
    if not stats and not fault_counts:
        return
    lines = []
    if stats:
        width = max(len(n) for n in stats)
        lines.append("Timer report (seconds):")
        for name, s in sorted(stats.items(), key=lambda kv: -kv[1]["max"]):
            lines.append(
                f"  {name:<{width}}  min={s['min']:.3f}  max={s['max']:.3f}  "
                f"avg={s['avg']:.3f}"
            )
    if fault_counts:
        lines.append("Fault counters:")
        for name, n in sorted(fault_counts.items()):
            lines.append(f"  {name}: {n}")
    print_distributed(verbosity, "\n".join(lines))
