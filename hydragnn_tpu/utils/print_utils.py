"""Verbosity-gated logging (reference /root/reference/hydragnn/utils/print_utils.py:20-103).

Levels: 0 = silent, 1-2 = rank 0 only, 3-4 = all ranks; 2 and 4 add tqdm bars.
``iterate_tqdm`` guards the uninitialized-distributed case the reference crashes
on (print_utils.py:57 quirk, SURVEY.md §7)."""

from __future__ import annotations

import logging
import os
from typing import Iterable

import jax


def _rank() -> int:
    try:
        return jax.process_index()
    except Exception:
        return 0


def print_distributed(verbosity: int, *args) -> None:
    if verbosity in (1, 2):
        if _rank() == 0:
            print(*args, flush=True)
    elif verbosity in (3, 4):
        print(f"[rank {_rank()}]", *args, flush=True)


def iterate_tqdm(iterable: Iterable, verbosity: int):
    show = verbosity in (2, 4) and (_rank() == 0 or verbosity == 4)
    if show:
        try:
            from tqdm import tqdm

            return tqdm(iterable)
        except ImportError:
            pass
    return iterable


_logger = None


def setup_log(log_name: str, log_dir: str = "./logs") -> logging.Logger:
    """File+console logger under ./logs/<name>/run.log, rank-prefixed messages
    (print_utils.py:63-103)."""
    global _logger
    path = os.path.join(log_dir, log_name)
    os.makedirs(path, exist_ok=True)
    logger = logging.getLogger("hydragnn")
    logger.setLevel(logging.INFO)
    logger.handlers.clear()
    fmt = logging.Formatter(f"[rank {_rank()}] %(message)s")
    fh = logging.FileHandler(os.path.join(path, "run.log"))
    fh.setFormatter(fmt)
    sh = logging.StreamHandler()
    sh.setFormatter(fmt)
    logger.addHandler(fh)
    logger.addHandler(sh)
    _logger = logger
    return logger


def log(*args) -> None:
    if _logger is not None:
        _logger.info(" ".join(str(a) for a in args))


def get_log_dir(log_name: str, log_dir: str = "./logs") -> str:
    return os.path.join(log_dir, log_name)
