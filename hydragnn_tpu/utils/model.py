"""Checkpointing, TensorBoard, and model statistics
(reference /root/reference/hydragnn/utils/model.py:28-97).

Checkpoint format: single file ``./logs/<name>/<name>.pk`` holding msgpack-encoded
{params, batch_stats, opt_state} via flax.serialization — same single-file,
rank-0-only semantics as the reference's torch.save of
{model_state_dict, optimizer_state_dict}. Improvement over reference (documented
divergence, SURVEY.md §5.4): ``save_model`` can be called periodically, and
``get_summary_writer`` actually RETURNS the writer (the reference's returns None,
leaving its TensorBoard path dead — model.py:50-54)."""

from __future__ import annotations

import glob
import json
import os
import pickle
import time
from typing import Any, Dict, List, Optional

import jax
import numpy as np
from flax import serialization

from .print_utils import print_distributed


def _is_rank_zero() -> bool:
    return jax.process_index() == 0


def cleanup_stale_checkpoint_tmp(run_dir: str) -> List[str]:
    """Remove ``*.tmp`` files a crash left behind mid-``os.replace``. Safe to
    call whenever no save is in flight — checkpoint writes are rank-0 and
    single-threaded, so any ``.tmp`` present at save entry (or at run/resume
    startup) is by construction a torn leftover, never a live write. Returns
    the removed paths (logged by the fault drills)."""
    removed = []
    for p in glob.glob(os.path.join(run_dir, "*.tmp")):
        try:
            os.remove(p)
            removed.append(p)
        except OSError:
            pass
    return removed


def _manifest_path(run_dir: str, name: str) -> str:
    return os.path.join(run_dir, name + ".manifest.json")


def load_checkpoint_manifest(
    name: str, path: str = "./logs/"
) -> Dict[str, Any]:
    """The retention manifest written by ``save_model(keep_last_k=...)``
    ({} when retention was never enabled, or the manifest is torn)."""
    try:
        with open(_manifest_path(os.path.join(path, name), name)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _retain_checkpoints(
    run_dir: str, name: str, latest: str, keep_last_k: int, meta
) -> None:
    """keep_last_k retention: hard-link the just-written latest checkpoint to
    an epoch-tagged retained file, prune retained files beyond k, and update
    the manifest ATOMICALLY (tmp + os.replace) — a crash at any point leaves
    either the old or the new manifest, both listing only files that exist."""
    epoch = (meta or {}).get("epoch")
    try:
        with open(_manifest_path(run_dir, name)) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        manifest = {}
    entries = [
        e
        for e in manifest.get("entries", [])
        if os.path.exists(os.path.join(run_dir, e["file"]))
    ]
    serial = (max((e.get("serial", 0) for e in entries), default=0)) + 1
    tag = f"e{int(epoch):06d}" if epoch is not None else f"s{serial:06d}"
    retained = f"{name}.{tag}.pk"
    retained_path = os.path.join(run_dir, retained)
    link_tmp = retained_path + ".tmp"
    if os.path.exists(link_tmp):
        os.remove(link_tmp)
    try:
        os.link(latest, link_tmp)  # same content, no second serialization
        os.replace(link_tmp, retained_path)
    except OSError:
        import shutil  # filesystems without hard links

        shutil.copyfile(latest, link_tmp)
        os.replace(link_tmp, retained_path)
    entries = [e for e in entries if e["file"] != retained]
    entries.append(
        {
            "file": retained,
            "epoch": epoch,
            "serial": serial,
            "saved_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }
    )
    entries.sort(key=lambda e: e["serial"])
    for drop in entries[:-keep_last_k] if keep_last_k > 0 else []:
        try:
            os.remove(os.path.join(run_dir, drop["file"]))
        except OSError:
            pass
    entries = entries[-keep_last_k:] if keep_last_k > 0 else entries
    doc = {"name": name, "keep_last_k": keep_last_k, "entries": entries}
    mpath = _manifest_path(run_dir, name)
    mtmp = mpath + ".tmp"
    with open(mtmp, "w") as f:
        json.dump(doc, f, indent=2)
    os.replace(mtmp, mpath)


def save_model(
    variables: Dict[str, Any],
    opt_state: Any,
    name: str,
    path: str = "./logs/",
    meta: Optional[Dict[str, Any]] = None,
    keep_last_k: int = 0,
) -> None:
    """Rank-0 single-file checkpoint (model.py:35-47). ``meta`` carries
    training progress (epoch, scheduler state, loss history) so a preempted
    run can resume exactly where it stopped (Training.resume).

    ``keep_last_k > 0`` additionally retains the last k checkpoints as
    epoch-tagged hard links next to the latest (``<name>.e000004.pk``) with an
    atomically-updated ``<name>.manifest.json`` — a corrupted-latest scenario
    (or a rollback past the last save) has history to fall back on. The
    ``<name>.pk`` latest-checkpoint contract is unchanged either way."""
    if not _is_rank_zero():
        return
    path_name = os.path.join(path, name, name + ".pk")
    payload = {
        "params": serialization.to_bytes(variables["params"]),
        "batch_stats": serialization.to_bytes(variables.get("batch_stats", {})),
        "opt_state": serialization.to_bytes(opt_state)
        if opt_state is not None
        else None,
    }
    if meta is not None:
        payload["meta"] = meta
    run_dir = os.path.dirname(path_name)
    os.makedirs(run_dir, exist_ok=True)
    # A crash mid-os.replace in an EARLIER incarnation leaves *.tmp litter;
    # a save in flight is impossible here (rank-0, single-threaded).
    cleanup_stale_checkpoint_tmp(run_dir)
    # Atomic write: a crash mid-dump must not leave a torn checkpoint that a
    # later warm start would fail on.
    tmp_name = path_name + ".tmp"
    with open(tmp_name, "wb") as f:
        pickle.dump(payload, f)
    os.replace(tmp_name, path_name)
    if keep_last_k and keep_last_k > 0:
        _retain_checkpoints(run_dir, name, path_name, int(keep_last_k), meta)


def load_checkpoint_file(
    variables: Dict[str, Any], path_name: str, opt_state: Any = None
):
    """Restore one checkpoint FILE (the save_model payload) onto a variables
    template. The single deserialization implementation — the log-name
    convenience below and direct-path consumers (serve engine) share it, so
    a payload-schema change cannot diverge them. Returns
    (variables, opt_state, meta)."""
    with open(path_name, "rb") as f:
        payload = pickle.load(f)
    new_vars = dict(variables)
    new_vars["params"] = serialization.from_bytes(
        variables["params"], payload["params"]
    )
    new_vars["batch_stats"] = serialization.from_bytes(
        variables.get("batch_stats", {}), payload["batch_stats"]
    )
    if opt_state is not None and payload.get("opt_state") is not None:
        opt_state = serialization.from_bytes(opt_state, payload["opt_state"])
    return new_vars, opt_state, payload.get("meta") or {}


def load_existing_model(
    variables: Dict[str, Any],
    model_name: str,
    path: str = "./logs/",
    opt_state: Any = None,
    return_meta: bool = False,
):
    """Restore params/batch_stats (+optimizer state if given a template) from the
    single-file checkpoint (model.py:63-78). Returns (variables, opt_state), plus
    the progress meta dict when ``return_meta`` (one file read, not two)."""
    path_name = os.path.join(path, model_name, model_name + ".pk")
    new_vars, opt_state, meta = load_checkpoint_file(
        variables, path_name, opt_state
    )
    if return_meta:
        return new_vars, opt_state, meta
    return new_vars, opt_state


def load_existing_model_config(
    variables, config: Dict[str, Any], path: str = "./logs/", opt_state: Any = None
):
    """Warm start when Training.continue is set (model.py:57-60)."""
    if config.get("continue", 0):
        model_name = config.get("startfrom", "existing_model")
        return load_existing_model(variables, model_name, path, opt_state)
    return variables, opt_state


def checkpoint_exists(model_name: str, path: str = "./logs/") -> bool:
    return os.path.exists(os.path.join(path, model_name, model_name + ".pk"))


def load_checkpoint_meta(model_name: str, path: str = "./logs/") -> Dict[str, Any]:
    """Training-progress metadata stored alongside the weights ({} for
    checkpoints written before meta existed, or when none was saved)."""
    path_name = os.path.join(path, model_name, model_name + ".pk")
    with open(path_name, "rb") as f:
        payload = pickle.load(f)
    return payload.get("meta") or {}


def get_summary_writer(name: str, path: str = "./logs/"):
    """Rank-0 TensorBoard writer — actually returned, unlike the reference
    (model.py:50-54 returns None and the TB path is dead)."""
    if not _is_rank_zero():
        return None
    try:
        from torch.utils.tensorboard import SummaryWriter
    except Exception:
        return None
    return SummaryWriter(os.path.join(path, name))


def calculate_PNA_degree(dataset, max_neighbours: int) -> np.ndarray:
    """In-degree histogram over the train set for PNA scalers
    (model.py:81-86)."""
    hist = np.zeros(max_neighbours + 1, dtype=np.int64)
    for s in dataset:
        deg = np.bincount(
            np.asarray(s.edge_index[1], dtype=np.int64), minlength=s.num_nodes
        )
        hist += np.bincount(
            np.clip(deg, 0, max_neighbours), minlength=max_neighbours + 1
        )
    return hist


def count_parameters(params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))


def print_model(model, params, verbosity: int = 0) -> None:
    print_distributed(verbosity, str(model))
    print_distributed(verbosity, f"Total parameters: {count_parameters(params)}")
