"""Checkpointing wrappers, TensorBoard, and model statistics
(reference /root/reference/hydragnn/utils/model.py:28-97).

The checkpoint implementation moved to :mod:`hydragnn_tpu.checkpoint`
(verified v2 msgpack format, corruption fallback chain, async writer —
docs/CHECKPOINTING.md); this module keeps the historical public names as
thin re-exports so every existing consumer (run_training, run_prediction,
serve engine, tests) is source-compatible. Same single-file, rank-0-only
semantics as the reference's torch.save of {model_state_dict,
optimizer_state_dict}; improvement over reference (documented divergence,
SURVEY.md §5.4): ``save_model`` can be called periodically, and
``get_summary_writer`` actually RETURNS the writer (the reference's returns
None, leaving its TensorBoard path dead — model.py:50-54)."""

from __future__ import annotations

import os

import jax
import numpy as np

from ..checkpoint import (  # noqa: F401  (public re-exports)
    checkpoint_exists,
    cleanup_stale_checkpoint_tmp,
    load_checkpoint_file,
    load_checkpoint_manifest,
    load_checkpoint_meta,
    load_existing_model,
    load_existing_model_config,
    save_model,
)
from .print_utils import print_distributed


def _is_rank_zero() -> bool:
    return jax.process_index() == 0


def get_summary_writer(name: str, path: str = "./logs/"):
    """Rank-0 TensorBoard writer — actually returned, unlike the reference
    (model.py:50-54 returns None and the TB path is dead)."""
    if not _is_rank_zero():
        return None
    try:
        from torch.utils.tensorboard import SummaryWriter
    except Exception:
        return None
    return SummaryWriter(os.path.join(path, name))


def calculate_PNA_degree(dataset, max_neighbours: int) -> np.ndarray:
    """In-degree histogram over the train set for PNA scalers
    (model.py:81-86)."""
    hist = np.zeros(max_neighbours + 1, dtype=np.int64)
    for s in dataset:
        deg = np.bincount(
            np.asarray(s.edge_index[1], dtype=np.int64), minlength=s.num_nodes
        )
        hist += np.bincount(
            np.clip(deg, 0, max_neighbours), minlength=max_neighbours + 1
        )
    return hist


def count_parameters(params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))


def print_model(model, params, verbosity: int = 0) -> None:
    print_distributed(verbosity, str(model))
    print_distributed(verbosity, f"Total parameters: {count_parameters(params)}")
