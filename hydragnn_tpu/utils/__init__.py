from .config_utils import get_log_name_config, update_config, update_config_minmax
from .model import (
    calculate_PNA_degree,
    get_summary_writer,
    load_existing_model,
    load_existing_model_config,
    save_model,
)
from .optimizer import ReduceLROnPlateau, select_optimizer
from .print_utils import iterate_tqdm, log, print_distributed, setup_log
from .profile import Profiler
from .time_utils import Timer, print_timers
