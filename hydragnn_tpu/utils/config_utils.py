"""Config system: JSON schema identical to the reference, including data-driven
completion (reference /root/reference/hydragnn/utils/config_utils.py:17-195).

``update_config`` fills Architecture fields from the first training sample:
output_dim/output_type from the packed y_loc, input_dim from selected features,
the PNA degree histogram from the train set, edge_dim validation, and defaults —
then pushes the inferred head spec into the data loaders (which need it to emit
per-head dense targets)."""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict

from ..preprocess.graph_build import check_if_graph_size_variable
from .model import calculate_PNA_degree


def update_config(config: Dict[str, Any], train_loader, val_loader, test_loader):
    graph_size_variable = check_if_graph_size_variable(
        train_loader.dataset, val_loader.dataset, test_loader.dataset
    )

    if "Dataset" in config:
        check_output_dim_consistent(train_loader.dataset[0], config)

    config["NeuralNetwork"] = update_config_NN_outputs(
        config["NeuralNetwork"], train_loader.dataset[0], graph_size_variable
    )
    config = normalize_output_config(config)

    arch = config["NeuralNetwork"]["Architecture"]
    voi = config["NeuralNetwork"]["Variables_of_interest"]
    arch["input_dim"] = len(voi["input_node_features"])

    if arch["model_type"] == "PNA":
        deg = calculate_PNA_degree(train_loader.dataset, arch["max_neighbours"])
        arch["pna_deg"] = deg.tolist()
    else:
        arch["pna_deg"] = None

    config["NeuralNetwork"]["Architecture"] = update_config_edge_dim(arch)

    arch.setdefault("freeze_conv_layers", False)
    arch.setdefault("initial_bias", None)
    config["NeuralNetwork"]["Training"].setdefault("optimizer", "AdamW")

    # Push the inferred head spec into the loaders so batches carry targets.
    for loader in (train_loader, val_loader, test_loader):
        loader.set_head_spec(arch["output_type"], arch["output_dim"])
        loader.edge_dim = arch["edge_dim"]

    return config


def update_config_edge_dim(arch: Dict[str, Any]) -> Dict[str, Any]:
    arch["edge_dim"] = None
    edge_models = ["PNA", "CGCNN"]
    if "edge_features" in arch and arch["edge_features"]:
        assert (
            arch["model_type"] in edge_models
        ), "Edge features can only be used with PNA and CGCNN."
        arch["edge_dim"] = len(arch["edge_features"])
    elif arch["model_type"] == "CGCNN":
        # CGCNN always needs an integer edge_dim (config_utils.py:68-71).
        arch["edge_dim"] = 0
    return arch


def check_output_dim_consistent(data, config: Dict[str, Any]) -> None:
    output_type = config["NeuralNetwork"]["Variables_of_interest"]["type"]
    output_index = config["NeuralNetwork"]["Variables_of_interest"]["output_index"]
    for ihead in range(len(output_type)):
        span = int(data.y_loc[0, ihead + 1]) - int(data.y_loc[0, ihead])
        if output_type[ihead] == "graph":
            assert (
                span
                == config["Dataset"]["graph_features"]["dim"][output_index[ihead]]
            )
        elif output_type[ihead] == "node":
            assert (
                span // data.num_nodes
                == config["Dataset"]["node_features"]["dim"][output_index[ihead]]
            )


def update_config_NN_outputs(
    nn_config: Dict[str, Any], data, graph_size_variable: bool
) -> Dict[str, Any]:
    output_type = nn_config["Variables_of_interest"]["type"]
    dims_list = []
    for ihead in range(len(output_type)):
        span = int(data.y_loc[0, ihead + 1]) - int(data.y_loc[0, ihead])
        if output_type[ihead] == "graph":
            dim_item = span
        elif output_type[ihead] == "node":
            if (
                graph_size_variable
                and nn_config["Architecture"]["output_heads"]["node"]["type"]
                == "mlp_per_node"
            ):
                raise ValueError(
                    '"mlp_per_node" is not allowed for variable graph size, Please '
                    'set config["NeuralNetwork"]["Architecture"]["output_heads"]'
                    '["node"]["type"] to be "mlp" or "conv" in input file.'
                )
            dim_item = span // data.num_nodes
        else:
            raise ValueError("Unknown output type", output_type[ihead])
        dims_list.append(dim_item)
    nn_config["Architecture"]["output_dim"] = dims_list
    nn_config["Architecture"]["output_type"] = output_type
    nn_config["Architecture"]["num_nodes"] = data.num_nodes
    return nn_config


def normalize_output_config(config: Dict[str, Any]) -> Dict[str, Any]:
    var_config = config["NeuralNetwork"]["Variables_of_interest"]
    if var_config.get("denormalize_output"):
        if list(config["Dataset"]["path"].values())[0].endswith(".pkl"):
            dataset_path = list(config["Dataset"]["path"].values())[0]
        else:
            base = os.environ["SERIALIZED_DATA_PATH"]
            if "total" in config["Dataset"]["path"]:
                dataset_path = (
                    f"{base}/serialized_dataset/{config['Dataset']['name']}.pkl"
                )
            else:
                dataset_path = (
                    f"{base}/serialized_dataset/{config['Dataset']['name']}_train.pkl"
                )
        var_config = update_config_minmax(dataset_path, var_config)
    else:
        var_config["denormalize_output"] = False
    config["NeuralNetwork"]["Variables_of_interest"] = var_config
    return config


def update_config_minmax(dataset_path: str, config: Dict[str, Any]):
    """Load per-feature min/max tables pickled ahead of the dataset
    (config_utils.py:142-161)."""
    with open(dataset_path, "rb") as f:
        node_minmax = pickle.load(f)
        graph_minmax = pickle.load(f)
    config["x_minmax"] = []
    config["y_minmax"] = []
    for item in config["input_node_features"]:
        config["x_minmax"].append(node_minmax[:, item].tolist())
    for out_type, out_index in zip(config["type"], config["output_index"]):
        if out_type == "graph":
            config["y_minmax"].append(graph_minmax[:, out_index].tolist())
        elif out_type == "node":
            config["y_minmax"].append(node_minmax[:, out_index].tolist())
        else:
            raise ValueError("Unknown output type", out_type)
    return config


def get_log_name_config(config: Dict[str, Any]) -> str:
    """Hyperparameter-encoding log/checkpoint name (config_utils.py:164-195)."""
    arch = config["NeuralNetwork"]["Architecture"]
    train = config["NeuralNetwork"]["Training"]
    voi = config["NeuralNetwork"]["Variables_of_interest"]
    return (
        arch["model_type"]
        + "-r-"
        + str(arch["radius"])
        + "-mnnn-"
        + str(arch["max_neighbours"])
        + "-ncl-"
        + str(arch["num_conv_layers"])
        + "-hd-"
        + str(arch["hidden_dim"])
        + "-ne-"
        + str(train["num_epoch"])
        + "-lr-"
        + str(train["learning_rate"])
        + "-bs-"
        + str(train["batch_size"])
        + "-data-"
        + config["Dataset"]["name"]
        + "-node_ft-"
        + "".join(str(x) for x in voi["input_node_features"])
        + "-task_weights-"
        + "".join(str(w) + "-" for w in arch["task_weights"])
    )
