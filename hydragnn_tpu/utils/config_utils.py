"""Data-driven config completion.

Accepts the reference's JSON schema (/root/reference/hydragnn/utils/
config_utils.py:17-195 describes the contract: infer output_dim/output_type
from the packed y_loc of the first training sample, input_dim from the
selected node features, the PNA degree histogram from the train set, edge_dim
from the declared edge features, then apply defaults) and produces the same
completed config — pinned by the golden tests in
tests/test_config_completion.py.

The implementation is organized as a completion PIPELINE over a small context:
each stage is a function of (config, ctx) run in order by ``update_config``,
with per-head logic driven by a kind→handler dispatch table and the trailing
defaults/log-name encoding declared as data.
"""

from __future__ import annotations

import os
import pickle
from dataclasses import dataclass
from typing import Any, Dict, List

from ..preprocess.graph_build import check_if_graph_size_variable
from .model import calculate_PNA_degree

# Conv stacks that consume per-edge feature vectors.
_EDGE_FEATURE_MODELS = frozenset({"PNA", "CGCNN"})

# Trailing defaults: (path into config, key, default value).
_DEFAULTS = (
    (("NeuralNetwork", "Architecture"), "freeze_conv_layers", False),
    (("NeuralNetwork", "Architecture"), "initial_bias", None),
    (("NeuralNetwork", "Training"), "optimizer", "AdamW"),
    # Per-epoch shuffle granularity: "sample" (reference DistributedSampler
    # parity) or "batch" (frozen membership; enables collation + device
    # batch caching across epochs — see preprocess/dataloader.py).
    (("NeuralNetwork", "Training"), "reshuffle", "sample"),
)

# Log-name encoding: "<tag><value>" segments in this order, then the two
# list-valued trailers appended by get_log_name_config.
_LOG_NAME_FIELDS = (
    ("", ("NeuralNetwork", "Architecture"), "model_type"),
    ("-r-", ("NeuralNetwork", "Architecture"), "radius"),
    ("-mnnn-", ("NeuralNetwork", "Architecture"), "max_neighbours"),
    ("-ncl-", ("NeuralNetwork", "Architecture"), "num_conv_layers"),
    ("-hd-", ("NeuralNetwork", "Architecture"), "hidden_dim"),
    ("-ne-", ("NeuralNetwork", "Training"), "num_epoch"),
    ("-lr-", ("NeuralNetwork", "Training"), "learning_rate"),
    ("-bs-", ("NeuralNetwork", "Training"), "batch_size"),
    ("-data-", ("Dataset",), "name"),
)


def _at(config: Dict[str, Any], path) -> Dict[str, Any]:
    for key in path:
        config = config[key]
    return config


@dataclass
class _Ctx:
    """Everything the completion stages read besides the config itself."""

    loaders: tuple
    sample: Any  # first training sample
    spans: List[int]  # per-head slice widths in the packed y vector
    variable_size: bool


def _head_spans(sample) -> List[int]:
    offsets = [int(v) for v in sample.y_loc[0]]
    return [b - a for a, b in zip(offsets, offsets[1:])]


# ------------------------------------------------------------- per-head kinds
def _head_dim(kind: str, span: int, ctx: _Ctx, arch: Dict[str, Any]) -> int:
    if kind == "graph":
        return span
    if kind == "node":
        if (
            ctx.variable_size
            and arch["output_heads"]["node"]["type"] == "mlp_per_node"
        ):
            raise ValueError(
                "node head type 'mlp_per_node' needs every graph in the "
                "dataset to have the same node count; switch NeuralNetwork."
                "Architecture.output_heads.node.type to 'mlp' or 'conv'."
            )
        return span // ctx.sample.num_nodes
    raise ValueError(f"unrecognized head kind: {kind!r}")


# ----------------------------------------------------------- pipeline stages
def _stage_check_declared_dims(config, ctx):
    """Cross-check y_loc-derived widths against Dataset.*_features.dim."""
    if "Dataset" not in config:
        return
    voi = _at(config, ("NeuralNetwork", "Variables_of_interest"))
    declared = {
        "graph": lambda span, i: span
        == config["Dataset"]["graph_features"]["dim"][i],
        "node": lambda span, i: span // ctx.sample.num_nodes
        == config["Dataset"]["node_features"]["dim"][i],
    }
    for kind, index, span in zip(voi["type"], voi["output_index"], ctx.spans):
        check = declared.get(kind)
        if check is not None and not check(span, index):
            raise AssertionError(
                f"head of kind {kind!r} at output_index {index} does not match "
                "the declared Dataset feature dimension"
            )


def _stage_infer_heads(config, ctx):
    arch = _at(config, ("NeuralNetwork", "Architecture"))
    voi = _at(config, ("NeuralNetwork", "Variables_of_interest"))
    if len(voi["type"]) != len(ctx.spans):
        raise ValueError(
            f"config declares {len(voi['type'])} heads but the data's y_loc "
            f"packs {len(ctx.spans)}"
        )
    arch["output_dim"] = [
        _head_dim(kind, span, ctx, arch)
        for kind, span in zip(voi["type"], ctx.spans)
    ]
    arch["output_type"] = voi["type"]
    arch["num_nodes"] = ctx.sample.num_nodes


def _stage_denormalize(config, ctx):
    voi = _at(config, ("NeuralNetwork", "Variables_of_interest"))
    if voi.get("denormalize_output"):
        update_config_minmax(_serialized_dataset_path(config), voi)
    else:
        voi["denormalize_output"] = False


def _stage_input_dim(config, ctx):
    arch = _at(config, ("NeuralNetwork", "Architecture"))
    voi = _at(config, ("NeuralNetwork", "Variables_of_interest"))
    arch["input_dim"] = len(voi["input_node_features"])


def _stage_pna_degree(config, ctx):
    arch = _at(config, ("NeuralNetwork", "Architecture"))
    arch["pna_deg"] = (
        calculate_PNA_degree(
            ctx.loaders[0].dataset, arch["max_neighbours"]
        ).tolist()
        if arch["model_type"] == "PNA"
        else None
    )


def _stage_edge_dim(config, ctx):
    arch = _at(config, ("NeuralNetwork", "Architecture"))
    features = arch.get("edge_features")
    if features:
        assert arch["model_type"] in _EDGE_FEATURE_MODELS, (
            "edge features are only supported by the "
            f"{'/'.join(sorted(_EDGE_FEATURE_MODELS))} stacks"
        )
        arch["edge_dim"] = len(features)
    elif arch["model_type"] == "CGCNN":
        # CGCNN's gate MLP needs an integer edge width even with no features.
        arch["edge_dim"] = 0
    else:
        arch["edge_dim"] = None


def _stage_defaults(config, ctx):
    for path, key, value in _DEFAULTS:
        _at(config, path).setdefault(key, value)


def _stage_push_head_spec(config, ctx):
    """Loaders need the inferred head spec to emit per-head dense targets."""
    arch = _at(config, ("NeuralNetwork", "Architecture"))
    for loader in ctx.loaders:
        loader.set_head_spec(arch["output_type"], arch["output_dim"])
        loader.edge_dim = arch["edge_dim"]


_PIPELINE = (
    _stage_check_declared_dims,
    _stage_infer_heads,
    _stage_denormalize,
    _stage_input_dim,
    _stage_pna_degree,
    _stage_edge_dim,
    _stage_defaults,
    _stage_push_head_spec,
)


def update_config(config, train_loader, val_loader, test_loader):
    """Complete a user config from the training data (the reference's
    data-driven completion contract; output pinned by golden tests)."""
    loaders = (train_loader, val_loader, test_loader)
    sample = train_loader.dataset[0]
    ctx = _Ctx(
        loaders=loaders,
        sample=sample,
        spans=_head_spans(sample),
        variable_size=check_if_graph_size_variable(
            *(loader.dataset for loader in loaders)
        ),
    )
    for stage in _PIPELINE:
        stage(config, ctx)
    return config


# ------------------------------------------------------------------- minmax
def _serialized_dataset_path(config) -> str:
    """Where the min/max tables live: a GSHD dataset's manifest (train split
    preferred), the configured .pkl directly, or the serialized dataset
    derived from SERIALIZED_DATA_PATH + dataset name (the train shard when
    the config has per-split paths)."""
    from ..datasets.shards import is_gshd_path

    paths = config["Dataset"]["path"]
    first = next(iter(paths.values()))
    if is_gshd_path(first):
        return paths.get("train", first)
    if first.endswith(".pkl"):
        return first
    stem = config["Dataset"]["name"] + ("" if "total" in paths else "_train")
    return os.path.join(
        os.environ["SERIALIZED_DATA_PATH"], "serialized_dataset", stem + ".pkl"
    )


def update_config_minmax(dataset_path: str, config: Dict[str, Any]):
    """Fill x_minmax/y_minmax from the per-feature min/max tables pickled
    ahead of the serialized dataset samples — or, for a GSHD dataset, from
    the tables the conversion preserved in the manifest."""
    from ..datasets.shards import is_gshd_path, read_manifest

    if is_gshd_path(dataset_path):
        import numpy as np

        manifest = read_manifest(dataset_path)
        node = manifest.get("minmax_node_feature")
        graph = manifest.get("minmax_graph_feature")
        if node is None or graph is None:
            raise ValueError(
                f"{dataset_path}: manifest has no min/max tables — re-run "
                "`python -m hydragnn_tpu.datasets convert` from the pickle "
                "corpus to carry them over"
            )
        tables = {"node": np.asarray(node), "graph": np.asarray(graph)}
    else:
        with open(dataset_path, "rb") as f:
            # graftlint: disable=pickle-load-outside-compat(legacy minmax-table shim for pre-GSHD corpora — the shard manifest branch above is the supported path)
            tables = {"node": pickle.load(f), "graph": pickle.load(f)}
    config["x_minmax"] = [
        tables["node"][:, i].tolist() for i in config["input_node_features"]
    ]
    y_minmax = []
    for kind, index in zip(config["type"], config["output_index"]):
        if kind not in tables:
            raise ValueError(f"unrecognized head kind: {kind!r}")
        y_minmax.append(tables[kind][:, index].tolist())
    config["y_minmax"] = y_minmax
    return config


# ----------------------------------------------------------------- log name
def get_log_name_config(config: Dict[str, Any]) -> str:
    """Hyperparameter-encoding log/checkpoint directory name (identical string
    to the reference's encoding — checkpoints must resolve across both)."""
    arch = _at(config, ("NeuralNetwork", "Architecture"))
    voi = _at(config, ("NeuralNetwork", "Variables_of_interest"))
    segments = [
        f"{tag}{_at(config, path)[key]}" for tag, path, key in _LOG_NAME_FIELDS
    ]
    segments.append(
        "-node_ft-" + "".join(str(f) for f in voi["input_node_features"])
    )
    segments.append(
        "-task_weights-" + "".join(f"{w}-" for w in arch["task_weights"])
    )
    return "".join(segments)
