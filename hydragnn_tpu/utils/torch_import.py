"""Reference-checkpoint importer: torch ``.pk`` → flax variables.

The reference saves rank-0 checkpoints as
``torch.save({"model_state_dict": ..., "optimizer_state_dict": ...}, <name>.pk)``
(/root/reference/hydragnn/utils/model.py:35-47). This module maps that
``model_state_dict`` — whose key grammar is fixed by the reference's module
tree (Base.py:99-223 plus the per-family PyG convs) — onto this framework's
flax parameter tree, completing the migration story in docs/MIGRATION.md:
train in the reference, predict here (or keep fine-tuning).

Weight-layout notes (verified in the round-trip test):
- torch ``Linear.weight`` is [out, in]; flax ``Dense.kernel`` is [in, out] →
  transposed on import.
- PyG ``PNAConv`` keeps a separate ``edge_encoder`` Linear ahead of the
  pre-MLP; our PNAConv fuses it into one Dense over [x_i ‖ x_j ‖ e]. The two
  are exactly equivalent by linear composition, so the encoder is FOLDED:
  ``W_edge = W3 @ E_w`` and ``b' = b + W3 @ E_b`` where W3 is the pre-MLP's
  edge-column block.
- PyG ``BatchNorm`` wraps a torch BatchNorm1d as ``.module`` → running_mean/
  running_var land in the ``batch_stats`` collection.
- The optimizer_state_dict is NOT imported (torch Adam moments have no
  well-defined mapping onto optax state for a re-designed tree); training
  resumed here starts with fresh optimizer state.

Shared-MLP layout: the reference's shared-MLP Sequential has no ReLU between
its Linears (Base.py:155-162 appends [ReLU, Linear, ..., Linear, ReLU] —
activation only before the first Linear, a no-op on the non-negative pooled
input, and after the last). Build the model with
``output_heads.graph.shared_layout = "reference"`` (models/layers.MLP
``inner_activation=False``) and imported forwards are EXACT for any
``num_sharedlayers`` (locked at fp32 tolerance by
tests/test_torch_import_numeric.py); the framework's default layout
(ReLU between every pair) is only flagged as a caveat when the two grammars
actually diverge, i.e. ``num_sharedlayers > 1``.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


def _to_np(t) -> np.ndarray:
    return np.asarray(t.detach().cpu().numpy(), dtype=np.float32)


def _load_model_state_dict(path: str) -> Dict[str, np.ndarray]:
    import torch

    # graftlint: disable=pickle-load-outside-compat(sanctioned torch-interop shim: weights_only=True restricted unpickler, tensors-and-containers only)
    ckpt = torch.load(path, map_location="cpu", weights_only=True)
    sd = ckpt["model_state_dict"] if "model_state_dict" in ckpt else ckpt
    # DDP checkpoints prefix every key with "module."
    out = {}
    for k, v in sd.items():
        if k.startswith("module."):
            k = k[len("module.") :]
        out[k] = _to_np(v) if hasattr(v, "detach") else np.asarray(v, np.float32)
    return out


def _linears_of_sequential(
    sd: Dict[str, np.ndarray], prefix: str, consumed: set
) -> List[Tuple[np.ndarray, Optional[np.ndarray]]]:
    """Ordered (kernel, bias) list of the Linears inside a torch Sequential —
    indices are walked numerically so interleaved ReLUs don't matter."""
    pat = re.compile(re.escape(prefix) + r"\.(\d+)\.weight$")
    idxs = sorted(int(m.group(1)) for k in sd if (m := pat.match(k)))
    out = []
    for i in idxs:
        w = sd[f"{prefix}.{i}.weight"]
        consumed.add(f"{prefix}.{i}.weight")
        b = sd.get(f"{prefix}.{i}.bias")
        if b is not None:
            consumed.add(f"{prefix}.{i}.bias")
        out.append((w.T, b))
    return out


def _dense(kernel: np.ndarray, bias: Optional[np.ndarray], like: Dict) -> Dict:
    d = {"kernel": kernel}
    if "bias" in like:
        d["bias"] = bias if bias is not None else np.zeros(kernel.shape[1], np.float32)
    return d


def _bn(sd, tprefix: str, consumed: set) -> Tuple[Dict, Dict]:
    """PyG BatchNorm (`.module.` nesting) or bare BatchNorm1d keys →
    (params {scale, bias}, batch_stats {mean, var})."""
    base = tprefix + ".module" if f"{tprefix}.module.weight" in sd else tprefix
    for suffix in ("weight", "bias", "running_mean", "running_var"):
        consumed.add(f"{base}.{suffix}")
    consumed.add(f"{base}.num_batches_tracked")  # harmless if absent
    return (
        {"scale": sd[f"{base}.weight"], "bias": sd[f"{base}.bias"]},
        {"mean": sd[f"{base}.running_mean"], "var": sd[f"{base}.running_var"]},
    )


def _map_conv(
    family: str, sd, tprefix: str, template: Dict, consumed: set
) -> Dict:
    """One PyG conv's tensors → our flax conv module dict (family grammar)."""

    def lin(name, tname=None):
        tname = tname or name
        w = sd[f"{tprefix}.{tname}.weight"]
        consumed.add(f"{tprefix}.{tname}.weight")
        b = sd.get(f"{tprefix}.{tname}.bias")
        if b is not None:
            consumed.add(f"{tprefix}.{tname}.bias")
        return w, b

    if family == "PNA":
        # PyG PNAConv towers=1, pre/post_layers=1 (PNAStack.py:40-51):
        # pre_nns.0.0, post_nns.0.0, lin, optional edge_encoder.
        pre_w, pre_b = lin("pre", "pre_nns.0.0")
        post_w, post_b = lin("post", "post_nns.0.0")
        lin_w, lin_b = lin("lin")
        f_in = pre_w.shape[0]  # pre-MLP output width == conv input width
        out = {
            "post_nn": _dense(post_w.T, post_b, template["post_nn"]),
            "lin": _dense(lin_w.T, lin_b, template["lin"]),
        }
        if f"{tprefix}.edge_encoder.weight" in sd:
            enc_w, enc_b = lin("enc", "edge_encoder")
            # pre weight is [F, 3F]: [W_recv | W_send | W3]. Fold the encoder:
            # pre([xi, xj, Ee+be]) = W_recv xi + W_send xj + (W3 E) e + (b + W3 be)
            w3 = pre_w[:, 2 * f_in :]
            kernel = np.concatenate([pre_w[:, : 2 * f_in], w3 @ enc_w], axis=1).T
            # Both source Linears may be bias=False; the folded bias must stay
            # a length-f_in vector, not a 0-d scalar, or the template shape
            # check rejects with a misleading "configs differ" error.
            bias = (
                pre_b if pre_b is not None else np.zeros(f_in, np.float32)
            ) + (w3 @ enc_b if enc_b is not None else 0.0)
            out["pre_nn"] = _dense(kernel, np.asarray(bias, np.float32), template["pre_nn"])
        else:
            out["pre_nn"] = _dense(pre_w.T, pre_b, template["pre_nn"])
        return out

    if family == "GIN":
        # GINStack.py:26-34: nn = Sequential(Linear, ReLU, Linear), train_eps.
        w0, b0 = lin("m0", "nn.0")
        w1, b1 = lin("m1", "nn.2")
        consumed.add(f"{tprefix}.eps")
        return {
            "mlp_0": _dense(w0.T, b0, template["mlp_0"]),
            "mlp_1": _dense(w1.T, b1, template["mlp_1"]),
            "eps": np.asarray(sd[f"{tprefix}.eps"], np.float32).reshape(()),
        }

    if family == "SAGE":
        # PyG SAGEConv: lin_l = neighbor-mean transform (bias), lin_r = root.
        wl, bl = lin("l", "lin_l")
        wr, br = lin("r", "lin_r")
        return {
            "lin_nbr": _dense(wl.T, bl, template["lin_nbr"]),
            "lin_self": _dense(wr.T, br, template["lin_self"]),
        }

    if family == "MFC":
        # PyG MFConv: per-degree Linear lists — lins_l over the neighbor sum
        # (carries the bias), lins_r over the root features (bias=False).
        pat = re.compile(re.escape(tprefix) + r"\.lins_l\.(\d+)\.weight$")
        degs = sorted(int(m.group(1)) for k in sd if (m := pat.match(k)))
        w_nbr, w_self, bias = [], [], []
        for d in degs:
            wl, bl = lin(f"l{d}", f"lins_l.{d}")
            wr, _ = lin(f"r{d}", f"lins_r.{d}")
            w_nbr.append(wl.T)
            w_self.append(wr.T)
            bias.append(bl if bl is not None else np.zeros(wl.shape[0], np.float32))
        return {
            "w_nbr": np.stack(w_nbr),
            "w_self": np.stack(w_self),
            "bias": np.stack(bias),
        }

    if family == "GAT":
        # PyG GATv2Conv: lin_l = source transform, lin_r = target, att [1,H,F].
        wl, bl = lin("l", "lin_l")
        wr, br = lin("r", "lin_r")
        consumed.update({f"{tprefix}.att", f"{tprefix}.bias"})
        att = sd[f"{tprefix}.att"].reshape(template["att"].shape)
        return {
            "lin_src": _dense(wl.T, bl, template["lin_src"]),
            "lin_dst": _dense(wr.T, br, template["lin_dst"]),
            "att": att,
            "bias": sd[f"{tprefix}.bias"].reshape(template["bias"].shape),
        }

    if family == "CGCNN":
        wf, bf = lin("f", "lin_f")
        ws, bs = lin("s", "lin_s")
        return {
            "lin_f": _dense(wf.T, bf, template["lin_f"]),
            "lin_s": _dense(ws.T, bs, template["lin_s"]),
        }

    raise ValueError(f"Unknown conv family {family}")


def _map_mlp(sd, tprefix: str, template: Dict, consumed: set) -> Dict:
    """torch Sequential of Linears(+ReLUs) → our MLP {dense_i} by Linear order."""
    linears = _linears_of_sequential(sd, tprefix, consumed)
    dense_names = sorted(
        (k for k in template if k.startswith("dense_")),
        key=lambda s: int(s.split("_")[1]),
    )
    if len(linears) != len(dense_names):
        raise ValueError(
            f"{tprefix}: {len(linears)} torch Linears vs "
            f"{len(dense_names)} flax Dense layers"
        )
    return {
        name: _dense(k, b, template[name])
        for name, (k, b) in zip(dense_names, linears)
    }


def import_torch_checkpoint(
    path: str, model, variables: Dict[str, Any]
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Map a reference HydraGNN torch checkpoint onto ``variables``.

    ``model`` is the flax HydraGNN built by ``create_model`` with the SAME
    architecture config the torch checkpoint was trained with; ``variables``
    its initialized variables (shape template). Returns ``(new_variables,
    report)`` where report lists consumed/ignored torch keys and any caveats.
    Every imported array is shape-checked against the template; a mismatch
    means the configs differ and raises.
    """
    import jax

    sd = _load_model_state_dict(path)
    consumed: set = set()
    params = jax.tree_util.tree_map(np.asarray, dict(variables["params"]))
    stats = jax.tree_util.tree_map(
        np.asarray, dict(variables.get("batch_stats", {}))
    )
    caveats: List[str] = []
    family = model.conv_type

    # --- encoder convs + batch norms (Base._init_conv) ---
    n_convs = len([k for k in params if re.fullmatch(r"conv_\d+", k)])
    for i in range(n_convs):
        params[f"conv_{i}"] = _map_conv(
            family, sd, f"convs.{i}", params[f"conv_{i}"], consumed
        )
        p, s = _bn(sd, f"batch_norms.{i}", consumed)
        params[f"bn_{i}"] = p
        stats[f"bn_{i}"] = s

    # --- node-head conv chains (Base._init_node_conv; the reference ALSO
    # aliases these modules under heads_NN.*, which we ignore as duplicates) ---
    for ours, theirs in (
        ("node_conv_", "convs_node_hidden."),
        ("node_out_conv_", "convs_node_output."),
    ):
        for k in [k for k in params if k.startswith(ours)]:
            i = int(k.rsplit("_", 1)[1])
            params[k] = _map_conv(family, sd, f"{theirs}{i}", params[k], consumed)
    for ours, theirs in (
        ("node_bn_", "batch_norms_node_hidden."),
        ("node_out_bn_", "batch_norms_node_output."),
    ):
        for k in [k for k in params if k.startswith(ours)]:
            i = int(k.rsplit("_", 1)[1])
            p, s = _bn(sd, f"{theirs}{i}", consumed)
            params[k] = p
            stats[k] = s
    # Conv-type node heads: the reference appends the SAME conv/bn module
    # objects to heads_NN (Base.py:209-216), so their tensors appear twice in
    # the state_dict (convs_node_* and heads_NN.{i}.{j}.*). The former were
    # imported above; mark the aliases consumed so they don't read as ignored.
    for ihead, htype in enumerate(model.output_type):
        if htype == "node" and f"head_{ihead}" not in params:
            consumed.update(
                k for k in sd if k.startswith(f"heads_NN.{ihead}.")
            )

    # --- graph shared MLP (Base._multihead, Base.py:155-162) ---
    if "graph_shared" in params:
        params["graph_shared"] = _map_mlp(
            sd, "graph_shared", params["graph_shared"], consumed
        )
        n_shared = len(params["graph_shared"])
        shared_layout = model.config_heads.get("graph", {}).get(
            "shared_layout", "framework"
        )
        if n_shared > 1 and shared_layout != "reference":
            caveats.append(
                "num_sharedlayers > 1 with the framework shared-MLP layout: "
                "the reference Sequential lacks the inter-Linear ReLU — "
                "weights transferred 1:1 but forward outputs will differ; "
                'build the model with output_heads.graph.shared_layout = '
                '"reference" for exact parity'
            )

    # --- per-head MLPs ---
    for ihead, htype in enumerate(model.output_type):
        key = f"head_{ihead}"
        if key not in params:
            continue  # conv node heads live in node_conv_* above
        tprefix = f"heads_NN.{ihead}"
        if htype == "graph":
            params[key] = _map_mlp(sd, tprefix, params[key], consumed)
        elif "mlp" in params[key]:  # node 'mlp': shared MLPNode → mlp.0
            params[key] = {
                "mlp": _map_mlp(sd, f"{tprefix}.mlp.0", params[key]["mlp"], consumed)
            }
        else:  # node 'mlp_per_node': one Sequential per node slot
            tmpl = params[key]
            n_layers = len([k for k in tmpl if k.startswith("w_")])
            num_nodes = tmpl["w_0"].shape[0]
            per_node = [
                _linears_of_sequential(sd, f"{tprefix}.mlp.{inode}", consumed)
                for inode in range(num_nodes)
            ]
            new = {}
            for li in range(n_layers):
                new[f"w_{li}"] = np.stack([pn[li][0] for pn in per_node])
                new[f"b_{li}"] = np.stack(
                    [
                        pn[li][1]
                        if pn[li][1] is not None
                        else np.zeros(pn[li][0].shape[1], np.float32)
                        for pn in per_node
                    ]
                )
            params[key] = new

    # --- shape-check against the template and freeze dtypes ---
    flat_new = jax.tree_util.tree_leaves_with_path(params)
    flat_tmpl = dict(jax.tree_util.tree_leaves_with_path(variables["params"]))
    for path_k, leaf in flat_new:
        tmpl_leaf = flat_tmpl.get(path_k)
        if tmpl_leaf is None:
            raise ValueError(f"imported leaf {path_k} not in template tree")
        if tuple(np.shape(leaf)) != tuple(np.shape(tmpl_leaf)):
            raise ValueError(
                f"shape mismatch at {jax.tree_util.keystr(path_k)}: "
                f"checkpoint {np.shape(leaf)} vs model {np.shape(tmpl_leaf)} "
                "— architecture configs differ"
            )
    if len(flat_new) != len(flat_tmpl):
        missing = set(flat_tmpl) - {p for p, _ in flat_new}
        raise ValueError(f"unfilled parameter leaves: {sorted(map(str, missing))}")

    ignored = sorted(k for k in sd if k not in consumed)
    new_vars = dict(variables)
    new_vars["params"] = params
    if stats:
        new_vars["batch_stats"] = stats
    return new_vars, {
        "consumed": sorted(consumed & set(sd)),
        "ignored": ignored,
        "caveats": caveats,
    }
