from .lsms import (
    compositional_histogram_cutoff,
    compute_formation_enthalpy,
    convert_raw_data_energy_to_gibbs,
)

__all__ = [
    "convert_raw_data_energy_to_gibbs",
    "compute_formation_enthalpy",
    "compositional_histogram_cutoff",
]
