"""Offline LSMS dataset tooling: total energy → formation enthalpy → formation
Gibbs free energy, and compositional-histogram downselection.

Behavioral parity with the reference offline utilities
(/root/reference/utils/lsms/convert_total_energy_to_formation_gibbs.py:30-183 and
/root/reference/utils/lsms/compositional_histogram_cutoff.py:16-75), re-implemented
vectorized:

  * A directory of LSMS text files (one header line whose first token is the total
    energy in Rydberg, then one row per atom with the proton count in column 0) is
    rewritten into ``<dir>_gibbs_energy/`` with the total energy replaced by the
    formation Gibbs free energy at a given temperature.
  * Formation enthalpy = total energy − linear mixing energy, where the linear
    mixing energy interpolates the per-atom energies of the two pure-element
    configurations (binary alloys only).
  * Entropy is the *configurational* (thermodynamic) term
    k_B · ln C(num_atoms, count_element1) in Rydberg/K; we evaluate the
    log-binomial via ``lgamma`` so large supercells don't overflow.
  * ``compositional_histogram_cutoff`` caps the number of samples per composition
    bin, symlinking the survivors into ``<dir>_histogram_cutoff/``.
"""

from __future__ import annotations

import math
import os
import shutil
from typing import Dict, List, Sequence, Tuple

import numpy as np

# LSMS energies are in Rydberg; Boltzmann constant converted accordingly
# (reference convert_total_energy_to_formation_gibbs.py:174-177).
_KB_JOULE_PER_KELVIN = 1.380649e-23
_JOULE_TO_RYDBERG = 4.5874208973812e17
KB_RYDBERG_PER_KELVIN = _KB_JOULE_PER_KELVIN * _JOULE_TO_RYDBERG


def _log_binomial(n: int, k: int) -> float:
    """ln C(n, k) computed stably for arbitrarily large supercells."""
    if k < 0 or k > n:
        return -math.inf
    return (
        math.lgamma(n + 1) - math.lgamma(k + 1) - math.lgamma(n - k + 1)
    )


def _read_lsms_file(path: str) -> Tuple[str, List[str], np.ndarray]:
    """Returns (total_energy_token, raw_lines, atoms_table).

    LSMS format: a single header line whose first whitespace token is the total
    energy, followed by one row of numbers per atom (column 0 = atomic number).
    """
    with open(path, "r") as fh:
        lines = fh.readlines()
    energy_token = lines[0].split()[0]
    atoms = np.loadtxt(lines[1:], ndmin=2)
    return energy_token, lines, atoms


def _element_counts(
    atoms: np.ndarray, elements_list: Sequence[float]
) -> np.ndarray:
    """Per-element atom counts aligned with sorted(elements_list); raises if the
    sample contains an element outside the binary."""
    species = atoms[:, 0]
    ordered = sorted(elements_list)
    counts = np.array([np.count_nonzero(species == e) for e in ordered])
    if counts.sum() != atoms.shape[0]:
        unknown = sorted(set(np.unique(species)) - set(ordered))
        raise ValueError(
            f"sample contains element(s) {unknown} not in the binary {ordered}"
        )
    return counts


def compute_formation_enthalpy(
    path: str,
    elements_list: Sequence[float],
    pure_elements_energy: Dict[float, float],
    total_energy: float,
    atoms: np.ndarray,
):
    """Formation enthalpy of one binary-alloy sample.

    Returns (composition_of_element1, total_energy, linear_mixing_energy,
    formation_enthalpy, entropy) exactly like the reference
    (convert_total_energy_to_formation_gibbs.py:143-183). `path` is only used in
    error messages.
    """
    try:
        counts = _element_counts(atoms, elements_list)
    except ValueError as err:
        raise AssertionError(f"Sample {path}: {err}") from err

    ordered = sorted(elements_list)
    num_atoms = atoms.shape[0]
    composition = counts[0] / num_atoms

    linear_mixing_energy = num_atoms * (
        pure_elements_energy[ordered[0]] * composition
        + pure_elements_energy[ordered[1]] * (1.0 - composition)
    )
    formation_enthalpy = total_energy - linear_mixing_energy

    entropy = KB_RYDBERG_PER_KELVIN * _log_binomial(num_atoms, int(counts[0]))
    return composition, total_energy, linear_mixing_energy, formation_enthalpy, entropy


def convert_raw_data_energy_to_gibbs(
    dir: str,
    elements_list: Sequence[float],
    temperature_kelvin: float = 0,
    overwrite_data: bool = False,
    create_plots: bool = True,
):
    """Rewrite every LSMS file in ``dir`` into ``<dir>_gibbs_energy/`` with the
    header total energy replaced by the formation Gibbs free energy.

    Binary alloys only: the directory must contain exactly two pure-element
    configurations, whose per-atom energies anchor the linear mixing line.
    Returns the array of formation Gibbs energies (one per file, in listdir
    order) so callers/tests can inspect the result without re-parsing.
    """
    dir = dir.rstrip("/")
    new_dir = dir + "_gibbs_energy/"
    if os.path.exists(new_dir) and overwrite_data:
        shutil.rmtree(new_dir)
    os.makedirs(new_dir, exist_ok=True)

    elements_list = sorted(elements_list)
    all_files = sorted(os.listdir(dir))

    # Pass 1: per-atom energies of the two pure-element configurations.
    pure_elements_energy: Dict[float, float] = {}
    for filename in all_files:
        energy_token, _, atoms = _read_lsms_file(os.path.join(dir, filename))
        species = np.unique(atoms[:, 0])
        if len(species) == 1:
            pure_elements_energy[species[0]] = float(energy_token) / atoms.shape[0]
    assert len(pure_elements_energy) == 2, "Must have two single element files."

    # Pass 2: enthalpy → Gibbs, rewrite header, collect plot series.
    n = len(all_files)
    total_e = np.empty(n)
    linear_e = np.empty(n)
    comp = np.empty(n)
    enthalpy = np.empty(n)
    gibbs = np.empty(n)
    for i, filename in enumerate(all_files):
        path = os.path.join(dir, filename)
        energy_token, lines, atoms = _read_lsms_file(path)
        comp[i], total_e[i], linear_e[i], enthalpy[i], entropy = (
            compute_formation_enthalpy(
                path, elements_list, pure_elements_energy,
                float(energy_token), atoms,
            )
        )
        gibbs[i] = enthalpy[i] - temperature_kelvin * entropy

        lines[0] = lines[0].replace(energy_token, str(gibbs[i]))
        with open(os.path.join(new_dir, filename), "w") as fh:
            fh.write("".join(lines))

    print("Min formation enthalpy: ", gibbs.min())
    print("Max formation enthalpy: ", gibbs.max())

    if create_plots:
        _scatter_plots(
            [
                (total_e, linear_e, "Total energy (Rydberg)",
                 "Linear mixing energy (Rydberg)", "linear_mixing_energy.png"),
                (comp, enthalpy, "Concentration",
                 "Formation enthalpy (Rydberg)", "formation_enthalpy.png"),
                (comp, gibbs, "Concentration",
                 "Formation Gibbs energy (Rydberg)", "formation_gibbs_energy.png"),
            ]
        )
    return gibbs


def _scatter_plots(specs):
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    for x, y, xlabel, ylabel, fname in specs:
        fig, ax = plt.subplots()
        ax.scatter(x, y, edgecolor="b", facecolor="none")
        ax.set_xlabel(xlabel)
        ax.set_ylabel(ylabel)
        fig.savefig(fname)
        plt.close(fig)


def compositional_histogram_cutoff(
    dir: str,
    elements_list: Sequence[float],
    histogram_cutoff: int,
    num_bins: int,
    overwrite_data: bool = False,
    create_plots: bool = True,
):
    """Downselect LSMS data to at most ``histogram_cutoff`` samples per binary
    composition bin; survivors are symlinked into ``<dir>_histogram_cutoff/``
    (reference compositional_histogram_cutoff.py:16-75).
    """
    dir = dir.rstrip("/")
    new_dir = dir + "_histogram_cutoff/"
    if os.path.exists(new_dir):
        if overwrite_data:
            shutil.rmtree(new_dir)
        else:
            print("Exiting: path to histogram cutoff data already exists")
            return np.asarray([]), np.zeros(num_bins, dtype=np.int64)
    os.makedirs(new_dir, exist_ok=True)

    bin_edges = np.linspace(0.0, 1.0, num_bins)
    kept_compositions = []
    bin_counts = np.zeros(num_bins, dtype=np.int64)
    for filename in sorted(os.listdir(dir)):
        path = os.path.join(dir, filename)
        atoms = np.loadtxt(path, skiprows=1, ndmin=2)
        counts = _element_counts(atoms, elements_list)
        composition = counts[0] / atoms.shape[0]

        # Interior-point binning matching the reference's find_bin: edge values
        # (including the pure compositions 0 and 1) fall into the last bin.
        hit = np.nonzero(
            (composition > bin_edges[:-1]) & (composition < bin_edges[1:])
        )[0]
        b = int(hit[0]) if hit.size else num_bins - 1

        bin_counts[b] += 1
        if bin_counts[b] < histogram_cutoff:
            kept_compositions.append(composition)
            os.symlink(os.path.abspath(path), os.path.join(new_dir, filename))

    if create_plots:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        fig, ax = plt.subplots()
        ax.hist(kept_compositions, bins=num_bins)
        fig.savefig("composition_histogram_cutoff.png")
        plt.close(fig)

        fig, ax = plt.subplots()
        ax.bar(np.linspace(0, 1, num_bins), bin_counts, width=1.0 / num_bins)
        fig.savefig("composition_initial.png")
        plt.close(fig)

    return np.asarray(kept_compositions), bin_counts
