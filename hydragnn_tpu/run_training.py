"""High-level E2E training driver — ``hydragnn_tpu.run_training(config_or_path)``
(reference /root/reference/hydragnn/run_training.py:40-122): env setup → process
bootstrap → data load/split → config completion → model build → optimizer +
ReduceLROnPlateau → log dir + config snapshot → optional warm start → epoch loop →
rank-0 checkpoint → timer report."""

from __future__ import annotations

import json
import os
from functools import singledispatch

import numpy as np

from .models.create import create_model_config, init_model_variables
from .parallel.distributed import barrier, setup_ddp
from .preprocess.load_data import dataset_loading_and_splitting
from .train.train_validate_test import TrainingDriver, train_validate_test
from .train.trainer import create_train_state
from .utils.config_utils import get_log_name_config, update_config
from .utils.model import (
    checkpoint_exists,
    get_summary_writer,
    load_existing_model,
    load_existing_model_config,
    save_model,
)
from .utils.optimizer import ReduceLROnPlateau, select_optimizer
from .utils.print_utils import print_distributed, setup_log
from .utils.profile import Profiler
from .utils.time_utils import print_timers


@singledispatch
def run_training(config, mesh=None, supervise=False, max_restarts=3):
    raise TypeError("Input must be filename string or configuration dictionary.")


@run_training.register
def _(config_file: str, mesh=None, supervise=False, max_restarts=3):
    with open(config_file, "r") as f:
        config = json.load(f)
    return run_training(
        config, mesh=mesh, supervise=supervise, max_restarts=max_restarts
    )


@run_training.register
def _(config: dict, mesh=None, supervise=False, max_restarts=3):
    if supervise:
        # Structural-only gate here (deep=False needs no XLA backend, which
        # must not initialize before the children's jax.distributed
        # bootstrap); each child re-enters run_training and runs the full
        # gate (docs/STATIC_ANALYSIS.md).
        from .analysis.contracts import gate_config

        gate_config(config, deep=False)
        # Crash-resume supervisor (docs/FAULT_TOLERANCE.md): the training run
        # happens in child processes under a restart loop around the periodic
        # checkpoint + Training.resume contract. Returns the restart metadata
        # (also persisted at logs/<name>/supervisor.json), not the history —
        # the epoch history lives in the run's checkpoint meta.
        if mesh is not None:
            raise ValueError(
                "run_training(supervise=True) spawns child processes and "
                "cannot adopt an in-process mesh; configure the mesh via "
                "Training.graph_axis / multi-process launch instead"
            )
        from .faults.supervisor import run_supervised

        return run_supervised(config, max_restarts=max_restarts)
    os.environ.setdefault("SERIALIZED_DATA_PATH", os.getcwd())

    # Bootstrap BEFORE anything touches jax (setup_log rank-prefixes via
    # jax.process_index(), which initializes the XLA backend —
    # jax.distributed.initialize must run first).
    world_size, world_rank = setup_ddp()
    # Contract gate AFTER the distributed bootstrap (the eval_shape pass may
    # initialize the XLA backend) but BEFORE data loading and any compile
    # (docs/STATIC_ANALYSIS.md; HYDRAGNN_CHECK_CONFIG=full|structural|off).
    from .analysis.contracts import gate_config

    gate_config(config, mode="training")
    setup_log(get_log_name_config(config))
    # Config-level mesh request (beyond-reference): Training.graph_axis > 1
    # shards each graph's edges over that many devices (the FeSi_1024-style
    # large-graph axis) without any programmatic mesh plumbing — pure-JSON
    # configs reach the same path tests/test_largegraph.py exercises.
    from .parallel.distributed import config_graph_axis

    graph_axis = config_graph_axis(config)
    # graftelastic (docs/DISTRIBUTED.md "Elastic runbook"): a RESUMING
    # incarnation consumes the supervisor.json `mesh` block — a topology that
    # contradicts the persisted world/axis metadata fails loudly with both
    # topologies named, unless Training.elastic admits the new world size
    # (then it is a logged elastic transition: the loader re-shards and the
    # mesh rebuilds at the current world below, exactly as on a fresh start).
    if config["NeuralNetwork"]["Training"].get("resume"):
        from .faults.supervisor import read_supervisor_meta
        from .parallel.elastic import ElasticConfig, check_restart_topology

        sup_meta = read_supervisor_meta(get_log_name_config(config))
        if sup_meta.get("mesh"):
            transition = check_restart_topology(
                sup_meta["mesh"],
                world_size,
                graph_axis,
                ElasticConfig.from_training(
                    config["NeuralNetwork"]["Training"]
                ),
            )
            if transition is not None:
                from .utils.print_utils import log as _log

                _log(
                    f"elastic restart: world_size "
                    f"{transition['from_world']} -> {transition['to_world']} "
                    f"({transition['kind']}) — loader re-shards and the mesh "
                    "rebuilds at the new size"
                )
                if world_rank == 0:
                    # Keep the persisted topology truthful for standalone
                    # resumes too — the supervisor's own restart loop records
                    # the same event when IT observes the change.
                    from .faults.supervisor import record_elastic_transition

                    record_elastic_transition(
                        get_log_name_config(config),
                        dict(transition, observed_by="run_training"),
                    )
    if mesh is None and (world_size > 1 or graph_axis > 1):
        # Reference semantics: training is data-parallel whenever the process
        # group is initialized (DDP wrap, reference run_training.py:78 +
        # distributed.py:216-226) — a multi-process launch without an explicit
        # mesh gets the global data mesh automatically.
        from .parallel.distributed import make_mesh

        mesh = make_mesh(graph_axis=graph_axis)

    verbosity = config["Verbosity"]["level"]
    train_loader, val_loader, test_loader, sampler_list = (
        dataset_loading_and_splitting(config=config)
    )
    config = update_config(config, train_loader, val_loader, test_loader)

    model = create_model_config(
        config=config["NeuralNetwork"]["Architecture"], verbosity=verbosity
    )
    example = next(iter(train_loader))
    variables = init_model_variables(model, example)
    # A mesh with a nontrivial 'graph' axis enables edge-sharded graph
    # parallelism (bound after init — collective axes are unbound outside the
    # sharded step).
    if mesh is not None and mesh.shape.get("graph", 1) > 1:
        model = model.clone(graph_axis="graph")

    optimizer = select_optimizer(
        config["NeuralNetwork"]["Training"]["optimizer"],
        config["NeuralNetwork"]["Training"]["learning_rate"],
        freeze_conv=config["NeuralNetwork"]["Architecture"]["freeze_conv_layers"],
    )
    scheduler = ReduceLROnPlateau(factor=0.5, patience=5, min_lr=0.00001)

    log_name = get_log_name_config(config)
    writer = get_summary_writer(log_name)
    barrier("logdir")
    os.makedirs("./logs/" + log_name, exist_ok=True)
    if world_rank == 0:
        # Startup cleanup: *.tmp litter from a crash mid-checkpoint-replace
        # in a previous incarnation (supervised restarts land here).
        from .utils.model import cleanup_stale_checkpoint_tmp

        cleanup_stale_checkpoint_tmp("./logs/" + log_name)
    with open("./logs/" + log_name + "/config.json", "w") as f:
        json.dump(config, f)

    # graftel (docs/OBSERVABILITY.md): point the flight recorder at this
    # run's log dir (guard trips / checkpoint fallbacks / engine poisonings
    # dump there) and turn on full span collection when asked — the
    # ``Telemetry`` config block or HYDRAGNN_TRACE=1.
    from . import telemetry

    tele_cfg = config.get("Telemetry") or {}
    collect_trace = bool(
        os.environ.get("HYDRAGNN_TRACE", "0") not in ("", "0", "false", "False")
        or tele_cfg.get("collect", 0)
    )
    telemetry.configure(
        run_dir="./logs/" + log_name,
        collect=collect_trace,
        jax_annotations=bool(tele_cfg.get("jax_annotations", 0)),
    )
    telemetry.install_jax_hooks()

    state = create_train_state(model, variables, optimizer)
    # Warm start (Training.continue / startfrom).
    new_vars, opt_state = load_existing_model_config(
        {"params": state.params, "batch_stats": state.batch_stats},
        config["NeuralNetwork"]["Training"],
        opt_state=state.opt_state,
    )
    state = state.replace(
        params=new_vars["params"],
        batch_stats=new_vars["batch_stats"],
        opt_state=opt_state,
    )

    # Crash resume (Training.resume — extension over the reference, which only
    # warm-starts weights and replays all epochs, SURVEY.md §5.3/5.4): pick up
    # THIS run's own checkpoint at the exact epoch/scheduler/history it saved.
    start_epoch = 0
    prior_history = None
    if config["NeuralNetwork"]["Training"].get("resume"):
        have = checkpoint_exists(log_name)
        if world_size > 1:
            # Every process replays the same epoch range — a rank resuming
            # while others start fresh would deadlock at the first mismatched
            # collective. Agree on the checkpoint's visibility up front.
            from jax.experimental import multihost_utils

            flags = multihost_utils.process_allgather(np.int32(have))
            if int(flags.min()) != int(flags.max()):
                raise RuntimeError(
                    "Training.resume: checkpoint for "
                    f"{log_name} is visible on some hosts but not others — "
                    "multi-host resume requires ./logs on shared storage"
                )
        if have:
            # Rank-0 save/restore points must not overlap across ranks: a
            # non-zero rank racing ahead here could read <name>.pk while a
            # rank-0 writer (a previous incarnation's final save, or a late
            # async flush) is still installing it.
            barrier("checkpoint_resume")
            # Verified load with the corruption fallback chain: a torn or
            # bit-flipped latest checkpoint falls back to the newest intact
            # keep_last_k entry instead of killing the (supervised) restart
            # loop (docs/CHECKPOINTING.md).
            new_vars, opt_state, meta = load_existing_model(
                {"params": state.params, "batch_stats": state.batch_stats},
                log_name,
                opt_state=state.opt_state,
                return_meta=True,
            )
            state = state.replace(
                params=new_vars["params"],
                batch_stats=new_vars["batch_stats"],
                opt_state=opt_state,
            )
            start_epoch = int(meta.get("epoch", 0))
            if meta.get("scheduler"):
                scheduler.load_state_dict(meta["scheduler"])
            prior_history = meta.get("history")
            print_distributed(
                verbosity, f"Resuming {log_name} from epoch {start_epoch}"
            )

    print_distributed(
        verbosity,
        "Starting training with the configuration: \n"
        + json.dumps(config, indent=4, sort_keys=True),
    )

    profiler = Profiler("./logs/" + log_name)
    profiler.setup(config.get("Profile"))

    # Fault tolerance (docs/FAULT_TOLERANCE.md): the non-finite step guard is
    # opt-in via the Training.fault_tolerance block (disabled = compiled
    # steps identical to the unguarded build); fault DRILLS come from the
    # HYDRAGNN_FAULTS env or the Training.faults spec string.
    training_cfg = config["NeuralNetwork"]["Training"]
    fault_plan = None
    if training_cfg.get("faults") and not os.environ.get("HYDRAGNN_FAULTS"):
        from .faults import FaultPlan

        fault_plan = FaultPlan(training_cfg["faults"])
    # graftcache (docs/COMPILE_CACHE.md): Training.compile_cache enables the
    # persistent compiled-executable store — a string is the store directory
    # (shareable across runs/replicas), any other truthy value defaults to
    # logs/<name>/compile_cache. The config fingerprint half of every key is
    # the digest of the completed Architecture + optimizer blocks, so a
    # resumed/restarted run hydrates its own executables and a changed model
    # or optimizer can never collide with them. The digest is computed
    # UNCONDITIONALLY: a store enabled via HYDRAGNN_COMPILE_CACHE alone must
    # carry the same key strength (optimizer hyperparameters like weight
    # decay change the compiled program without changing any tree shape).
    import hashlib

    compile_cache_fp = hashlib.sha256(
        json.dumps(
            {
                "architecture": config["NeuralNetwork"]["Architecture"],
                "optimizer": training_cfg.get("optimizer"),
                # Precision changes the compiled program (bf16 casts + the
                # loss-scale state machine) without changing any tree shape —
                # a key component (docs/PRECISION.md), belt to the driver's
                # flags suspenders. Folded in ONLY when a policy is active:
                # f32 runs must keep their pre-graftprec digests so existing
                # stores stay warm across the upgrade.
                **(
                    {
                        "precision": training_cfg["precision"],
                        "loss_scale": training_cfg.get("loss_scale"),
                    }
                    if training_cfg.get("precision") not in (None, "f32")
                    else {}
                ),
            },
            sort_keys=True,
            default=str,
        ).encode()
    ).hexdigest()
    if "compile_cache" in training_cfg:
        cc = training_cfg["compile_cache"]
        if not cc:
            # An EXPLICIT falsy value is a hard opt-out (the supervisor
            # documents `compile_cache: 0`) — it must also override an
            # exported HYDRAGNN_COMPILE_CACHE ("" disables, None defers).
            compile_cache_dir = ""
        else:
            compile_cache_dir = (
                cc
                if isinstance(cc, str)
                else "./logs/" + log_name + "/compile_cache"
            )
    else:
        compile_cache_dir = None  # defer to HYDRAGNN_COMPILE_CACHE
    driver = TrainingDriver(
        model,
        optimizer,
        state,
        mesh=mesh,
        verbosity=verbosity,
        fault_tolerance=training_cfg.get("fault_tolerance"),
        fault_plan=fault_plan,
        compile_cache=compile_cache_dir,
        compile_cache_fingerprint=compile_cache_fp,
        # graftprec (docs/PRECISION.md): Training.precision = "f32"|"bf16";
        # bf16 trains in bf16 compute against f32 master weights with dynamic
        # loss scaling (Training.loss_scale block tunes it). Since graftmesh
        # the policy also rides the mesh step (backoff lockstep post-psum).
        precision=training_cfg.get("precision"),
        loss_scale=training_cfg.get("loss_scale"),
        # graftmesh (docs/DISTRIBUTED.md): Training.grad_sync selects the
        # gradient-reduction arm of the mesh step ("single" | "bucketed" |
        # "ring"); grad_bucket_mb sizes the overlap buckets.
        grad_sync=training_cfg.get("grad_sync"),
        grad_bucket_mb=training_cfg.get("grad_bucket_mb"),
    )

    # Visualizer gets the test set's input node features and graph sizes
    # (reference train_validate_test.py:62-76).
    viz = None
    voi = config["NeuralNetwork"]["Variables_of_interest"]
    output_names = voi.get("output_names")
    if config["Visualization"].get("create_plots"):
        from .postprocess.visualizer import Visualizer

        node_feature = []
        nodes_num_list = []
        for sample in getattr(test_loader, "dataset", []):
            node_feature.extend(np.asarray(sample.x)[:, 0].tolist())
            nodes_num_list.append(int(np.asarray(sample.x).shape[0]))
        viz = Visualizer(
            "./logs/" + log_name,
            node_feature=node_feature,
            num_nodes_list=nodes_num_list,
            num_heads=len(model.output_dim),
            head_dims=list(model.output_dim),
            head_types=list(model.output_type),
        )

    history = train_validate_test(
        driver,
        train_loader,
        val_loader,
        test_loader,
        config["NeuralNetwork"]["Training"]["num_epoch"],
        writer=writer,
        scheduler=scheduler,
        profiler=profiler,
        verbosity=verbosity,
        visualizer=viz,
        output_names=output_names,
        plot_init_solution=config["Visualization"].get("plot_init_solution", True),
        plot_hist_solution=config["Visualization"].get("plot_hist_solution", False),
        checkpoint_name=log_name,
        checkpoint_every=config["NeuralNetwork"]["Training"].get(
            "periodic_checkpoint_every", 0
        ),
        checkpoint_keep_last_k=config["NeuralNetwork"]["Training"].get(
            "checkpoint_keep_last_k", 0
        ),
        checkpoint_async=bool(
            config["NeuralNetwork"]["Training"].get("checkpoint_async", 1)
        ),
        start_epoch=start_epoch,
        history=prior_history,
    )

    if world_rank == 0 and hasattr(train_loader, "write_size_histogram"):
        # Per-run size record for the ladder fitter (docs/SERVING.md
        # "Fitting a ladder from production histograms"): refit with
        # python -m hydragnn_tpu.graphs.packing fit-ladder --hist <file>.
        train_loader.write_size_histogram(
            "./logs/" + log_name + "/size_histogram.json"
        )

    if viz is not None:
        # Final test pass for the latest predictions; denormalize first when
        # requested (reference train_validate_test.py:141-163).
        _, _, true_values, predicted_values = driver.evaluate(
            test_loader, return_values=True
        )
        if voi.get("denormalize_output") and "y_minmax" in voi:
            from .postprocess.postprocess import output_denormalize

            true_values, predicted_values = output_denormalize(
                voi["y_minmax"], true_values, predicted_values
            )
        viz.create_plot_global(true_values, predicted_values, output_names)
        viz.create_scatter_plots(true_values, predicted_values, output_names)
        viz.plot_history(
            history,
            task_weights=list(model.task_weights),
            task_names=output_names,
        )

    save_model(
        {"params": driver.state.params, "batch_stats": driver.state.batch_stats},
        driver.state.opt_state,
        log_name,
        meta={
            "epoch": config["NeuralNetwork"]["Training"]["num_epoch"],
            "scheduler": scheduler.state_dict(),
            "history": history,
        },
        keep_last_k=config["NeuralNetwork"]["Training"].get(
            "checkpoint_keep_last_k", 0
        ),
    )
    # Non-zero ranks must not race ahead into a checkpoint load (e.g.
    # run_prediction immediately after training) while rank 0 is still writing.
    barrier("final_checkpoint")
    print_timers(verbosity)
    if world_rank == 0:
        # Telemetry artifacts (docs/OBSERVABILITY.md): the Prometheus
        # textfile of the registry (training gauges included) always; the
        # JSONL event log + Chrome/Perfetto trace when collection was on.
        run_dir = "./logs/" + log_name
        try:
            with open(os.path.join(run_dir, "train_metrics.prom"), "w") as f:
                f.write(telemetry.render_prometheus())
            if collect_trace:
                telemetry.export_events_jsonl(
                    os.path.join(run_dir, "trace_events.jsonl")
                )
                telemetry.export_chrome_trace(
                    os.path.join(run_dir, "trace_chrome.json")
                )
        except OSError as e:
            print_distributed(verbosity, f"telemetry export failed: {e}")
    return history
