"""Offline batch inference over a GSHD corpus (docs/SERVING.md "Batch
inference", docs/DATA_PLANE.md) — the screening-campaign entry point::

    python -m hydragnn_tpu.serve batch --config c.json --dataset <gshd_dir> \\
        --out preds/ [--ckpt ...] [--bucket-ladder ...] [--limit N]

The corpus streams one shard at a time through the engine's packed bucket
ladder (never materialized whole), and predictions are written as
digest-verified shards aligned 1:1 with the input shards — prediction shard
``k`` holds exactly the outputs for input shard ``k``, in sample order, so a
campaign can be resumed, spot-checked, or joined back to its inputs by
index. The headline metric is graphs/s end-to-end (decode + packing +
device + writeback).

A corrupt input shard costs that shard, loudly, never the campaign: it is
recorded in the prediction manifest's ``skipped_shards`` (with the decode
error) up to ``skip_budget`` shards, and its aligned prediction shard is
simply absent.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

import msgpack
import numpy as np

from ..checkpoint import format as ckpt_format
from ..checkpoint.io import atomic_write_json, write_checkpoint_blob
from ..datasets import shards as gshd
from ..graphs.sample import GraphSample

PRED_MANIFEST_NAME = "gshd_predictions.json"


def encode_pred_shard(preds: List[List[np.ndarray]]) -> bytes:
    """Encode one shard's predictions (per-sample per-head arrays) into a v2
    container: one section per head, concatenated raveled bytes + per-sample
    shapes in the meta section — the same exact-encoding scheme as GSHD
    sample fields."""
    num_heads = len(preds[0]) if preds else 0
    sections: Dict[str, Optional[bytes]] = {}
    heads_meta: Dict[str, Any] = {}
    for h in range(num_heads):
        arrays = [np.asarray(p[h]) for p in preds]
        dtype = arrays[0].dtype
        shapes = []
        chunks = []
        for a in arrays:
            if a.dtype != dtype:
                a = a.astype(dtype)
            shapes.append(list(a.shape))
            chunks.append(np.ascontiguousarray(a).tobytes())
        heads_meta[f"head{h}"] = {"dtype": dtype.str, "shapes": shapes}
        sections[f"head{h}"] = b"".join(chunks)
    sections["meta"] = msgpack.packb(
        {
            "schema_version": gshd.GSHD_SCHEMA_VERSION,
            "num_samples": len(preds),
            "num_heads": num_heads,
            "heads": heads_meta,
        },
        use_bin_type=True,
    )
    return ckpt_format.encode(
        sections,
        header={
            "kind": "gshd-pred",
            "schema_version": gshd.GSHD_SCHEMA_VERSION,
            "num_samples": len(preds),
        },
    )


def decode_pred_shard(
    blob: bytes, path: str = "<bytes>"
) -> List[List[np.ndarray]]:
    """Digest-verify + decode one prediction shard back to per-sample
    per-head arrays."""
    header, sections = ckpt_format.decode(blob, path)
    if header.get("kind") != "gshd-pred":
        raise ckpt_format.CheckpointCorruptError(
            path, f"not a gshd prediction shard (kind={header.get('kind')!r})"
        )
    meta = msgpack.unpackb(sections["meta"], raw=False, strict_map_key=False)
    out: List[List[np.ndarray]] = [[] for _ in range(int(meta["num_samples"]))]
    for h in range(int(meta["num_heads"])):
        hmeta = meta["heads"][f"head{h}"]
        flat = np.frombuffer(sections[f"head{h}"], np.dtype(hmeta["dtype"]))
        off = 0
        for i, shape in enumerate(hmeta["shapes"]):
            count = int(np.prod(shape)) if shape else 1
            out[i].append(flat[off : off + count].reshape(shape))
            off += count
    return out


def iter_predictions(pred_dir: str):
    """Stream (sample_index, per-head outputs) over a prediction directory in
    global sample order (skipped input shards leave index gaps)."""
    with open(os.path.join(pred_dir, PRED_MANIFEST_NAME)) as f:
        import json

        manifest = json.load(f)
    for sh in manifest["shards"]:
        with open(os.path.join(pred_dir, sh["file"]), "rb") as f:
            blob = f.read()
        preds = decode_pred_shard(blob, sh["file"])
        base = int(sh["base"])
        for i, p in enumerate(preds):
            yield base + i, p


def run_batch_inference(
    engine,
    dataset_path: str,
    out_dir: str,
    chunk_size: int = 64,
    limit: Optional[int] = None,
    skip_budget: int = 0,
    timeout: Optional[float] = 300.0,
) -> Dict[str, Any]:
    """Stream a GSHD corpus through ``engine.predict`` and write prediction
    shards + manifest to ``out_dir``. Returns the manifest dict (including
    the ``graphs_per_sec`` headline). ``limit`` bounds the campaign to the
    first N samples (still shard-aligned); ``chunk_size`` is the per-call
    graph count (clamped to the engine's queue limit)."""
    manifest = gshd.read_manifest(dataset_path)
    os.makedirs(out_dir, exist_ok=True)
    chunk = max(1, min(int(chunk_size), int(getattr(engine, "queue_limit", chunk_size))))
    pred_shards: List[Dict[str, Any]] = []
    skipped: List[Dict[str, Any]] = []
    done = 0
    num_heads = None
    t0 = time.perf_counter()
    for sid, sh in enumerate(manifest["shards"]):
        if limit is not None and done >= limit:
            break
        path = os.path.join(manifest["_dir"], sh["file"])
        try:
            samples: List[GraphSample] = gshd.load_shard(path)
        except ckpt_format.CheckpointCorruptError as e:
            skipped.append({"file": sh["file"], "error": e.reason})
            print(
                f"WARNING: skipping corrupt input shard {sh['file']} "
                f"({e.reason})"
            )
            if len(skipped) > skip_budget:
                raise RuntimeError(
                    f"batch inference: {len(skipped)} corrupt input shard(s) "
                    f"> skip_budget={skip_budget} — "
                    + "; ".join(f"{s['file']}: {s['error']}" for s in skipped)
                ) from e
            continue
        if limit is not None:
            samples = samples[: max(0, limit - done)]
        preds: List[List[np.ndarray]] = []
        for start in range(0, len(samples), chunk):
            preds.extend(
                engine.predict(samples[start : start + chunk], timeout=timeout)
            )
        if preds:
            num_heads = len(preds[0])
        blob = encode_pred_shard(preds)
        fname = f"pred-{sid:05d}.gshd"
        write_checkpoint_blob(os.path.join(out_dir, fname), blob)
        pred_shards.append(
            {
                "file": fname,
                "source": sh["file"],
                "base": int(gshd.shard_offsets(manifest)[sid]),
                "num_samples": len(preds),
                "bytes": len(blob),
                "sha256": gshd._sha256(blob),
            }
        )
        done += len(preds)
    wall = time.perf_counter() - t0
    pred_manifest: Dict[str, Any] = {
        "schema": gshd.GSHD_PRED_SCHEMA,
        "schema_version": gshd.GSHD_SCHEMA_VERSION,
        "source_dataset": manifest["name"],
        "source_manifest": gshd.manifest_path_of(dataset_path),
        "num_samples": done,
        "num_heads": num_heads,
        "shards": pred_shards,
        "skipped_shards": skipped,
        "wall_s": wall,
        "graphs_per_sec": (done / wall) if wall > 0 else None,
    }
    atomic_write_json(os.path.join(out_dir, PRED_MANIFEST_NAME), pred_manifest)
    return pred_manifest
