"""Online inference serving: micro-batching engine + stdlib HTTP front end.

    python -m hydragnn_tpu.serve --config logs/<name>/config.json [--ckpt ...]

See docs/SERVING.md for the request schema, bucket-ladder/warmup
configuration, backpressure semantics, and the metrics reference.
"""

from .engine import (
    BackpressureError,
    EngineClosedError,
    EngineFailedError,
    InferenceEngine,
    NonFiniteOutputError,
    PrecisionToleranceError,
    SwapFingerprintError,
)
from .metrics import LatencyHistogram, ServeMetrics
from .server import InferenceServer, parse_graph

__all__ = [
    "BackpressureError",
    "EngineClosedError",
    "EngineFailedError",
    "InferenceEngine",
    "InferenceServer",
    "LatencyHistogram",
    "NonFiniteOutputError",
    "PrecisionToleranceError",
    "ServeMetrics",
    "SwapFingerprintError",
    "parse_graph",
]
