"""Stdlib-only HTTP front end for the inference engine (docs/SERVING.md).

Endpoints:
  POST /predict  — JSON graphs in, per-head predictions out (200);
                   400 on malformed input, 429 + Retry-After under
                   backpressure, 503 after a worker failure.
  GET  /healthz  — liveness + queue depth (JSON).
  GET  /metrics  — Prometheus text exposition of the serving metrics.

Deliberately ``http.server`` (ThreadingHTTPServer): the container bakes no
web framework, and the engine does all the concurrency work — each handler
thread only parses JSON, blocks on its requests' futures, and serializes the
answer. Request batching across connections happens INSIDE the engine, so
even this simple threaded server gets micro-batched device execution.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

import numpy as np

from ..graphs.sample import GraphSample
from ..telemetry import graftel as telemetry
from ..telemetry import render_prometheus
from .engine import BackpressureError, EngineFailedError, InferenceEngine

REQUEST_ID_HEADER = "X-HydraGNN-Request-Id"
# Replica-mode plumbing (docs/SERVING.md "Multi-replica tier"): a serve
# process running as one replica of a routed fleet labels every response so
# the router's hop logs and clients can attribute answers to replicas.
REPLICA_ID_HEADER = "X-HydraGNN-Replica"
# Live model lifecycle (docs/SERVING.md "Live model lifecycle"): every
# response names the model version that answered it — echoed on ALL paths
# like the request-id header, so a client (and the swap-under-load drill)
# can assert no response is ever version-torn across a hot swap.
MODEL_VERSION_HEADER = "X-HydraGNN-Model-Version"


def parse_graph(doc: dict) -> GraphSample:
    """One request graph: {"x": [[...]], "edge_index": [[s...],[r...]],
    "edge_attr": [[...]]?, "pos": [[...]]?}."""
    if not isinstance(doc, dict) or "x" not in doc:
        raise ValueError('each graph must be an object with an "x" field')
    x = np.asarray(doc["x"], dtype=np.float32)
    if x.ndim != 2:
        raise ValueError('"x" must be a [num_nodes, F] nested list')
    edge_index = None
    if doc.get("edge_index") is not None:
        edge_index = np.asarray(doc["edge_index"], dtype=np.int32)
        if edge_index.ndim != 2 or edge_index.shape[0] != 2:
            raise ValueError('"edge_index" must be [2, num_edges]')
        if edge_index.size and (
            edge_index.min() < 0 or edge_index.max() >= x.shape[0]
        ):
            raise ValueError('"edge_index" references nodes outside "x"')
    edge_attr = None
    if doc.get("edge_attr") is not None:
        edge_attr = np.asarray(doc["edge_attr"], dtype=np.float32)
        if edge_attr.ndim != 2 or (
            edge_index is not None and edge_attr.shape[0] != edge_index.shape[1]
        ):
            raise ValueError('"edge_attr" must be [num_edges, D]')
    pos = None
    if doc.get("pos") is not None:
        pos = np.asarray(doc["pos"], dtype=np.float32)
    return GraphSample(x=x, pos=pos, edge_index=edge_index, edge_attr=edge_attr)


class RequestPlumbing:
    """Shared HTTP plumbing for the engine and router front ends
    (route/server.py): request-id hygiene and JSON/text response emission.
    A mixin, NOT a BaseHTTPRequestHandler subclass — each concrete handler
    keeps ``BaseHTTPRequestHandler`` as an explicit base so graftrace's
    handler-thread-root discovery still sees it. One implementation of the
    PR-9 contract: the correlation id is echoed on EVERY response path, and
    a malformed caller header is REPLACED, never echoed."""

    def log_message(self, fmt, *args):  # quiet by default
        if getattr(self.server, "verbose", False):  # type: ignore[attr-defined]
            super().log_message(fmt, *args)  # type: ignore[misc]

    def _request_id(self) -> str:
        """This request's correlation id — echoed on EVERY response path
        (200/400/404/429/5xx — docs/OBSERVABILITY.md)."""
        rid = getattr(self, "_rid", None)
        return rid if rid is not None else self._begin_request()

    # Caller-supplied ids are reflected into response headers, telemetry
    # records, /healthz payloads, and flight dumps: restrict to a safe
    # charset and length so a crafted header (CRLF folds = response-header
    # injection; megabyte values = ring/artifact bloat) is REPLACED by a
    # generated id rather than echoed.
    _RID_SAFE = frozenset(
        "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789.-_/"
    )
    _RID_MAX_LEN = 64

    def _begin_request(self) -> str:
        """Per-request id (re)set — handler instances persist across
        keep-alive requests, so the id must NOT be cached beyond one
        request; honors a well-formed caller header, generates otherwise."""
        raw = self.headers.get(REQUEST_ID_HEADER) or ""  # type: ignore[attr-defined]
        ok = (
            0 < len(raw) <= self._RID_MAX_LEN
            and all(c in self._RID_SAFE for c in raw)
        )
        self._rid = raw if ok else telemetry.new_request_id()
        # Per-request model-version override (the router front end sets it
        # from the answering replica's RouteResult); handler instances
        # persist across keep-alive requests, so it must reset here.
        self._mv_override: Optional[str] = None
        return self._rid

    def _model_version(self) -> Optional[str]:
        """The model version this response reports: a per-request override
        (router path — whatever replica answered) or the server-wide
        provider (engine path — the engine's CURRENT version, which is the
        honest answer on non-predict paths like /healthz and 4xx)."""
        override = getattr(self, "_mv_override", None)
        if override:
            return override
        fn = getattr(self.server, "model_version_fn", None)  # type: ignore[attr-defined]
        return fn() if fn is not None else None

    def _send_json(self, code: int, payload: dict, headers: Optional[dict] = None):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.send_header(REQUEST_ID_HEADER, self._request_id())
        replica_id = getattr(self.server, "replica_id", None)
        if replica_id:
            self.send_header(REPLICA_ID_HEADER, replica_id)
        model_version = self._model_version()
        if model_version:
            self.send_header(MODEL_VERSION_HEADER, model_version)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, code: int, text: str, content_type: str):
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.send_header(REQUEST_ID_HEADER, self._request_id())
        replica_id = getattr(self.server, "replica_id", None)
        if replica_id:
            self.send_header(REPLICA_ID_HEADER, replica_id)
        model_version = self._model_version()
        if model_version:
            self.send_header(MODEL_VERSION_HEADER, model_version)
        self.end_headers()
        self.wfile.write(body)


class _Handler(RequestPlumbing, BaseHTTPRequestHandler):
    # Engine injected by InferenceServer via the server object.
    protocol_version = "HTTP/1.1"

    @property
    def engine(self) -> InferenceEngine:
        return self.server.engine  # type: ignore[attr-defined]

    # ---------------------------------------------------------------- routes
    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
        self._begin_request()
        if self.path == "/healthz":
            engine = self.engine
            # Three health states instead of the old binary: ok (200),
            # degraded-but-serving (200, degraded: true — bad batches,
            # non-finite outputs, or a worker restart happened), down (503).
            fault_counters = engine.metrics.read_counters(
                "bad_batches_total",
                "nonfinite_total",
                "engine_restarts_total",
                # Warmup provenance for the router's warm-spin-up gate
                # (docs/COMPILE_CACHE.md): how many buckets came from the
                # persistent store vs fresh compiles.
                "exec_cache_hydrated_total",
                "cache_misses_total",
                # Lifecycle (docs/SERVING.md "Live model lifecycle"): the
                # router's health map learns which version each replica
                # runs and whether swaps happened/were refused.
                "weight_swaps_total",
                "swap_rejected_total",
            )
            self._send_json(
                200 if engine.running else 503,
                {
                    "ok": engine.running,
                    "replica": getattr(self.server, "replica_id", None),
                    "degraded": engine.degraded,
                    # Recent degraded transitions with the correlation ids
                    # that tripped them (docs/OBSERVABILITY.md).
                    "degraded_events": engine.degraded_events,
                    "queue_depth": engine._queue.qsize(),
                    "queue_limit": engine.queue_limit,
                    "compiled_buckets": engine.compiled_buckets,
                    # Serving arm (docs/PRECISION.md): operators must see at
                    # a glance whether this replica answers under the
                    # bit-exactness contract or a tolerance gate.
                    "precision": engine.precision,
                    # Which model version this replica answers with — the
                    # router's per-replica version view (docs/SERVING.md
                    # "Live model lifecycle").
                    "model_version": engine.model_version,
                    "weight_swaps": fault_counters["weight_swaps_total"],
                    "swaps_rejected": fault_counters["swap_rejected_total"],
                    "bad_batches": fault_counters["bad_batches_total"],
                    "nonfinite_outputs": fault_counters["nonfinite_total"],
                    "restarts": fault_counters["engine_restarts_total"],
                    "hydrated_buckets": fault_counters[
                        "exec_cache_hydrated_total"
                    ],
                    "compiled_fresh_buckets": fault_counters[
                        "cache_misses_total"
                    ],
                },
            )
        elif self.path == "/metrics":
            # Engine-scoped serving metrics + the process-wide graftel
            # registry (timer totals, fault counters, training gauges when
            # this process also trains) — one scrape, one registry.
            self._send_text(
                200,
                self.engine.metrics.render_prometheus()
                + render_prometheus(),
                "text/plain; version=0.0.4",
            )
        else:
            self._send_json(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):  # noqa: N802
        rid = self._begin_request()
        # Always drain the body first: HTTP/1.1 keep-alive would otherwise
        # parse leftover body bytes as the NEXT request line after a 404.
        length = int(self.headers.get("Content-Length", "0"))
        body = self.rfile.read(length) if length else b""
        if self.path == "/swap":
            self._handle_swap(body, rid)
            return
        if self.path != "/predict":
            self._send_json(404, {"error": f"unknown path {self.path}"})
            return
        try:
            doc = json.loads(body or b"{}")
            graphs_doc = doc.get("graphs")
            if not isinstance(graphs_doc, list) or not graphs_doc:
                raise ValueError('body must be {"graphs": [<graph>, ...]}')
            samples = [parse_graph(g) for g in graphs_doc]
        except (ValueError, TypeError, json.JSONDecodeError) as e:
            self._send_json(400, {"error": str(e), "request_id": rid})
            return

        engine = self.engine
        try:
            results, versions = engine.predict_versioned(
                samples,
                timeout=getattr(self.server, "request_timeout_s", 60.0),
                request_id=rid,
            )
        except BackpressureError as e:
            self._send_json(
                429,
                {
                    "error": str(e),
                    "retry_after_s": e.retry_after_s,
                    "request_id": rid,
                },
                headers={"Retry-After": f"{max(1, round(e.retry_after_s))}"},
            )
            return
        except (ValueError, TypeError) as e:  # per-graph validation
            self._send_json(400, {"error": str(e), "request_id": rid})
            return
        except TimeoutError as e:
            self._send_json(504, {"error": str(e), "request_id": rid})
            return
        except (EngineFailedError, RuntimeError) as e:
            # NonFiniteOutputError lands here too (RuntimeError subclass):
            # the failing request's 503 still carries its correlation id.
            self._send_json(503, {"error": str(e), "request_id": rid})
            return

        self._finish_predict(rid, results, versions)

    def _handle_swap(self, body: bytes, rid: str) -> None:
        """POST /swap — the fleet-orchestration admin endpoint (ROADMAP item
        4 remainder): ``{"checkpoint": <path>, "version"?: <str>,
        "expected_identity"?: <hex>}`` loads the named v2 checkpoint from
        THIS replica's filesystem (shared storage in a fleet) and hot-swaps
        it through ``engine.swap_weights`` — zero recompiles, per-request
        version consistency, the ``X-HydraGNN-Model-Version`` header flips
        on the next response. Gated behind ``--admin`` (serving replicas
        must opt in to being driven): 403 otherwise. Refusals keep serving:
        409 on identity/fingerprint/tolerance-gate mismatches, 400 on a
        missing/corrupt file, 503 on a dead engine."""
        if not getattr(self.server, "allow_admin", False):  # type: ignore[attr-defined]
            self._send_json(
                403,
                {
                    "error": "/swap is disabled — start the replica with "
                    "--admin to allow lifecycle orchestration",
                    "request_id": rid,
                },
            )
            return
        from ..checkpoint.format import CheckpointError
        from .engine import (
            PrecisionToleranceError,
            SwapFingerprintError,
            SwapIdentityError,
            swap_from_checkpoint,
        )

        try:
            doc = json.loads(body or b"{}")
            path = doc.get("checkpoint")
            if not isinstance(path, str) or not path:
                raise ValueError(
                    'body must be {"checkpoint": "<path>", "version"?: ..., '
                    '"expected_identity"?: ...}'
                )
        except (ValueError, TypeError, json.JSONDecodeError) as e:
            self._send_json(400, {"error": str(e), "request_id": rid})
            return
        try:
            report = swap_from_checkpoint(
                self.engine,
                path,
                version=doc.get("version"),
                expected_identity=doc.get("expected_identity"),
            )
        except (
            SwapIdentityError,
            SwapFingerprintError,
            PrecisionToleranceError,
        ) as e:
            self._send_json(409, {"error": str(e), "request_id": rid})
            return
        except CheckpointError as e:
            # Corrupt/unreadable/wrong-format file: the candidate is bad, the
            # replica keeps serving.
            self._send_json(400, {"error": str(e), "request_id": rid})
            return
        except OSError as e:
            self._send_json(400, {"error": str(e), "request_id": rid})
            return
        except (EngineFailedError, RuntimeError) as e:
            self._send_json(503, {"error": str(e), "request_id": rid})
            return
        self._mv_override = report["version"]
        self._send_json(200, {"request_id": rid, "swapped": True, **report})

    def _finish_predict(self, rid: str, results, versions) -> None:
        engine = self.engine
        # The header (and body field) report the version that actually
        # answered: the newest version any of the call's graphs executed
        # against — for single-graph requests (the swap drill's shape) this
        # is exact; a multi-graph call legitimately spanning a swap reports
        # the newer version and carries the per-graph tags in the body.
        call_versions = [v for v in versions if v]
        if call_versions:
            self._mv_override = call_versions[-1]
        self._send_json(
            200,
            {
                "request_id": rid,
                "model_version": call_versions[-1] if call_versions else None,
                "model_versions": versions,
                "heads": [
                    {"name": name, "type": htype, "dim": int(dim)}
                    for name, htype, dim in zip(
                        engine.head_names,
                        engine.model.output_type,
                        engine.model.output_dim,
                    )
                ],
                "predictions": [
                    [np.asarray(h).tolist() for h in per_graph]
                    for per_graph in results
                ],
            },
        )


class InferenceServer:
    """ThreadingHTTPServer wrapper owning one engine.

    ``port=0`` binds an ephemeral port (tests); ``.port`` reports the bound
    one. ``serve_forever`` blocks; ``start_background`` runs it on a daemon
    thread and returns immediately.
    """

    def __init__(
        self,
        engine: InferenceEngine,
        host: str = "127.0.0.1",
        port: int = 8000,
        request_timeout_s: float = 60.0,
        verbose: bool = False,
        replica_id: Optional[str] = None,
        enable_admin: bool = False,
    ):
        self.engine = engine
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.engine = engine  # type: ignore[attr-defined]
        # /swap fleet orchestration (docs/SERVING.md "Live model
        # lifecycle"): replicas must OPT IN to being driven — the endpoint
        # loads checkpoints from this process's filesystem.
        self._httpd.allow_admin = bool(enable_admin)  # type: ignore[attr-defined]
        # Every response path names the serving model version (the
        # lifecycle echo contract — see RequestPlumbing._model_version).
        self._httpd.model_version_fn = lambda: engine.model_version  # type: ignore[attr-defined]
        self._httpd.verbose = verbose  # type: ignore[attr-defined]
        self._httpd.request_timeout_s = request_timeout_s  # type: ignore[attr-defined]
        self._httpd.replica_id = replica_id  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def serve_forever(self) -> None:
        self._httpd.serve_forever()

    def start_background(self) -> "InferenceServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="hydragnn-serve-http",
            daemon=True,
        )
        self._thread.start()
        return self

    def shutdown(self, close_engine: bool = True) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(5.0)
        if close_engine:
            self.engine.close()
