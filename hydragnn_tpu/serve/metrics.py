"""Serving metrics: latency histograms, batch-shape counters, and a
Prometheus text exposition — the observability half of the online engine
(docs/SERVING.md "Metrics reference").

Everything here is host-side and lock-protected (observations arrive from the
engine's batcher/transfer/dispatch threads plus every caller thread). Seconds
observed into the latency histograms are ALSO credited into the existing
``Timer`` registry (utils/time_utils.py) under ``serve_*`` names, so a process
that both trains and serves prints one merged timer report.

Histogram design: fixed log-spaced bucket bounds (factor 2 from 100 µs to
~1638 s) — the standard Prometheus shape. Quantiles are estimated by linear
interpolation inside the first bucket whose cumulative count covers the
requested rank; with 2x-spaced bounds the estimate is within 2x of the true
value, which is the resolution serving SLOs are stated at.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

from ..analysis import tsan
from ..graphs.packing import SizeHistogram
from ..utils.time_utils import Timer

# 100 µs .. ~1638 s in 2x steps (25 bounds) — covers queue waits on an idle
# engine through multi-minute pathological stalls.
_DEFAULT_BOUNDS = tuple(1e-4 * (2.0**i) for i in range(25))

# Tolerance-diff bounds for the quantized precision arm (docs/PRECISION.md):
# 1e-9 .. ~275 in 4x steps — spans bf16 rounding noise on tiny heads through
# an unmistakably-broken quantization, at the 4x resolution tolerance bounds
# are stated at.
_DIFF_BOUNDS = tuple(1e-9 * (4.0**i) for i in range(20))


class LatencyHistogram:
    """Fixed-bound histogram of seconds with count/sum and quantile estimates."""

    def __init__(self, bounds: Sequence[float] = _DEFAULT_BOUNDS):
        self.bounds = tuple(float(b) for b in bounds)
        self._lock = tsan.instrument_lock(
            threading.Lock(), "LatencyHistogram._lock"
        )
        self._counts = [0] * (len(self.bounds) + 1)  # guarded-by: self._lock
        self.count = 0  # guarded-by: self._lock
        self.sum = 0.0  # guarded-by: self._lock

    def observe(self, seconds: float) -> None:
        seconds = float(seconds)
        i = 0
        for i, b in enumerate(self.bounds):
            if seconds <= b:
                break
        else:
            i = len(self.bounds)
        with self._lock:
            self._counts[i] += 1
            self.count += 1
            self.sum += seconds

    def mean(self) -> Optional[float]:
        """Locked mean seconds per observation (None when empty) — the
        per-request service estimate cross-thread readers (the router's
        admission check) must use instead of a torn sum/count pair."""
        with self._lock:
            return self.sum / self.count if self.count else None

    def quantile(self, q: float) -> Optional[float]:
        """Interpolated q-quantile in seconds (None when empty)."""
        with self._lock:
            counts = list(self._counts)
        return self.quantile_of(self.bounds, counts, q)

    @staticmethod
    def quantile_of(
        bounds: Sequence[float], counts: Sequence[int], q: float
    ) -> Optional[float]:
        """Interpolated q-quantile of an explicit per-bucket count vector
        (None when empty). Exposed so windowed readers — the router's
        rolling fleet-p99 sensor diffs successive ``counts_snapshot``
        vectors — estimate quantiles of a DELTA distribution with the same
        interpolation the cumulative :meth:`quantile` uses."""
        total = sum(counts)
        if total == 0:
            return None
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            prev_cum = cum
            cum += c
            if cum >= rank and c > 0:
                hi = bounds[i] if i < len(bounds) else bounds[-1] * 2.0
                lo = bounds[i - 1] if i > 0 else 0.0
                frac = (rank - prev_cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        return bounds[-1] * 2.0

    def counts_snapshot(self) -> List[int]:
        """One locked copy of the per-bucket counts (len(bounds) + 1 with
        the overflow bucket last) — the windowed-quantile reader's input."""
        with self._lock:
            return list(self._counts)

    def snapshot(self) -> Dict[str, Optional[float]]:
        with self._lock:
            count, total = self.count, self.sum
        out = {"count": count, "sum_s": round(total, 6)}
        for name, q in (("p50", 0.5), ("p95", 0.95), ("p99", 0.99)):
            v = self.quantile(q)
            out[name + "_ms"] = None if v is None else round(v * 1000.0, 3)
        return out

    def prometheus_lines(
        self, name: str, labels: str = "", le_fmt=None
    ) -> List[str]:
        """Cumulative-bucket exposition for one histogram. ``le_fmt`` formats
        bound labels; the default (6 decimal places, the historical latency
        rendering) COLLAPSES sub-1e-6 bounds to "0.0" — histograms with tiny
        bounds (the precision tolerance-diff family) must pass a
        significant-digit formatter instead, or strict parsers see duplicate
        le labels."""
        lab = f"{{{labels}}}" if labels else ""
        if le_fmt is None:
            le_fmt = lambda b: repr(round(b, 6))  # noqa: E731

        def with_le(le: str) -> str:
            inner = (labels + "," if labels else "") + f'le="{le}"'
            return f"{{{inner}}}"

        with self._lock:
            counts = list(self._counts)
            count, total = self.count, self.sum
        lines = []
        cum = 0
        for b, c in zip(self.bounds, counts):
            cum += c
            lines.append(f"{name}_bucket{with_le(le_fmt(b))} {cum}")
        lines.append(f"{name}_bucket{with_le('+Inf')} {count}")
        lines.append(f"{name}_sum{lab} {total}")
        lines.append(f"{name}_count{lab} {count}")
        return lines


class ServeMetrics:
    """All counters/histograms of one ``InferenceEngine``.

    Latency stages (per docs/SERVING.md):
      queue_wait — submit() to the request joining a flushed micro-batch;
      collate    — host packing of the micro-batch into its padded arena;
      h2d        — blocking device_put wire time (pipeline transfer thread);
      device     — compiled executable dispatch + readback;
      e2e        — submit() to future resolution.
    """

    _STAGES = ("queue_wait", "collate", "h2d", "device", "e2e")

    def __init__(self):
        self._lock = tsan.instrument_lock(
            threading.Lock(), "ServeMetrics._lock"
        )
        # Observations arrive from the batcher (feed-host), transfer,
        # dispatch, and caller threads; every field below is declared
        # guarded (graftrace enforces the with-blocks mechanically).
        self.latency = {  # guarded-by: self._lock, dirty-reads(dict is immutable after construction; the leaf histograms carry their own lock)
            s: LatencyHistogram() for s in self._STAGES
        }
        # Counters (monotonic).
        self.requests_total = 0  # guarded-by: self._lock
        self.rejected_total = 0  # guarded-by: self._lock
        self.errors_total = 0  # guarded-by: self._lock
        # Fault-tolerance split of errors (docs/FAULT_TOLERANCE.md):
        # batch-scoped failures keep the engine serving; worker restarts
        # consume the engine's restart budget; non-finite outputs fail the
        # REQUEST, not the engine.
        self.bad_batches_total = 0  # guarded-by: self._lock
        self.nonfinite_total = 0  # guarded-by: self._lock
        self.engine_restarts_total = 0  # guarded-by: self._lock
        # Live model lifecycle (graftswap, docs/SERVING.md): completed hot
        # weight swaps, fingerprint-rejected swap attempts, and post-swap
        # tolerance-gate reverts on quantized arms.
        self.weight_swaps_total = 0  # guarded-by: self._lock
        self.swap_rejected_total = 0  # guarded-by: self._lock
        self.swap_gate_failures_total = 0  # guarded-by: self._lock
        # Completed hot bucket-ladder swaps (the flywheel's drift-refit
        # path, serve/engine.py swap_ladder — docs/FLYWHEEL.md).
        self.ladder_swaps_total = 0  # guarded-by: self._lock
        self.batches_total = 0  # guarded-by: self._lock
        self.graphs_total = 0  # guarded-by: self._lock
        self.cache_hits_total = 0  # guarded-by: self._lock
        self.cache_misses_total = 0  # guarded-by: self._lock
        self.ladder_fallback_total = 0  # guarded-by: self._lock
        self.compile_seconds_total = 0.0  # guarded-by: self._lock
        # Persistent executable cache (graftcache, docs/COMPILE_CACHE.md):
        # disk hydrations — executables deserialized from the store instead
        # of compiled. A hydration is NOT a compile (no XLA compile event)
        # and NOT an in-memory hit; it gets its own pair so warmup cost is
        # attributable (exported as hydragnn_serve_exec_cache_*).
        self.exec_cache_hydrated_total = 0  # guarded-by: self._lock
        self.exec_cache_hydrate_seconds_total = 0.0  # guarded-by: self._lock
        self.h2d_bytes_total = 0  # guarded-by: self._lock
        # Occupancy / padding accumulators (averages derived in snapshot()).
        self._occupancy_sum = 0.0  # guarded-by: self._lock
        self._node_fill_sum = 0.0  # guarded-by: self._lock
        self._edge_fill_sum = 0.0  # guarded-by: self._lock
        # Per-bucket occupancy: the same accumulators keyed by the padded
        # (N_pad, E_pad) shape the batch compiled into, so a ladder's rungs
        # are individually observable (which rungs carry traffic, which
        # waste it) — docs/SERVING.md "Metrics reference".
        self._per_bucket: Dict[Tuple[int, int], Dict[str, float]] = {}  # guarded-by: self._lock
        # Observed request/batch sizes: the feedback record the ladder
        # fitter consumes (graphs/packing.py fit_ladder; dump via
        # histogram_json()). Guarded by the same lock as the counters.
        self.size_hist = SizeHistogram()  # guarded-by: self._lock
        # Precision arm (graftprec, docs/PRECISION.md): which arm this engine
        # serves, its tolerance bound, and the tolerance-gate record — the
        # hydragnn_serve_precision_* exposition family.
        self.precision_arm = "f32"  # guarded-by: self._lock, dirty-reads(set once at engine construction, before worker threads exist)
        self.precision_tolerance: Optional[float] = None  # guarded-by: self._lock, dirty-reads(same single-assignment lifecycle as precision_arm)
        self.precision_gate_checks_total = 0  # guarded-by: self._lock
        self.precision_gate_failures_total = 0  # guarded-by: self._lock
        self.precision_diff_max = 0.0  # guarded-by: self._lock
        # Per-head max-abs-diff observations.
        self.precision_diff = LatencyHistogram(bounds=_DIFF_BOUNDS)  # guarded-by: self._lock, dirty-reads(rebound never after construction; the leaf histogram carries its own lock, like the latency family)

    # ------------------------------------------------------------- recorders
    def observe(self, stage: str, seconds: float) -> None:
        self.latency[stage].observe(seconds)
        Timer.credit(f"serve_{stage}", seconds)

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + n)
            tsan.shared_access("ServeMetrics.counters")

    def read_counters(self, *names: str) -> Dict[str, float]:
        """One locked copy of the named counters — cross-thread readers
        (/healthz) must not assemble their view field-by-field between the
        recorder's updates (torn pairs; same defect render_prometheus had)."""
        with self._lock:
            return {n: getattr(self, n) for n in names}

    def record_compile(self, seconds: float) -> None:
        with self._lock:
            self.cache_misses_total += 1
            self.compile_seconds_total += seconds
        Timer.credit("serve_compile", seconds)

    def record_hydrate(self, seconds: float) -> None:
        """One executable deserialized from the persistent store (a
        graftcache disk hit — docs/COMPILE_CACHE.md)."""
        with self._lock:
            self.exec_cache_hydrated_total += 1
            self.exec_cache_hydrate_seconds_total += seconds
        Timer.credit("serve_exec_cache_hydrate", seconds)

    def set_precision(self, arm: str, tolerance: Optional[float]) -> None:
        """Engine-construction registration of the serving arm."""
        with self._lock:
            self.precision_arm = str(arm)
            self.precision_tolerance = tolerance

    def record_precision_gate(self, report: Dict) -> None:
        """Fold one check_tolerance verdict into the precision family: gate
        counters, running max diff, and the per-head diff histogram."""
        with self._lock:
            self.precision_gate_checks_total += 1
            if not report.get("ok"):
                self.precision_gate_failures_total += 1
            self.precision_diff_max = max(
                self.precision_diff_max, float(report.get("fwd_err", 0.0))
            )
        for head in report.get("per_head", ()):
            self.precision_diff.observe(float(head["max_abs_diff"]))

    def record_request(self, num_nodes: int, num_edges: int) -> None:
        """One admitted request's graph size — the serve half of the size
        histogram (the training half lives on GraphDataLoader)."""
        with self._lock:
            self.size_hist.record_graph(num_nodes, num_edges)

    def record_batch(
        self,
        num_graphs: int,
        max_batch_graphs: int,
        real_nodes: int,
        n_pad: int,
        real_edges: int,
        e_pad: int,
    ) -> None:
        with self._lock:
            tsan.shared_access("ServeMetrics.counters")
            self.batches_total += 1
            self.graphs_total += num_graphs
            self._occupancy_sum += num_graphs / max(max_batch_graphs, 1)
            self._node_fill_sum += real_nodes / max(n_pad, 1)
            self._edge_fill_sum += real_edges / max(e_pad, 1)
            bucket = self._per_bucket.setdefault(
                (int(n_pad), int(e_pad)),
                {"batches": 0, "graphs": 0, "node_fill": 0.0, "edge_fill": 0.0},
            )
            bucket["batches"] += 1
            bucket["graphs"] += num_graphs
            bucket["node_fill"] += real_nodes / max(n_pad, 1)
            bucket["edge_fill"] += real_edges / max(e_pad, 1)
            self.size_hist.record_batch(real_nodes, real_edges, num_graphs)

    # -------------------------------------------------------------- reporters
    def snapshot(self) -> Dict:
        with self._lock:
            batches = self.batches_total
            out = {
                "requests_total": self.requests_total,
                "rejected_total": self.rejected_total,
                "errors_total": self.errors_total,
                "bad_batches_total": self.bad_batches_total,
                "nonfinite_total": self.nonfinite_total,
                "engine_restarts_total": self.engine_restarts_total,
                "weight_swaps_total": self.weight_swaps_total,
                "swap_rejected_total": self.swap_rejected_total,
                "swap_gate_failures_total": self.swap_gate_failures_total,
                "ladder_swaps_total": self.ladder_swaps_total,
                "batches_total": batches,
                "graphs_total": self.graphs_total,
                "bucket_cache": {
                    "hits": self.cache_hits_total,
                    "misses": self.cache_misses_total,
                    "compile_seconds": round(self.compile_seconds_total, 4),
                    "ladder_fallbacks": self.ladder_fallback_total,
                    "hydrated": self.exec_cache_hydrated_total,
                    "hydrate_seconds": round(
                        self.exec_cache_hydrate_seconds_total, 4
                    ),
                },
                "h2d_bytes_total": self.h2d_bytes_total,
                # Precision arm + tolerance-gate record (docs/PRECISION.md).
                "precision": {
                    "arm": self.precision_arm,
                    "tolerance": self.precision_tolerance,
                    "gate_checks": self.precision_gate_checks_total,
                    "gate_failures": self.precision_gate_failures_total,
                    "max_abs_diff": self.precision_diff_max,
                },
                "batch_occupancy_mean": round(
                    self._occupancy_sum / batches, 4
                )
                if batches
                else None,
                # Padding waste = 1 - fill: the share of padded rows that
                # carried no real node/edge (compiled FLOPs spent on padding).
                "padding_waste_nodes_mean": round(
                    1.0 - self._node_fill_sum / batches, 4
                )
                if batches
                else None,
                "padding_waste_edges_mean": round(
                    1.0 - self._edge_fill_sum / batches, 4
                )
                if batches
                else None,
                # Per compiled (N_pad, E_pad) shape: which ladder rungs carry
                # the traffic and how full they run.
                "per_bucket": {
                    f"{n}x{e}": {
                        "batches": int(b["batches"]),
                        "graphs": int(b["graphs"]),
                        "node_fill_mean": round(
                            b["node_fill"] / b["batches"], 4
                        ),
                        "edge_fill_mean": round(
                            b["edge_fill"] / b["batches"], 4
                        ),
                    }
                    for (n, e), b in sorted(self._per_bucket.items())
                },
            }
        out["latency_ms"] = {s: h.snapshot() for s, h in self.latency.items()}
        out["precision"]["diff"] = self.precision_diff.snapshot()
        return out

    def histogram_json(self) -> Dict:
        """The observed-size record (requests + collated batch totals) in the
        ``fit-ladder`` CLI's input schema — the production feedback loop of
        docs/SERVING.md "Fitting a ladder from production histograms"."""
        with self._lock:
            return self.size_hist.to_json()

    # Counter attr -> exported Prometheus metric name. Exposition reads the
    # whole set in ONE locked copy — graftrace flagged the original
    # field-by-field unlocked reads (a scrape mid-record saw torn pairs,
    # e.g. batches_total incremented but graphs_total not yet).
    _PROM_COUNTERS = (
        ("requests_total", "requests_total"),
        ("rejected_total", "rejected_total"),
        ("errors_total", "errors_total"),
        ("bad_batches_total", "bad_batches_total"),
        ("nonfinite_total", "nonfinite_total"),
        ("engine_restarts_total", "engine_restarts_total"),
        # Hot-swap lifecycle counters (docs/OBSERVABILITY.md catalogue).
        ("weight_swaps_total", "weight_swaps_total"),
        ("swap_rejected_total", "swap_rejected_total"),
        ("swap_gate_failures_total", "swap_gate_failures_total"),
        ("ladder_swaps_total", "ladder_swaps_total"),
        ("batches_total", "batches_total"),
        ("graphs_total", "graphs_total"),
        ("cache_hits_total", "bucket_cache_hits_total"),
        ("cache_misses_total", "bucket_cache_misses_total"),
        ("ladder_fallback_total", "ladder_fallback_total"),
        ("compile_seconds_total", "compile_seconds_total"),
        # graftcache exposition (docs/COMPILE_CACHE.md): the persistent
        # executable store's view of this engine — hits/misses alias the
        # bucket-cache pair (one registry serves both), hydrations are the
        # disk-restore half only this family carries.
        ("cache_hits_total", "exec_cache_hits_total"),
        ("cache_misses_total", "exec_cache_misses_total"),
        ("exec_cache_hydrated_total", "exec_cache_hydrated_total"),
        ("exec_cache_hydrate_seconds_total", "exec_cache_hydrate_seconds_total"),
        ("h2d_bytes_total", "h2d_bytes_total"),
    )

    def render_prometheus(self) -> str:
        """Prometheus text-format exposition (the /metrics payload)."""
        p = "hydragnn_serve"
        with self._lock:
            counters = {
                attr: getattr(self, attr) for attr, _ in self._PROM_COUNTERS
            }
        lines = []
        for attr, metric in self._PROM_COUNTERS:
            lines.append(f"# TYPE {p}_{metric} counter")
            lines.append(f"{p}_{metric} {counters[attr]}")
        snap = self.snapshot()
        for gauge in (
            "batch_occupancy_mean",
            "padding_waste_nodes_mean",
            "padding_waste_edges_mean",
        ):
            v = snap[gauge]
            if v is not None:
                lines.append(f"# TYPE {p}_{gauge} gauge")
                lines.append(f"{p}_{gauge} {v}")
        if snap.get("per_bucket"):
            # One contiguous sample group per metric family (the exposition
            # format requires all of a metric's samples directly under its
            # TYPE line — interleaving families breaks strict parsers).
            lines.append(f"# TYPE {p}_bucket_batches_total counter")
            for key, b in snap["per_bucket"].items():
                lines.append(
                    f'{p}_bucket_batches_total{{bucket="{key}"}} '
                    f"{b['batches']}"
                )
            lines.append(f"# TYPE {p}_bucket_node_fill_mean gauge")
            for key, b in snap["per_bucket"].items():
                lines.append(
                    f'{p}_bucket_node_fill_mean{{bucket="{key}"}} '
                    f"{b['node_fill_mean']}"
                )
        # Precision family (docs/PRECISION.md "Telemetry"): which arm serves
        # (info-style gauge with the arm label), the gate counters, and the
        # per-head tolerance-diff histogram — empty (all-zero buckets) on
        # the f32 arm, where no gate runs.
        prec = snap["precision"]
        lines.append(f"# TYPE {p}_precision_info gauge")
        lines.append(f'{p}_precision_info{{arm="{prec["arm"]}"}} 1')
        lines.append(f"# TYPE {p}_precision_gate_checks_total counter")
        lines.append(
            f"{p}_precision_gate_checks_total {prec['gate_checks']}"
        )
        lines.append(f"# TYPE {p}_precision_gate_failures_total counter")
        lines.append(
            f"{p}_precision_gate_failures_total {prec['gate_failures']}"
        )
        if prec["tolerance"] is not None:
            lines.append(f"# TYPE {p}_precision_tolerance_bound gauge")
            lines.append(
                f"{p}_precision_tolerance_bound {prec['tolerance']}"
            )
        lines.append(f"# TYPE {p}_precision_tolerance_diff histogram")
        lines.extend(
            self.precision_diff.prometheus_lines(
                f"{p}_precision_tolerance_diff",
                labels=f'arm="{prec["arm"]}"',
                # Significant digits, not decimal places: the 1e-9-scale
                # bounds would otherwise all collapse to le="0.0".
                le_fmt=lambda b: f"{b:.3g}",
            )
        )
        lines.append(f"# TYPE {p}_latency_seconds histogram")
        for stage, hist in self.latency.items():
            lines.extend(
                hist.prometheus_lines(
                    f"{p}_latency_seconds", labels=f'stage="{stage}"'
                )
            )
        return "\n".join(lines) + "\n"
