"""Online inference engine: bucketed micro-batching over the padded-arena
collation contract, with a compiled-executable cache and bounded-queue
backpressure (docs/SERVING.md).

Why this shape: the repo's only inference surface before this module was the
offline ``run_prediction`` batch pass. Online traffic needs the same three
invariants that make the training path fast, re-assembled around a request
queue:

* **Static shapes.** Requests are collated into the exact padded
  ``(N_pad, E_pad, G_pad)`` buckets the training collator emits
  (graphs/collate.py: "XLA compiles once per bucket"), so steady-state
  traffic reuses a small set of AOT-compiled executables. The cache is
  explicit (``_executables``) — hits/misses/compile-seconds are serving
  metrics, and ``warmup()`` pre-compiles a declared bucket ladder so the
  first user request never pays a compile.

* **Overlap.** Batches flow through the PR-1 two-stage ``DeviceFeed``
  pipeline (train/pipeline.py): the micro-batcher generator runs on the
  feed's host thread (queue pop + deadline flush + arena collation), the
  transfer stage commits each batch with a blocking ``device_put`` on its
  own thread, and the dispatch thread only ever executes on
  already-committed device arrays — batch *k+1* transfers while batch *k*
  computes, exactly like a training epoch.

* **Bounded memory + honest failure.** The request queue is bounded;
  ``submit`` on a full queue raises :class:`BackpressureError` with a
  retry-after hint instead of queueing unboundedly (the caller — or the
  HTTP front end, as 429 — sheds the load). Any exception on the
  batcher/transfer/dispatch threads fails every pending future and poisons
  the engine (subsequent submits re-raise the original error): a worker
  crash is a loud caller-visible failure, never a silently wedged queue.

Numerical contract: the forward is ``_apply_model(model, ..., train=False)``
— the same function the offline eval step wraps — and padding is inert by
construction (masked BN/pool/heads, padding edges connect padding nodes), so
engine outputs are bit-identical to ``run_prediction`` on CPU for the same
checkpoint and graphs regardless of how requests are grouped into buckets
(locked by tests/test_serve_engine.py).
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..analysis import tsan
from ..cache import CacheKey, ExecutableRegistry, ExecutableStore, tree_signature
from ..graphs.collate import GraphArena, round_up_pow2
from ..graphs.packing import PackCaps, first_fit_decreasing
from ..graphs.sample import GraphSample
from ..telemetry import graftel as telemetry
from ..train.pipeline import DeviceFeed
from .metrics import ServeMetrics


class BackpressureError(RuntimeError):
    """Bounded request queue is full — retry after ``retry_after_s``."""

    def __init__(self, message: str, retry_after_s: float):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class EngineClosedError(RuntimeError):
    """The engine was shut down (close()) before the request resolved."""


class EngineFailedError(RuntimeError):
    """A worker thread died; the original exception is ``__cause__``."""


class NonFiniteOutputError(RuntimeError):
    """The model produced NaN/Inf for this request — the request fails, the
    engine keeps serving (the serving analog of the training step guard,
    docs/FAULT_TOLERANCE.md)."""


class PrecisionToleranceError(RuntimeError):
    """The quantized arm's outputs diverged from the f32 reference beyond the
    declared tolerance bound (docs/PRECISION.md "Tolerance gate"). Raised by
    :meth:`InferenceEngine.check_tolerance`; the full verdict rides on
    ``report``."""

    def __init__(self, message: str, report: dict):
        super().__init__(message)
        self.report = report


class SwapIdentityError(RuntimeError):
    """:func:`swap_from_checkpoint` rejected the checkpoint file: its
    verified content identity does not match the identity the caller pinned
    (``expected_identity``) — the file changed since it was staged. The
    engine keeps serving its current weights. A dedicated type so the /swap
    endpoint and orchestration callers classify the refusal structurally,
    never by parsing the message."""


class SwapFingerprintError(RuntimeError):
    """:meth:`InferenceEngine.swap_weights` rejected the incoming variables:
    their param-tree fingerprint (key paths/shapes/dtypes) does not match the
    tree the engine's executables were compiled against. The engine keeps
    serving its CURRENT weights — a wrong-architecture swap must never take
    the tier down (docs/SERVING.md "Live model lifecycle")."""


class _Future:
    """Minimal thread-safe future.

    Deliberately NOT ``concurrent.futures.Future``: the engine's race
    closures (submit-vs-close rejection after enqueue, collation-failure
    rejection racing a normal resolve) rely on a second completion being a
    benign no-op-overwrite with at-most-one outcome visible to the waiter —
    the stdlib future raises InvalidStateError there, which inside
    ``_resolve`` would poison the whole engine. (And on this Python,
    ``concurrent.futures.TimeoutError`` is not the builtin ``TimeoutError``
    callers naturally catch.)"""

    __slots__ = ("_event", "_result", "_error", "request_id", "model_version")

    def __init__(self, request_id: Optional[str] = None):
        self._event = threading.Event()
        self._result = None
        self._error = None
        # Correlation id (docs/OBSERVABILITY.md): assigned at submit, echoed
        # by the HTTP layer as X-HydraGNN-Request-Id.
        self.request_id = request_id
        # Model version the resolving batch executed against (set before
        # set_result; the lifecycle layer's per-response version tag —
        # docs/SERVING.md "Live model lifecycle").
        self.model_version: Optional[str] = None

    def set_result(self, value) -> None:
        self._result = value
        self._event.set()

    def set_exception(self, exc: BaseException) -> None:
        self._error = exc
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("inference request did not resolve in time")
        if self._error is not None:
            raise self._error
        return self._result


@dataclass
class _Request:
    sample: GraphSample
    future: _Future
    t_submit: float
    request_id: str = ""


@dataclass
class _BatchWork:
    """One flushed micro-batch between the collation and dispatch stages."""

    requests: List[_Request]
    node_start: np.ndarray  # per-request node offsets into the padded batch
    batch: Any  # host GraphBatch
    fallback: bool  # shape came from pow2 fallback, not the ladder


_SHUTDOWN = object()


class InferenceEngine:
    """Micro-batching online inference over a HydraGNN model.

    Parameters
    ----------
    model, variables:
        The flax module (``create_model``/``create_model_config``) and its
        restored variables ({"params", "batch_stats"}).
    max_batch_graphs:
        Flush a micro-batch at this many graphs. Also fixes the padded graph
        dimension: every batch uses ``G_pad = max_batch_graphs + 1`` so the
        graph axis never contributes extra compiled shapes.
    max_delay_ms:
        Flush an open (non-full) batch this many ms after it opened — the
        bound on latency a lone request pays waiting for batch-mates.
    queue_limit:
        Bounded request-queue depth; beyond it ``submit`` raises
        :class:`BackpressureError`.
    bucket_ladder:
        Optional sequence of ``(N_pad, E_pad)`` shapes. A batch takes the
        smallest ladder entry it fits; only when none fits does it fall back
        to the round-up ladder (counted as ``ladder_fallback_total``). With
        ``warmup=True`` every ladder entry is compiled at construction, so
        steady-state traffic never recompiles. Fit one from observed traffic
        with ``graphs/packing.py fit_ladder`` (CLI: ``--bucket-ladder
        auto:<histogram-or-ladder.json>``).
    packing:
        Bin-pack each flushed micro-batch by first-fit-decreasing under the
        TOP ladder rung's (nodes, edges) capacity (graphs/packing.py): an
        over-capacity flush splits into several bins that each take their
        tightest rung instead of one batch falling back to a worst-case
        round-up shape. Per-request identity is preserved — every bin
        carries its own requests and node offsets through to response
        demux. No-op without a ladder.
    ladder_step:
        Round-up ladder for shapes that miss the bucket ladder: ``"pow2"``
        (historical) or ``"mult64"`` (multiples of 64 above 256 — a
        520-node batch pads to 576, not 1024).
    head_names, y_minmax:
        Optional per-head names and min-max pairs; with ``y_minmax`` set,
        outputs are denormalized (``v * (ymax - ymin) + ymin``, the
        postprocess.output_denormalize arithmetic) before futures resolve.
    guard_outputs:
        Check every resolved output for NaN/Inf on the host; a
        non-finite output fails THAT request with
        :class:`NonFiniteOutputError` instead of returning garbage with a
        200 (the serving reuse of the training non-finite guard).
    max_worker_restarts:
        Fatal worker errors within this budget RESTART the pipeline threads
        (pending/queued requests fail, the engine goes ``degraded`` but keeps
        accepting traffic) instead of poisoning the engine. 0 = the
        historical binary poisoning.
    precision, tolerance:
        Serving arm (docs/PRECISION.md): ``"f32"`` (default) keeps the
        bit-exactness contract against ``run_prediction``; ``"bf16"`` runs
        the forward in bf16 compute (f32 weights, cast in-executable);
        ``"int8"`` additionally snaps every weight matrix to a per-tensor
        symmetric int8 grid (precision/quantize.py). Both quantized arms
        REQUIRE a positive ``tolerance`` — the bit-exactness gate relaxes to
        :meth:`check_tolerance` (max-abs-diff vs a retained f32 reference,
        shared machinery with certify_pallas) for quantized mode only. The
        arm is a CacheKey policy component: quantized executables can never
        hydrate an f32 entry or vice versa.
    compile_cache:
        Optional graftcache directory (docs/COMPILE_CACHE.md). With it set,
        ``warmup()`` and cache misses first try to HYDRATE the executable
        from the persistent store (a verified deserialize — seconds, zero
        XLA compiles) before paying a fresh compile, and fresh compiles are
        serialized back, so a restarted or newly spun-up replica warms its
        whole ladder from disk. ``None`` falls back to the
        ``HYDRAGNN_COMPILE_CACHE`` env var; empty/unset disables
        persistence (the historical in-memory-only cache).
    model_version:
        The version tag of the weights the engine boots with
        (docs/SERVING.md "Live model lifecycle"): tagged on every
        response (``fut.model_version``, the ``X-HydraGNN-Model-Version``
        header) and /healthz, and replaced atomically by
        :meth:`swap_weights`. ``from_config`` derives it from the
        checkpoint's verified content identity.
    autostart:
        Tests set False to exercise queue behavior without worker threads;
        call :meth:`start` to launch them later.
    """

    def __init__(
        self,
        model,
        variables: Dict[str, Any],
        *,
        max_batch_graphs: int = 32,
        max_delay_ms: float = 5.0,
        queue_limit: int = 256,
        bucket_ladder: Optional[Sequence[Tuple[int, int]]] = None,
        warmup: bool = False,
        packing: bool = False,
        ladder_step: str = "pow2",
        head_names: Optional[Sequence[str]] = None,
        y_minmax: Optional[Sequence] = None,
        metrics: Optional[ServeMetrics] = None,
        guard_outputs: bool = True,
        max_worker_restarts: int = 0,
        compile_cache: Optional[str] = None,
        precision: str = "f32",
        tolerance: Optional[float] = None,
        model_version: str = "v0",
        autostart: bool = True,
    ):
        import jax

        from ..precision import SERVE_PRECISIONS, fake_quantize_params
        from ..train.trainer import _apply_model

        # Precision arm resolution (docs/PRECISION.md) BEFORE anything reads
        # the model: quantized arms serve a bf16-compute clone (and, for
        # int8, grid-snapped weights) while the original f32 model+variables
        # are retained as the tolerance gate's reference.
        if precision not in SERVE_PRECISIONS:
            raise ValueError(
                f"precision {precision!r} is not one of {SERVE_PRECISIONS}"
            )
        self.precision = precision
        self.tolerance = None if tolerance is None else float(tolerance)
        # Quantized-arm reference state: rebound only under _swap_lock
        # (created below; __init__ is pre-publication) — a swap and a
        # concurrent tolerance check must agree on which f32 reference
        # belongs to the published weights.
        self._quant_report: Optional[Dict[str, Any]] = None  # guarded-by: self._swap_lock
        self._ref_model = None  # guarded-by: self._swap_lock, dirty-reads(bound once in __init__, never rebound — swaps replace the reference VARIABLES, not the f32 module clone)
        self._ref_variables: Optional[Dict[str, Any]] = None  # guarded-by: self._swap_lock
        if precision != "f32":
            if self.tolerance is None or self.tolerance <= 0:
                raise ValueError(
                    f"quantized serving (precision={precision!r}) requires a "
                    "positive tolerance bound — the bit-exactness contract "
                    "is relaxed, never silently dropped (docs/PRECISION.md)"
                )
            # The gate's reference must be a REAL f32 forward: a checkpoint
            # whose Architecture already pins compute_dtype='bfloat16' would
            # otherwise be its own reference (max_abs_diff identically 0 —
            # a vacuous gate claiming a bound that was never measured).
            self._ref_model = (
                model
                if model.compute_dtype is None
                else model.clone(compute_dtype=None)
            )
            self._ref_variables = variables
            if model.compute_dtype != "bfloat16":
                model = model.clone(compute_dtype="bfloat16")
            if precision == "int8":
                variables = dict(variables)
                variables["params"], self._quant_report = fake_quantize_params(
                    variables["params"]
                )
        elif tolerance is not None:
            raise ValueError(
                "tolerance is a quantized-arm knob; precision='f32' serves "
                "under the bit-exactness contract and accepts none"
            )

        self.model = model
        self.max_batch_graphs = int(max_batch_graphs)
        self.max_delay_ms = float(max_delay_ms)
        self.queue_limit = int(queue_limit)
        self.metrics = metrics if metrics is not None else ServeMetrics()
        self.metrics.set_precision(self.precision, self.tolerance)
        self.head_names = (
            list(head_names)
            if head_names
            else [f"head_{i}" for i in range(len(model.output_dim))]
        )
        self._y_minmax = y_minmax
        self._g_pad = self.max_batch_graphs + 1
        self._edge_dim = model.edge_dim if model.use_edge_attr else 0
        # The bucket ladder is published like the weights: ONE sorted-list
        # reference, rebound atomically under _lock by warmup()'s merge and
        # swap_ladder() (the flywheel's drift-refit path). The batcher takes
        # a single locked snapshot per flush and threads it through
        # _pack_groups/_collate/_bucket_shape, so every batch — and
        # therefore every request — is planned against exactly one ladder
        # even while a swap lands mid-flush.
        self._ladder = sorted(  # guarded-by: self._lock, dirty-reads(status surfaces read the immutable list reference for display; consistency-bearing readers snapshot under the lock via _current_ladder)
            (int(n), int(e)) for n, e in (bucket_ladder or ())
        )
        self._packing = bool(packing)
        self._ladder_step = ladder_step

        params = jax.device_put(variables["params"])
        bstats = jax.device_put(variables.get("batch_stats", {}))
        self._jit = jax.jit(
            lambda params, bstats, batch: _apply_model(
                model, params, bstats, batch, train=False
            )
        )
        self._lock = tsan.instrument_lock(
            threading.Lock(), "InferenceEngine._lock"
        )
        # Serializes whole swaps (validate → quantize → gate → publish):
        # two concurrent swap_weights calls must publish in a total order,
        # and the quantized-arm reference state above must always describe
        # the published weights. Never held by the dispatch/feed threads —
        # request traffic only ever takes _lock. Lock order: _swap_lock
        # before _lock (the publish inside a swap).
        self._swap_lock = tsan.instrument_lock(
            threading.Lock(), "InferenceEngine._swap_lock"
        )
        # THE atomic weight reference (docs/SERVING.md "Live model
        # lifecycle"): (params, batch_stats, model_version) published as ONE
        # tuple — the dispatch thread reads it once per batch, so every
        # in-flight batch executes entirely against one version and every
        # response is tagged with exactly the version that produced it.
        # swap_weights() rebinds it under the lock; the compiled executables
        # take params/batch_stats as ARGUMENTS (and CacheKey fingerprints the
        # param TREE, not the values), so a same-architecture swap reuses
        # every compiled bucket with zero recompiles.
        self._weights: Tuple[Any, Any, str] = (  # guarded-by: self._lock
            params,
            bstats,
            str(model_version),
        )
        # Compiled-executable cache: filled by warmup() on the caller thread
        # AND by cache misses on the dispatch thread — since the graftcache
        # PR one shared ExecutableRegistry (cache/registry.py) whose single
        # locked lookup→(compile outside the lock)→store path replaced the
        # historical self._executables dict. With a compile_cache directory
        # bound, misses hydrate from the persistent store before compiling
        # fresh (docs/COMPILE_CACHE.md).
        cache_dir = (
            compile_cache
            if compile_cache is not None
            else os.environ.get("HYDRAGNN_COMPILE_CACHE", "")
        )
        self._registry = ExecutableRegistry(
            ExecutableStore(cache_dir) if cache_dir else None, name="serve"
        )
        # The serve half of the persistent key: model/weights identity from
        # the checkpoint layer's param-tree fingerprint plus the module's
        # field repr (hyperparameters without parameters — activation,
        # aggregation list — change the program but not the param tree).
        self._config_fingerprint = ""
        # Precision is BOTH a fingerprint component and a named CacheKey flag
        # (docs/PRECISION.md "Cache-key interaction"): the model repr already
        # separates f32 from the bf16-compute clone, but bf16 and int8 share
        # a module repr and a param-tree signature (int8 quantization moves
        # VALUES, not shapes/dtypes) — the explicit arm label is what makes
        # cross-precision hydration structurally impossible.
        self._key_flags: Tuple[str, ...] = (
            () if self.precision == "f32" else (f"precision={self.precision}",)
        )
        if self._registry.store is not None:
            from ..checkpoint.format import param_fingerprint

            self._config_fingerprint = hashlib.sha256(
                (
                    param_fingerprint(variables["params"])
                    + param_fingerprint(variables.get("batch_stats", {}))
                    + repr(model)
                    # Quantized arms only: the f32 digest must stay byte-
                    # identical to pre-graftprec stores — an upgraded replica
                    # fleet keeps hydrating its warm f32 entries.
                    + (
                        f"|precision={self.precision}"
                        if self.precision != "f32"
                        else ""
                    )
                ).encode()
            ).hexdigest()

        self._queue: "queue.Queue" = queue.Queue(maxsize=self.queue_limit)
        self._pending: set = set()  # guarded-by: self._lock
        self._closing = threading.Event()
        self._error: Optional[BaseException] = None  # guarded-by: self._lock, dirty-reads(set at most once before _closing; the submit fast path may read one poison late and is re-checked post-enqueue)
        self._feed: Optional[DeviceFeed] = None  # guarded-by: self._lock, dirty-reads(rebound only by start/_fail, serialized by the _closing/_gen_stop protocol; close() joins a possibly-stale feed harmlessly)
        self._dispatcher: Optional[threading.Thread] = None  # guarded-by: self._lock, dirty-reads(same lifecycle protocol as _feed)
        self._guard_outputs = bool(guard_outputs)
        self._restarts_left = int(max_worker_restarts)  # guarded-by: self._lock, dirty-reads(decremented only by _fail on the dispatch thread; budget off-by-one under a torn restart is acceptable degradation)
        self._degraded = False  # guarded-by: self._lock, dirty-reads(sticky monotonic bool; a stale False read only delays the /healthz downgrade by one scrape)
        # Bounded log of degraded-state transitions, correlation ids
        # included — surfaced by /healthz so "degraded: true" names the
        # requests that tripped it (docs/OBSERVABILITY.md).
        self._degraded_events: "deque" = deque(maxlen=16)  # guarded-by: self._lock
        # Telemetry context of the CURRENT pipeline incarnation, handed to
        # the feed threads + dispatcher (explicit cross-thread propagation).
        self._pipeline_ctx = None  # guarded-by: self._lock, dirty-reads(rebound only by start(); stage threads read the ctx they were constructed with)
        # Per-incarnation stop flag for the batcher generator: on a worker
        # restart the OLD batcher must stop consuming the shared request
        # queue before the new one starts (two live batchers would race).
        self._gen_stop: Optional[threading.Event] = None

        if warmup and self._ladder:
            self.warmup()
        if autostart:
            self.start()

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        """Launch the batcher→transfer→dispatch pipeline (idempotent)."""
        if self._dispatcher is not None:
            return
        self._gen_stop = threading.Event()
        # One telemetry context per pipeline incarnation: the batcher /
        # transfer / dispatcher spans all parent here, so a flight-recorder
        # dump shows which incarnation served which requests.
        ctx = telemetry.new_context()
        feed = DeviceFeed(
            self._batch_source(self._gen_stop),
            transfer=self._transfer,
            host_depth=2,
            ctx=ctx,
        )
        dispatcher = threading.Thread(
            target=self._dispatch_loop, name="hydragnn-serve-dispatch",
            daemon=True,
        )
        with self._lock:
            self._feed = feed
            self._dispatcher = dispatcher
            self._pipeline_ctx = ctx
        dispatcher.start()

    @property
    def running(self) -> bool:
        return (
            self._dispatcher is not None
            and self._dispatcher.is_alive()
            and self._error is None
            and not self._closing.is_set()
        )

    @property
    def compiled_buckets(self) -> int:
        """Locked executable-cache size — /healthz and the serve CLI read
        this cross-thread (the registry's len() holds its own lock;
        callers must not reach through the registry's internals directly)."""
        return len(self._registry)

    def _current_weights(self) -> Tuple[Any, Any, str]:
        """One locked read of the atomic (params, batch_stats, version)
        reference — the only way any consumer (dispatch, warmup, tolerance
        gate, status surfaces) may observe the weights."""
        with self._lock:
            return self._weights

    def _current_ladder(self) -> List[Tuple[int, int]]:
        """One locked read of the published bucket-ladder reference (the
        ladder analog of ``_current_weights``). The returned list is never
        mutated in place — swaps rebind the reference — so callers may hold
        the snapshot across a whole flush."""
        with self._lock:
            return self._ladder

    def variables_template(self) -> Dict[str, Any]:
        """THE variables template verified checkpoint loads restore onto
        (flax ``from_bytes``: structure used, values ignored). For quantized
        arms the retained f32 reference is the honest template — the served
        params carry the same tree either way. One definition shared by
        ``swap_from_checkpoint`` and ``LifecycleManager._template`` so the
        /swap path and the in-process lifecycle path can never diverge."""
        ref = getattr(self, "_ref_variables", None)
        if ref is not None:
            return ref
        params, bstats, _v = self._current_weights()
        return {"params": params, "batch_stats": bstats}

    @property
    def model_version(self) -> str:
        """The version the engine currently answers with (tagged on every
        response and /healthz — docs/SERVING.md "Live model lifecycle")."""
        return self._current_weights()[2]

    @property
    def degraded(self) -> bool:
        """Sticky health downgrade: the engine is serving, but it has seen
        batch-scoped failures, non-finite outputs, or a worker restart since
        construction — surfaced in /healthz next to the counters so operators
        see gray, not just green/black."""
        return self._degraded

    def close(self, timeout: float = 10.0) -> None:
        """Drain in-flight batches, stop the threads, fail stragglers."""
        if self._closing.is_set():
            return
        self._closing.set()
        # The shutdown marker must reach the batcher even under a full
        # queue: evict (and fail) queued requests until it fits.
        while True:
            try:
                self._queue.put_nowait(_SHUTDOWN)
                break
            except queue.Full:
                try:
                    req = self._queue.get_nowait()
                except queue.Empty:
                    continue
                if req is not _SHUTDOWN:
                    self._reject(req, EngineClosedError("engine closing"))
        if self._dispatcher is not None:
            self._dispatcher.join(timeout)
        if self._feed is not None:
            self._feed.close()
            self._feed.join(2.0)
        # Anything still unresolved (e.g. batches dropped by feed teardown).
        self._fail_pending(EngineClosedError("engine closed"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------- requests
    def submit(
        self, sample: GraphSample, request_id: Optional[str] = None
    ) -> _Future:
        """Enqueue one graph; returns a future resolving to the per-head
        output list ([dim] arrays for graph heads, [n, dim] for node heads).
        ``request_id`` is the correlation id carried end-to-end (submit →
        pack bin → device batch → demux → response; docs/OBSERVABILITY.md);
        one is generated when the caller brings none. The id is available on
        the returned future (``fut.request_id``).
        """
        if self._error is not None:
            raise EngineFailedError(
                "inference worker died; engine must be rebuilt"
            ) from self._error
        if self._closing.is_set():
            raise EngineClosedError("engine is shut down")
        self._validate(sample)
        rid = request_id or telemetry.new_request_id()
        req = _Request(
            sample=sample,
            future=_Future(request_id=rid),
            t_submit=time.perf_counter(),
            request_id=rid,
        )
        telemetry.event(
            "serve/submit",
            request_id=rid,
            nodes=int(sample.num_nodes),
            edges=int(sample.num_edges),
        )
        with self._lock:
            self._pending.add(req.future)
        # Annotated interleaving site: the window between pending-set entry
        # and enqueue is where a concurrent _fail must not strand the future
        # (tsan's seeded schedule fuzzing widens it deterministically).
        tsan.yield_point("serve.submit.pre_enqueue")
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            with self._lock:
                self._pending.discard(req.future)
            self.metrics.count("rejected_total")
            telemetry.event("serve/reject", request_id=rid)
            hint = self._retry_after_hint()
            raise BackpressureError(
                f"request queue full ({self.queue_limit}); retry in "
                f"~{hint:.2f}s",
                retry_after_s=hint,
            ) from None
        # Close the check-then-act race with close()/_fail(): if shutdown or
        # a worker death landed BETWEEN the checks above and the enqueue, the
        # batcher may already be past its drain and never pop this request —
        # fail the future here. (If the batcher does still pop it, the caller
        # sees the rejection; at-most-one outcome is visible either way.)
        if self._closing.is_set() or self._error is not None:
            self._reject(
                req,
                EngineClosedError("engine closed during submit")
                if self._error is None
                else EngineFailedError("inference worker died"),
            )
            return req.future
        self.metrics.count("requests_total")
        self.metrics.record_request(sample.num_nodes, sample.num_edges)
        return req.future

    def predict(
        self,
        samples: Sequence[GraphSample],
        timeout: Optional[float] = 60.0,
        request_id: Optional[str] = None,
    ) -> List[List[np.ndarray]]:
        """Synchronous convenience: submit all, wait all (results only; see
        :meth:`predict_versioned` for the per-graph model-version tags)."""
        results, _versions = self.predict_versioned(
            samples, timeout=timeout, request_id=request_id
        )
        return results

    def predict_versioned(
        self,
        samples: Sequence[GraphSample],
        timeout: Optional[float] = 60.0,
        request_id: Optional[str] = None,
    ) -> Tuple[List[List[np.ndarray]], List[Optional[str]]]:
        """Submit all, wait all → ``(results, versions)`` where versions[i]
        is the model version graph i's batch executed against. Returns one
        per-head output list per input graph. A multi-graph call shares one
        ``request_id`` base (the HTTP layer's correlation id); each graph
        gets ``<request_id>/<i>``. Per-request version consistency: each
        graph's version is exact; a multi-graph call racing a hot swap may
        legitimately span the old and new versions across its graphs.

        All samples are validated BEFORE any is admitted (a malformed graph
        rejects the call without consuming device work), and a multi-graph
        call that cannot fit the queue's free slots is rejected up front —
        so a 429 for the whole call does not leave a half-admitted batch
        computing results nobody will read (retry amplification)."""
        for s in samples:
            self._validate(s)
        if len(samples) > self.queue_limit:
            # Terminal, not transient: no amount of retrying fits this call.
            raise ValueError(
                f"predict() of {len(samples)} graphs exceeds queue_limit "
                f"{self.queue_limit}; split the call or raise the limit"
            )
        free = self.queue_limit - self._queue.qsize()
        if len(samples) > free:
            self.metrics.count("rejected_total")
            hint = self._retry_after_hint()
            raise BackpressureError(
                f"{len(samples)} graphs exceed the queue's ~{free} free "
                f"slots; retry in ~{hint:.2f}s",
                retry_after_s=hint,
            )
        rid = request_id or telemetry.new_request_id()
        futures = []
        try:
            for i, s in enumerate(samples):
                futures.append(self.submit(s, request_id=f"{rid}/{i}"))
        except BackpressureError:
            # Lost the capacity race to concurrent callers: the already-
            # admitted graphs will compute regardless — drain them so the
            # engine is quiescent for the caller's retry, then re-raise.
            for f in futures:
                try:
                    f.result(timeout)
                except Exception:
                    pass
            raise
        results = [f.result(timeout) for f in futures]
        return results, [f.model_version for f in futures]

    def _validate(self, sample: GraphSample) -> None:
        # Overlaps structurally with the loader-side quarantine validator
        # (preprocess/dataloader.py:invalid_sample_reason) but is a distinct
        # contract: request-facing errors, model input/edge width checks, no
        # y/y_loc (requests are unlabeled) and no finiteness (non-finite
        # OUTPUTS fail per-request in _resolve). Mirror changes to the
        # shared structural checks there.
        x = sample.x
        if x is None or np.ndim(x) != 2:
            raise ValueError("sample.x must be a [num_nodes, F] array")
        if x.shape[1] != self.model.input_dim:
            raise ValueError(
                f"sample.x feature width {x.shape[1]} != model input_dim "
                f"{self.model.input_dim}"
            )
        if sample.edge_index is not None:
            ei = np.asarray(sample.edge_index)
            if ei.ndim != 2 or ei.shape[0] != 2:
                raise ValueError("sample.edge_index must be [2, num_edges]")
            # Bounds matter for batch ISOLATION, not just this request: after
            # the arena's per-graph offset shift an out-of-range index would
            # alias this graph's edges onto a co-batched graph's nodes.
            if ei.size and (ei.min() < 0 or ei.max() >= sample.num_nodes):
                raise ValueError(
                    "sample.edge_index references nodes outside the graph"
                )
        if self._edge_dim and sample.num_edges:
            # The model consumes per-edge features: a missing attr would
            # silently zero-fill (wrong predictions with a 200), a wrong
            # width would blow up collation mid-batch — reject here instead.
            ea = sample.edge_attr
            if ea is None:
                raise ValueError(
                    f"model expects edge_attr of width {self._edge_dim}; "
                    "request carries none"
                )
            # Row count too: the arena reads attr rows by edge_index counts,
            # so a mismatch corrupts (or crashes) co-batched requests.
            if np.ndim(ea) != 2 or np.shape(ea) != (
                sample.num_edges,
                self._edge_dim,
            ):
                raise ValueError(
                    f"sample.edge_attr must be [{sample.num_edges}, "
                    f"{self._edge_dim}], got shape {np.shape(ea)}"
                )
        # No size ceiling: a graph too large for every ladder rung is still
        # serveable through _bucket_shape's pow2 fallback (one compile,
        # counted as ladder_fallback_total).

    def _retry_after_hint(self) -> float:
        """Seconds until the queue has likely drained one batch's worth:
        queued batches x per-batch service estimate (measured device latency
        when available, else the flush deadline)."""
        dev = self.metrics.latency["device"]
        per_batch = (
            dev.sum / dev.count if dev.count else self.max_delay_ms / 1000.0
        )
        batches_queued = max(1, self._queue.qsize() // self.max_batch_graphs)
        return max(0.05, batches_queued * max(per_batch, 1e-3))

    # ----------------------------------------------------------- the worker
    def _batch_source(self, stop: threading.Event):
        """Micro-batcher generator (runs on the DeviceFeed host thread):
        pop → deadline/size flush → arena collation → host batch. ``stop`` is
        this incarnation's kill switch — set by a worker restart so a stale
        batcher cannot keep consuming the shared queue."""
        q = self._queue
        while True:
            try:
                first = q.get(timeout=0.05)
            except queue.Empty:
                if self._closing.is_set() or stop.is_set():
                    return
                continue
            if first is _SHUTDOWN:
                return
            entries = [first]
            saw_shutdown = False
            deadline = time.perf_counter() + self.max_delay_ms / 1000.0
            while len(entries) < self.max_batch_graphs:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    nxt = q.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is _SHUTDOWN:
                    saw_shutdown = True
                    break
                entries.append(nxt)
            # ONE ladder snapshot per flush: bin planning and bucket
            # selection below must agree on the rung set, even if
            # swap_ladder publishes a new ladder mid-flush.
            ladder = self._current_ladder()
            for group in self._pack_groups(entries, ladder):
                try:
                    work = self._collate(group, ladder)
                except Exception as e:  # noqa: BLE001
                    # A bad batch (collation failure past _validate's
                    # checks) fails ITS requests loudly but must not poison
                    # the engine — batch-mates and later traffic are
                    # innocent. Under packing the scope is one BIN: sibling
                    # bins of the same flush still serve.
                    for req in group:
                        self._reject(req, e)
                    self.metrics.count("errors_total")
                    self.metrics.count("bad_batches_total")
                    self._mark_degraded(
                        "collation_failure",
                        [r.request_id for r in group],
                    )
                    continue
                yield work
            if saw_shutdown:
                return

    def _pack_groups(
        self, entries: List[_Request], ladder: List[Tuple[int, int]]
    ) -> List[List[_Request]]:
        """Split one flush into arena-slot bins (first-fit-decreasing under
        the top ladder rung's capacity) when packing is on; otherwise the
        flush is one bin, the historical behavior. Every request of the
        flush appears in exactly one bin (demux identity is per-bin).
        ``ladder`` is the batcher's per-flush snapshot."""
        if not (self._packing and ladder):
            return [entries]
        top_n, top_e = ladder[-1]
        caps = PackCaps(
            nodes=top_n - 1, edges=top_e, graphs=self.max_batch_graphs
        )
        bins = first_fit_decreasing(
            [r.sample.num_nodes for r in entries],
            [r.sample.num_edges for r in entries],
            caps,
        )
        return [[entries[i] for i in members] for members in bins]

    def _bucket_shape(
        self,
        tot_nodes: int,
        tot_edges: int,
        ladder: Optional[List[Tuple[int, int]]] = None,
    ) -> Tuple[int, int, bool]:
        """Smallest ladder (N_pad, E_pad) the batch fits, else round-up
        fallback (``ladder_step`` mode). collate requires N_pad > tot_nodes
        (>=1 padding node) and E_pad >= tot_edges. The batcher passes its
        per-flush ladder snapshot; other callers default to a fresh one."""
        if ladder is None:
            ladder = self._current_ladder()
        for n, e in ladder:
            if n > tot_nodes and e >= tot_edges:
                return n, e, False
        return (
            round_up_pow2(tot_nodes + 1, mode=self._ladder_step),
            round_up_pow2(max(tot_edges, 1), mode=self._ladder_step),
            bool(ladder),
        )

    def _collate(
        self,
        entries: List[_Request],
        ladder: Optional[List[Tuple[int, int]]] = None,
    ) -> _BatchWork:
        t0 = time.perf_counter()
        # Queue wait ends at the FLUSH (now), before collation starts — the
        # stage decomposition must not double-count collate seconds.
        for r in entries:
            self.metrics.observe("queue_wait", t0 - r.t_submit)
        # "pack bin" stage of the correlation trail: this span names every
        # request collated into the bin (docs/OBSERVABILITY.md).
        with telemetry.span(
            "serve/collate", request_ids=[r.request_id for r in entries]
        ):
            samples = [r.sample for r in entries]
            arena = GraphArena(samples)
            tot_nodes = int(arena.ns.sum())
            tot_edges = int(arena.es.sum())
            n_pad, e_pad, fallback = self._bucket_shape(
                tot_nodes, tot_edges, ladder
            )
            batch = arena.collate(
                np.arange(len(samples)),
                num_nodes_pad=n_pad,
                num_edges_pad=e_pad,
                num_graphs_pad=self._g_pad,
                edge_dim=self._edge_dim,
            )
        self.metrics.observe("collate", time.perf_counter() - t0)
        self.metrics.record_batch(
            len(entries), self.max_batch_graphs, tot_nodes, n_pad,
            tot_edges, e_pad,
        )
        if fallback:
            self.metrics.count("ladder_fallback_total")
        return _BatchWork(
            requests=entries,
            node_start=np.asarray(arena.node_start[:-1], dtype=np.int64),
            batch=batch,
            fallback=fallback,
        )

    def _transfer(self, work: _BatchWork):
        """DeviceFeed transfer stage: one blocking device_put per batch —
        batch k+1 commits over DMA while batch k executes."""
        import jax

        t0 = time.perf_counter()
        with telemetry.span(
            "serve/h2d", request_ids=[r.request_id for r in work.requests]
        ):
            dev = jax.device_put(work.batch)
            jax.block_until_ready(dev)
        self.metrics.observe("h2d", time.perf_counter() - t0)
        self.metrics.count(
            "h2d_bytes_total",
            sum(
                getattr(leaf, "nbytes", 0)
                for leaf in jax.tree_util.tree_leaves(work.batch)
            ),
        )
        return work, dev

    def _cache_key(
        self, bucket: Tuple[int, int, int], batch, params, bstats
    ) -> Optional[CacheKey]:
        """Persistent-store key for one bucket shape, or None when no store
        is bound (in-memory misses then skip the fingerprint arithmetic).
        The args digest covers the FULL call signature (params, batch_stats,
        batch) — host and device copies of a batch share shapes/dtypes, so
        warmup (host dummy batch) and live traffic (device batch) agree —
        and ``tree_signature`` hashes STRUCTURE, so a hot weight swap of the
        same architecture keys identically (zero recompiles, zero
        cross-architecture hits)."""
        if self._registry.store is None:
            return None
        return CacheKey.for_environment(
            program="serve_forward",
            config_fingerprint=self._config_fingerprint,
            flags=self._key_flags,
            bucket=bucket,
            args_digest=tree_signature((params, bstats, batch)),
        )

    def _executable_for(self, dev_batch, params, bstats):
        key = (
            dev_batch.num_nodes_pad,
            dev_batch.num_edges_pad,
            dev_batch.num_graphs_pad,
        )
        # The registry's single lookup path: locked in-memory get; on miss
        # (outside the lock — a 10-50 s lowering must not block submit()'s
        # pending-set bookkeeping or /healthz reads) a persistent-store
        # hydrate, then a fresh compile + store-back. The CacheKey closure
        # is evaluated on misses only — steady-state hits never pay the
        # param-tree fingerprint arithmetic.
        exe, outcome, seconds = self._registry.lookup_or_compile(
            key,
            lambda: self._cache_key(key, dev_batch, params, bstats),
            lambda: self._jit.lower(params, bstats, dev_batch),
        )
        if outcome == "memory":
            self.metrics.count("cache_hits_total")
        elif outcome == "disk":
            self.metrics.record_hydrate(seconds)
        else:
            self.metrics.record_compile(seconds)
        return exe

    def no_recompile(self, allow: int = 0, action: str = "raise"):
        """Post-warmup steady-state assertion, generalized from this engine's
        executable-cache accounting into the shared recompile sentinel
        (analysis/sentinel.py): the wrapped region must not trigger ANY XLA
        compilation — not just engine cache misses, also stray jit traffic
        from co-resident code. Load tests and the serving benchmark wrap
        their measured windows with it."""
        from ..analysis import no_recompile as _no_recompile

        return _no_recompile(
            allow=allow, action=action, label="serve steady state"
        )

    def _execute(self, dev_batch) -> Tuple[List[np.ndarray], str]:
        """Run the (cached) compiled executable; host numpy outputs plus the
        model version the batch executed against. The weight reference is
        read ONCE here, so the whole batch — and every response demuxed from
        it — belongs to exactly one version even while a swap publishes a
        new one concurrently."""
        import jax

        params, bstats, version = self._current_weights()
        exe = self._executable_for(dev_batch, params, bstats)
        t0 = time.perf_counter()
        outputs = exe(params, bstats, dev_batch)
        outputs = jax.block_until_ready(outputs)
        self.metrics.observe("device", time.perf_counter() - t0)
        return [np.asarray(o) for o in outputs], version

    def _dispatch_loop(self) -> None:
        # Explicit context handoff: the dispatcher's device spans parent to
        # this incarnation's pipeline context (docs/OBSERVABILITY.md).
        telemetry.attach(self._pipeline_ctx)
        try:
            # The batcher's shutdown marker ends the feed iteration; every
            # batch flushed before it is still executed and resolved here.
            for work, dev_batch in self._feed:
                tsan.yield_point("serve.dispatch.pre_execute")
                # _execute failures (compile, device runtime) fall through to
                # _fail: the device's health is engine-scoped. Resolution
                # failures (per-request slicing/denormalization) are
                # BATCH-scoped: fail this batch's futures, keep serving.
                with telemetry.span(
                    "serve/device",
                    request_ids=[r.request_id for r in work.requests],
                ):
                    outputs, version = self._execute(dev_batch)
                try:
                    self._resolve(work, outputs, version)
                except Exception as e:  # noqa: BLE001 — batch-scoped
                    for req in work.requests:
                        self._reject(req, e)
                    self.metrics.count("errors_total")
                    self.metrics.count("bad_batches_total")
                    self._mark_degraded(
                        "resolution_failure",
                        [r.request_id for r in work.requests],
                    )
        except BaseException as e:  # noqa: BLE001 — re-raised at callers
            self._fail(e)

    def _resolve(
        self, work: _BatchWork, outputs: List[np.ndarray], version: str
    ) -> None:
        now = time.perf_counter()
        batch_had_nonfinite = False
        for i, req in enumerate(work.requests):
            per_head: List[np.ndarray] = []
            for ihead, htype in enumerate(self.model.output_type):
                out = outputs[ihead]
                if htype == "graph":
                    val = out[i]
                else:
                    start = int(work.node_start[i])
                    val = out[start : start + req.sample.num_nodes]
                per_head.append(self._denormalize(ihead, val))
            if self._guard_outputs and any(
                not np.isfinite(v).all() for v in per_head
            ):
                # The serving reuse of the non-finite guard: THIS request
                # fails; batch-mates and the engine are unaffected.
                self.metrics.count("nonfinite_total")
                batch_had_nonfinite = True
                telemetry.event(
                    "serve/nonfinite", request_id=req.request_id
                )
                self._reject(
                    req,
                    NonFiniteOutputError(
                        "model produced non-finite outputs for this request"
                    ),
                )
                continue
            with self._lock:
                self._pending.discard(req.future)
            # Version tag BEFORE set_result: a waiter woken by the event
            # must never observe a result without its version.
            req.future.model_version = version
            req.future.set_result(per_head)
            self.metrics.observe("e2e", now - req.t_submit)
            # Demux complete: the end of the correlation trail
            # (submit → pack bin → device batch → demux → response).
            telemetry.event(
                "serve/response",
                request_id=req.request_id,
                model_version=version,
                e2e_s=round(now - req.t_submit, 6),
            )
        if batch_had_nonfinite:
            self.metrics.count("bad_batches_total")
            self._mark_degraded(
                "nonfinite_output",
                [
                    r.request_id
                    for r in work.requests
                    if r.future._error is not None
                ],
            )

    def _denormalize(self, ihead: int, value: np.ndarray) -> np.ndarray:
        if self._y_minmax is None:
            return value
        ymin = np.asarray(self._y_minmax[ihead][0])
        ymax = np.asarray(self._y_minmax[ihead][1])
        return value * (ymax - ymin) + ymin

    def _mark_degraded(self, reason: str, request_ids: Sequence[str] = ()) -> None:
        """Sticky health downgrade + a bounded transition log: /healthz
        shows WHY the engine grayed out and which correlation ids were
        involved, and the transition lands in the telemetry stream (so a
        flight-recorder dump carries it too)."""
        entry = {
            "ts_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "reason": reason,
            "request_ids": [r for r in request_ids if r][:8],
        }
        with self._lock:
            self._degraded = True
            self._degraded_events.append(entry)
        telemetry.event(
            "serve/degraded",
            reason=reason,
            request_ids=entry["request_ids"],
        )

    @property
    def degraded_events(self) -> List[dict]:
        """Locked copy of the recent degraded-state transitions (newest
        last) — the /healthz payload's ``degraded_events`` field."""
        with self._lock:
            return list(self._degraded_events)

    def _reject(self, req: _Request, exc: BaseException) -> None:
        with self._lock:
            self._pending.discard(req.future)
        req.future.set_exception(exc)

    def _fail_pending(self, exc: BaseException) -> None:
        with self._lock:
            pending, self._pending = self._pending, set()
        for fut in pending:
            fut.set_exception(exc)

    def _fail(self, exc: BaseException) -> None:
        """A worker thread died. Within the ``max_worker_restarts`` budget:
        fail the in-flight/queued requests (their work is unrecoverable),
        mark the engine degraded, and RESTART the pipeline threads — the
        engine keeps serving. Budget exhausted (or 0, the default): poison
        the engine and fail every pending future so no caller blocks forever
        (the 'never wedge the queue' contract)."""
        if isinstance(exc, EngineClosedError) or (
            self._closing.is_set() and self._error is None
        ):
            self._fail_pending(EngineClosedError("engine closed"))
            return
        self.metrics.count("errors_total")
        restartable = self._restarts_left > 0 and not self._closing.is_set()
        if not restartable:
            # Poison FIRST so concurrent submits fail fast (their post-
            # enqueue re-check sees the error) before the queue drain below.
            with self._lock:
                self._error = exc
            self._closing.set()
            # Flight-recorder trigger (docs/OBSERVABILITY.md): the last
            # thing operators get from a poisoned engine is the timeline
            # that killed it.
            telemetry.event("serve/engine_poisoned", error=repr(exc))
            telemetry.flight_dump(
                "engine_poison", extra={"error": repr(exc)}
            )
        # Tear down this incarnation's pipeline either way: stop the batcher
        # FIRST (a stale batcher racing a successor on the shared queue would
        # strand whatever it popped), then cancel + join the feed threads.
        if self._gen_stop is not None:
            self._gen_stop.set()
        if self._feed is not None:
            self._feed.close()
            self._feed.join(2.0)
        # Drain queued requests that never reached a batch. (A request
        # admitted during this window may be failed here yet still sit in
        # the queue; the successor batcher then computes it and its
        # set_result is a benign no-op over the already-failed future.)
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is not _SHUTDOWN:
                self._reject(req, exc)
        self._fail_pending(exc)
        if restartable:
            with self._lock:
                self._restarts_left -= 1
                self._degraded = True
                self._feed = None
                self._dispatcher = None
                self._degraded_events.append(
                    {
                        "ts_utc": time.strftime(
                            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
                        ),
                        "reason": "worker_restart",
                        "request_ids": [],
                        "error": repr(exc),
                    }
                )
            self.metrics.count("engine_restarts_total")
            telemetry.event("serve/engine_restart", error=repr(exc))
            self.start()

    # -------------------------------------------------------------- warmup
    def warmup(self, ladder: Optional[Sequence[Tuple[int, int]]] = None) -> int:
        """AOT-compile every (declared or constructor) ladder bucket so
        steady-state traffic never pays a compile. An explicitly passed
        ladder is MERGED into the engine's bucket ladder — a warmed shape
        _bucket_shape can never select would be wasted compile time.
        Returns the number of executables compiled."""
        if ladder:
            with self._lock:
                self._ladder = sorted(
                    set(self._ladder) | {(int(n), int(e)) for n, e in ladder}
                )
        compiled = 0
        params, bstats, _version = self._current_weights()
        # Iterate the MERGED ladder: constructor-declared buckets still cold
        # at this point must warm too, as the docstring promises. With a
        # persistent store bound, a rung found on disk HYDRATES (seconds,
        # zero XLA compiles — the replica-spin-up path docs/COMPILE_CACHE.md
        # exists for) and does not count toward the compile total.
        for n_pad, e_pad in self._current_ladder():
            key = (int(n_pad), int(e_pad), self._g_pad)
            if self._registry.get(key) is not None:
                continue
            batch = self._dummy_batch(int(n_pad), int(e_pad))
            _exe, outcome, seconds = self._registry.lookup_or_compile(
                key,
                self._cache_key(key, batch, params, bstats),
                lambda b=batch: self._jit.lower(params, bstats, b),
            )
            if outcome == "disk":
                self.metrics.record_hydrate(seconds)
            elif outcome == "compiled":
                self.metrics.record_compile(seconds)
                compiled += 1
        return compiled

    def _dummy_batch(self, n_pad: int, e_pad: int):
        """Structurally-real batch of one 1-node graph at the given pads —
        shape/dtype/pytree-identical to live traffic's batches."""
        s = GraphSample(
            x=np.zeros((1, self.model.input_dim), np.float32),
            pos=np.zeros((1, 3), np.float32),
            edge_index=np.zeros((2, 1), np.int32),
            edge_attr=np.zeros((1, max(self._edge_dim, 1)), np.float32)
            if self._edge_dim
            else None,
        )
        return GraphArena([s]).collate(
            np.array([0]),
            num_nodes_pad=n_pad,
            num_edges_pad=e_pad,
            num_graphs_pad=self._g_pad,
            edge_dim=self._edge_dim,
        )

    # ------------------------------------------------------ hot ladder swap
    def swap_ladder(
        self, ladder: Sequence[Tuple[int, int]], warm: bool = True
    ) -> Dict[str, Any]:
        """Atomic, per-request-consistent hot bucket-ladder swap — the data
        loop's analog of :meth:`swap_weights` (flywheel drift-refit,
        docs/FLYWHEEL.md).

        ``warm=True`` (the default, and what the flywheel uses) compiles or
        hydrates every rung of the NEW ladder through the shared executable
        registry BEFORE publishing, on the calling thread — so the batcher
        never selects a cold rung and rungs the old ladder already compiled
        (or a previous process persisted to the graftcache store) publish
        with ZERO XLA compiles. The publish itself rebinds the single sorted
        ladder reference under the engine lock; the batcher snapshots that
        reference once per flush, so every request is planned entirely
        against one ladder — no torn flush, no dropped request.

        Old-ladder executables stay in the registry (memory + store): a
        rollback swap re-publishes them without compiling, and oversized
        in-flight traffic still resolves through the pow2 fallback.

        Returns {ladder, previous, compiled, hydrated, wall_s}.
        """
        new = sorted({(int(n), int(e)) for n, e in ladder})
        if not new:
            raise ValueError(
                "swap_ladder needs at least one (N_pad, E_pad) rung"
            )
        if self._error is not None:
            raise EngineFailedError(
                "inference worker died; engine must be rebuilt"
            ) from self._error
        if self._closing.is_set():
            raise EngineClosedError("engine is shut down")
        t0 = time.perf_counter()
        compiled = hydrated = 0
        # Same whole-swap mutex as weight swaps: a ladder swap racing a
        # weight swap must warm against a settled weight reference, and two
        # ladder swaps must publish in a total order.
        with self._swap_lock:
            if warm:
                params, bstats, _version = self._current_weights()
                for n_pad, e_pad in new:
                    key = (n_pad, e_pad, self._g_pad)
                    if self._registry.get(key) is not None:
                        continue
                    batch = self._dummy_batch(n_pad, e_pad)
                    _exe, outcome, seconds = self._registry.lookup_or_compile(
                        key,
                        self._cache_key(key, batch, params, bstats),
                        lambda b=batch: self._jit.lower(params, bstats, b),
                    )
                    if outcome == "disk":
                        self.metrics.record_hydrate(seconds)
                        hydrated += 1
                    elif outcome == "compiled":
                        self.metrics.record_compile(seconds)
                        compiled += 1
            # Annotated interleaving site: the publish races the batcher's
            # per-flush snapshot — the tsan flywheel drill perturbs exactly
            # this window (benchmarks/tsan_drill.py _flywheel_drill).
            tsan.yield_point("serve.ladder.pre_publish")
            with self._lock:
                previous = self._ladder
                self._ladder = new
        wall = time.perf_counter() - t0
        self.metrics.count("ladder_swaps_total")
        telemetry.event(
            "serve/ladder_swapped",
            rungs=len(new),
            compiled=compiled,
            hydrated=hydrated,
            wall_s=round(wall, 4),
        )
        return {
            "ladder": [list(r) for r in new],
            "previous": [list(r) for r in previous],
            "compiled": compiled,
            "hydrated": hydrated,
            "wall_s": round(wall, 4),
        }

    # ------------------------------------------------------ hot weight swap
    def swap_weights(self, variables: Dict[str, Any], version: str) -> Dict[str, Any]:
        """Atomic, per-request-consistent hot weight swap (docs/SERVING.md
        "Live model lifecycle"; ROADMAP item 4).

        Validates the incoming param-tree fingerprint against the tree the
        compiled executables take as arguments — a mismatch raises
        :class:`SwapFingerprintError` and the engine KEEPS SERVING its
        current weights. On a match, the new ``(params, batch_stats,
        version)`` triple is published as one reference under the engine
        lock: every in-flight batch executes entirely against one version
        (the dispatch thread reads the reference once per batch), versions
        observed by responses are monotonic, and — because ``CacheKey`` /
        ``tree_signature`` fingerprint the param TREE, not the values —
        every compiled bucket is reused with ZERO recompiles.

        Quantized arms (``precision != 'f32'``) re-apply their transform to
        the incoming f32 variables (int8 re-snaps the weight grid) and
        RE-RUN the PR-11 tolerance gate on the CANDIDATE weights before they
        publish; a gate failure raises :class:`PrecisionToleranceError` with
        the engine untouched — a candidate that cannot meet the declared
        bound never serves a single request. On success the new f32
        reference is retained for future gates.

        Returns a small report: {version, previous_version, wall_s, gate}.
        """
        import jax

        from ..checkpoint.format import param_fingerprint
        from ..precision import fake_quantize_params

        if self._error is not None:
            raise EngineFailedError(
                "inference worker died; engine must be rebuilt"
            ) from self._error
        if self._closing.is_set():
            raise EngineClosedError("engine is shut down")
        t0 = time.perf_counter()
        # Whole-swap mutex: concurrent swaps (a promote racing a rollback)
        # must validate against, gate against, and replace the SAME
        # predecessor in a total order — and the quantized-arm reference
        # state must always describe the published weights.
        with self._swap_lock:
            old_params, old_bstats, old_version = self._current_weights()
            want = param_fingerprint(old_params) + param_fingerprint(
                old_bstats
            )
            got = param_fingerprint(variables["params"]) + param_fingerprint(
                variables.get("batch_stats", {})
            )
            if got != want:
                self.metrics.count("swap_rejected_total")
                telemetry.event(
                    "serve/swap_rejected",
                    version=str(version),
                    reason="param-tree fingerprint mismatch",
                )
                raise SwapFingerprintError(
                    f"swap to version {version!r} rejected: its param-tree "
                    "fingerprint does not match the serving architecture — "
                    "the engine keeps serving version "
                    f"{old_version!r} (rebuild the engine for an "
                    "architecture change; a hot swap is weights-only)"
                )
            serve_params = variables["params"]
            quant_report = None
            if self.precision == "int8":
                serve_params, quant_report = fake_quantize_params(
                    serve_params
                )
            params = jax.device_put(serve_params)
            bstats = jax.device_put(variables.get("batch_stats", {}))
            jax.block_until_ready((params, bstats))
            gate_report = None
            if self.precision != "f32":
                # The tolerance gate runs on the CANDIDATE weights BEFORE
                # they publish: a candidate that cannot meet its declared
                # bound must never serve a single live request (and response
                # versions stay monotonic — no publish-then-revert flicker).
                try:
                    gate_report = self._tolerance_gate(
                        params, bstats, variables, quant_report
                    )
                except PrecisionToleranceError:
                    self.metrics.count("swap_gate_failures_total")
                    telemetry.event(
                        "serve/swap_gate_failed", version=str(version)
                    )
                    raise
            # Annotated interleaving site: the publish races the dispatch
            # thread's per-batch read — the tsan swap drill perturbs exactly
            # this window (benchmarks/tsan_drill.py _swap_drill).
            tsan.yield_point("serve.swap.pre_publish")
            with self._lock:
                self._weights = (params, bstats, str(version))
            if self.precision != "f32":
                self._ref_variables = variables
                if quant_report is not None:
                    self._quant_report = quant_report
        wall = time.perf_counter() - t0
        self.metrics.count("weight_swaps_total")
        telemetry.event(
            "serve/weights_swapped",
            version=str(version),
            previous_version=old_version,
            wall_s=round(wall, 4),
        )
        return {
            "version": str(version),
            "previous_version": old_version,
            "wall_s": round(wall, 4),
            "gate": gate_report,
        }

    def restore_weights(self, weights: Tuple[Any, Any, str]) -> None:
        """Republish a triple previously read from :meth:`_current_weights`
        — the manager's mid-fleet unwind (a swap that failed on replica k
        must not leave replicas 0..k-1 serving a version the registry never
        promoted). No fingerprint or gate re-run: the triple already served
        on this engine."""
        with self._swap_lock:
            with self._lock:
                self._weights = weights
        telemetry.event("serve/weights_restored", version=weights[2])

    # ------------------------------------------------------- tolerance gate
    def _calibration_samples(
        self, count: int = 4, seed: int = 0
    ) -> List[GraphSample]:
        """Deterministic random calibration graphs at the model's feature
        widths — the default probe batch for :meth:`check_tolerance` when the
        operator brings no representative samples. Seeded: the gate verdict
        is reproducible across restarts/replicas."""
        rng = np.random.default_rng(seed)
        out = []
        for _ in range(count):
            n = int(rng.integers(4, 9))
            ei = np.stack(
                [np.arange(n), (np.arange(n) + 1) % n]
            ).astype(np.int32)
            ei = np.concatenate([ei, ei[::-1]], axis=1)
            out.append(
                GraphSample(
                    x=rng.normal(size=(n, self.model.input_dim)).astype(
                        np.float32
                    ),
                    pos=np.zeros((n, 3), np.float32),
                    edge_index=ei,
                    edge_attr=rng.normal(
                        size=(ei.shape[1], self._edge_dim)
                    ).astype(np.float32)
                    if self._edge_dim
                    else None,
                )
            )
        return out

    def check_tolerance(self, samples: Optional[Sequence[GraphSample]] = None):
        """The quantized-arm gate (docs/PRECISION.md): collate one probe
        batch, run it through BOTH the serving executable (bf16/int8) and a
        retained f32 reference forward, and compare with the shared tolerance
        machinery (precision/tolerance.py — the same helpers certify_pallas
        gates kernels with). Within the bound: returns the verdict report
        (also folded into ``hydragnn_serve_precision_*`` metrics). Beyond it:
        raises :class:`PrecisionToleranceError` — a quantized arm that cannot
        meet its declared tolerance must not take traffic.

        ``precision="f32"`` returns a trivial verdict: the f32 contract is
        bit-exactness against ``run_prediction`` (tests/test_serve_engine.py),
        not a tolerance."""
        if self.precision == "f32":
            return {
                "ok": True,
                "arm": "f32",
                "note": "bit-exactness contract — no tolerance gate",
            }
        # Consistent (weights, reference) pair: a swap completing after this
        # read yields a stale-but-self-consistent verdict, never a mixed one.
        with self._swap_lock:
            params, bstats, _version = self._current_weights()
            ref_vars = self._ref_variables
            quant_report = self._quant_report
        return self._tolerance_gate(params, bstats, ref_vars, quant_report, samples)

    def _tolerance_gate(
        self,
        params,
        bstats,
        ref_vars,
        quant_report,
        samples: Optional[Sequence[GraphSample]] = None,
    ):
        """The gate body over EXPLICIT weights + reference: shared by
        :meth:`check_tolerance` (the live weights) and :meth:`swap_weights`
        (candidate weights BEFORE they publish — a failing candidate must
        never serve a single live request)."""
        import jax

        from ..precision import tolerance_report
        from ..train.trainer import _apply_model

        if samples is None:
            samples = self._calibration_samples()
        else:
            samples = list(samples)
            if not samples:
                # An empty probe set is an upstream bug, not a request for
                # synthetic calibration — a verdict must never claim coverage
                # of data it did not see.
                raise ValueError(
                    "check_tolerance received an empty sample sequence; pass "
                    "None for the seeded synthetic calibration batch"
                )
        for s in samples:
            self._validate(s)
        arena = GraphArena(samples)
        n_pad, e_pad, _ = self._bucket_shape(
            int(arena.ns.sum()), int(arena.es.sum())
        )
        batch = arena.collate(
            np.arange(len(samples)),
            num_nodes_pad=n_pad,
            num_edges_pad=e_pad,
            num_graphs_pad=self._g_pad,
            edge_dim=self._edge_dim,
        )
        dev = jax.device_put(batch)
        quant = [
            np.asarray(o)
            for o in jax.block_until_ready(self._jit(params, bstats, dev))
        ]
        ref_model = self._ref_model
        assert ref_model is not None and ref_vars is not None
        ref_fn = jax.jit(
            lambda p, b, x: _apply_model(ref_model, p, b, x, train=False)
        )
        reference = [
            np.asarray(o)
            for o in jax.block_until_ready(
                ref_fn(
                    ref_vars["params"], ref_vars.get("batch_stats", {}), dev
                )
            )
        ]
        report = tolerance_report(
            quant, reference, self.tolerance, names=self.head_names
        )
        report["arm"] = self.precision
        report["probe_graphs"] = len(samples)
        if quant_report is not None:
            report["quantization"] = quant_report
        self.metrics.record_precision_gate(report)
        telemetry.event(
            "serve/precision_gate",
            arm=self.precision,
            ok=report["ok"],
            fwd_err=report["fwd_err"],
            tol=report["tol"],
        )
        if not report["ok"]:
            raise PrecisionToleranceError(
                f"{self.precision} arm diverges from the f32 reference by "
                f"{report['fwd_err']:.3e} (> tolerance {self.tolerance:g})",
                report,
            )
        return report

    # ------------------------------------------------------- checkpoint load
    @classmethod
    def from_config(
        cls,
        config,
        checkpoint: Optional[str] = None,
        checkpoint_format: str = "auto",
        logs_path: str = "./logs/",
        **options,
    ) -> "InferenceEngine":
        """Build an engine from a COMPLETED config (the snapshot
        ``run_training`` writes to ``logs/<name>/config.json`` — it must
        already carry input_dim/output_dim/output_type/pna_deg etc., since
        serving has no datasets to re-run config completion against).

        ``checkpoint`` is a path to either a native flax checkpoint
        (utils/model.save_model payload) or a reference torch ``.pk``
        (mapped through utils/torch_import); ``"auto"`` sniffs the format.
        ``checkpoint=None`` restores this framework's own
        ``logs/<log_name>/<log_name>.pk`` derived from the config. For torch
        checkpoints with ``num_sharedlayers > 1`` the model is built with the
        reference shared-MLP activation layout (models/layers.MLP
        ``inner_activation=False``) so imported forwards are exact.
        """
        from ..models.create import create_model_config, init_model_variables, make_example_batch
        from ..utils.config_utils import get_log_name_config
        from ..utils.model import load_checkpoint_file, load_existing_model

        if isinstance(config, str):
            with open(config) as f:
                config = json.load(f)
        arch = dict(config["NeuralNetwork"]["Architecture"])
        for required in ("input_dim", "output_dim", "output_type"):
            if required not in arch:
                raise ValueError(
                    f"config is not completed (missing Architecture."
                    f"{required}) — pass the logs/<name>/config.json "
                    "snapshot run_training wrote, not the raw input config"
                )

        fmt = checkpoint_format
        if fmt == "auto":
            fmt = "native" if checkpoint is None else cls._sniff_format(checkpoint)
        if fmt not in ("native", "torch"):
            raise ValueError(f"unknown checkpoint_format {fmt!r}")
        if fmt == "torch" and checkpoint is None:
            raise ValueError(
                "checkpoint_format='torch' requires an explicit checkpoint "
                "path (--ckpt); only native checkpoints can be derived from "
                "the config's log name"
            )
        if fmt == "torch":
            # The reference's shared-MLP Sequential has no ReLU between its
            # shared Linears; build the model with that exact layout so the
            # imported checkpoint serves bit-faithful outputs.
            heads = json.loads(json.dumps(arch["output_heads"]))
            if "graph" in heads:
                heads["graph"]["shared_layout"] = "reference"
            arch["output_heads"] = heads

        model = create_model_config(config=arch, verbosity=0)
        example = make_example_batch(
            arch["input_dim"],
            arch["output_dim"],
            arch["output_type"],
            edge_dim=arch.get("edge_dim"),
            num_nodes=arch.get("num_nodes") or 4,
        )
        variables = init_model_variables(model, example)

        if fmt == "torch":
            from ..utils.torch_import import import_torch_checkpoint

            variables, report = import_torch_checkpoint(
                checkpoint, model, variables
            )
            if report["caveats"]:
                raise ValueError(
                    "torch checkpoint import is not exact for this config: "
                    + "; ".join(report["caveats"])
                )
        elif checkpoint is None:
            name = get_log_name_config(config)
            variables, _ = load_existing_model(variables, name, path=logs_path)
        else:
            variables, _, _ = load_checkpoint_file(variables, checkpoint)

        voi = config["NeuralNetwork"].get("Variables_of_interest", {})
        options.setdefault("head_names", voi.get("output_names"))
        if voi.get("denormalize_output") and voi.get("y_minmax"):
            options.setdefault("y_minmax", voi["y_minmax"])
        if "model_version" not in options:
            # The lifecycle layer's per-response version tag defaults to the
            # checkpoint's verified content identity (short form) so a
            # config-booted replica reports the same version id a
            # ModelRegistry would assign. v1/torch checkpoints carry no
            # verifiable identity — labeled, never guessed.
            if fmt == "native":
                path_name = checkpoint or os.path.join(
                    logs_path,
                    get_log_name_config(config),
                    get_log_name_config(config) + ".pk",
                )
                try:
                    from ..checkpoint.format import file_content_identity

                    options["model_version"] = file_content_identity(
                        path_name
                    )[0][:12]
                except Exception:  # noqa: BLE001 — v1 pickle, fallback load
                    options["model_version"] = "unverified"
            else:
                options["model_version"] = "torch-import"
        return cls(model, variables, **options)

    @staticmethod
    def _sniff_format(path: str) -> str:
        """Native v2 checkpoints carry the HGNN2 magic (sniffed WITHOUT
        executing any deserializer); torch.save writes a zip archive (PK
        magic). Legacy native v1 files are a plain pickle of
        {"params": bytes, ...} — the one remaining pickle sniff, kept through
        the v1 read-compat window (docs/CHECKPOINTING.md "Migration")."""
        from ..checkpoint import MAGIC

        try:
            with open(path, "rb") as f:
                head = f.read(max(len(MAGIC), 2))
        except OSError:
            return "torch"
        if head[: len(MAGIC)] == MAGIC:
            return "native"
        if head[:2] == b"PK":  # zip archive: torch.save
            return "torch"
        try:
            with open(path, "rb") as f:
                # graftlint: disable=pickle-load-outside-compat(format sniffer for v1 legacy checkpoints — classification only, result discarded, errors swallowed)
                payload = pickle.load(f)
            if isinstance(payload, dict) and "params" in payload:
                return "native"
        except Exception:
            pass
        return "torch"


# --------------------------------------------------------- checkpoint hot swap
def swap_from_checkpoint(
    engine: InferenceEngine,
    path: str,
    version: Optional[str] = None,
    expected_identity: Optional[str] = None,
) -> Dict[str, Any]:
    """Load a v2 checkpoint FILE and hot-swap it into ``engine`` — the shared
    implementation behind the ``/swap`` admin endpoint (serve/server.py) and
    ``Replica.swap_checkpoint`` (route/replica.py), so ``LifecycleManager``
    can drive spawned HTTP replicas with the exact semantics of an
    in-process ``engine.swap_weights`` (docs/SERVING.md "Live model
    lifecycle").

    ONE read: the bytes whose content identity is computed are the bytes
    deserialized (``checkpoint.io.load_checkpoint_bytes`` — the TOCTOU-free
    candidate-load contract from graftswap). ``expected_identity``, when
    given, must match the file's full content identity — the caller's staged
    version and the weights that publish provably attest the same bytes.
    ``version`` defaults to the identity's 12-hex short form (the registry's
    display convention). Returns the swap report plus ``identity``/``epoch``.
    """
    from ..checkpoint.format import content_identity
    from ..checkpoint.io import load_checkpoint_bytes

    with open(path, "rb") as f:
        blob = f.read()
    identity, _details = content_identity(blob, path)
    if expected_identity and identity != expected_identity:
        raise SwapIdentityError(
            f"{path}: content identity {identity[:12]} does not match the "
            f"expected {expected_identity[:12]} — the file changed since it "
            "was staged; the engine keeps serving its current version"
        )
    variables, _opt, meta = load_checkpoint_bytes(
        engine.variables_template(), blob, path
    )
    report = engine.swap_weights(variables, version or identity[:12])
    report["identity"] = identity
    report["epoch"] = (meta or {}).get("epoch")
    return report
