"""CLI entry: ``python -m hydragnn_tpu.serve --config ... [--ckpt ...]``.

Loads a checkpoint (native or reference-torch), optionally warms the bucket
ladder, and serves /predict, /healthz, /metrics until interrupted.

``python -m hydragnn_tpu.serve router ...`` starts the multi-replica front
router instead (hydragnn_tpu/route/, docs/SERVING.md "Multi-replica tier").

``python -m hydragnn_tpu.serve batch ...`` runs offline batch inference over
a GSHD corpus — streams shards through the packed bucket ladder and writes
digest-verified prediction shards (serve/batch.py, docs/DATA_PLANE.md).
"""

from __future__ import annotations

import argparse
import sys

from .engine import InferenceEngine
from .server import InferenceServer


def parse_ladder(spec: str, max_rungs: int = 4):
    """--bucket-ladder "512x4096,1024x8192" → [(512, 4096), (1024, 8192)];
    --bucket-ladder auto:<path> loads a fitted ladder JSON or fits one from
    a size-histogram JSON now (graphs/packing.resolve_ladder_spec)."""
    from ..graphs.packing import resolve_ladder_spec

    return resolve_ladder_spec(spec, max_rungs=max_rungs)


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m hydragnn_tpu.serve",
        description="Online inference server for HydraGNN checkpoints.",
    )
    ap.add_argument(
        "--config",
        required=True,
        help="COMPLETED config JSON (the logs/<name>/config.json snapshot)",
    )
    ap.add_argument(
        "--ckpt",
        default=None,
        help="checkpoint path (native .pk or reference torch .pk); default: "
        "the config-derived logs/<log_name>/<log_name>.pk",
    )
    ap.add_argument(
        "--ckpt-format",
        choices=("auto", "native", "torch"),
        default="auto",
    )
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--max-batch-graphs", type=int, default=32)
    ap.add_argument("--max-delay-ms", type=float, default=5.0)
    ap.add_argument("--queue-limit", type=int, default=256)
    ap.add_argument(
        "--bucket-ladder",
        default="",
        help='comma-separated "NxE" padded shapes, e.g. "512x4096,1024x8192", '
        'or "auto:<path>" where <path> is a size-histogram JSON '
        "(logs/<name>/size_histogram.json, SERVE_rNN_hist.json) or a "
        "fit-ladder output JSON; compiled at startup unless --no-warmup",
    )
    ap.add_argument(
        "--max-ladder-rungs",
        type=int,
        default=4,
        help="compile budget when --bucket-ladder auto: fits from a "
        "histogram (ignored for literal and pre-fitted ladders)",
    )
    ap.add_argument(
        "--packing",
        action="store_true",
        help="bin-pack each flushed micro-batch under the top ladder rung "
        "(first-fit-decreasing) so over-capacity flushes split into "
        "tightest-rung bins instead of falling back to a worst-case shape",
    )
    ap.add_argument(
        "--ladder-step",
        choices=("pow2", "mult64"),
        default="pow2",
        help="round-up ladder for shapes that miss the bucket ladder: "
        "mult64 pads a 520-node batch to 576 instead of 1024",
    )
    ap.add_argument(
        "--precision",
        choices=("f32", "bf16", "int8"),
        default="f32",
        help="serving arm (docs/PRECISION.md): f32 keeps the bit-exactness "
        "contract; bf16 runs the forward in bf16 compute; int8 additionally "
        "quantizes weight matrices to a per-tensor symmetric int8 grid. "
        "Quantized arms require --tolerance and pass a startup gate against "
        "an f32 reference before taking traffic",
    )
    ap.add_argument(
        "--tolerance",
        type=float,
        default=None,
        metavar="MAX_ABS_DIFF",
        help="max absolute output divergence from the f32 reference the "
        "quantized arm may show (required with --precision bf16|int8; "
        "invalid with f32)",
    )
    ap.add_argument("--no-warmup", action="store_true")
    ap.add_argument(
        "--compile-cache",
        default=None,
        metavar="DIR",
        help="persistent compiled-executable store (graftcache, docs/"
        "COMPILE_CACHE.md): warmup hydrates the ladder's executables "
        "from DIR instead of recompiling — a restarted replica is warm "
        "in seconds; fresh compiles are serialized back. Default: the "
        "HYDRAGNN_COMPILE_CACHE env var (unset = no persistence)",
    )
    ap.add_argument(
        "--max-worker-restarts",
        type=int,
        default=1,
        help="fatal worker errors tolerated by restarting the pipeline "
        "(degraded, keeps serving) before the engine poisons; 0 = poison "
        "on the first (docs/FAULT_TOLERANCE.md)",
    )
    ap.add_argument(
        "--no-output-guard",
        action="store_true",
        help="disable the non-finite output guard (NaN outputs then return "
        "as 200s instead of failing the request)",
    )
    ap.add_argument(
        "--replica-id",
        default=None,
        metavar="NAME",
        help="label this serve process as one replica of a routed fleet: "
        "echoed as the X-HydraGNN-Replica response header and in /healthz "
        "so the router's hop logs and health map name it (docs/SERVING.md "
        '"Multi-replica tier")',
    )
    ap.add_argument(
        "--admin",
        action="store_true",
        help="enable the POST /swap admin endpoint so a LifecycleManager "
        "can hot-swap this replica's weights from a (shared-storage) "
        "checkpoint path — fleet-wide swap orchestration for spawned HTTP "
        'replicas (docs/SERVING.md "Live model lifecycle")',
    )
    ap.add_argument("--verbose", action="store_true")
    return ap


def build_batch_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m hydragnn_tpu.serve batch",
        description="Offline batch inference over a GSHD streaming corpus.",
    )
    ap.add_argument("--config", required=True,
                    help="COMPLETED config JSON (logs/<name>/config.json)")
    ap.add_argument("--ckpt", default=None)
    ap.add_argument("--ckpt-format", choices=("auto", "native", "torch"),
                    default="auto")
    ap.add_argument("--dataset", required=True,
                    help="GSHD dataset directory (or its manifest JSON)")
    ap.add_argument("--out", required=True,
                    help="output directory for prediction shards + manifest")
    ap.add_argument("--chunk-size", type=int, default=64,
                    help="graphs per predict() call (default 64)")
    ap.add_argument("--limit", type=int, default=None,
                    help="stop after N samples (spot-check a campaign)")
    ap.add_argument("--skip-budget", type=int, default=0,
                    help="corrupt input shards tolerated (skipped loudly)")
    ap.add_argument("--max-batch-graphs", type=int, default=32)
    ap.add_argument("--max-delay-ms", type=float, default=0.0,
                    help="micro-batch flush delay; 0 = flush greedily "
                    "(offline work has no latency SLO)")
    ap.add_argument("--queue-limit", type=int, default=256)
    ap.add_argument("--bucket-ladder", default="")
    ap.add_argument("--max-ladder-rungs", type=int, default=4)
    ap.add_argument("--packing", action="store_true")
    ap.add_argument("--ladder-step", choices=("pow2", "mult64"),
                    default="pow2")
    ap.add_argument("--compile-cache", default=None, metavar="DIR")
    ap.add_argument("--no-warmup", action="store_true")
    return ap


def batch_main(argv) -> int:
    args = build_batch_parser().parse_args(argv)
    from ..analysis.contracts import gate_config

    ladder = (
        parse_ladder(args.bucket_ladder, max_rungs=args.max_ladder_rungs)
        if args.bucket_ladder
        else None
    )
    gate_config(args.config, mode="serving", bucket_ladder=ladder)
    engine = InferenceEngine.from_config(
        args.config,
        checkpoint=args.ckpt,
        checkpoint_format=args.ckpt_format,
        max_batch_graphs=args.max_batch_graphs,
        max_delay_ms=args.max_delay_ms,
        queue_limit=args.queue_limit,
        bucket_ladder=ladder,
        warmup=not args.no_warmup,
        packing=args.packing,
        ladder_step=args.ladder_step,
        compile_cache=args.compile_cache,
    )
    from .batch import run_batch_inference

    try:
        manifest = run_batch_inference(
            engine,
            args.dataset,
            args.out,
            chunk_size=args.chunk_size,
            limit=args.limit,
            skip_budget=args.skip_budget,
        )
    finally:
        engine.close()
    gps = manifest["graphs_per_sec"]
    print(
        f"batch inference: {manifest['num_samples']} graphs in "
        f"{manifest['wall_s']:.2f}s "
        f"({gps:.1f} graphs/s)" if gps else "batch inference: 0 graphs",
        flush=True,
    )
    if manifest["skipped_shards"]:
        print(f"skipped corrupt shards: {len(manifest['skipped_shards'])}")
    return 0


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "router":
        # The front-router subcommand (hydragnn_tpu/route/__main__.py):
        # one CLI surface for both the single engine and the fleet.
        from ..route.__main__ import main as router_main

        return router_main(argv[1:])
    if argv and argv[0] == "batch":
        return batch_main(argv[1:])
    args = build_parser().parse_args(argv)
    # Static contract gate (docs/STATIC_ANALYSIS.md): a broken completed
    # config or an infeasible/unparseable bucket ladder — including the
    # auto:<path> form — is one actionable line at startup, not a mid-warmup
    # stack trace after the checkpoint loaded. The spec is resolved ONCE,
    # with the CLI's rung budget, and the checker validates the rungs that
    # will actually deploy; only when resolution itself fails does the RAW
    # spec go to the checker, whose own resolution failure becomes the
    # actionable oob-bucket line.
    from ..analysis.contracts import gate_config

    ladder = None
    parse_error = None
    if args.bucket_ladder:
        try:
            ladder = parse_ladder(
                args.bucket_ladder, max_rungs=args.max_ladder_rungs
            )
        except Exception as e:  # noqa: BLE001 — checker diagnoses it below
            parse_error = e
    gate_config(
        args.config,
        mode="serving",
        bucket_ladder=ladder
        if ladder is not None
        else (args.bucket_ladder or None),
        serve_precision=args.precision,
        serve_tolerance=args.tolerance,
    )
    if parse_error is not None:
        # The gate normally turns a bad spec into one actionable oob-bucket
        # line — but it honors HYDRAGNN_CHECK_CONFIG=off. An explicit
        # operator flag must never be silently dropped, so if the gate let
        # the broken spec through, the original parse failure still aborts.
        raise parse_error
    # graftel (docs/OBSERVABILITY.md): point the flight recorder at the
    # run's log dir so an engine poisoning dumps its timeline next to the
    # checkpoint it served.
    import json as _json
    import os as _os

    from .. import telemetry
    from ..utils.config_utils import get_log_name_config

    try:
        with open(args.config) as f:
            _cfg = _json.load(f)
        telemetry.configure(
            run_dir=_os.path.join("./logs", get_log_name_config(_cfg))
        )
    except (OSError, ValueError, KeyError):
        pass  # from_config reports config problems with better messages
    engine = InferenceEngine.from_config(
        args.config,
        checkpoint=args.ckpt,
        checkpoint_format=args.ckpt_format,
        max_batch_graphs=args.max_batch_graphs,
        max_delay_ms=args.max_delay_ms,
        queue_limit=args.queue_limit,
        bucket_ladder=ladder,
        warmup=not args.no_warmup,
        packing=args.packing,
        ladder_step=args.ladder_step,
        max_worker_restarts=args.max_worker_restarts,
        guard_outputs=not args.no_output_guard,
        compile_cache=args.compile_cache,
        precision=args.precision,
        tolerance=args.tolerance,
    )
    if args.precision != "f32":
        # The quantized arm's startup gate (docs/PRECISION.md): compare the
        # serving executable against the retained f32 reference on a seeded
        # probe batch BEFORE taking traffic — a PrecisionToleranceError here
        # aborts startup with the full per-head verdict.
        report = engine.check_tolerance()
        print(
            f"precision gate: arm={args.precision} "
            f"max_abs_diff={report['fwd_err']:.3e} "
            f"tolerance={args.tolerance:g} ok={report['ok']}",
            flush=True,
        )
    server = InferenceServer(
        engine,
        host=args.host,
        port=args.port,
        verbose=args.verbose,
        replica_id=args.replica_id,
        enable_admin=args.admin,
    )
    print(
        f"hydragnn_tpu.serve listening on http://{server.host}:{server.port} "
        f"(buckets compiled: {engine.compiled_buckets})",
        flush=True,
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
