"""CLI entry: ``python -m hydragnn_tpu.serve --config ... [--ckpt ...]``.

Loads a checkpoint (native or reference-torch), optionally warms the bucket
ladder, and serves /predict, /healthz, /metrics until interrupted.
"""

from __future__ import annotations

import argparse
import sys

from .engine import InferenceEngine
from .server import InferenceServer


def parse_ladder(spec: str):
    """--bucket-ladder "512x4096,1024x8192" → [(512, 4096), (1024, 8192)]."""
    ladder = []
    for part in filter(None, (p.strip() for p in spec.split(","))):
        n, e = part.split("x")
        ladder.append((int(n), int(e)))
    return ladder


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m hydragnn_tpu.serve",
        description="Online inference server for HydraGNN checkpoints.",
    )
    ap.add_argument(
        "--config",
        required=True,
        help="COMPLETED config JSON (the logs/<name>/config.json snapshot)",
    )
    ap.add_argument(
        "--ckpt",
        default=None,
        help="checkpoint path (native .pk or reference torch .pk); default: "
        "the config-derived logs/<log_name>/<log_name>.pk",
    )
    ap.add_argument(
        "--ckpt-format",
        choices=("auto", "native", "torch"),
        default="auto",
    )
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--max-batch-graphs", type=int, default=32)
    ap.add_argument("--max-delay-ms", type=float, default=5.0)
    ap.add_argument("--queue-limit", type=int, default=256)
    ap.add_argument(
        "--bucket-ladder",
        default="",
        help='comma-separated "NxE" padded shapes, e.g. "512x4096,1024x8192"; '
        "compiled at startup unless --no-warmup",
    )
    ap.add_argument("--no-warmup", action="store_true")
    ap.add_argument(
        "--max-worker-restarts",
        type=int,
        default=1,
        help="fatal worker errors tolerated by restarting the pipeline "
        "(degraded, keeps serving) before the engine poisons; 0 = poison "
        "on the first (docs/FAULT_TOLERANCE.md)",
    )
    ap.add_argument(
        "--no-output-guard",
        action="store_true",
        help="disable the non-finite output guard (NaN outputs then return "
        "as 200s instead of failing the request)",
    )
    ap.add_argument("--verbose", action="store_true")
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    ladder = parse_ladder(args.bucket_ladder) if args.bucket_ladder else None
    # Static contract gate (docs/STATIC_ANALYSIS.md): a broken completed
    # config or an infeasible bucket ladder is one actionable line at
    # startup, not a mid-warmup stack trace after the checkpoint loaded.
    from ..analysis.contracts import gate_config

    gate_config(args.config, mode="serving", bucket_ladder=ladder)
    engine = InferenceEngine.from_config(
        args.config,
        checkpoint=args.ckpt,
        checkpoint_format=args.ckpt_format,
        max_batch_graphs=args.max_batch_graphs,
        max_delay_ms=args.max_delay_ms,
        queue_limit=args.queue_limit,
        bucket_ladder=ladder,
        warmup=not args.no_warmup,
        max_worker_restarts=args.max_worker_restarts,
        guard_outputs=not args.no_output_guard,
    )
    server = InferenceServer(
        engine, host=args.host, port=args.port, verbose=args.verbose
    )
    print(
        f"hydragnn_tpu.serve listening on http://{server.host}:{server.port} "
        f"(buckets compiled: {len(engine._executables)})",
        flush=True,
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
