"""Compiled train/eval steps. THE distribution contract (SURVEY.md §7 pillar 2):
there is no DDP wrapper object — data parallelism is a psum inside the
shard_map-compiled step over the 'data' mesh axis, replacing the reference's
DistributedDataParallel + NCCL allreduce (/root/reference/hydragnn/utils/
distributed.py:216-226, gradient sync at train_validate_test.py:231).

Two step flavors:
  * make_train_step(model, opt)            — single-device jit.
  * make_train_step_dp(model, opt, mesh)   — batch stacked [D, ...] over the
    'data' axis; grads/metrics psum'd over ICI. Eval metrics are also reduced
    (fixing the reference's per-rank-only eval metrics, SURVEY.md §3.4).

Metrics are returned as (weighted sum, count) pairs so the host can form
graph-count-weighted epoch averages exactly like the reference's
loss.item()*num_graphs accumulation (train_validate_test.py:234-237).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax.sharding import PartitionSpec as P

from ..graphs.batch import GraphBatch
from ..models.base import HydraGNN
from ..models.loss import multihead_rmse_loss
from ..ops.pallas_segment import pallas_platform


def _mesh_platform(mesh) -> str:
    """Platform of the devices a mesh's step will execute on — what the Pallas
    gating must key off (jax.default_backend() lies when a TPU-attached host
    traces a step for a CPU-device mesh)."""
    return next(iter(mesh.devices.flat)).platform


@struct.dataclass
class TrainState:
    params: Any
    batch_stats: Any
    opt_state: Any
    step: jnp.ndarray
    # Dynamic loss-scale state (precision/policy.LossScaleState) — present
    # only under Training.precision="bf16"; None is an empty pytree subtree,
    # so the f32 state (and every compiled f32 program) is unchanged.
    loss_scale: Any = None


def create_train_state(model, variables, optimizer) -> TrainState:
    # init() on a COPY of params: optimizers that store the params pytree in
    # their state (optax.lbfgs memory) would otherwise alias params buffers,
    # and the donating train steps may not donate the same buffer twice.
    params_copy = jax.tree_util.tree_map(jnp.array, variables["params"])
    return TrainState(
        params=variables["params"],
        batch_stats=variables.get("batch_stats", {}),
        opt_state=optimizer.init(params_copy),
        step=jnp.zeros((), jnp.int32),
    )


def _cast_floats(tree, dtype):
    """Cast floating leaves to dtype (ints/masks untouched)."""
    return jax.tree_util.tree_map(
        lambda x: x.astype(dtype)
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
        else x,
        tree,
    )


def _apply_model(model: HydraGNN, params, batch_stats, batch, **kwargs):
    """model.apply with the model's mixed-precision policy: bf16 compute
    (params + input features cast inside the differentiated function, so
    gradients accumulate in the float32 master params), float32 outputs."""
    cd = model.compute_dtype
    if cd:
        params = _cast_floats(params, jnp.dtype(cd))
        batch = batch.replace(
            node_features=batch.node_features.astype(jnp.dtype(cd)),
            edge_features=None
            if batch.edge_features is None
            else batch.edge_features.astype(jnp.dtype(cd)),
        )
    out = model.apply({"params": params, "batch_stats": batch_stats}, batch, **kwargs)
    if cd:
        if isinstance(out, tuple):  # (outputs, mutated)
            return [o.astype(jnp.float32) for o in out[0]], *out[1:]
        return [o.astype(jnp.float32) for o in out]
    return out


def _loss_and_metrics(model: HydraGNN, params, batch_stats, batch, dropout_key):
    outputs, mut = _apply_model(
        model,
        params,
        batch_stats,
        batch,
        train=True,
        mutable=["batch_stats"],
        rngs={"dropout": dropout_key},
    )
    loss, rmses = multihead_rmse_loss(
        outputs, batch, model.output_type, model.task_weights
    )
    return loss, (mut["batch_stats"], rmses)


def state_donation_safe(state: TrainState) -> bool:
    """Donation requires every buffer in the state to appear exactly once;
    optimizers that store the params pytree inside their own state (optax
    lbfgs memory) repeat buffers and must run without donation."""
    seen = set()
    for leaf in jax.tree_util.tree_leaves(state):
        if isinstance(leaf, jax.Array):
            if id(leaf) in seen:
                return False
            seen.add(id(leaf))
    return True


def _all_finite(loss, grads):
    """ONE fused reduction: loss and every gradient leaf are finite. The
    compiled step's non-finite guard flag (docs/FAULT_TOLERANCE.md)."""
    ok = jnp.isfinite(loss)
    for g in jax.tree_util.tree_leaves(grads):
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(g)))
    return ok


def _keep_if(ok, new_tree, old_tree):
    """Elementwise select: the new pytree on a finite step, the old one on a
    bad step. Deliberately ``where`` and NOT ``lax.cond``: a conditional
    region changes XLA's fusion boundaries and the clean path would no longer
    be bit-identical to the unguarded build (measured on CPU), while
    ``jnp.where(True, n, o)`` selects ``n`` exactly. The select pass costs a
    state-sized read per step — noise next to fwd+bwd at production batch
    sizes (guard_overhead_pct in FAULTS_rNN.json tracks it)."""
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(ok, n, o), new_tree, old_tree
    )


def _step_body(
    model: HydraGNN, optimizer, guard: bool = False, loss_scaling=None
):
    """The single-device gradient step shared by make_train_step and the
    scanned epoch (one definition — the two compiled paths must never drift).

    With ``guard=True`` the step additionally computes an all-finite flag over
    loss + grads and SKIPS the update on a non-finite step: params, opt_state,
    and batch_stats keep their previous values, the step's metrics carry zero
    weight, and ``metrics["bad"]`` reports the skip (summed per chunk on the
    scan path) for the host-side StepGuard policy. guard=False emits exactly
    the historical computation — the flag costs nothing when disabled.

    ``loss_scaling`` (a precision.LossScaleConfig, docs/PRECISION.md) selects
    the mixed-precision step: the loss is multiplied by the running scale in
    ``state.loss_scale`` before value_and_grad (bf16's exponent range would
    otherwise flush small gradients to zero), gradients are unscaled in f32
    before the optimizer, and the guard's skip machinery is ALWAYS on — an
    overflowed step must not apply inf/NaN updates — with the scale backing
    off on overflow and growing after a clean streak, all inside the jit so
    the policy rides ``lax.scan`` epochs per step. ``None`` emits the
    historical body byte-for-byte."""
    from ..utils.optimizer import ValueFnTransformation

    needs_value_fn = isinstance(optimizer, ValueFnTransformation)
    if loss_scaling is not None:
        if needs_value_fn:
            raise NotImplementedError(
                "loss scaling + LBFGS is unsupported: the zoom linesearch "
                "re-evaluates the SCALED loss along the search direction and "
                "its Wolfe conditions are not scale-invariant under dynamic "
                "rescaling; use a first-order optimizer with precision='bf16'"
            )
        return _scaled_step_body(model, optimizer, guard, loss_scaling)

    def body(state: TrainState, batch: GraphBatch, rng):
        dropout_key = jax.random.fold_in(rng, state.step)
        grad_fn = jax.value_and_grad(
            lambda p: _loss_and_metrics(model, p, state.batch_stats, batch, dropout_key),
            has_aux=True,
        )
        (loss, (new_bstats, rmses)), grads = grad_fn(state.params)
        if needs_value_fn:
            # LBFGS zoom linesearch: update() re-evaluates the loss along the
            # search direction via value_fn (deterministic eval — same batch,
            # same dropout key).
            def value_fn(p):
                return _loss_and_metrics(
                    model, p, state.batch_stats, batch, dropout_key
                )[0]

            updates, new_opt = optimizer.update(
                grads,
                state.opt_state,
                state.params,
                value=loss,
                grad=grads,
                value_fn=value_fn,
            )
        else:
            updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = jax.tree_util.tree_map(
            lambda p, u: p + u, state.params, updates
        )
        count = batch.count_real_graphs().astype(jnp.float32)
        if guard:
            ok = _all_finite(loss, grads)
            new_params = _keep_if(ok, new_params, state.params)
            new_opt = _keep_if(ok, new_opt, state.opt_state)
            new_bstats = _keep_if(ok, new_bstats, state.batch_stats)
            okf = ok.astype(jnp.float32)
            count = count * okf
            # Zero the VALUES before weighting: NaN * 0 is NaN, so a bad
            # step's loss must be selected away, not merely zero-weighted.
            metrics = {
                "loss": jnp.where(ok, loss, 0.0) * count,
                "rmses": jnp.where(ok, rmses, jnp.zeros_like(rmses)) * count,
                "count": count,
                "bad": 1.0 - okf,
            }
        else:
            metrics = {"loss": loss * count, "rmses": rmses * count, "count": count}
        new_state = TrainState(
            params=new_params,
            batch_stats=new_bstats,
            opt_state=new_opt,
            step=state.step + 1,
            loss_scale=state.loss_scale,
        )
        return new_state, metrics

    return body


def _scaled_step_body(
    model: HydraGNN, optimizer, guard: bool, loss_scaling
):
    """The mixed-precision step (docs/PRECISION.md): scaled loss → f32
    unscaled grads → guarded (always) update → in-jit dynamic-scale update.
    Metric semantics mirror the guarded body — an overflowed step carries
    zero weight, its values are selected away before weighting — plus the
    precision pair ``overflow`` / ``scale_growths`` (summed per chunk on the
    scan path) consumed by the host LossScaleMonitor. ``guard`` only adds
    the ``bad`` metric for StepGuard's streak accounting: the computation is
    bit-inert to the flag (the skip machinery is structural here)."""
    from ..precision.policy import loss_scale_update

    def body(state: TrainState, batch: GraphBatch, rng):
        dropout_key = jax.random.fold_in(rng, state.step)
        ls = state.loss_scale

        def scaled_loss(p):
            loss, aux = _loss_and_metrics(
                model, p, state.batch_stats, batch, dropout_key
            )
            # The ONE extra multiply of the policy: everything downstream of
            # value_and_grad sees gradients of scale*loss; the aux carries
            # the unscaled loss for metrics.
            return loss * ls.scale, (loss, aux)

        (_, (loss, (new_bstats, rmses))), sgrads = jax.value_and_grad(
            scaled_loss, has_aux=True
        )(state.params)
        inv = 1.0 / ls.scale
        # Unscale in the grads' own (f32 master) dtype: inf/NaN from an
        # overflowed backward survive the divide, so the finite check below
        # sees them; finite grads come out exactly scale-free.
        grads = jax.tree_util.tree_map(lambda g: g * inv, sgrads)
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = jax.tree_util.tree_map(
            lambda p, u: p + u, state.params, updates
        )
        ok = _all_finite(loss, grads)
        new_params = _keep_if(ok, new_params, state.params)
        new_opt = _keep_if(ok, new_opt, state.opt_state)
        new_bstats = _keep_if(ok, new_bstats, state.batch_stats)
        new_ls, grew = loss_scale_update(ls, ok, loss_scaling)
        okf = ok.astype(jnp.float32)
        count = batch.count_real_graphs().astype(jnp.float32) * okf
        metrics = {
            "loss": jnp.where(ok, loss, 0.0) * count,
            "rmses": jnp.where(ok, rmses, jnp.zeros_like(rmses)) * count,
            "count": count,
            "overflow": 1.0 - okf,
            "scale_growths": grew.astype(jnp.float32),
        }
        if guard:
            metrics["bad"] = 1.0 - okf
        new_state = TrainState(
            params=new_params,
            batch_stats=new_bstats,
            opt_state=new_opt,
            step=state.step + 1,
            loss_scale=new_ls,
        )
        return new_state, metrics

    return body


def make_train_step(
    model: HydraGNN,
    optimizer,
    donate: bool = True,
    guard: bool = False,
    loss_scaling=None,
) -> Callable:
    body = _step_body(model, optimizer, guard, loss_scaling)

    # donate_argnums: params/opt_state buffers are reused in place, halving
    # HBM traffic for the state update (callers must drop the old state).
    def step(state: TrainState, batch: GraphBatch, rng):
        # The compiled-step half of the graftel trace bridge
        # (docs/OBSERVABILITY.md): a named scope is pure op metadata — the
        # emitted computation is numerically identical — but XLA carries it
        # into the profiler, so a captured Perfetto trace shows device ops
        # under the same name the host-side telemetry spans use.
        with jax.named_scope("hydragnn.train_step"):
            return body(state, batch, rng)

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_eval_step(model: HydraGNN) -> Callable:
    @jax.jit
    def step(state: TrainState, batch: GraphBatch):
        with jax.named_scope("hydragnn.eval_step"):
            outputs = _apply_model(
                model, state.params, state.batch_stats, batch, train=False
            )
            loss, rmses = multihead_rmse_loss(
                outputs, batch, model.output_type, model.task_weights
            )
            count = batch.count_real_graphs().astype(jnp.float32)
        return (
            {"loss": loss * count, "rmses": rmses * count, "count": count},
            outputs,
        )

    return step


def make_train_epoch_scan(
    model: HydraGNN,
    optimizer,
    donate: bool = True,
    guard: bool = False,
    loss_scaling=None,
) -> Callable:
    """Whole-epoch driver: one compiled call scans the train step over a
    stacked batch array [S, ...] (single dispatch per epoch instead of per
    step — the python-loop dispatch overhead dominates at HydraGNN's model
    sizes, hidden_dim 5-50 in every shipped config). Metrics come back summed
    over steps, matching EpochMetrics' weighted accumulation. With ``guard``,
    the per-step skip rides INSIDE the scan (a NaN step never poisons later
    steps of the same chunk) and the summed ``bad`` metric reports how many
    steps were skipped. With ``loss_scaling`` the dynamic-scale state rides
    the scan carry (TrainState.loss_scale), so backoff/growth stay exact per
    step even inside a single-dispatch epoch."""

    body = _step_body(model, optimizer, guard, loss_scaling)

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def epoch(state: TrainState, batches: GraphBatch, rng):
        # Trace-annotation bridge: same metadata-only scope as
        # make_train_step, so scanned epochs attribute identically.
        with jax.named_scope("hydragnn.train_epoch_scan"):
            state, metrics = jax.lax.scan(
                lambda s, b: body(s, b, rng), state, batches
            )
        return state, jax.tree_util.tree_map(
            lambda m: jnp.sum(m, axis=0), metrics
        )

    return epoch


# ------------------------------------------------------------- DP × graph-par
def _batch_pspec(batch: GraphBatch, graph_sharded: bool) -> GraphBatch:
    """PartitionSpec tree. Every array is sharded on its leading (device) axis
    over 'data'. With graph_sharded, edge arrays are ALSO sharded over 'graph'
    (edge-partitioned message passing — nodes replicated, one collective per
    aggregation inside the convs)."""
    edge_spec = P("data", "graph") if graph_sharded else P("data")
    return GraphBatch(
        node_features=P("data"),
        edge_features=None if batch.edge_features is None else edge_spec,
        senders=edge_spec,
        receivers=edge_spec,
        node_graph=P("data"),
        node_mask=P("data"),
        edge_mask=edge_spec,
        graph_mask=P("data"),
        targets=tuple(P("data") for _ in batch.targets),
        # CSR boundaries are node-/graph-indexed (never edge-sharded;
        # replicated across 'graph', where the ops layer LOCALIZES them per
        # edge shard — pallas_segment.localize_row_ptr, the graftmesh
        # halo/edge-cut contract — so graph-partitioned steps stay
        # zero-searchsorted).
        row_ptr=None if batch.row_ptr is None else P("data"),
        graph_ptr=None if batch.graph_ptr is None else P("data"),
        num_graphs_pad=batch.num_graphs_pad,
    )


def _dp_local_graftmesh(
    model: HydraGNN,
    optimizer,
    guard: bool,
    loss_scaling,
    grad_sync: str,
    grad_bucket_mb: float,
    grad_axes,
    data_axis_size: int,
):
    """The generalized per-shard DP body (graftmesh, docs/DISTRIBUTED.md):
    selected whenever the step needs dynamic loss scaling and/or an
    overlapped gradient-sync arm. The default single-psum unscaled path keeps
    its historical body in ``make_train_step_dp`` byte-for-byte.

    Overlapped arms (``grad_sync`` = "bucketed" | "ring") multiply the LOCAL
    loss by ``count / max(psum(count), 1)`` before differentiation and let
    the per-bucket backward hooks SUM cotangents across shards — identical
    math to the single arm's weighted psum (the weight is constant w.r.t.
    params), but each bucket's collective depends only on its own backward
    segment, so it can overlap remaining backward compute.

    With ``loss_scaling`` the scale state machine updates in LOCKSTEP after
    the reduction: the all-finite flag is computed from the REDUCED loss and
    gradients, so every shard sees the same overflow verdict and the
    backoff/growth update applies identically everywhere (the property
    tests/test_graftmesh.py pins: a NaN on one shard backs off all)."""
    from ..parallel import overlap

    scaled = loss_scaling is not None
    if scaled:
        from ..precision.policy import loss_scale_update
    graph = "graph" in grad_axes

    def body(state: TrainState, batch: GraphBatch, rng):
        batch = jax.tree_util.tree_map(lambda x: x[0], batch)
        dropout_key = jax.random.fold_in(
            rng, state.step * 1000 + jax.lax.axis_index("data")
        )
        ls = state.loss_scale
        count = batch.count_real_graphs().astype(jnp.float32)
        count_total = jax.lax.psum(count, "data")
        denom = jnp.maximum(count_total, 1.0)
        scale = ls.scale if scaled else jnp.float32(1.0)

        if grad_sync == "single":
            def fn(p):
                loss, (bstats, rmses) = _loss_and_metrics(
                    model, p, state.batch_stats, batch, dropout_key
                )
                return loss * scale, (loss, bstats, rmses)

            (_, (loss, new_bstats, rmses)), sgrads = jax.value_and_grad(
                fn, has_aux=True
            )(state.params)
            grads = jax.tree_util.tree_map(
                lambda g: jax.lax.psum(g * count, "data") / denom, sgrads
            )
            if graph:
                grads = jax.lax.pmean(grads, "graph")
        else:
            w = count / denom
            plan = overlap.plan_buckets(
                state.params, grad_bucket_mb * (1 << 20)
            )
            reduce_fn = overlap.make_reduce(
                grad_sync, grad_axes, data_axis_size
            )

            def fn(p):
                ps = overlap.attach_grad_sync(p, plan, reduce_fn)
                loss, (bstats, rmses) = _loss_and_metrics(
                    model, ps, state.batch_stats, batch, dropout_key
                )
                return loss * scale * w, (loss, bstats, rmses)

            # The bucket hooks already reduced these across shards.
            (_, (loss, new_bstats, rmses)), grads = jax.value_and_grad(
                fn, has_aux=True
            )(state.params)
        if scaled:
            # Unscale AFTER the reduction in the grads' f32 master dtype —
            # inf/NaN from an overflowed shard survives the psum and the
            # divide, so the lockstep finite check below sees it everywhere.
            inv = 1.0 / ls.scale
            grads = jax.tree_util.tree_map(lambda g: g * inv, grads)
        new_bstats = jax.tree_util.tree_map(
            lambda s: jax.lax.psum(s * count, "data") / denom, new_bstats
        )
        if graph:
            new_bstats = jax.lax.pmean(new_bstats, "graph")
        loss_sum = jax.lax.psum(loss * count, "data")
        rmses_sum = jax.lax.psum(rmses * count, "data")
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = jax.tree_util.tree_map(
            lambda p, u: p + u, state.params, updates
        )
        metrics = {"loss": loss_sum, "rmses": rmses_sum, "count": count_total}
        new_ls = ls
        if scaled or guard:
            # Post-reduction flag: every shard computes the SAME verdict from
            # the reduced values, so skip/keep (and the scale update) apply
            # in lockstep — no shard can diverge.
            ok = _all_finite(loss_sum, grads)
            new_params = _keep_if(ok, new_params, state.params)
            new_opt = _keep_if(ok, new_opt, state.opt_state)
            new_bstats = _keep_if(ok, new_bstats, state.batch_stats)
            okf = ok.astype(jnp.float32)
            metrics = {
                "loss": jnp.where(ok, loss_sum, 0.0),
                "rmses": jnp.where(ok, rmses_sum, jnp.zeros_like(rmses_sum)),
                "count": count_total * okf,
            }
            if scaled:
                new_ls, grew = loss_scale_update(ls, ok, loss_scaling)
                metrics["overflow"] = 1.0 - okf
                metrics["scale_growths"] = grew.astype(jnp.float32)
            if guard:
                metrics["bad"] = 1.0 - okf
        new_state = TrainState(
            params=new_params,
            batch_stats=new_bstats,
            opt_state=new_opt,
            step=state.step + 1,
            loss_scale=new_ls,
        )
        return new_state, metrics

    return body


def make_train_step_dp(
    model: HydraGNN,
    optimizer,
    mesh,
    donate: bool = True,
    guard: bool = False,
    loss_scaling=None,
    grad_sync: str = "single",
    grad_bucket_mb: float = 4.0,
) -> Callable:
    """SPMD step over a ('data', 'graph') mesh. ``batch`` arrays carry a leading
    device axis [D, ...] dealt over 'data'; when the model was built with
    graph_axis='graph' and the mesh has a nontrivial 'graph' axis, edges are
    additionally sharded over 'graph'. Grads are pmean'd over BOTH axes — with
    JAX's psum-transposes-to-psum rule this recovers the exact full gradient
    (replicated node contributions stay unscaled, edge-shard contributions sum).

    ``grad_sync`` selects the gradient-reduction arm (graftmesh,
    docs/DISTRIBUTED.md): "single" (default) reduces the whole tree in one
    psum after the full backward — the historical step, byte-identical;
    "bucketed" / "ring" dispatch per-bucket collectives as each backward
    segment completes (``grad_bucket_mb`` sizes the buckets), overlapping
    all-reduce with backward compute. ``loss_scaling`` arms the bf16 dynamic
    loss-scale state machine with the backoff update in lockstep post-psum."""
    from jax.experimental.shard_map import shard_map

    from ..parallel.overlap import resolve_grad_sync
    from ..utils.optimizer import ValueFnTransformation

    if isinstance(optimizer, ValueFnTransformation):
        raise NotImplementedError(
            "LBFGS is not supported in the distributed (mesh) train step: the "
            "zoom linesearch would evaluate per-shard losses and diverge "
            "across devices. Use a first-order optimizer (AdamW) for "
            "distributed runs, or LBFGS on a single device."
        )
    grad_sync = resolve_grad_sync(grad_sync)
    graph_sharded = model.graph_axis is not None and mesh.shape.get("graph", 1) > 1
    grad_axes = ("data", "graph") if graph_sharded else ("data",)
    if loss_scaling is not None or grad_sync != "single":
        _local = _dp_local_graftmesh(
            model, optimizer, guard, loss_scaling, grad_sync,
            float(grad_bucket_mb), grad_axes, int(mesh.shape["data"]),
        )
        return _wrap_dp_step(_local, mesh, graph_sharded, donate)

    def _local(state, batch, rng):
        # Inside shard_map the leading device axis is size 1: drop it.
        batch = jax.tree_util.tree_map(lambda x: x[0], batch)
        dropout_key = jax.random.fold_in(
            rng, state.step * 1000 + jax.lax.axis_index("data")
        )
        grad_fn = jax.value_and_grad(
            lambda p: _loss_and_metrics(model, p, state.batch_stats, batch, dropout_key),
            has_aux=True,
        )
        (loss, (new_bstats, rmses)), grads = grad_fn(state.params)
        count = batch.count_real_graphs().astype(jnp.float32)
        # Gradient allreduce (the DDP-allreduce analog, over ICI), weighted by
        # real-graph count so all-masked tail-padding batches contribute zero
        # weight instead of diluting the step (count=0 ⇒ zero numerator term).
        count_total = jax.lax.psum(count, "data")
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g * count, "data")
            / jnp.maximum(count_total, 1.0),
            grads,
        )
        new_bstats = jax.tree_util.tree_map(
            lambda s: jax.lax.psum(s * count, "data")
            / jnp.maximum(count_total, 1.0),
            new_bstats,
        )
        if "graph" in grad_axes:
            # Edge-shard contributions sum under pmean (psum-transpose rule).
            grads = jax.lax.pmean(grads, "graph")
            new_bstats = jax.lax.pmean(new_bstats, "graph")
        loss_sum = jax.lax.psum(loss * count, "data")
        rmses_sum = jax.lax.psum(rmses * count, "data")
        count_sum = jax.lax.psum(count, "data")
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = jax.tree_util.tree_map(lambda p, u: p + u, state.params, updates)
        metrics = {"loss": loss_sum, "rmses": rmses_sum, "count": count_sum}
        if guard:
            # Checked AFTER the psum: a NaN on any shard propagates into the
            # reduced grads/metrics, so every device computes the SAME flag
            # and skips (or keeps) the replicated state update in lockstep.
            ok = _all_finite(loss_sum, grads)
            new_params = _keep_if(ok, new_params, state.params)
            new_opt = _keep_if(ok, new_opt, state.opt_state)
            new_bstats = _keep_if(ok, new_bstats, state.batch_stats)
            okf = ok.astype(jnp.float32)
            metrics = {
                "loss": jnp.where(ok, loss_sum, 0.0),
                "rmses": jnp.where(ok, rmses_sum, jnp.zeros_like(rmses_sum)),
                "count": count_sum * okf,
                "bad": 1.0 - okf,
            }
        new_state = TrainState(
            params=new_params,
            batch_stats=new_bstats,
            opt_state=new_opt,
            step=state.step + 1,
            loss_scale=state.loss_scale,
        )
        return new_state, metrics

    return _wrap_dp_step(_local, mesh, graph_sharded, donate)


def _wrap_dp_step(local, mesh, graph_sharded: bool, donate: bool):
    """shard_map + jit wrapper shared by every DP train-step arm (one
    definition so the graftmesh arms and the historical body can never
    diverge in specs/donation/platform pinning)."""
    from jax.experimental.shard_map import shard_map

    platform = _mesh_platform(mesh)

    def step(state, batch, rng):
        # Tracing happens inside this call: pin the Pallas gate to the mesh's
        # execution platform for the duration.
        with pallas_platform(platform):
            sharded = shard_map(
                local,
                mesh=mesh,
                in_specs=(P(), _batch_pspec(batch, graph_sharded), P()),
                out_specs=(P(), P()),
                check_rep=False,
            )
            return sharded(state, batch, rng)

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_eval_step_dp(model: HydraGNN, mesh) -> Callable:
    from jax.experimental.shard_map import shard_map

    graph_sharded = model.graph_axis is not None and mesh.shape.get("graph", 1) > 1

    def _local(state, batch):
        batch = jax.tree_util.tree_map(lambda x: x[0], batch)
        outputs = _apply_model(
            model, state.params, state.batch_stats, batch, train=False
        )
        loss, rmses = multihead_rmse_loss(
            outputs, batch, model.output_type, model.task_weights
        )
        count = batch.count_real_graphs().astype(jnp.float32)
        metrics = {
            "loss": jax.lax.psum(loss * count, "data"),
            "rmses": jax.lax.psum(rmses * count, "data"),
            "count": jax.lax.psum(count, "data"),
        }
        outputs = [o[None] for o in outputs]  # restore device axis for gather
        return metrics, outputs

    platform = _mesh_platform(mesh)

    def step(state, batch):
        with pallas_platform(platform):
            sharded = shard_map(
                _local,
                mesh=mesh,
                in_specs=(P(), _batch_pspec(batch, graph_sharded)),
                out_specs=(P(), [P("data") for _ in model.output_dim]),
                check_rep=False,
            )
            return sharded(state, batch)

    return jax.jit(step)


def stack_batches(batches: Sequence[GraphBatch], n_devices: int) -> GraphBatch:
    """Stack per-device GraphBatches along a new leading axis, padding the tail
    with empty (all-masked) batches so every device has work every step."""
    batches = list(batches)
    template = batches[0]
    while len(batches) < n_devices:
        empty = jax.tree_util.tree_map(lambda x: np.zeros_like(x), template)
        empty = empty.replace(
            senders=np.full_like(template.senders, template.num_nodes_pad - 1),
            receivers=np.full_like(template.receivers, template.num_nodes_pad - 1),
            node_graph=np.full_like(template.node_graph, template.num_graphs_pad - 1),
        )
        batches.append(empty)
    return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *batches)
