from .train_validate_test import TrainingDriver, train_validate_test
from .trainer import (
    TrainState,
    create_train_state,
    make_eval_step,
    make_eval_step_dp,
    make_train_step,
    make_train_step_dp,
    stack_batches,
)
