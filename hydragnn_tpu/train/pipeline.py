"""Async input pipeline: host collation and host->device transfer overlapped
with device compute (docs/INPUT_PIPELINE.md).

Round-5 hardware benches put the streamed production path at 770-808 graphs/s
against 926k graphs/s/chip on the pre-staged scan path (BENCH_r05_hw.json):
host->device transfer serialized with compute because the single prefetch
thread overlapped host collation only. The fix is the standard double-buffered
device feed (tf.data / flax.jax_utils.prefetch_to_device pattern):

    loader.__iter__            _Prefetcher             _Prefetcher
    (collation, thread 1) --> [host queue] --> transfer (device_put +
                                               block_until_ready, thread 2)
                                          --> [device queue, depth 2] --> step

While step *k* executes on device, batch *k+1* is already committed device
memory and batch *k+2* is in flight on the DMA engine — the steady-state step
never waits on H2D. The device queue depth of 2 is the double buffer: it
bounds in-flight HBM to (depth + one being transferred) batches.

Blocking on the transfer INSIDE the transfer thread is deliberate: transfers
land on the DMA engine, so the wait does not stall compute, it gives the
pipeline backpressure, and it makes the recorded H2D seconds the true wire
time rather than the (async) dispatch time. Those seconds land in
``FeedStats`` — the per-epoch transfer-vs-compute split surfaced through
``Timer``/``Profiler`` and reported by bench.py next to the throughput.

Consumers: every epoch-level TrainingDriver path (train_epoch, the chunked
scan, evaluate) AND the online inference engine (serve/engine.py), whose
micro-batcher generator runs as the host stage and whose dispatch thread is
the consumer. An out-of-core corpus composes transparently: a
``StreamingGraphLoader`` (datasets/stream.py, docs/DATA_PLANE.md) iterated
by thread 1 adds its shard-prefetch ring as a stage 0 — disk I/O + decode
overlap collation, which overlaps transfer, which overlaps compute — the
serving path gets the same batch-k+1-commits-while-k-
computes overlap as a training epoch.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterable, Optional

from ..analysis import tsan
from ..telemetry import graftel as telemetry


def transfer_error_is_transient(e: BaseException) -> bool:
    """Transfer failures worth retrying: runtime transport flaps (the tunnel's
    UNAVAILABLE / connection-refused RPC errors, transient allocator
    exhaustion) and anything explicitly marked ``transient`` (the fault
    layer's injected drill errors). Programming errors — shape/dtype
    mismatches, cancelled pipelines — are NOT transient and propagate on the
    first raise."""
    if getattr(e, "transient", False):
        return True
    msg = f"{type(e).__name__}: {e}"
    return (
        "UNAVAILABLE" in msg
        or "Connection refused" in msg
        or "RESOURCE_EXHAUSTED" in msg
        or "Socket closed" in msg
    )


def with_transfer_retries(
    transfer: Callable,
    retries: int = 2,
    backoff_s: float = 0.05,
    max_backoff_s: float = 2.0,
    transient: Callable = transfer_error_is_transient,
) -> Callable:
    """Wrap a transfer callable with capped exponential backoff on TRANSIENT
    failures (docs/FAULT_TOLERANCE.md). Runs on the pipeline's transfer
    thread, so the backoff sleep never stalls device compute — the device
    queue simply drains one slot deeper. Retries are counted
    (FaultCounters ``transfer_retries``); a non-transient error, or a
    transient one that survives every attempt, propagates to the consumer
    exactly like before."""
    if retries <= 0:
        return transfer

    def retrying(item):
        delay = backoff_s
        for attempt in range(retries + 1):
            try:
                return transfer(item)
            except Exception as e:  # noqa: BLE001 — classified below
                if attempt >= retries or not transient(e):
                    raise
                from ..faults.counters import FaultCounters

                FaultCounters.inc("transfer_retries")
                time.sleep(min(delay, max_backoff_s))
                delay *= 2.0

    return retrying


class _Prefetcher:
    """Background-thread batch producer: the stage boundary of the pipeline.
    Bounded queue; exceptions re-raised at the consumer; abandoning iteration
    (e.g. the train step raising) cancels the producer so neither the thread
    nor queued batches leak."""

    _SENTINEL = object()

    def __init__(self, iterable: Iterable, depth: int = 8, ctx=None):
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._err = None
        self._cancel = threading.Event()

        def _run():
            # Explicit telemetry context handoff (docs/OBSERVABILITY.md):
            # spans opened by the stage callable on THIS thread parent to the
            # epoch/pipeline span the consumer captured — thread-locals alone
            # cannot cross the stage boundary.
            telemetry.attach(ctx)
            try:
                for item in iterable:
                    while not self._cancel.is_set():
                        try:
                            self._q.put(item, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if self._cancel.is_set():
                        return
            except BaseException as e:  # propagate to consumer
                self._err = e
            finally:
                # The sentinel must not be dropped: with the queue full (>=
                # depth batches and a momentarily slow consumer) put_nowait
                # would raise Full, the consumer would drain the items and
                # then block on get() forever. Block with cancel checks,
                # exactly like regular items.
                while not self._cancel.is_set():
                    try:
                        self._q.put(self._SENTINEL, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        self._thread = threading.Thread(
            target=_run, name="hydragnn-prefetch", daemon=True
        )
        self._thread.start()

    def close(self):
        self._cancel.set()
        # Drain so a producer blocked on put() wakes and exits.
        try:
            while True:
                self._q.get_nowait()
        except Exception:
            pass
        # Wake a CONSUMER blocked on get(): when stages are chained, the
        # downstream stage's thread sits in this queue's get() — draining
        # alone could swallow the sentinel and leave it blocked forever.
        try:
            self._q.put_nowait(self._SENTINEL)
        except Exception:
            pass

    def __iter__(self):
        try:
            while True:
                item = self._q.get()
                if item is self._SENTINEL:
                    if self._err is not None:
                        raise self._err
                    return
                yield item
        finally:
            self.close()


class FeedStats:
    """Per-epoch transfer-vs-compute split of one epoch-level driver call.

    Written from two threads: the transfer thread records the ``h2d_*``
    fields while the consumer credits ``feed_wait_s``/``step_s`` and calls
    ``reset()`` at epoch start. The original lock-free disjoint-field design
    was safe only until ``reset()`` raced a late in-flight ``record_h2d``
    from the previous epoch's draining pipeline — graftrace flagged the
    pair, and one coarse lock (two uncontended acquisitions per batch)
    closes it for every field.

    - ``h2d_bytes`` / ``h2d_s``: payload bytes moved host->device and the
      true wire seconds (measured around a blocking device_put in the
      transfer thread — overlapped with compute, so this is NOT a share of
      epoch wall time unless the pipeline is transfer-bound).
    - ``feed_wait_s``: consumer seconds blocked on the device queue — where
      an input-bound pipeline actually stalls.
    - ``step_s``: consumer seconds in step dispatch + metrics readback (the
      readback blocks on the device computation, so this is compute-bound
      wall time).
    """

    def __init__(self):
        self._lock = tsan.instrument_lock(threading.Lock(), "FeedStats._lock")
        self.reset()

    def reset(self):
        with self._lock:
            self.h2d_bytes = 0  # guarded-by: self._lock
            self.h2d_s = 0.0  # guarded-by: self._lock
            self.h2d_transfers = 0  # guarded-by: self._lock
            self.feed_wait_s = 0.0  # guarded-by: self._lock
            self.step_s = 0.0  # guarded-by: self._lock

    def record_h2d(self, nbytes: int, seconds: float):
        with self._lock:
            self.h2d_bytes += int(nbytes)
            self.h2d_s += seconds
            self.h2d_transfers += 1
            idx = self.h2d_transfers
            tsan.shared_access("FeedStats.fields")
        # graftel emitter (docs/OBSERVABILITY.md): the transfer thread's wire
        # time becomes a retroactive "h2d" span, parented to the epoch
        # context the DeviceFeed attached to this thread — the flight
        # recorder's per-batch H2D timeline.
        telemetry.record_span("h2d", seconds, index=idx, bytes=int(nbytes))

    def credit(self, field: str, seconds: float) -> None:
        """Add consumer-side seconds to ``feed_wait_s``/``step_s`` (the
        ``timed_consume`` sink — one locked add per region exit)."""
        with self._lock:
            setattr(self, field, getattr(self, field) + seconds)
            tsan.shared_access("FeedStats.fields")

    def as_dict(self) -> dict:
        with self._lock:
            return {
                "h2d_bytes": self.h2d_bytes,
                "h2d_s": round(self.h2d_s, 4),
                "h2d_transfers": self.h2d_transfers,
                "feed_wait_s": round(self.feed_wait_s, 4),
                "step_s": round(self.step_s, 4),
            }


class DeviceFeed:
    """Two-stage bounded pipeline: a host stage runs ``iterable`` (collation)
    in one thread; a transfer stage applies ``transfer`` (device_put dispatch
    + completion wait) in a second thread; the consumer iterates committed
    device arrays. With ``transfer=None`` this degrades to the single-stage
    host prefetcher (the pre-round-6 behavior).

    Exceptions raised in either stage re-raise at the consumer; ``close()``
    (also triggered by abandoning iteration) cancels both threads, in
    downstream-first order so a transfer thread blocked on the host queue is
    woken by the host stage's close.

    Transient transfer failures (transfer_error_is_transient) are retried
    with capped exponential backoff on the transfer thread before
    propagating — ``transfer_retries=0`` restores fail-on-first-raise."""

    def __init__(
        self,
        iterable: Iterable,
        transfer: Optional[Callable] = None,
        host_depth: int = 8,
        device_depth: int = 2,
        transfer_retries: int = 2,
        transfer_backoff_s: float = 0.05,
        ctx=None,
    ):
        if transfer is not None and transfer_retries > 0:
            transfer = with_transfer_retries(
                transfer, retries=transfer_retries, backoff_s=transfer_backoff_s
            )
        # ``ctx`` is the caller's telemetry context (the epoch / serve
        # pipeline span): handed EXPLICITLY to both stage threads so their
        # spans parent to it (docs/OBSERVABILITY.md "context handoff").
        self._host = _Prefetcher(iterable, depth=host_depth, ctx=ctx)
        self._dev = (
            None
            if transfer is None
            else _Prefetcher(
                map(transfer, self._host), depth=device_depth, ctx=ctx
            )
        )

    def close(self):
        if self._dev is not None:
            self._dev.close()
        self._host.close()

    def join(self, timeout: float = 5.0) -> bool:
        """True when both stage threads have exited (tests/diagnostics)."""
        self._host._thread.join(timeout)
        if self._dev is not None:
            self._dev._thread.join(timeout)
        return not (
            self._host._thread.is_alive()
            or (self._dev is not None and self._dev._thread.is_alive())
        )

    def __iter__(self):
        src = self._dev if self._dev is not None else self._host
        try:
            yield from src
        finally:
            self.close()


def traced_batches(iterable: Iterable, name: str = "collate"):
    """Wrap a batch source so each pull becomes a graftel span (the host
    collation timeline of the flight recorder). Runs wherever the iterable
    is consumed — on the DeviceFeed host thread for the pipelined paths — so
    the spans parent to the context that thread attached."""
    it = iter(iterable)
    i = 0
    while True:
        with telemetry.span(name, index=i):
            try:
                b = next(it)
            except StopIteration:
                return
        yield b
        i += 1


class timed_consume:
    """Context manager crediting a wall-time region to a FeedStats field.
    Plain class (not contextlib.contextmanager): it sits twice in the
    per-batch consumer hot loop, so one small allocation per use."""

    __slots__ = ("_stats", "_field", "_t0")

    def __init__(self, stats: FeedStats, field: str):
        self._stats = stats
        self._field = field

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._stats.credit(self._field, time.perf_counter() - self._t0)
