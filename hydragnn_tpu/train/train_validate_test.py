"""Epoch loop + train/validate/test
(reference /root/reference/hydragnn/train/train_validate_test.py:32-304).

Per epoch: loader.set_epoch (DP reshuffle) → train over all batches → validate →
test → plateau-scheduler step on validation RMSE → TensorBoard scalars + history.
Deviations from the reference, on purpose: eval metrics are reduced across all
devices/processes (the reference reports per-rank-local averages, SURVEY.md §3.4),
and the TensorBoard writer actually works (model.py:50-54 quirk)."""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..graphs.batch import GraphBatch
from ..models.base import HydraGNN
from ..utils.optimizer import ReduceLROnPlateau, get_learning_rate, set_learning_rate
from ..utils.print_utils import iterate_tqdm, print_distributed
from ..utils.profile import Profiler
from ..utils.time_utils import Timer
from .trainer import (
    TrainState,
    make_eval_step,
    make_eval_step_dp,
    make_train_epoch_scan,
    make_train_step,
    make_train_step_dp,
    stack_batches,
    state_donation_safe,
)


class _Prefetcher:
    """Background-thread batch producer: host-side collation (numpy packing in
    GraphDataLoader.__iter__) overlaps with device compute instead of
    serializing with it. Bounded queue; exceptions re-raised at the consumer;
    abandoning iteration (e.g. the train step raising) cancels the producer so
    neither the thread nor queued batches leak."""

    _SENTINEL = object()

    def __init__(self, iterable, depth: int = 8):
        import queue
        import threading

        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._err = None
        self._cancel = threading.Event()

        def _run():
            try:
                for item in iterable:
                    while not self._cancel.is_set():
                        try:
                            self._q.put(item, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if self._cancel.is_set():
                        return
            except BaseException as e:  # propagate to consumer
                self._err = e
            finally:
                # The sentinel must not be dropped: with the queue full (>=
                # depth batches and a momentarily slow consumer) put_nowait
                # would raise Full, the consumer would drain the items and
                # then block on get() forever. Block with cancel checks,
                # exactly like regular items.
                while not self._cancel.is_set():
                    try:
                        self._q.put(self._SENTINEL, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        self._thread = threading.Thread(
            target=_run, name="hydragnn-prefetch", daemon=True
        )
        self._thread.start()

    def close(self):
        self._cancel.set()
        # Drain so a producer blocked on put() wakes and exits.
        try:
            while True:
                self._q.get_nowait()
        except Exception:
            pass

    def __iter__(self):
        try:
            while True:
                item = self._q.get()
                if item is self._SENTINEL:
                    if self._err is not None:
                        raise self._err
                    return
                yield item
        finally:
            self.close()


class EpochMetrics:
    """Graph-count-weighted averages accumulated over an epoch."""

    def __init__(self):
        self.loss = 0.0
        self.rmses = None
        self.count = 0.0

    def update(self, metrics):
        self.loss += float(metrics["loss"])
        r = np.asarray(metrics["rmses"])
        self.rmses = r if self.rmses is None else self.rmses + r
        self.count += float(metrics["count"])

    def averages(self):
        c = max(self.count, 1.0)
        return self.loss / c, (
            (self.rmses / c).tolist() if self.rmses is not None else []
        )


class TrainingDriver:
    """Owns the compiled steps + scheduler/profiler state for one model run."""

    def __init__(
        self,
        model: HydraGNN,
        optimizer,
        state: TrainState,
        mesh=None,
        verbosity: int = 0,
    ):
        self.model = model
        self.optimizer = optimizer
        self.state = state
        self.mesh = mesh
        self.verbosity = verbosity
        self.n_devices = 1
        self.multihost = jax.process_count() > 1
        if mesh is not None:
            # Each process stacks only its LOCAL slice of the data axis; the
            # stacked host-local array is lifted to a global jax.Array below —
            # otherwise every host would feed its own copy and devices would
            # silently take non-matching slices.
            self.n_devices = (
                mesh.local_mesh.shape["data"] if self.multihost
                else mesh.shape["data"]
            )
            donate = state_donation_safe(state)
            self.train_step = make_train_step_dp(model, optimizer, mesh, donate)
            self.eval_step = make_eval_step_dp(model, mesh)
        else:
            donate = state_donation_safe(state)
            self.train_step = make_train_step(model, optimizer, donate)
            self.eval_step = make_eval_step(model)
            self.epoch_scan = make_train_epoch_scan(model, optimizer, donate)
        # Chunked lax.scan over the epoch: one device dispatch per chunk
        # instead of per batch (dispatch overhead dominates at HydraGNN's
        # model sizes). Chunk bounds the stacked batches' HBM footprint.
        self.scan_chunk = 64
        self.rng = jax.random.PRNGKey(0)
        # Device-resident batch caches (reshuffle="batch" train loaders and
        # static eval loaders): id(loader) -> {"loader": strong ref (keeps
        # the id stable), "chunks"/"batches": device pytrees} or None once a
        # loader is known to exceed the byte budget. Batches are never
        # donated by the compiled steps, so reuse is safe.
        self._scan_cache: dict = {}
        self._eval_cache: dict = {}
        # Permuted replay of a cached chunk, compiled: the within-chunk order
        # shuffle rides INSIDE the jit (one dispatch, fused gather) instead
        # of eager per-leaf gathers. State is donated like epoch_scan; the
        # cached payload must NOT be (it is reused every epoch).
        self._perm_scan = None
        if mesh is None:
            self._perm_scan = jax.jit(
                lambda s, p, perm, rng: self.epoch_scan(
                    s, jax.tree_util.tree_map(lambda x: x[perm], p), rng
                ),
                donate_argnums=(0,),
            )

    @staticmethod
    def _cache_budget_bytes() -> int:
        import os

        return int(os.environ.get("HYDRAGNN_DEVICE_CACHE_MB", "512")) * (1 << 20)

    @staticmethod
    def _tree_nbytes(tree) -> int:
        return sum(
            getattr(leaf, "nbytes", 0) for leaf in jax.tree_util.tree_leaves(tree)
        )

    # ------------------------------------------------------------------ train
    @staticmethod
    def _shape_key(batch: GraphBatch):
        return (
            batch.node_features.shape,
            batch.senders.shape,
            batch.num_graphs_pad,
        )

    def _device_groups(self, loader):
        """Lazily yield per-device batch groups stacked for shard_map. Used for
        ANY mesh run (even data_axis=1 — the sharded step always expects the
        leading device axis). Bucketed loaders emit several static shapes;
        groups are formed per shape (tail groups are padded with empty
        batches by stack_batches)."""
        groups: dict = {}
        for b in loader:
            key = self._shape_key(b)
            group = groups.setdefault(key, [])
            group.append(b)
            if len(group) == self.n_devices:
                # Host-side numpy only — the consumer lifts to device arrays
                # one group at a time, so the prefetch queue never pins HBM.
                yield stack_batches(group, self.n_devices)
                groups[key] = []
        for group in groups.values():
            if group:
                yield stack_batches(group, self.n_devices)

    def _lift(self, stacked):
        """Host-local stacked batch → global jax.Array across processes."""
        if not self.multihost:
            return stacked
        from jax.experimental import multihost_utils
        from jax.sharding import PartitionSpec as P

        return multihost_utils.host_local_array_to_global_array(
            stacked, self.mesh, P("data")
        )

    def train_epoch(self, loader, profiler: Optional[Profiler] = None):
        # Scan path only when nothing needs per-step host hooks.
        if self.mesh is None and not (profiler and profiler.active):
            return self._train_epoch_scan(loader)
        metrics = EpochMetrics()
        batches = _Prefetcher(
            self._device_groups(loader) if self.mesh is not None else iter(loader)
        )
        prof = profiler or Profiler()
        batch_iter = iter(iterate_tqdm(batches, self.verbosity))
        while True:
            # "feed" covers batch ACQUISITION (the prefetcher queue wait —
            # where an input-bound pipeline actually stalls) plus the
            # multi-host lift, not just the lift.
            with prof.annotate("feed"):
                batch = next(batch_iter, None)
                if batch is None:
                    break
                if self.mesh is not None:
                    batch = self._lift(batch)
            with prof.annotate("train_step"):
                self.state, m = self.train_step(self.state, batch, self.rng)
                metrics.update(m)
            if profiler:
                profiler.step()
        return metrics.averages()

    def _train_epoch_scan(self, loader):
        """Whole-epoch lax.scan in fixed-size chunks, buffered per batch shape
        (bucketed loaders emit a handful of static shapes). Chunk sizes repeat
        across epochs (loader length is constant), so compiles stay bounded:
        per shape, the full chunk plus remainders. The tqdm bar (verbosity
        2/4) ticks per batch as batches are consumed into chunks.

        reshuffle="batch" loaders (frozen membership) additionally get their
        stacked chunks cached ON DEVICE after the first epoch: steady-state
        epochs then do zero host collation and zero host->device transfer —
        the dominant cost when the device link is a tunnel. Batch visit
        order still reshuffles per epoch (chunk dispatch order on host, plus
        a device-side permutation of each chunk's stacked axis). Capped by
        HYDRAGNN_DEVICE_CACHE_MB (default 512)."""
        cached = self._scan_cache.get(id(loader))
        if cached is not None and cached.get("chunks") is not None:
            metrics = EpochMetrics()
            rng = np.random.default_rng(
                getattr(loader, "seed", 0) + getattr(loader, "epoch", 0)
            )
            for ci in rng.permutation(len(cached["chunks"])):
                single, payload = cached["chunks"][ci]
                if single:
                    self.state, m = self.train_step(self.state, payload, self.rng)
                else:
                    # Batch-level order reshuffle WITHIN the chunk too —
                    # compiled into the scan dispatch (see _perm_scan), so
                    # the mode's "order reshuffles per epoch" promise holds
                    # even when the whole epoch fits one chunk. Membership
                    # and batch->chunk assignment stay frozen (the cache).
                    steps = jax.tree_util.tree_leaves(payload)[0].shape[0]
                    perm = jnp.asarray(rng.permutation(steps))
                    self.state, m = self._perm_scan(
                        self.state, payload, perm, self.rng
                    )
                metrics.update(m)
            return metrics.averages()

        cacheable = (
            getattr(loader, "reshuffle", None) == "batch"
            and self.mesh is None
            and id(loader) not in self._scan_cache  # not marked over-budget
        )
        sink: Optional[dict] = {"items": [], "bytes": 0} if cacheable else None
        metrics = EpochMetrics()
        bufs: dict = {}
        for b in iterate_tqdm(_Prefetcher(iter(loader)), self.verbosity):
            buf = bufs.setdefault(self._shape_key(b), [])
            buf.append(b)
            if len(buf) == self.scan_chunk:
                sink = self._run_scan_chunk(buf, metrics, sink)
                buf.clear()
        for buf in bufs.values():
            if buf:
                sink = self._run_scan_chunk(buf, metrics, sink)
        if cacheable:
            # A None sink means the budget was blown mid-epoch. The loader
            # ref is kept EITHER WAY: the verdict is keyed by id(loader),
            # and without a strong ref a garbage-collected loader could hand
            # its id to a new loader that would silently inherit it.
            self._scan_cache[id(loader)] = {
                "loader": loader,
                "chunks": sink["items"] if sink is not None else None,
            }
        return metrics.averages()

    def _run_scan_chunk(self, batches, metrics, sink: Optional[dict] = None):
        """Dispatch one chunk; when ``sink`` is given, also device_put the
        dispatched payload into it (the reshuffle="batch" device cache),
        returning None instead once the byte budget is exceeded. ``sink``
        carries a running byte total so the first (timed) epoch's
        bookkeeping stays O(1) per chunk."""
        if len(batches) == 1:
            payload, single = batches[0], True
            self.state, m = self.train_step(self.state, payload, self.rng)
        else:
            payload, single = stack_batches(batches, len(batches)), False
            self.state, m = self.epoch_scan(self.state, payload, self.rng)
        metrics.update(m)
        if sink is not None:
            nbytes = self._tree_nbytes(payload)
            if sink["bytes"] + nbytes <= self._cache_budget_bytes():
                sink["items"].append((single, jax.device_put(payload)))
                sink["bytes"] += nbytes
            else:
                sink = None
        return sink

    # ------------------------------------------------------------------- eval
    def evaluate(self, loader, return_values: bool = False, profiler=None):
        """validate()/test() analog. With return_values, also gathers per-head
        (true, predicted) arrays over real rows (test(), reference
        train_validate_test.py:267-304)."""
        prof = profiler or Profiler()
        metrics = EpochMetrics()
        num_heads = len(self.model.output_dim)
        true_values: List[List[np.ndarray]] = [[] for _ in range(num_heads)]
        pred_values: List[List[np.ndarray]] = [[] for _ in range(num_heads)]

        def to_host(arr):
            """Local rows of a possibly multi-host global array (per-process
            values, like the reference's per-rank test() lists)."""
            if self.multihost and hasattr(arr, "addressable_shards"):
                return np.concatenate(
                    [np.asarray(s.data) for s in arr.addressable_shards]
                )
            return np.asarray(arr)

        def consume(batch_host: GraphBatch, outputs):
            for ih, (htype, out) in enumerate(
                zip(self.model.output_type, outputs)
            ):
                out = to_host(out)
                if out.ndim == 3:  # DP: [D, rows, dim] → per-device slices
                    out = out.reshape(-1, out.shape[-1])
                mask = to_host(
                    batch_host.graph_mask if htype == "graph" else batch_host.node_mask
                ).reshape(-1)
                tgt = to_host(batch_host.targets[ih]).reshape(-1, out.shape[-1])
                pred_values[ih].append(out[mask])
                true_values[ih].append(tgt[mask])

        # Static eval loaders (shuffle=False: membership AND order are fixed,
        # so caching changes nothing semantically) keep their batches device-
        # resident after the first evaluate() — the per-epoch validation pass
        # then skips collation and host->device transfer entirely. Host
        # copies ride along for consume()'s masks/targets.
        cached = self._eval_cache.get(id(loader))
        if cached is not None and cached.get("batches") is not None:
            for host_b, dev_b in cached["batches"]:
                with prof.annotate("eval_step"):
                    m, outputs = self.eval_step(self.state, dev_b)
                    metrics.update(m)
                if return_values:
                    consume(host_b, outputs)
        else:
            cacheable = (
                self.mesh is None
                and getattr(loader, "shuffle", True) is False
                and id(loader) not in self._eval_cache
            )
            sink: Optional[dict] = {"items": [], "bytes": 0} if cacheable else None
            batches = _Prefetcher(
                self._device_groups(loader) if self.mesh is not None else iter(loader)
            )
            for batch in batches:
                # Same multi-host lift as train_epoch: the sharded eval step
                # wants a GLOBAL [D_global, ...] array; each process only
                # stacked its local slice. consume() keeps the host-local
                # batch (its masks and targets are this process's rows, like
                # the reference's per-rank test() lists).
                lifted = self._lift(batch) if self.mesh is not None else batch
                with prof.annotate("eval_step"):
                    m, outputs = self.eval_step(self.state, lifted)
                    metrics.update(m)
                if return_values:
                    consume(batch, outputs)
                if sink is not None:
                    nbytes = self._tree_nbytes(batch)
                    if sink["bytes"] + nbytes <= self._cache_budget_bytes():
                        sink["items"].append((batch, jax.device_put(batch)))
                        sink["bytes"] += nbytes
                    else:
                        sink = None
            if cacheable:
                # Keep the loader ref even on an over-budget verdict so a
                # recycled id() cannot inherit it (see _scan_cache).
                self._eval_cache[id(loader)] = {
                    "loader": loader,
                    "batches": sink["items"] if sink is not None else None,
                }

        loss, rmses = metrics.averages()
        if return_values:
            tv = [np.concatenate(v) if v else np.zeros((0, 1)) for v in true_values]
            pv = [np.concatenate(v) if v else np.zeros((0, 1)) for v in pred_values]
            return loss, rmses, tv, pv
        return loss, rmses


def train_validate_test(
    driver: TrainingDriver,
    train_loader,
    val_loader,
    test_loader,
    num_epoch: int,
    writer=None,
    scheduler: Optional[ReduceLROnPlateau] = None,
    profiler: Optional[Profiler] = None,
    verbosity: int = 0,
    visualizer=None,
    output_names: Optional[List[str]] = None,
    plot_init_solution: bool = True,
    plot_hist_solution: bool = False,
    checkpoint_name: Optional[str] = None,
    checkpoint_every: int = 0,
    start_epoch: int = 0,
    history: Optional[dict] = None,
):
    """The epoch loop (train_validate_test.py:94-137). Returns the loss history
    dict consumed by the Visualizer. With a visualizer attached, mirrors the
    reference's plot hooks: graph-size histogram + initial-solution scatter
    before training (train_validate_test.py:68-85), optional per-epoch scatter
    (plot_hist_solution, :131-137)."""
    if visualizer is not None:
        visualizer.num_nodes_plot()
        if plot_init_solution:
            _, _, tv, pv = driver.evaluate(test_loader, return_values=True)
            visualizer.create_scatter_plots(
                tv, pv, output_names=output_names, iepoch=-1
            )
    history = history or {
        "total_loss_train": [],
        "total_loss_val": [],
        "total_loss_test": [],
        "task_loss_train": [],
        "task_loss_val": [],
        "task_loss_test": [],
    }
    timer = Timer("train_validate_test")
    timer.start()
    for epoch in range(start_epoch, num_epoch):
        for loader in (train_loader, val_loader, test_loader):
            if hasattr(loader, "set_epoch"):
                loader.set_epoch(epoch)
        if profiler:
            profiler.set_current_epoch(epoch)

        train_loss, train_rmses = driver.train_epoch(train_loader, profiler)
        val_loss, val_rmses = driver.evaluate(val_loader, profiler=profiler)
        test_loss, test_rmses = driver.evaluate(test_loader, profiler=profiler)

        if scheduler is not None:
            current_lr = get_learning_rate(driver.state.opt_state)
            # None = no injected LR knob (LBFGS: linesearch owns the step
            # size) — the plateau scheduler has nothing to act on.
            new_lr = (
                scheduler.step(val_loss, current_lr)
                if current_lr is not None
                else None
            )
            if new_lr is not None and new_lr != current_lr:
                driver.state = driver.state.replace(
                    opt_state=set_learning_rate(driver.state.opt_state, new_lr)
                )
                print_distributed(
                    verbosity, f"Epoch {epoch}: learning rate reduced to {new_lr}"
                )

        if writer is not None:
            writer.add_scalar("train error", train_loss, epoch)
            writer.add_scalar("validate error", val_loss, epoch)
            writer.add_scalar("test error", test_loss, epoch)
            for ivar, rmse in enumerate(train_rmses):
                writer.add_scalar(f"train error of task {ivar}", rmse, epoch)

        print_distributed(
            verbosity,
            f"Epoch: {epoch:4d}  Train: {train_loss:.8f}  Val: {val_loss:.8f}  "
            f"Test: {test_loss:.8f}",
        )
        history["total_loss_train"].append(train_loss)
        history["total_loss_val"].append(val_loss)
        history["total_loss_test"].append(test_loss)
        history["task_loss_train"].append(train_rmses)
        history["task_loss_val"].append(val_rmses)
        history["task_loss_test"].append(test_rmses)

        if visualizer is not None and plot_hist_solution:
            _, _, tv, pv = driver.evaluate(test_loader, return_values=True)
            visualizer.create_scatter_plots(
                tv, pv, output_names=output_names, iepoch=epoch
            )

        # Mid-training periodic checkpoint — an improvement over the
        # reference, which saves only once at the very end (SURVEY.md §5.4);
        # a preempted multi-hour run can warm-start from the last save.
        if (
            checkpoint_name
            and checkpoint_every > 0
            and (epoch + 1) % checkpoint_every == 0
        ):
            from ..utils.model import save_model

            save_model(
                {
                    "params": driver.state.params,
                    "batch_stats": driver.state.batch_stats,
                },
                driver.state.opt_state,
                checkpoint_name,
                meta={
                    "epoch": epoch + 1,
                    "scheduler": scheduler.state_dict() if scheduler else None,
                    "history": history,
                },
            )
    if profiler:
        profiler.stop()
    timer.stop()
    return history
