"""Epoch loop + train/validate/test
(reference /root/reference/hydragnn/train/train_validate_test.py:32-304).

Per epoch: loader.set_epoch (DP reshuffle) → train over all batches → validate →
test → plateau-scheduler step on validation RMSE → TensorBoard scalars + history.
Deviations from the reference, on purpose: eval metrics are reduced across all
devices/processes (the reference reports per-rank-local averages, SURVEY.md §3.4),
and the TensorBoard writer actually works (model.py:50-54 quirk)."""

from __future__ import annotations

import contextlib
import os
import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..graphs.batch import GraphBatch
from ..models.base import HydraGNN
from ..utils.optimizer import ReduceLROnPlateau, get_learning_rate, set_learning_rate
from ..telemetry import graftel as telemetry
from ..utils.print_utils import iterate_tqdm, print_distributed
from ..utils.profile import Profiler
from ..utils.time_utils import Timer
from .pipeline import (  # noqa: F401  (_Prefetcher re-exported for compat)
    DeviceFeed,
    FeedStats,
    _Prefetcher,
    timed_consume,
    traced_batches,
)
from .trainer import (
    TrainState,
    _batch_pspec,
    make_eval_step,
    make_eval_step_dp,
    make_train_epoch_scan,
    make_train_step,
    make_train_step_dp,
    stack_batches,
    state_donation_safe,
)


# One pump per process: started lazily by the first supervised epoch loop.
_heartbeat_pump_started = False


def _start_supervisor_heartbeat_pump() -> None:
    """graftelastic child-side liveness (docs/DISTRIBUTED.md "Elastic
    runbook"): under an elastic supervisor (``HYDRAGNN_ELASTIC_COORD``), a
    daemon timer thread beats every ``heartbeat_s / 4`` for the PROCESS
    lifetime — liveness must not depend on epoch cadence, or a
    compile-inflated first epoch (XLA compiles dwarf the steady wall) and
    the beat-less post-loop finalization would read as hangs. The per-epoch
    beat below still runs for epoch attribution in the coordinator log."""
    global _heartbeat_pump_started
    if _heartbeat_pump_started or not os.environ.get("HYDRAGNN_ELASTIC_COORD"):
        return
    _heartbeat_pump_started = True
    import threading

    try:
        hb = float(os.environ.get("HYDRAGNN_ELASTIC_HEARTBEAT_S") or 5.0)
    except ValueError:
        hb = 5.0
    interval = max(0.2, hb / 4.0)

    def pump() -> None:
        while True:
            _post_supervisor_heartbeat(None)
            time.sleep(interval)

    threading.Thread(
        target=pump, name="elastic-heartbeat-pump", daemon=True
    ).start()


def _post_supervisor_heartbeat(epoch: Optional[int] = None) -> None:
    """One best-effort beat into the elastic supervisor's coordinator
    mailbox (no-op without ``HYDRAGNN_ELASTIC_COORD``). Best-effort by
    design — a beat that cannot land is exactly the signal the supervisor's
    heartbeat deadline exists to catch, and a failed post must never take
    down the training it reports on."""
    addr = os.environ.get("HYDRAGNN_ELASTIC_COORD")
    if not addr:
        return
    from ..parallel.loopback import LoopbackError, ProxyRendezvous

    rank = jax.process_index()
    try:
        ProxyRendezvous.post(
            addr,
            "heartbeat",
            rank=rank,
            payload={"wid": f"proc{rank}", "epoch": epoch, "pid": os.getpid()},
            timeout_s=5.0,
            connect_retries=1,
        )
    except (LoopbackError, OSError):
        pass  # missed beat == the supervisor's deadline does its job


class EpochMetrics:
    """Graph-count-weighted averages accumulated over an epoch. The guarded
    step's extra ``bad`` metric is consumed by StepGuard (per step/chunk) and
    aggregated process-wide in FaultCounters, not here — bad steps carry zero
    ``count`` weight so the averages are already skip-correct."""

    def __init__(self):
        self.loss = 0.0
        self.rmses = None
        self.count = 0.0

    def update(self, metrics):
        self.loss += float(metrics["loss"])
        r = np.asarray(metrics["rmses"])
        self.rmses = r if self.rmses is None else self.rmses + r
        self.count += float(metrics["count"])

    def averages(self):
        c = max(self.count, 1.0)
        return self.loss / c, (
            (self.rmses / c).tolist() if self.rmses is not None else []
        )


class TrainingDriver:
    """Owns the compiled steps + scheduler/profiler state for one model run."""

    def __init__(
        self,
        model: HydraGNN,
        optimizer,
        state: TrainState,
        mesh=None,
        verbosity: int = 0,
        fault_tolerance: Optional[dict] = None,
        fault_plan=None,
        compile_cache: Optional[str] = None,
        compile_cache_fingerprint: str = "",
        precision: Optional[str] = None,
        loss_scale: Optional[dict] = None,
        grad_sync: Optional[str] = None,
        grad_bucket_mb: Optional[float] = None,
    ):
        from ..faults import FaultPlan, StepGuard

        self.model = model
        self.optimizer = optimizer
        self.state = state
        self.mesh = mesh
        self.verbosity = verbosity
        self.n_devices = 1
        self.multihost = jax.process_count() > 1
        # Non-finite step guard (Training.fault_tolerance): None = disabled =
        # the compiled steps are built WITHOUT the flag — bit-identical to
        # the historical build. Fault injection (drills) is env/config-driven
        # and independent of the guard.
        self.guard = StepGuard.from_config(fault_tolerance, verbosity)
        self.fault_plan = (
            fault_plan if fault_plan is not None else FaultPlan.from_env()
        )
        # Checkpoint drills (corrupt_ckpt/truncate_ckpt/kill@save) ride the
        # checkpoint subsystem's post-save hook. Registered (or CLEARED — a
        # stale hook from a previous driver must never corrupt this run's
        # saves) for every driver construction.
        from ..checkpoint import set_post_save_hook

        set_post_save_hook(
            self.fault_plan.on_checkpoint_saved
            if self.fault_plan is not None and self.fault_plan.active
            else None
        )
        # Precision policy (graftprec, docs/PRECISION.md): Training.precision
        # = "bf16" clones the model onto its own compute_dtype mechanism (bf16
        # compute, f32 master weights — trainer._apply_model) and arms dynamic
        # loss scaling; "f32"/None resolves to no policy object at all, so the
        # compiled steps below are byte-identical to the seed build.
        from ..precision import (
            LossScaleMonitor,
            PrecisionPolicy,
            make_loss_scale_state,
        )

        self.precision = PrecisionPolicy.resolve(precision, loss_scale)
        self.precision_monitor = None
        loss_scaling = None
        if self.precision is not None:
            if model.compute_dtype is None:
                model = model.clone(
                    compute_dtype=self.precision.compute_dtype
                )
                self.model = model
            elif model.compute_dtype != self.precision.compute_dtype:
                # The runtime mirror of the check-config contradiction gate:
                # an explicit non-bf16 compute_dtype under precision='bf16'
                # would silently train at that dtype with pointless loss
                # scaling armed — never proceed on a lie.
                raise ValueError(
                    f"Training.precision='{self.precision.mode}' contradicts "
                    f"Architecture.compute_dtype={model.compute_dtype!r} — "
                    "unset compute_dtype (the policy sets it) or pin it to "
                    f"{self.precision.compute_dtype!r}"
                )
            state = state.replace(
                loss_scale=make_loss_scale_state(self.precision.loss_scale)
            )
            self.state = state
            loss_scaling = self.precision.loss_scale
            self.precision_monitor = LossScaleMonitor(verbosity)
        guard = self.guard is not None
        # graftmesh gradient-sync arm (Training.grad_sync, docs/
        # DISTRIBUTED.md): "single" (default) is the historical one-psum
        # step; "bucketed"/"ring" overlap per-bucket all-reduce with the
        # backward. Resolved here so a bad knob fails at driver build, not
        # mid-epoch inside a trace.
        from ..parallel.overlap import DEFAULT_BUCKET_MB, resolve_grad_sync

        self.grad_sync = resolve_grad_sync(grad_sync)
        self.grad_bucket_mb = float(
            grad_bucket_mb if grad_bucket_mb is not None else DEFAULT_BUCKET_MB
        )
        if self.grad_sync != "single" and mesh is None:
            # The knob selects the MESH step's reduction arm; on a
            # single-device driver it would be silently ignored — say so
            # loudly (and below, keep it OUT of the cache flags so the
            # compiled single-device program keeps its warm store entries).
            import warnings

            warnings.warn(
                f"Training.grad_sync={self.grad_sync!r} has no effect "
                "without a device mesh (single-device run) — the knob "
                "selects the distributed step's gradient-reduction arm",
                RuntimeWarning,
                stacklevel=2,
            )
        if mesh is not None:
            # Each process stacks only its LOCAL slice of the data axis; the
            # stacked host-local array is lifted to a global jax.Array below —
            # otherwise every host would feed its own copy and devices would
            # silently take non-matching slices.
            self.n_devices = (
                mesh.local_mesh.shape["data"] if self.multihost
                else mesh.shape["data"]
            )
            donate = state_donation_safe(state)
            self.train_step = make_train_step_dp(
                model, optimizer, mesh, donate, guard=guard,
                loss_scaling=loss_scaling,
                grad_sync=self.grad_sync,
                grad_bucket_mb=self.grad_bucket_mb,
            )
            self.eval_step = make_eval_step_dp(model, mesh)
        else:
            donate = state_donation_safe(state)
            self.train_step = make_train_step(
                model, optimizer, donate, guard=guard,
                loss_scaling=loss_scaling,
            )
            self.eval_step = make_eval_step(model)
            self.epoch_scan = make_train_epoch_scan(
                model, optimizer, donate, guard=guard,
                loss_scaling=loss_scaling,
            )
        # Chunked lax.scan over the epoch: one device dispatch per chunk
        # instead of per batch (dispatch overhead dominates at HydraGNN's
        # model sizes). Chunk bounds the stacked batches' HBM footprint.
        self.scan_chunk = 64
        self.rng = jax.random.PRNGKey(0)
        # Device-resident batch caches (reshuffle="batch" train loaders and
        # static eval loaders): id(loader) -> {"loader": strong ref (keeps
        # the id stable), "chunks"/"batches": device pytrees} or None once a
        # loader is known to exceed the byte budget. Batches are never
        # donated by the compiled steps, so reuse is safe.
        self._scan_cache: dict = {}
        self._eval_cache: dict = {}
        # Permuted replay of a cached chunk, compiled: the within-chunk order
        # shuffle rides INSIDE the jit (one dispatch, fused gather) instead
        # of eager per-leaf gathers. State is donated like epoch_scan; the
        # cached payload must NOT be (it is reused every epoch).
        self._perm_scan = None
        if mesh is None:
            self._perm_scan = jax.jit(
                lambda s, p, perm, rng: self.epoch_scan(
                    s, jax.tree_util.tree_map(lambda x: x[perm], p), rng
                ),
                donate_argnums=(0,),
            )
        # Persistent compiled-executable store (graftcache, docs/
        # COMPILE_CACHE.md): ALL compiled steps — the single-device train_step
        # / epoch_scan / perm_scan / eval_step AND the shard_map mesh steps
        # (graftmesh) — dispatch through the shared ExecutableRegistry — the
        # same locked lookup→compile-outside-lock→store path the serve engine
        # uses — so a crash-resumed or restarted run hydrates its train
        # compile from disk in well under a second. Mesh programs carry the
        # mesh axis layout as a CacheKey component (a 4-device step must
        # never hydrate a 2-device executable; the environment topology
        # string already pins the device count). Opt-in
        # (Training.compile_cache / HYDRAGNN_COMPILE_CACHE); disabled = the
        # dispatch helper is a pass-through to the jit wrappers,
        # byte-identical to the historical path.
        cache_dir = (
            compile_cache
            if compile_cache is not None
            else os.environ.get("HYDRAGNN_COMPILE_CACHE", "")
        )
        self._exec_registry = None
        self._cache_fingerprint = ""
        self._cache_flags: tuple = ()
        self._cache_mesh = ""
        if mesh is not None:
            from ..parallel.distributed import mesh_descriptor

            self._cache_mesh = mesh_descriptor(mesh)
        if cache_dir:
            import hashlib

            from ..cache import ExecutableRegistry, ExecutableStore
            from ..checkpoint.format import param_fingerprint

            self._exec_registry = ExecutableRegistry(
                ExecutableStore(cache_dir), name="train"
            )
            # Program identity: the caller's config digest (run_training
            # hashes the Architecture + optimizer blocks) on top of the
            # checkpoint layer's param/opt-state tree fingerprints and the
            # module field repr — any model/optimizer change is a miss.
            self._cache_fingerprint = hashlib.sha256(
                (
                    compile_cache_fingerprint
                    + param_fingerprint(state.params)
                    + param_fingerprint(
                        {"opt": state.opt_state, "bstats": state.batch_stats}
                    )
                    + repr(model)
                ).encode()
            ).hexdigest()
            self._cache_flags = (
                (("donate",) if donate else ())
                + (("guard",) if guard else ())
                # Precision is a program-mode key component: a bf16 step and
                # the f32 seed step must NEVER hydrate each other's entries
                # (docs/PRECISION.md "Cache-key interaction").
                + (
                    (f"precision={self.precision.mode}",)
                    if self.precision is not None
                    else ()
                )
                # The gradient-sync arm AND its bucket size change the
                # compiled MESH program (plan_buckets groups leaves into
                # different per-bucket collectives) without changing any tree
                # shape; on a single-device driver the knob is inert and must
                # not cool a warm store (byte-identical program, same key).
                + (
                    (
                        f"grad_sync={self.grad_sync}"
                        f":bucket_mb={self.grad_bucket_mb}",
                    )
                    if self.grad_sync != "single" and mesh is not None
                    else ()
                )
            )
        # Whether the 'graph' mesh axis is active (edge arrays then need the
        # P('data','graph') placement the sharded step expects).
        self._graph_sharded = (
            mesh is not None
            and model.graph_axis is not None
            and mesh.shape.get("graph", 1) > 1
        )
        # Per-epoch transfer-vs-compute split of the LAST epoch-level call
        # (train_epoch / evaluate): filled by the device-feed pipeline,
        # credited into the Timer registry, read by bench.py.
        self.feed_stats = FeedStats()
        # Batch structure -> NamedSharding tree. Written from the
        # transfer thread AND the main-thread eval path; safe without a
        # lock because it is an idempotent memo (the value for a key is
        # deterministic, dict get/set are single-bytecode atomic under
        # the GIL, and a racing duplicate store just re-memoizes).
        self._sharding_trees: dict = {}  # guarded-by: none(idempotent memo; deterministic value per key; GIL-atomic dict ops; duplicate store is a benign re-memoization)

    # -------------------------------------------------- per-update host hooks
    def _after_update(self, metrics) -> None:
        """The host half of the step policies, once per step (streamed path)
        or per scan chunk: the precision monitor folds the summed overflow/
        growth metrics into telemetry (train/loss_scale gauge, prec/*
        counters, backoff flight event), then StepGuard runs its skip/rollback
        streak accounting — in that order, so a rollback's flight dump
        already carries the scale movement that preceded it."""
        if self.precision_monitor is not None:
            self.precision_monitor.after_update(self, metrics)
        if self.guard is not None:
            self.guard.after_update(self, metrics)

    # ------------------------------------------------- compiled-step dispatch
    def _dispatch(self, program: str, fn, shape_key, *args):
        """Route one compiled-step call through the shared
        :class:`~hydragnn_tpu.cache.ExecutableRegistry` when the persistent
        compile cache is enabled; otherwise call the jit wrapper directly
        (byte-identical to the historical path — the registry is the ONLY
        behavioral delta, and a cache-hit executable is bit-exact against a
        fresh compile, tests/test_compile_cache.py).

        ``shape_key`` is the caller's CHEAP signature of the varying
        arguments (the payload batch's padded shapes — state/rng structure
        is constant per driver, and the registry is per-driver): steady-state
        memory hits pay one tuple build, never fingerprint arithmetic. The
        full args-tree digest and environment key are computed lazily inside
        the miss closure only."""
        reg = self._exec_registry
        if reg is None:
            return fn(*args)
        from ..cache import CacheKey, tree_signature

        exe, _outcome, _seconds = reg.lookup_or_compile(
            (program, shape_key),
            lambda: CacheKey.for_environment(
                program=program,
                config_fingerprint=self._cache_fingerprint,
                flags=self._cache_flags,
                args_digest=tree_signature(args),
                mesh=self._cache_mesh,
            ),
            lambda: fn.lower(*args),
        )
        return exe(*args)

    @staticmethod
    def _dispatch_shape_key(batch: GraphBatch):
        """Cheap per-batch signature for _dispatch's in-memory key: padded
        array shapes plus the head-spec layout (targets change with
        set_head_spec without moving node shapes — they must miss)."""
        return (
            batch.node_features.shape,
            batch.senders.shape,
            batch.num_graphs_pad,
            batch.edge_features is None,
            tuple(t.shape for t in batch.targets),
        )

    # ----------------------------------------------------------- device feed
    def _sharding_tree(self, batch):
        """NamedSharding tree matching the placement the sharded step expects
        (the same _batch_pspec its shard_map uses), so the pipeline's
        device_put commits arrays exactly where the step reads them.
        Shardings are shape-agnostic, so the tree is memoized per batch
        STRUCTURE (edge presence, head count, static pad) — the transfer
        thread must not rebuild ~10 NamedShardings per batch."""
        from jax.sharding import NamedSharding, PartitionSpec

        key = (
            batch.edge_features is None,
            len(batch.targets),
            batch.num_graphs_pad,
        )
        cached = self._sharding_trees.get(key)
        if cached is None:
            spec = _batch_pspec(batch, self._graph_sharded)
            cached = jax.tree_util.tree_map(
                lambda s: NamedSharding(self.mesh, s),
                spec,
                is_leaf=lambda x: isinstance(x, PartitionSpec),
            )
            self._sharding_trees[key] = cached
        return cached

    def _wrap_faults(self, iterable):
        """Route a host batch source through the fault plan's injection hooks
        (NaN batches, collation stalls, process kill) — identity when no plan
        is active. Sits on the pipeline's host thread, BEFORE chunk stacking
        and transfer, on every train path."""
        if self.fault_plan is None or not self.fault_plan.active:
            return iterable
        return self.fault_plan.wrap_batches(iterable)

    def _put_timed(self, payload, prof=None):
        """The transfer stage: ONE blocking device_put per payload, on the
        pipeline's transfer thread. Batch k+1 commits (DMA) while step k
        computes; blocking here records true wire seconds, not dispatch.
        Transient failures (including the fault plan's injected transfer
        crashes, consulted here) are retried by the DeviceFeed's backoff
        wrapper around this function."""
        if self.fault_plan is not None:
            self.fault_plan.on_transfer()
        span = (
            prof.annotate("h2d") if prof is not None else contextlib.nullcontext()
        )
        t0 = time.perf_counter()
        with span:
            if self.multihost:
                dev = self._lift(payload)
            elif self.mesh is not None:
                dev = jax.device_put(payload, self._sharding_tree(payload))
            else:
                dev = jax.device_put(payload)
            jax.block_until_ready(dev)
        self.feed_stats.record_h2d(
            self._tree_nbytes(payload), time.perf_counter() - t0
        )
        return dev

    def _put_chunk(self, item):
        single, payload = item
        return single, self._put_timed(payload)

    def _drain_feed(self, feed, label: str):
        """End-of-epoch teardown: cancel the pipeline and give its threads a
        bounded window to exit BEFORE the stats are credited/reset — an
        in-flight transfer completing later must not record H2D into the
        next epoch's split (the join is bounded so a transfer wedged on a
        dead device link cannot hang the caller)."""
        feed.close()
        feed.join(2.0)
        self._credit_timers(label)

    def _credit_timers(self, label: str):
        """Fold the epoch's split into the Timer registry (print_timers)."""
        s = self.feed_stats
        if s.h2d_transfers:
            Timer.credit(f"{label}_h2d_transfer", s.h2d_s)
        if s.step_s:
            Timer.credit(f"{label}_device_step", s.step_s)
        if s.feed_wait_s:
            Timer.credit(f"{label}_feed_wait", s.feed_wait_s)

    @staticmethod
    def _cache_budget_bytes() -> int:
        import os

        return int(os.environ.get("HYDRAGNN_DEVICE_CACHE_MB", "512")) * (1 << 20)

    @staticmethod
    def _tree_nbytes(tree) -> int:
        return sum(
            getattr(leaf, "nbytes", 0) for leaf in jax.tree_util.tree_leaves(tree)
        )

    # ------------------------------------------------------------------ train
    @staticmethod
    def _shape_key(batch: GraphBatch):
        return (
            batch.node_features.shape,
            batch.senders.shape,
            batch.num_graphs_pad,
        )

    def _device_groups(self, loader):
        """Lazily yield per-device batch groups stacked for shard_map. Used for
        ANY mesh run (even data_axis=1 — the sharded step always expects the
        leading device axis). Bucketed loaders emit several static shapes;
        groups are formed per shape (tail groups are padded with empty
        batches by stack_batches)."""
        groups: dict = {}
        for b in loader:
            key = self._shape_key(b)
            group = groups.setdefault(key, [])
            group.append(b)
            if len(group) == self.n_devices:
                # Host-side numpy only — the TRANSFER stage lifts to device
                # arrays one group at a time (bounded device queue), so the
                # host prefetch queue never pins HBM.
                yield stack_batches(group, self.n_devices)
                groups[key] = []
        for group in groups.values():
            if group:
                yield stack_batches(group, self.n_devices)

    def _lift(self, stacked):
        """Host-local stacked batch → global jax.Array across processes."""
        if not self.multihost:
            return stacked
        from jax.experimental import multihost_utils
        from jax.sharding import PartitionSpec as P

        return multihost_utils.host_local_array_to_global_array(
            stacked, self.mesh, P("data")
        )

    def train_epoch(self, loader, profiler: Optional[Profiler] = None):
        self.feed_stats.reset()
        if self.guard is not None:
            # Epoch-start last-good snapshot: the rollback target (taken
            # before the donating step can consume these buffers).
            self.guard.begin_epoch(self)
        # The epoch-level telemetry span: its context is handed to the
        # DeviceFeed threads so collate/h2d spans parent here (the
        # flight-recorder timeline a guard-trip dump carries).
        with telemetry.span(
            "train_epoch", epoch=getattr(loader, "epoch", None)
        ) as ep:
            # Scan path only when nothing needs per-step host hooks.
            if self.mesh is None and not (profiler and profiler.active):
                return self._train_epoch_scan(loader, ep.ctx)
            metrics = EpochMetrics()
            prof = profiler or Profiler()
            # Two-stage device feed: collation thread -> transfer thread
            # (device_put with the step's placement) -> this consumer. Batch
            # k+1 is committed device memory while step k executes.
            batches = DeviceFeed(
                self._device_groups(
                    traced_batches(self._wrap_faults(loader))
                )
                if self.mesh is not None
                else traced_batches(self._wrap_faults(iter(loader))),
                transfer=lambda b: self._put_timed(b, prof),
                ctx=ep.ctx,
            )
            batch_iter = iter(iterate_tqdm(batches, self.verbosity))
            bi = 0
            try:
                while True:
                    # "feed" covers batch ACQUISITION (the device-queue wait
                    # — where an input-bound pipeline actually stalls);
                    # collation, the multi-host lift, and the H2D transfer
                    # all already happened on the pipeline threads.
                    with prof.annotate("feed"), timed_consume(
                        self.feed_stats, "feed_wait_s"
                    ):
                        batch = next(batch_iter, None)
                    if batch is None:
                        break
                    with prof.annotate("train_step"), telemetry.span(
                        "device_step", index=bi
                    ), timed_consume(self.feed_stats, "step_s"):
                        self.state, m = self._dispatch(
                            "train_step", self.train_step,
                            self._dispatch_shape_key(batch),
                            self.state, batch, self.rng,
                        )
                        metrics.update(m)
                    bi += 1
                    self._after_update(m)
                    if profiler:
                        profiler.step()
            finally:
                self._drain_feed(batches, "train")
            return metrics.averages()

    def _train_epoch_scan(self, loader, ctx=None):
        """Whole-epoch lax.scan in fixed-size chunks, buffered per batch shape
        (bucketed loaders emit a handful of static shapes). Chunk sizes repeat
        across epochs (loader length is constant), so compiles stay bounded:
        per shape, the full chunk plus remainders. The tqdm bar (verbosity
        2/4) ticks per batch as batches are consumed into chunks.

        reshuffle="batch" loaders (frozen membership) additionally get their
        stacked chunks cached ON DEVICE after the first epoch: steady-state
        epochs then do zero host collation and zero host->device transfer —
        the dominant cost when the device link is a tunnel. Batch visit
        order still reshuffles per epoch (chunk dispatch order on host, plus
        a device-side permutation of each chunk's stacked axis). Capped by
        HYDRAGNN_DEVICE_CACHE_MB (default 512). Cache entries carry the
        loader's head-spec generation; a set_head_spec after the build makes
        the entry a miss (the device batches baked the old targets)."""
        gen = getattr(loader, "generation", None)
        cached = self._scan_cache.get(id(loader))
        if cached is not None and cached.get("generation") != gen:
            del self._scan_cache[id(loader)]
            cached = None
        if cached is not None and cached.get("chunks") is not None:
            metrics = EpochMetrics()
            rng = np.random.default_rng(
                getattr(loader, "seed", 0) + getattr(loader, "epoch", 0)
            )
            # Recompile sentinel over steady replay epochs: the FIRST replay
            # epoch legitimately compiles the permuted-replay dispatch
            # (_perm_scan); from the second on, every executable exists and a
            # compile means a static-shape contract broke. Warn (never die)
            # in production; HYDRAGNN_NO_RECOMPILE=raise hardens it for
            # benchmarks/tests, =off silences it.
            from ..analysis import no_recompile

            sentinel_action = os.environ.get("HYDRAGNN_NO_RECOMPILE", "warn")
            if sentinel_action not in ("raise", "warn", "count", "off"):
                # An observability knob must never kill a training run: a
                # typo'd value degrades to the default, not a ValueError.
                sentinel_action = "warn"
            sentinel = (
                no_recompile(action=sentinel_action, label="cached replay epoch")
                if cached.get("warm") and sentinel_action != "off"
                else contextlib.nullcontext()
            )
            with sentinel:
                for ci in rng.permutation(len(cached["chunks"])):
                    single, payload = cached["chunks"][ci]
                    with telemetry.span(
                        "device_step", index=int(ci), cached=True
                    ), timed_consume(self.feed_stats, "step_s"):
                        if single:
                            self.state, m = self._dispatch(
                                "train_step", self.train_step,
                                self._dispatch_shape_key(payload),
                                self.state, payload, self.rng,
                            )
                        else:
                            # Batch-level order reshuffle WITHIN the chunk too —
                            # compiled into the scan dispatch (see _perm_scan), so
                            # the mode's "order reshuffles per epoch" promise holds
                            # even when the whole epoch fits one chunk. Membership
                            # and batch->chunk assignment stay frozen (the cache).
                            steps = jax.tree_util.tree_leaves(payload)[0].shape[0]
                            perm = jnp.asarray(rng.permutation(steps))
                            self.state, m = self._dispatch(
                                "perm_scan", self._perm_scan,
                                self._dispatch_shape_key(payload),
                                self.state, payload, perm, self.rng,
                            )
                        metrics.update(m)
                    self._after_update(m)
            cached["warm"] = True
            self._credit_timers("train")
            return metrics.averages()

        cacheable = (
            getattr(loader, "reshuffle", None) == "batch"
            # A fixed-order loader (shuffle=False) must never be replayed
            # with per-epoch permutations: the cache's replay contract IS
            # the "membership frozen, order reshuffles" mode.
            and getattr(loader, "shuffle", False)
            and self.mesh is None
            and id(loader) not in self._scan_cache  # not marked over-budget
        )
        sink: Optional[dict] = {"items": [], "bytes": 0} if cacheable else None
        metrics = EpochMetrics()
        # Two-stage device feed over stacked chunks: collation + stacking on
        # the host thread, device_put on the transfer thread, so chunk k+1
        # is committed while chunk k's scan executes. device_depth=1 (not
        # the per-batch default): payloads here are WHOLE scan chunks, and
        # one queued + one transferring + one computing already bounds the
        # transient HBM at ~3 chunks while keeping the overlap.
        feed = DeviceFeed(
            self._host_chunks(loader),
            transfer=self._put_chunk,
            device_depth=1,
            ctx=ctx,
        )
        try:
            for ci, (single, payload) in enumerate(feed):
                sink = self._run_scan_chunk(
                    single, payload, metrics, sink, index=ci
                )
        finally:
            self._drain_feed(feed, "train")
        if cacheable:
            # A None sink means the budget was blown mid-epoch. The loader
            # ref is kept EITHER WAY: the verdict is keyed by id(loader),
            # and without a strong ref a garbage-collected loader could hand
            # its id to a new loader that would silently inherit it.
            self._scan_cache[id(loader)] = {
                "loader": loader,
                "generation": gen,
                "chunks": sink["items"] if sink is not None else None,
            }
        return metrics.averages()

    def _host_chunks(self, loader):
        """Stage-1 producer for the scan path: collate (loader.__iter__) and
        group batches by shape into scan-chunk stacks, yielding
        ``(single, host payload)``. Runs on the pipeline's host thread, so
        numpy stacking also overlaps device compute."""
        bufs: dict = {}
        for b in traced_batches(
            self._wrap_faults(iterate_tqdm(loader, self.verbosity))
        ):
            buf = bufs.setdefault(self._shape_key(b), [])
            buf.append(b)
            if len(buf) == self.scan_chunk:
                yield self._stack_chunk(buf)
                buf.clear()
        for buf in bufs.values():
            if buf:
                yield self._stack_chunk(buf)

    @staticmethod
    def _stack_chunk(batches):
        if len(batches) == 1:
            return True, batches[0]
        return False, stack_batches(batches, len(batches))

    def _run_scan_chunk(
        self, single, payload, metrics, sink: Optional[dict], index: int = 0
    ):
        """Dispatch one device-resident chunk; when ``sink`` is given, retain
        THE SAME device copy for the reshuffle="batch" cache — the pipeline
        already transferred it, so the cache-building epoch performs exactly
        one host->device transfer per chunk. Returns None instead once the
        byte budget is exceeded; ``sink`` carries a running byte total so the
        first (timed) epoch's bookkeeping stays O(1) per chunk."""
        with telemetry.span(
            "device_step", index=index, chunk=not single
        ), timed_consume(self.feed_stats, "step_s"):
            if single:
                self.state, m = self._dispatch(
                    "train_step", self.train_step,
                    self._dispatch_shape_key(payload),
                    self.state, payload, self.rng,
                )
            else:
                self.state, m = self._dispatch(
                    "epoch_scan", self.epoch_scan,
                    self._dispatch_shape_key(payload),
                    self.state, payload, self.rng,
                )
            metrics.update(m)
        self._after_update(m)
        if sink is not None:
            nbytes = self._tree_nbytes(payload)
            if sink["bytes"] + nbytes <= self._cache_budget_bytes():
                sink["items"].append((single, payload))
                sink["bytes"] += nbytes
            else:
                sink = None
        return sink

    # ------------------------------------------------------------------- eval
    def evaluate(self, loader, return_values: bool = False, profiler=None):
        """validate()/test() analog. With return_values, also gathers per-head
        (true, predicted) arrays over real rows (test(), reference
        train_validate_test.py:267-304)."""
        with telemetry.span("evaluate") as ep:
            return self._evaluate(loader, return_values, profiler, ep.ctx)

    def _evaluate(self, loader, return_values, profiler, ctx=None):
        self.feed_stats.reset()
        prof = profiler or Profiler()
        metrics = EpochMetrics()
        num_heads = len(self.model.output_dim)
        true_values: List[List[np.ndarray]] = [[] for _ in range(num_heads)]
        pred_values: List[List[np.ndarray]] = [[] for _ in range(num_heads)]

        def to_host(arr):
            """Local rows of a possibly multi-host global array (per-process
            values, like the reference's per-rank test() lists)."""
            if self.multihost and hasattr(arr, "addressable_shards"):
                return np.concatenate(
                    [np.asarray(s.data) for s in arr.addressable_shards]
                )
            return np.asarray(arr)

        def consume(batch_host: GraphBatch, outputs):
            for ih, (htype, out) in enumerate(
                zip(self.model.output_type, outputs)
            ):
                out = to_host(out)
                if out.ndim == 3:  # DP: [D, rows, dim] → per-device slices
                    out = out.reshape(-1, out.shape[-1])
                mask = to_host(
                    batch_host.graph_mask if htype == "graph" else batch_host.node_mask
                ).reshape(-1)
                tgt = to_host(batch_host.targets[ih]).reshape(-1, out.shape[-1])
                pred_values[ih].append(out[mask])
                true_values[ih].append(tgt[mask])

        # Static eval loaders (shuffle=False: membership AND order are fixed,
        # so caching changes nothing semantically) keep their batches device-
        # resident after the first evaluate() — the per-epoch validation pass
        # then skips collation and host->device transfer entirely. Host
        # copies ride along for consume()'s masks/targets.
        gen = getattr(loader, "generation", None)
        cached = self._eval_cache.get(id(loader))
        if cached is not None and cached.get("generation") != gen:
            # set_head_spec bumped the loader's generation after this cache
            # was built: the device batches baked the old head spec/targets.
            del self._eval_cache[id(loader)]
            cached = None
        if cached is not None and cached.get("batches") is not None:
            for ei, (host_b, dev_b) in enumerate(cached["batches"]):
                with prof.annotate("eval_step"), telemetry.span(
                    "eval_step", index=ei, cached=True
                ), timed_consume(self.feed_stats, "step_s"):
                    m, outputs = self._dispatch(
                        "eval_step", self.eval_step,
                        self._dispatch_shape_key(dev_b),
                        self.state, dev_b,
                    )
                    metrics.update(m)
                if return_values:
                    consume(host_b, outputs)
            self._credit_timers("eval")
        else:
            cacheable = (
                self.mesh is None
                and getattr(loader, "shuffle", True) is False
                and id(loader) not in self._eval_cache
            )
            sink: Optional[dict] = {"items": [], "bytes": 0} if cacheable else None
            # Two-stage device feed, pairing each host batch (consume()'s
            # masks/targets are host-side, like the reference's per-rank
            # test() lists) with its device copy — which on a mesh is the
            # same GLOBAL [D_global, ...] lift train_epoch performs. The
            # cache sink reuses that same device copy: one transfer per
            # batch, cache build included.
            batches = DeviceFeed(
                self._device_groups(loader) if self.mesh is not None else iter(loader),
                transfer=lambda b: (b, self._put_timed(b, prof)),
                ctx=ctx,
            )
            try:
                for ei, (batch, dev_b) in enumerate(batches):
                    with prof.annotate("eval_step"), telemetry.span(
                        "eval_step", index=ei
                    ), timed_consume(self.feed_stats, "step_s"):
                        m, outputs = self._dispatch(
                            "eval_step", self.eval_step,
                            self._dispatch_shape_key(dev_b),
                            self.state, dev_b,
                        )
                        metrics.update(m)
                    if return_values:
                        consume(batch, outputs)
                    if sink is not None:
                        nbytes = self._tree_nbytes(batch)
                        if sink["bytes"] + nbytes <= self._cache_budget_bytes():
                            sink["items"].append((batch, dev_b))
                            sink["bytes"] += nbytes
                        else:
                            sink = None
            finally:
                self._drain_feed(batches, "eval")
            if cacheable:
                # Keep the loader ref even on an over-budget verdict so a
                # recycled id() cannot inherit it (see _scan_cache).
                self._eval_cache[id(loader)] = {
                    "loader": loader,
                    "generation": gen,
                    "batches": sink["items"] if sink is not None else None,
                }

        loss, rmses = metrics.averages()
        if return_values:
            tv = [np.concatenate(v) if v else np.zeros((0, 1)) for v in true_values]
            pv = [np.concatenate(v) if v else np.zeros((0, 1)) for v in pred_values]
            return loss, rmses, tv, pv
        return loss, rmses


def train_validate_test(
    driver: TrainingDriver,
    train_loader,
    val_loader,
    test_loader,
    num_epoch: int,
    writer=None,
    scheduler: Optional[ReduceLROnPlateau] = None,
    profiler: Optional[Profiler] = None,
    verbosity: int = 0,
    visualizer=None,
    output_names: Optional[List[str]] = None,
    plot_init_solution: bool = True,
    plot_hist_solution: bool = False,
    checkpoint_name: Optional[str] = None,
    checkpoint_every: int = 0,
    checkpoint_keep_last_k: int = 0,
    checkpoint_async: bool = True,
    start_epoch: int = 0,
    history: Optional[dict] = None,
):
    """The epoch loop (train_validate_test.py:94-137). Returns the loss history
    dict consumed by the Visualizer. With a visualizer attached, mirrors the
    reference's plot hooks: graph-size histogram + initial-solution scatter
    before training (train_validate_test.py:68-85), optional per-epoch scatter
    (plot_hist_solution, :131-137)."""
    if visualizer is not None:
        visualizer.num_nodes_plot()
        if plot_init_solution:
            _, _, tv, pv = driver.evaluate(test_loader, return_values=True)
            visualizer.create_scatter_plots(
                tv, pv, output_names=output_names, iepoch=-1
            )
    history = history or {
        "total_loss_train": [],
        "total_loss_val": [],
        "total_loss_test": [],
        "task_loss_train": [],
        "task_loss_val": [],
        "task_loss_test": [],
    }
    timer = Timer("train_validate_test")
    timer.start()
    # Cross-layer telemetry (docs/OBSERVABILITY.md): XLA compiles fold into
    # the graftel registry (jax/compiles, jax/compile_s), and each epoch
    # publishes its step/h2d/feed-wait/compile split as hydragnn_train_*
    # Prometheus gauges — the training analog of the serve /metrics surface.
    telemetry.install_jax_hooks()
    # Async checkpointing (docs/CHECKPOINTING.md): periodic saves snapshot
    # device→host on this thread and hand serialize/fsync/rename to a single
    # background writer — the epoch loop stalls for the snapshot only. The
    # per-save stall (async) or full save wall (sync) is credited to the
    # ``ckpt_save_stall`` timer so print_timers/bench expose what
    # checkpointing costs the training thread.
    checkpointer = None
    if checkpoint_name and checkpoint_every > 0 and checkpoint_async:
        from ..checkpoint import AsyncCheckpointer

        checkpointer = AsyncCheckpointer()
    try:
        for epoch in range(start_epoch, num_epoch):
            _start_supervisor_heartbeat_pump()
            _post_supervisor_heartbeat(epoch)
            for loader in (train_loader, val_loader, test_loader):
                if hasattr(loader, "set_epoch"):
                    loader.set_epoch(epoch)
            if profiler:
                profiler.set_current_epoch(epoch)

            compile_s0 = telemetry.counter_value("jax/compile_s")
            t_epoch0 = time.perf_counter()
            train_loss, train_rmses = driver.train_epoch(train_loader, profiler)
            train_wall_s = time.perf_counter() - t_epoch0
            train_split = driver.feed_stats.as_dict()
            val_loss, val_rmses = driver.evaluate(val_loader, profiler=profiler)
            test_loss, test_rmses = driver.evaluate(test_loader, profiler=profiler)

            # Per-epoch training gauges (rendered by telemetry.
            # render_prometheus; served by /metrics in a co-resident serve
            # process, dumped to logs/<name>/train_metrics.prom at run end).
            telemetry.gauge("train/epoch", epoch)
            telemetry.gauge("train/epoch_wall_s", round(train_wall_s, 4))
            telemetry.gauge("train/step_s_per_epoch", train_split["step_s"])
            telemetry.gauge("train/h2d_s_per_epoch", train_split["h2d_s"])
            telemetry.gauge(
                "train/h2d_mb_per_epoch",
                round(train_split["h2d_bytes"] / (1 << 20), 4),
            )
            telemetry.gauge(
                "train/feed_wait_s_per_epoch", train_split["feed_wait_s"]
            )
            telemetry.gauge(
                "train/compile_s_epoch",
                round(telemetry.counter_value("jax/compile_s") - compile_s0, 4),
            )

            if scheduler is not None:
                current_lr = get_learning_rate(driver.state.opt_state)
                # None = no injected LR knob (LBFGS: linesearch owns the step
                # size) — the plateau scheduler has nothing to act on.
                new_lr = (
                    scheduler.step(val_loss, current_lr)
                    if current_lr is not None
                    else None
                )
                if new_lr is not None and new_lr != current_lr:
                    driver.state = driver.state.replace(
                        opt_state=set_learning_rate(driver.state.opt_state, new_lr)
                    )
                    print_distributed(
                        verbosity,
                        f"Epoch {epoch}: learning rate reduced to {new_lr}",
                    )

            if writer is not None:
                writer.add_scalar("train error", train_loss, epoch)
                writer.add_scalar("validate error", val_loss, epoch)
                writer.add_scalar("test error", test_loss, epoch)
                for ivar, rmse in enumerate(train_rmses):
                    writer.add_scalar(f"train error of task {ivar}", rmse, epoch)

            print_distributed(
                verbosity,
                f"Epoch: {epoch:4d}  Train: {train_loss:.8f}  "
                f"Val: {val_loss:.8f}  Test: {test_loss:.8f}",
            )
            history["total_loss_train"].append(train_loss)
            history["total_loss_val"].append(val_loss)
            history["total_loss_test"].append(test_loss)
            history["task_loss_train"].append(train_rmses)
            history["task_loss_val"].append(val_rmses)
            history["task_loss_test"].append(test_rmses)

            if visualizer is not None and plot_hist_solution:
                _, _, tv, pv = driver.evaluate(test_loader, return_values=True)
                visualizer.create_scatter_plots(
                    tv, pv, output_names=output_names, iepoch=epoch
                )

            # Mid-training periodic checkpoint — an improvement over the
            # reference, which saves only once at the very end (SURVEY.md
            # §5.4); a preempted multi-hour run warm-starts from the last
            # save. Non-blocking by default (checkpoint_async).
            if (
                checkpoint_name
                and checkpoint_every > 0
                and (epoch + 1) % checkpoint_every == 0
            ):
                ckpt_vars = {
                    "params": driver.state.params,
                    "batch_stats": driver.state.batch_stats,
                }
                ckpt_meta = {
                    "epoch": epoch + 1,
                    "scheduler": scheduler.state_dict() if scheduler else None,
                    "history": history,
                }
                if checkpointer is not None:
                    stall = checkpointer.save(
                        ckpt_vars,
                        driver.state.opt_state,
                        checkpoint_name,
                        meta=ckpt_meta,
                        keep_last_k=checkpoint_keep_last_k,
                    )
                else:
                    from ..utils.model import save_model

                    t0 = time.perf_counter()
                    save_model(
                        ckpt_vars,
                        driver.state.opt_state,
                        checkpoint_name,
                        meta=ckpt_meta,
                        keep_last_k=checkpoint_keep_last_k,
                    )
                    stall = time.perf_counter() - t0
                Timer.credit("ckpt_save_stall", stall)
                telemetry.event(
                    "train/checkpoint_saved",
                    epoch=epoch + 1,
                    stall_s=round(stall, 4),
                )
    finally:
        if checkpointer is not None:
            # Run-exit wait barrier: every queued write lands before the run
            # returns (resume/predict reads the file next). On the clean path
            # a writer failure re-raises here; on an exception path it must
            # not mask the original error.
            import sys as _sys

            checkpointer.close(raise_errors=_sys.exc_info()[0] is None)
    if profiler:
        profiler.stop()
    timer.stop()
    return history
