"""Model factory (reference /root/reference/hydragnn/models/create.py:28-178).

Builds a HydraGNN flax module + initialized variables from the completed
Architecture config block. The reference seeds torch.manual_seed(0) at creation
(create.py:75); here initialization is keyed on PRNGKey(seed) with seed 0 default.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np

from ..graphs.batch import GraphBatch
from ..graphs.collate import collate_graphs
from .base import HydraGNN
from .convs import pna_degree_averages
from .loss import normalize_task_weights


def create_model_config(
    config: Dict[str, Any], verbosity: int = 0, use_gpu: bool = True
) -> HydraGNN:
    return create_model(
        model_type=config["model_type"],
        input_dim=config["input_dim"],
        hidden_dim=config["hidden_dim"],
        output_dim=config["output_dim"],
        output_type=config["output_type"],
        output_heads=config["output_heads"],
        task_weights=config["task_weights"],
        num_conv_layers=config["num_conv_layers"],
        freeze_conv=config.get("freeze_conv_layers", False),
        initial_bias=config.get("initial_bias"),
        num_nodes=config.get("num_nodes"),
        max_neighbours=config.get("max_neighbours"),
        edge_dim=config.get("edge_dim"),
        pna_deg=config.get("pna_deg"),
        compute_dtype=config.get("compute_dtype"),
        remat=config.get("remat", False),
        verbosity=verbosity,
    )


def create_model(
    model_type: str,
    input_dim: int,
    hidden_dim: int,
    output_dim: Sequence[int],
    output_type: Sequence[str],
    output_heads: Dict[str, Any],
    task_weights: Sequence[float],
    num_conv_layers: int,
    freeze_conv: bool = False,
    initial_bias: Optional[float] = None,
    num_nodes: Optional[int] = None,
    max_neighbours: Optional[int] = None,
    edge_dim: Optional[int] = None,
    pna_deg: Optional[Sequence[float]] = None,
    compute_dtype: Optional[str] = None,
    remat: bool = False,
    verbosity: int = 0,
) -> HydraGNN:
    if len(task_weights) != len(output_dim):
        raise ValueError(
            f"Inconsistent number of loss weights and tasks: {len(task_weights)} "
            f"VS {len(output_dim)}"
        )
    from .base import CONV_TYPES

    if model_type not in CONV_TYPES:
        raise ValueError("Unknown model_type: {0}".format(model_type))
    kwargs: Dict[str, Any] = {}
    if model_type == "PNA":
        assert pna_deg is not None, "PNA requires degree input."
        avg_log, avg_lin = pna_degree_averages(pna_deg)
        kwargs.update(pna_deg_avg_log=avg_log, pna_deg_avg_lin=avg_lin)
    elif model_type == "MFC":
        assert max_neighbours is not None, "MFC requires max_neighbours input."
        kwargs.update(mfc_max_degree=int(max_neighbours))
    elif model_type == "CGCNN":
        hidden_dim = input_dim  # CGCNN preserves channels (CGCNNStack.py:31-42)
    return HydraGNN(
        conv_type=model_type,
        input_dim=input_dim,
        hidden_dim=hidden_dim,
        output_dim=tuple(output_dim),
        output_type=tuple(output_type),
        config_heads=output_heads,
        num_conv_layers=num_conv_layers,
        task_weights=normalize_task_weights(task_weights),
        freeze_conv=bool(freeze_conv),
        num_nodes=num_nodes,
        initial_bias=initial_bias,
        edge_dim=edge_dim,
        compute_dtype=compute_dtype,
        remat=bool(remat),
        **kwargs,
    )


def init_model_variables(
    model: HydraGNN, example_batch: GraphBatch, seed: int = 0
) -> Dict[str, Any]:
    rngs = {"params": jax.random.PRNGKey(seed), "dropout": jax.random.PRNGKey(seed + 1)}
    return model.init(rngs, example_batch, train=False)


def make_example_batch(
    input_dim: int,
    output_dim: Sequence[int],
    output_type: Sequence[str],
    edge_dim: Optional[int] = None,
    num_nodes: int = 4,
) -> GraphBatch:
    """A tiny structurally-valid batch for shape inference / init."""
    from ..graphs.sample import GraphSample

    n = num_nodes
    x = np.ones((n, input_dim), dtype=np.float32)
    ei = np.stack(
        [np.arange(n, dtype=np.int32), (np.arange(n, dtype=np.int32) + 1) % n]
    )
    ea = np.ones((n, max(edge_dim or 1, 1)), dtype=np.float32)
    total = sum(
        d if t == "graph" else d * n for d, t in zip(output_dim, output_type)
    )
    y = np.zeros((total,), dtype=np.float32)
    y_loc = np.zeros((1, len(output_dim) + 1), dtype=np.int64)
    off = 0
    for i, (d, t) in enumerate(zip(output_dim, output_type)):
        off += d if t == "graph" else d * n
        y_loc[0, i + 1] = off
    s = GraphSample(x=x, pos=np.zeros((n, 3), np.float32), y=y, y_loc=y_loc,
                    edge_index=ei, edge_attr=ea)
    return collate_graphs(
        [s],
        head_types=output_type,
        head_dims=output_dim,
        edge_dim=edge_dim,
    )
