"""Message-passing convolutions as XLA segment-op programs.

Each layer is the TPU-native equivalent of a PyTorch-Geometric conv used by the
reference model zoo (/root/reference/hydragnn/models/*Stack.py): gather source-node
rows, compute per-edge messages as dense (MXU-friendly) matmuls over the padded
edge array, and scatter-aggregate at the receivers with masked segment ops. No
dynamic shapes: padding edges connect padding nodes, so aggregation needs no
special-casing beyond the statistics masks.

Call convention (all convs):
    y = conv(x, senders, receivers, edge_attr, edge_mask, node_mask, train=...,
             row_ptr=None)
with x: [N_pad, F], senders/receivers: [E_pad], edge_attr: [E_pad, D] or None,
row_ptr: [N_pad + 1] CSR boundaries over the destination-sorted receivers (the
PR-7 batch contract, graphs/csr.py) or None — when present, every sorted-path
aggregation consumes precomputed boundaries (zero in-step searchsorted) and
the Pallas opt-in routes to the CSR run-walk kernels.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn

from ..ops import pallas_segment

# Conv families whose aggregation rides the sorted/CSR edge layout end to end
# (every family since PR 7 — GAT's sort-breaking [edges; self-loops] concat
# was replaced by an explicit self-attention term). check_config consults
# this registry: a future family missing here would silently fall back to
# the unsorted scatter path on TPU, which the contract checker now rejects
# instead (analysis/contracts.py).
SORTED_PATH_FAMILIES = frozenset({"SAGE", "GIN", "MFC", "GAT", "CGCNN", "PNA"})


class SAGEConv(nn.Module):
    """GraphSAGE (mean aggregation): W_self·x_i + W_nbr·mean_j x_j.
    Reference: /root/reference/hydragnn/models/SAGEStack.py:24-31."""

    out_dim: int
    axis_name: Optional[str] = None  # mesh axis for edge-sharded graph parallelism

    @nn.compact
    def __call__(self, x, senders, receivers, edge_attr, edge_mask, node_mask, train=False, row_ptr=None):
        n = x.shape[0]
        nbr = pallas_segment.fused_segment_mean(x[senders], receivers, n, mask=edge_mask, axis_name=self.axis_name, sorted_ids=True, row_ptr=row_ptr)
        return nn.Dense(self.out_dim, name="lin_nbr")(nbr) + nn.Dense(
            self.out_dim, name="lin_self"
        )(x)


class GINConv(nn.Module):
    """GIN with inner 2-layer MLP and trainable eps (init 100.0, matching the
    reference's unusually large eps — GINStack.py:24-33)."""

    out_dim: int
    eps_init: float = 100.0
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, senders, receivers, edge_attr, edge_mask, node_mask, train=False, row_ptr=None):
        n = x.shape[0]
        eps = self.param("eps", nn.initializers.constant(self.eps_init), ())
        agg = pallas_segment.fused_segment_sum(x[senders], receivers, n, mask=edge_mask, axis_name=self.axis_name, sorted_ids=True, row_ptr=row_ptr)
        h = (1.0 + eps) * x + agg
        h = nn.Dense(self.out_dim, name="mlp_0")(h)
        h = nn.relu(h)
        return nn.Dense(self.out_dim, name="mlp_1")(h)


class MFCConv(nn.Module):
    """Molecular-fingerprint conv: degree-indexed weight pair
    W1[deg]·x_i + W2[deg]·Σ_j x_j, degree clamped to max_degree
    (reference MFCStack.py:24-36 → PyG MFConv). The per-node weight gather is a
    [N, F, F'] take — tiny at the hidden sizes this model family uses."""

    out_dim: int
    max_degree: int
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, senders, receivers, edge_attr, edge_mask, node_mask, train=False, row_ptr=None):
        n, f = x.shape
        d = self.max_degree + 1
        w_self = self.param(
            "w_self", nn.initializers.lecun_normal(), (d, f, self.out_dim)
        )
        w_nbr = self.param("w_nbr", nn.initializers.lecun_normal(), (d, f, self.out_dim))
        b = self.param("bias", nn.initializers.zeros, (d, self.out_dim))
        agg, deg_f = pallas_segment.fused_segment_sum_count(
            x[senders], receivers, n, mask=edge_mask, axis_name=self.axis_name,
            sorted_ids=True, row_ptr=row_ptr,
        )
        deg = jnp.clip(deg_f.astype(jnp.int32), 0, self.max_degree)
        out = jnp.einsum("nf,nfo->no", x, w_self[deg]) + jnp.einsum(
            "nf,nfo->no", agg, w_nbr[deg]
        )
        return out + b[deg]


class GATv2Conv(nn.Module):
    """GATv2 multi-head attention over incoming edges, with implicit self-loops and
    masked segment softmax (reference GATStack.py:88-97; heads=6,
    negative_slope=0.05 hardcoded by create.py:112-114, attention dropout wired to
    the model's dropout rate).

    Self-loops are an EXPLICIT self-attention term, not the historical
    ``[edges; self-loops]`` concat: for node ``i`` the softmax runs over
    {incoming edges} ∪ {i itself}, with the self logit computed densely
    [N, h] and its exp added to the segment denominator. Mathematically
    identical to concatenating one identity edge per node (parity-locked in
    tests/test_csr_contract.py), but the edge array keeps collation's
    destination-sorted order — GAT rides the sorted/CSR aggregation path
    like every other family instead of being the one scatter-bound holdout."""

    out_dim: int  # per-head output dim
    heads: int = 6
    negative_slope: float = 0.05
    concat: bool = True
    dropout: float = 0.25
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, senders, receivers, edge_attr, edge_mask, node_mask, train=False, row_ptr=None):
        from ..ops import segment as seg

        n = x.shape[0]
        h, f = self.heads, self.out_dim
        x_src = nn.Dense(h * f, name="lin_src")(x).reshape(n, h, f)
        x_dst = nn.Dense(h * f, name="lin_dst")(x).reshape(n, h, f)

        att = self.param("att", nn.initializers.lecun_normal(), (h, f))
        pre = nn.leaky_relu(
            x_src[senders] + x_dst[receivers], self.negative_slope
        )  # [E, h, f]
        logits = jnp.einsum("ehf,hf->eh", pre, att)  # [E, h]
        # Self term: the diagonal of the attention matrix, computed densely
        # (x_src[i] + x_dst[i] — no gather, no extra edges).
        pre_self = nn.leaky_relu(x_src + x_dst, self.negative_slope)
        logit_self = jnp.einsum("nhf,hf->nh", pre_self, att)  # [N, h]

        # Stabilized softmax over edges ∪ self. The per-node shift is the
        # TRUE max of the contributing logits (stop_gradient like
        # seg.segment_softmax): edgeless segments fill with -1e9, not 0, so
        # an isolated node's shift is exactly its self logit and
        # alpha_self = 1 there for ANY magnitude (a 0 fill would underflow
        # exp(logit_self) for strongly negative self logits and silently
        # drop the self message the concat formulation kept). m stays
        # finite everywhere — logit_self is dense — so padding rows cannot
        # produce NaNs.
        edge_max = seg.segment_max(
            logits, receivers, n, mask=edge_mask, fill=-1e9,
            axis_name=self.axis_name,
        )  # [N, h]
        m = jax.lax.stop_gradient(jnp.maximum(edge_max, logit_self))
        exp_e = jnp.where(
            edge_mask[:, None], jnp.exp(logits - m[receivers]), 0.0
        )  # [E, h]
        exp_self = jnp.where(
            node_mask[:, None], jnp.exp(logit_self - m), 0.0
        )  # [N, h]
        # The edge half of the denominator is globally reduced under graph
        # parallelism (psum inside fused_segment_sum); the self half is
        # identical on every shard (nodes replicated) and added AFTER the
        # reduction, so it is counted exactly once — the replacement for the
        # old shard-0-only self-loop mask.
        denom = pallas_segment.fused_segment_sum(
            exp_e, receivers, n, mask=edge_mask, axis_name=self.axis_name,
            sorted_ids=True, row_ptr=row_ptr,
        ) + exp_self
        alpha = exp_e / jnp.maximum(denom[receivers], 1e-16)  # [E, h]
        alpha_self = exp_self / jnp.maximum(denom, 1e-16)  # [N, h]
        if train and self.dropout > 0.0:
            rng = self.make_rng("dropout")
            keep = jax.random.bernoulli(
                rng, 1.0 - self.dropout, (n + alpha.shape[0],) + alpha.shape[1:]
            )
            alpha = jnp.where(
                keep[n:], alpha / (1.0 - self.dropout), 0.0
            )
            alpha_self = jnp.where(
                keep[:n], alpha_self / (1.0 - self.dropout), 0.0
            )
        msgs = x_src[senders] * alpha[..., None]  # [E, h, f]
        msgs = jnp.where(edge_mask[:, None, None], msgs, 0.0)
        out = pallas_segment.fused_segment_sum(
            msgs, receivers, n, axis_name=self.axis_name, sorted_ids=True,
            row_ptr=row_ptr,
        )  # [N, h, f]
        out = out + x_src * alpha_self[..., None]  # the self-loop message
        if self.concat:
            out = out.reshape(n, h * f)
            bias = self.param("bias", nn.initializers.zeros, (h * f,))
        else:
            out = out.mean(axis=1)
            bias = self.param("bias", nn.initializers.zeros, (f,))
        return out + bias


class CGConv(nn.Module):
    """Crystal-graph conv (channel-preserving, add-aggregated, gated):
    x_i + Σ_j σ(z·W_f)·softplus(z·W_s), z = [x_i, x_j, e_ij]
    (reference CGCNNStack.py:44-51 → PyG CGConv with aggr='add')."""

    edge_dim: int = 0
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, senders, receivers, edge_attr, edge_mask, node_mask, train=False, row_ptr=None):
        n, f = x.shape
        z = [x[receivers], x[senders]]
        if self.edge_dim and edge_attr is not None:
            z.append(edge_attr)
        z = jnp.concatenate(z, axis=-1)
        gate = jax.nn.sigmoid(nn.Dense(f, name="lin_f")(z))
        core = jax.nn.softplus(nn.Dense(f, name="lin_s")(z))
        msgs = gate * core
        # Padding edges carry nonzero softplus output — mask before aggregation.
        msgs = jnp.where(edge_mask[:, None], msgs, 0.0)
        return x + pallas_segment.fused_segment_sum(msgs, receivers, n, axis_name=self.axis_name, sorted_ids=True, row_ptr=row_ptr)


class PNAConv(nn.Module):
    """Principal Neighborhood Aggregation: 4 aggregators × 4 degree scalers with a
    pre-MLP on messages and a post-MLP on [x ‖ aggregated]
    (reference PNAStack.py:28-53 → PyG PNAConv, towers=1, pre_layers=1,
    post_layers=1, divide_input=False).

    ``deg_avg_log`` / ``deg_avg_lin`` are dataset statistics from the training
    degree histogram (reference calculate_PNA_degree, utils/model.py:81-86).
    """

    out_dim: int
    deg_avg_log: float
    deg_avg_lin: float
    edge_dim: Optional[int] = None
    axis_name: Optional[str] = None
    aggregators: Tuple[str, ...] = ("mean", "min", "max", "std")
    scalers: Tuple[str, ...] = ("identity", "amplification", "attenuation", "linear")

    @nn.compact
    def __call__(self, x, senders, receivers, edge_attr, edge_mask, node_mask, train=False, row_ptr=None):
        n, f = x.shape
        z = [x[receivers], x[senders]]
        if self.edge_dim and edge_attr is not None:
            z.append(edge_attr)
        z = jnp.concatenate(z, axis=-1)
        msg = nn.Dense(f, name="pre_nn")(z)  # [E, f]

        # Fused Pallas moments kernel on TPU (one pass over msg for mean/std),
        # masked XLA segment ops elsewhere — see ops/pallas_segment.py.
        agg, deg = pallas_segment.pna_aggregate(
            msg, receivers, n, self.aggregators,
            mask=edge_mask, axis_name=self.axis_name, sorted_ids=True,
            row_ptr=row_ptr,
        )  # agg: [N, A, f]

        deg = jnp.maximum(deg, 1.0)
        log_deg = jnp.log(deg + 1.0)
        scales = []
        for s in self.scalers:
            if s == "identity":
                scales.append(jnp.ones_like(deg))
            elif s == "amplification":
                scales.append(log_deg / self.deg_avg_log)
            elif s == "attenuation":
                scales.append(self.deg_avg_log / log_deg)
            elif s == "linear":
                scales.append(deg / self.deg_avg_lin)
            else:
                raise ValueError(f"Unknown scaler {s}")
        scale = jnp.stack(scales, axis=1)  # [N, S]

        # [N, S, A, f] → flatten: every aggregator under every scaler.
        combined = agg[:, None, :, :] * scale[:, :, None, None]
        combined = combined.reshape(n, len(self.scalers) * len(self.aggregators) * f)
        out = jnp.concatenate([x, combined], axis=-1)
        out = nn.Dense(self.out_dim, name="post_nn")(out)
        # PyG applies a final linear after the tower post-MLPs (PNAConv.lin).
        return nn.Dense(self.out_dim, name="lin")(out)


def pna_degree_averages(deg_histogram: Sequence[float]) -> Tuple[float, float]:
    """avg(log(d+1)) and avg(d) over the training-set in-degree histogram, the two
    normalizers PNA scalers need. Averages use raw bin degrees (PyG clamps only
    the runtime degree, not the histogram average)."""
    import numpy as np

    hist = np.asarray(deg_histogram, dtype=np.float64)
    degrees = np.arange(len(hist))
    total = hist.sum()
    if total == 0:
        return 1.0, 1.0
    avg_log = float((hist * np.log(degrees + 1)).sum() / total)
    avg_lin = float((hist * degrees).sum() / total)
    return max(avg_log, 1e-6), max(avg_lin, 1e-6)
