from .base import HydraGNN, MLPNode
from .create import create_model, create_model_config, init_model_variables
from .loss import multihead_rmse_loss, normalize_task_weights
