"""HydraGNN multi-headed GNN — the flax re-design of the reference architecture
core (/root/reference/hydragnn/models/Base.py:20-372 plus the per-conv Stack
subclasses). One module covers all six conv families; the conv flavor is a static
field, so each (conv_type, dims) combination compiles to one XLA program.

Architecture (mirrors reference semantics under padding):
  encoder:   num_conv_layers × [conv → MaskedBatchNorm → ReLU]
  readout:   masked segment-mean over nodes per graph (global_mean_pool analog)
  heads:     graph heads = shared MLP ("graph_shared") + per-head MLP;
             node heads = shared MLPNode ('mlp' / 'mlp_per_node') or a conv chain
             ('conv'), exactly the reference's three node-head modes
             (Base._multihead, Base.py:152-223).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp
import flax.linen as nn

from ..graphs.batch import GraphBatch
from ..ops import pallas_segment
from ..ops import segment as seg
from .layers import MLP, MaskedBatchNorm
from .convs import CGConv, GATv2Conv, GINConv, MFCConv, PNAConv, SAGEConv

CONV_TYPES = ("PNA", "MFC", "GIN", "GAT", "CGCNN", "SAGE")


class MLPNode(nn.Module):
    """Node-level decoder head (reference MLPNode, Base.py:321-372).

    'mlp': one MLP shared across nodes. 'mlp_per_node': a distinct MLP per node
    slot — only valid for fixed-size graphs; implemented as degree-style weight
    gather over the node's position inside its graph rather than the reference's
    python loop over node indices."""

    hidden_dims: Tuple[int, ...]
    out_dim: int
    node_type: str  # 'mlp' | 'mlp_per_node'
    num_nodes: Optional[int] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, batch: GraphBatch) -> jnp.ndarray:
        dims = tuple(self.hidden_dims) + (self.out_dim,)
        if self.node_type == "mlp":
            return MLP(dims, name="mlp")(x)
        assert self.num_nodes is not None, "mlp_per_node requires fixed graph size"
        n, f = x.shape
        # Node position within its graph: nodes are contiguous per graph by
        # collation, so pos = arange - start_of_my_graph.
        counts = seg.segment_count(batch.node_graph, batch.num_graphs_pad)
        starts = jnp.concatenate([jnp.zeros(1), jnp.cumsum(counts)[:-1]])
        pos = (jnp.arange(n) - starts[batch.node_graph]).astype(jnp.int32)
        pos = jnp.clip(pos, 0, self.num_nodes - 1)
        h = x
        in_dim = f
        for li, d in enumerate(dims):
            w = self.param(
                f"w_{li}", nn.initializers.lecun_normal(), (self.num_nodes, in_dim, d)
            )
            b = self.param(f"b_{li}", nn.initializers.zeros, (self.num_nodes, d))
            h = jnp.einsum("nf,nfo->no", h, w[pos]) + b[pos]
            if li < len(dims) - 1:
                h = nn.relu(h)
            in_dim = d
        return h


class HydraGNN(nn.Module):
    """Static configuration mirrors create_model's signature
    (/root/reference/hydragnn/models/create.py:55-178)."""

    conv_type: str
    input_dim: int
    hidden_dim: int
    output_dim: Tuple[int, ...]
    output_type: Tuple[str, ...]
    config_heads: Dict[str, Any]
    num_conv_layers: int
    task_weights: Tuple[float, ...] = ()  # normalized to Σ|w|=1 (Base.py:74-75)
    freeze_conv: bool = False
    dropout: float = 0.25
    num_nodes: Optional[int] = None
    initial_bias: Optional[float] = None
    ilossweights_nll: int = 0
    # Mesh axis name for edge-sharded graph parallelism (None = off).
    graph_axis: Optional[str] = None
    # Mixed precision: 'bfloat16' runs the network in bf16 on the MXU with
    # float32 master weights, loss, and BatchNorm statistics (trainer casts;
    # None = full float32). Not a reference feature — TPU-native addition.
    compute_dtype: Optional[str] = None
    # Rematerialize conv layers in the backward pass (jax.checkpoint):
    # activations of the encoder are recomputed instead of stored, trading
    # FLOPs for HBM on large graphs. TPU-native addition.
    remat: bool = False
    # Conv-family-specific static parameters.
    edge_dim: Optional[int] = None
    pna_deg_avg_log: float = 1.0
    pna_deg_avg_lin: float = 1.0
    mfc_max_degree: int = 10
    gat_heads: int = 6  # create.py:113
    gat_negative_slope: float = 0.05  # create.py:114

    @property
    def use_edge_attr(self) -> bool:
        return self.edge_dim is not None and self.edge_dim > 0

    @property
    def enc_dim(self) -> int:
        """Width of the encoder output (hidden_dim except CGCNN, which preserves
        channels — CGCNNStack.py:31-42)."""
        return self.input_dim if self.conv_type == "CGCNN" else self.hidden_dim

    def _make_conv(self, in_dim: int, out_dim: int, name: str, concat: bool = True):
        ct = self.conv_type
        ax = self.graph_axis

        def cls(c):
            # static_argnums: `train` (last positional arg) is a python bool.
            return nn.remat(c, static_argnums=(7,)) if self.remat else c

        if ct == "SAGE":
            return cls(SAGEConv)(out_dim, axis_name=ax, name=name)
        if ct == "GIN":
            return cls(GINConv)(out_dim, axis_name=ax, name=name)
        if ct == "MFC":
            return cls(MFCConv)(out_dim, self.mfc_max_degree, axis_name=ax, name=name)
        if ct == "GAT":
            return cls(GATv2Conv)(
                out_dim,
                heads=self.gat_heads,
                negative_slope=self.gat_negative_slope,
                concat=concat,
                dropout=self.dropout,
                axis_name=ax,
                name=name,
            )
        if ct == "CGCNN":
            return cls(CGConv)(edge_dim=self.edge_dim or 0, axis_name=ax, name=name)
        if ct == "PNA":
            return cls(PNAConv)(
                out_dim,
                deg_avg_log=self.pna_deg_avg_log,
                deg_avg_lin=self.pna_deg_avg_lin,
                edge_dim=self.edge_dim,
                axis_name=ax,
                name=name,
            )
        raise ValueError(f"Unknown conv_type {ct}")

    def setup(self):
        if self.conv_type not in CONV_TYPES:
            raise ValueError(f"Unknown conv_type {self.conv_type}")
        gat = self.conv_type == "GAT"
        h = self.gat_heads

        # --- encoder (Base._init_conv, Base.py:99-105; GAT override
        # GATStack.py:35-46: concat widths on all but the last layer) ---
        convs, bns = [], []
        if gat:
            convs.append(self._make_conv(self.input_dim, self.hidden_dim, "conv_0"))
            bns.append(MaskedBatchNorm(self.hidden_dim * h, name="bn_0"))
            for i in range(1, max(self.num_conv_layers - 1, 1)):
                convs.append(
                    self._make_conv(self.hidden_dim * h, self.hidden_dim, f"conv_{i}")
                )
                bns.append(MaskedBatchNorm(self.hidden_dim * h, name=f"bn_{i}"))
            i = max(self.num_conv_layers - 1, 1)
            convs.append(
                self._make_conv(
                    self.hidden_dim * h, self.hidden_dim, f"conv_{i}", concat=False
                )
            )
            bns.append(MaskedBatchNorm(self.hidden_dim, name=f"bn_{i}"))
        else:
            dims = [self.input_dim] + [self.enc_dim] * self.num_conv_layers
            for i in range(self.num_conv_layers):
                convs.append(self._make_conv(dims[i], dims[i + 1], f"conv_{i}"))
                bns.append(MaskedBatchNorm(dims[i + 1], name=f"bn_{i}"))
        self.convs = convs
        self.batch_norms = bns

        node_head_idx = [i for i, t in enumerate(self.output_type) if t == "node"]
        self.node_nn_type = (
            self.config_heads.get("node", {}).get("type") if node_head_idx else None
        )

        # --- node-head conv chain (Base._init_node_conv, Base.py:120-150; GAT
        # override GATStack.py:48-86; CGCNN forbids 'conv' CGCNNStack.py:53-75) ---
        nch, ncb, nco, ncob = [], [], [], []
        if node_head_idx and self.node_nn_type == "conv":
            if self.conv_type == "CGCNN":
                raise ValueError(
                    '"conv" node decoder is not supported for CGCNN; use "mlp" or '
                    '"mlp_per_node"'
                )
            hd = list(self.config_heads["node"]["dim_headlayers"])
            nlayers = self.config_heads["node"]["num_headlayers"]
            # GAT concat widens hidden chain widths by `heads` and disables
            # concat on the output conv (GATStack.py:48-86); mult=1 otherwise.
            mult = h if gat else 1
            nch.append(self._make_conv(self.enc_dim, hd[0], "node_conv_0"))
            ncb.append(MaskedBatchNorm(hd[0] * mult, name="node_bn_0"))
            for i in range(nlayers - 1):
                nch.append(
                    self._make_conv(hd[i] * mult, hd[i + 1], f"node_conv_{i + 1}")
                )
                ncb.append(MaskedBatchNorm(hd[i + 1] * mult, name=f"node_bn_{i + 1}"))
            for k, ih in enumerate(node_head_idx):
                nco.append(
                    self._make_conv(
                        hd[-1] * mult,
                        self.output_dim[ih],
                        f"node_out_conv_{k}",
                        concat=False,
                    )
                )
                ncob.append(
                    MaskedBatchNorm(self.output_dim[ih], name=f"node_out_bn_{k}")
                )
        self.convs_node_hidden = nch
        self.batch_norms_node_hidden = ncb
        self.convs_node_output = nco
        self.batch_norms_node_output = ncob

        # --- heads (Base._multihead, Base.py:152-223) ---
        if "graph" in self.config_heads and any(
            t == "graph" for t in self.output_type
        ):
            gcfg = self.config_heads["graph"]
            # shared_layout "framework" (default): ReLU between every pair of
            # shared Linears. "reference": the reference's exact Sequential
            # grammar — NO inner ReLU, only the trailing one (Base.py:155-162)
            # — required for exact forward parity of imported torch
            # checkpoints with num_sharedlayers > 1 (utils/torch_import.py).
            layout = gcfg.get("shared_layout", "framework")
            if layout not in ("framework", "reference"):
                raise ValueError(
                    f"output_heads.graph.shared_layout must be 'framework' "
                    f"or 'reference', got {layout!r}"
                )
            self.graph_shared = MLP(
                tuple([gcfg["dim_sharedlayers"]] * gcfg["num_sharedlayers"]),
                activate_final=True,
                inner_activation=layout != "reference",
                name="graph_shared",
            )

        heads = []
        for ihead, (htype, hdim) in enumerate(zip(self.output_type, self.output_dim)):
            if htype == "graph":
                gcfg = self.config_heads["graph"]
                dims = tuple(gcfg["dim_headlayers"][: gcfg["num_headlayers"]]) + (
                    hdim + self.ilossweights_nll,
                )
                heads.append(
                    MLP(
                        dims,
                        final_bias_value=self.initial_bias,
                        name=f"head_{ihead}",
                    )
                )
            elif htype == "node":
                if self.node_nn_type in ("mlp", "mlp_per_node"):
                    ncfg = self.config_heads["node"]
                    heads.append(
                        MLPNode(
                            tuple(ncfg["dim_headlayers"][: ncfg["num_headlayers"]]),
                            hdim,
                            self.node_nn_type,
                            num_nodes=self.num_nodes,
                            name=f"head_{ihead}",
                        )
                    )
                elif self.node_nn_type == "conv":
                    heads.append(None)  # handled via convs_node_* chains
                else:
                    raise ValueError(
                        f"Unknown node head type {self.node_nn_type}; use 'mlp', "
                        "'mlp_per_node' or 'conv'"
                    )
            else:
                raise ValueError(f"Unknown head type {htype}")
        self.heads_nn = heads

    def __call__(self, batch: GraphBatch, train: bool = False):
        x = batch.node_features
        edge_attr = batch.edge_features if self.use_edge_attr else None
        # Reference encoder loop: x = relu(bn(conv(x))) (Base.py:236-243).
        for conv, bn in zip(self.convs, self.batch_norms):
            # train passed positionally: nn.remat static_argnums needs it
            # positional to keep the python-bool branch static. row_ptr (the
            # CSR batch contract) rides behind it so every layer consumes
            # collation's precomputed segment boundaries.
            c = conv(
                x,
                batch.senders,
                batch.receivers,
                edge_attr,
                batch.edge_mask,
                batch.node_mask,
                train,
                batch.row_ptr,
            )
            x = nn.relu(bn(c, batch.node_mask, train))

        # Masked global mean pool (Base.py:247-250); graph_ptr is the CSR
        # boundary array over node_graph (nodes are contiguous per graph).
        x_graph = pallas_segment.fused_segment_mean(
            x, batch.node_graph, batch.num_graphs_pad, mask=batch.node_mask,
            sorted_ids=True, row_ptr=batch.graph_ptr,
        )

        outputs = []
        inode = 0
        for ihead, htype in enumerate(self.output_type):
            if htype == "graph":
                xg = self.graph_shared(x_graph)
                outputs.append(self.heads_nn[ihead](xg))
            else:
                if self.node_nn_type == "conv":
                    xn = x
                    chain = list(
                        zip(self.convs_node_hidden, self.batch_norms_node_hidden)
                    ) + [
                        (
                            self.convs_node_output[inode],
                            self.batch_norms_node_output[inode],
                        )
                    ]
                    for conv, bn in chain:
                        xn = conv(
                            xn,
                            batch.senders,
                            batch.receivers,
                            None,
                            batch.edge_mask,
                            batch.node_mask,
                            train,
                            batch.row_ptr,
                        )
                        # Reference applies relu(bn(.)) through the output layer
                        # too (Base.forward, Base.py:261-265).
                        xn = nn.relu(bn(xn, batch.node_mask, train))
                    inode += 1
                    outputs.append(xn)
                else:
                    outputs.append(self.heads_nn[ihead](x, batch))
        return outputs
