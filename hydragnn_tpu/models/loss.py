"""Multi-task losses over padded batches (reference Base.loss_rmse /
loss_hpweighted, /root/reference/hydragnn/models/Base.py:271-315).

Total loss = Σ_i w_i · RMSE_i with the weights pre-normalized to Σ|w| = 1
(Base.py:74-75). RMSEs are computed over real rows only via the batch masks."""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp

from ..graphs.batch import GraphBatch


def normalize_task_weights(weights: Sequence[float]) -> Tuple[float, ...]:
    total = sum(abs(w) for w in weights)
    return tuple(w / total for w in weights)


def head_mse(
    pred: jnp.ndarray, target: jnp.ndarray, mask: jnp.ndarray
) -> jnp.ndarray:
    """Masked mean squared error over rows where mask is True (all columns)."""
    sq = jnp.square(pred - target) * mask[:, None]
    count = jnp.maximum(jnp.sum(mask), 1.0) * pred.shape[1]
    return jnp.sum(sq) / count


def multihead_rmse_loss(
    outputs: Sequence[jnp.ndarray],
    batch: GraphBatch,
    output_type: Sequence[str],
    task_weights: Sequence[float],
    ilossweights_nll: int = 0,
):
    """Returns (total_weighted_loss, per-head RMSE array).

    ``ilossweights_nll=1`` (uncertainty-weighted NLL) is unfinished in the
    reference too — it raises there (Base.py:277-281); we keep the config knob
    and the same explicit error rather than silently mis-shaping the loss."""
    if ilossweights_nll == 1:
        raise ValueError("loss_nll() not ready yet")
    rmses = []
    total = 0.0
    for pred, target, htype, w in zip(
        outputs, batch.targets, output_type, task_weights
    ):
        mask = batch.graph_mask if htype == "graph" else batch.node_mask
        # max() floor keeps the sqrt VJP finite when a head's masked MSE is
        # exactly 0 (all-masked padding batches from stack_batches would
        # otherwise inject NaN grads that pmean spreads to every replica).
        rmse = jnp.sqrt(jnp.maximum(head_mse(pred, target, mask), 1e-16))
        rmses.append(rmse)
        total = total + w * rmse
    return total, jnp.stack(rmses)
