"""Shared neural layers: padding-aware BatchNorm and plain MLP stacks.

The reference applies torch_geometric.nn.BatchNorm over the ragged node dimension
(/root/reference/hydragnn/models/Base.py:236-243). Under static padding the batch
statistics MUST exclude padding rows or they are biased toward zero — this masked
variant computes mean/var over real rows only and keeps torch-style running
averages (momentum 0.1, i.e. decay 0.9) for eval mode.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import flax.linen as nn

from ..ops.segment import masked_mean


class MaskedBatchNorm(nn.Module):
    features: int
    momentum: float = 0.9  # running = momentum * running + (1-momentum) * batch
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x: jnp.ndarray, mask: jnp.ndarray, train: bool) -> jnp.ndarray:
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((self.features,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((self.features,), jnp.float32)
        )
        scale = self.param("scale", nn.initializers.ones, (self.features,))
        bias = self.param("bias", nn.initializers.zeros, (self.features,))

        in_dtype = x.dtype
        # Statistics always in float32 — bf16 mixed-precision compute must not
        # degrade the running mean/var (sums over many rows lose bits in bf16).
        x = x.astype(jnp.float32)
        if train:
            mean = masked_mean(x, mask, axis=0)
            mean_sq = masked_mean(jnp.square(x), mask, axis=0)
            var = jnp.maximum(mean_sq - jnp.square(mean), 0.0)
            if not self.is_initializing():
                ra_mean.value = self.momentum * ra_mean.value + (1 - self.momentum) * mean
                ra_var.value = self.momentum * ra_var.value + (1 - self.momentum) * var
        else:
            mean, var = ra_mean.value, ra_var.value

        y = (x - mean) * jnp.reciprocal(jnp.sqrt(var + self.eps)) * scale + bias
        # Keep padding rows at zero so downstream masked statistics stay exact.
        return jnp.where(mask[:, None], y, 0.0).astype(in_dtype)


class MLP(nn.Module):
    """Dense stack: Linear(dims[0]) → ReLU → ... → Linear(dims[-1]), optionally with
    a trailing activation and a custom final-bias constant (UQ initial_bias,
    reference Base._set_bias, Base.py:113-118).

    ``inner_activation=False`` drops the ReLUs BETWEEN Linears (the trailing
    ``activate_final`` ReLU is unaffected) — the reference's shared-MLP
    Sequential grammar (Base.py:155-162 builds [ReLU, Linear, Linear, ...,
    ReLU]: activation only before the first Linear — a no-op on the
    non-negative pooled encoder output — and after the last). The
    checkpoint importer needs this layout to reproduce reference forwards
    exactly for ``num_sharedlayers > 1`` (utils/torch_import.py)."""

    dims: Sequence[int]
    activate_final: bool = False
    final_bias_value: float | None = None
    inner_activation: bool = True

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        for i, d in enumerate(self.dims):
            last = i == len(self.dims) - 1
            if last and self.final_bias_value is not None:
                x = nn.Dense(
                    d,
                    bias_init=nn.initializers.constant(self.final_bias_value),
                    name=f"dense_{i}",
                )(x)
            else:
                x = nn.Dense(d, name=f"dense_{i}")(x)
            if (last and self.activate_final) or (
                not last and self.inner_activation
            ):
                x = nn.relu(x)
        return x
