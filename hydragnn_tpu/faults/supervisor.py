"""Crash-resume supervisor: restart-on-death around the periodic-checkpoint +
``Training.resume`` contract (docs/FAULT_TOLERANCE.md).

``run_training`` already resumes a killed run from its own periodic
checkpoint — but only when an operator reruns it. This module makes that loop
a first-class API::

    hydragnn_tpu.run_training(config, supervise=True, max_restarts=3)
    python -m hydragnn_tpu.faults.supervisor <config.json> [--max-restarts N]

The supervisor forces ``Training.resume = 1`` (and a periodic checkpoint
cadence if the config has none), snapshots the effective config into the run's
log dir, then runs the training as a CHILD PROCESS so any death — SIGKILL,
OOM, a segfaulting extension, an injected ``kill@K`` drill — is observable as
a nonzero/negative returncode rather than taking the supervisor down with it.
Each child gets ``HYDRAGNN_RESTART_COUNT`` in its environment (incarnation
index — fault plans use it to fire process-kill drills only once), and every
attempt is recorded in an atomically-updated ``logs/<name>/supervisor.json``
(restart counts, returncodes, durations) — the restart metadata the tests and
``bench.py --faults`` assert on.
"""

from __future__ import annotations

import argparse
import copy
import json
import os
import sys
import subprocess
import time
from typing import Optional

from .counters import FaultCounters
from .plan import RESTART_ENV_VAR

SUPERVISOR_META = "supervisor.json"
# graftelastic (docs/DISTRIBUTED.md "Elastic runbook"): the coordinator
# address an elastic supervisor exports to its children; the training epoch
# loop posts liveness beats to it (train/train_validate_test.py).
ELASTIC_COORD_ENV_VAR = "HYDRAGNN_ELASTIC_COORD"


def _atomic_write_json(path: str, doc: dict) -> None:
    # Shared fsync'd unique-tmp install — one durability contract for every
    # checkpoint-adjacent sidecar (checkpoint/io.atomic_write_json).
    from ..checkpoint.io import atomic_write_json

    atomic_write_json(path, doc)


def _write_meta(meta_path: str, meta: dict) -> dict:
    """Atomic supervisor.json update that PRESERVES fields other writers own:
    the verified checkpoint loader records ``checkpoint_fallbacks`` into the
    same file from inside the CHILD process (docs/CHECKPOINTING.md), and a
    supervisor rewrite must not clobber them."""
    try:
        with open(meta_path) as f:
            existing = json.load(f)
    except (OSError, ValueError):
        existing = {}
    if existing.get("checkpoint_fallbacks"):
        meta = dict(meta, checkpoint_fallbacks=existing["checkpoint_fallbacks"])
    _atomic_write_json(meta_path, meta)
    return meta


def _prepare_config(config: dict) -> dict:
    """Supervised copy of the config: resume from this run's own checkpoint on
    every restart, and guarantee there IS a checkpoint to resume from. The
    graftcache executable store defaults ON under supervision (set
    ``compile_cache: 0`` to opt out): a restarted incarnation re-pays the
    whole compile wall otherwise, which is exactly the cold-start cost the
    store exists to absorb (docs/COMPILE_CACHE.md)."""
    cfg = copy.deepcopy(config)
    tr = cfg["NeuralNetwork"]["Training"]
    tr["resume"] = 1
    if not tr.get("periodic_checkpoint_every"):
        tr["periodic_checkpoint_every"] = 1
    if "compile_cache" not in tr:
        tr["compile_cache"] = 1
    return cfg


def read_supervisor_meta(log_name: str, path: str = "./logs/") -> dict:
    """The restart metadata of a supervised run ({} when none exists)."""
    meta_path = os.path.join(path, log_name, SUPERVISOR_META)
    if not os.path.exists(meta_path):
        return {}
    with open(meta_path) as f:
        return json.load(f)


def record_elastic_transition(
    log_name: str, transition: dict, path: str = "./logs/"
) -> None:
    """Persist an elastic world transition into ``supervisor.json`` — the
    `mesh` block must always describe the topology the run LAST trained
    under, whoever observed the change (the supervisor's restart loop, or a
    STANDALONE resume that check_restart_topology admitted — without this, a
    manual resume at a changed world would leave the metadata stale and a
    post-mortem reading it would reconstruct the wrong history). Atomic
    read-modify-write; rank-0 callers only."""
    meta_path = os.path.join(path, log_name, SUPERVISOR_META)
    try:
        with open(meta_path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = {}
    doc.setdefault("elastic_transitions", []).append(transition)
    doc.setdefault("mesh", {})["world_size"] = int(transition["to_world"])
    _atomic_write_json(meta_path, doc)


def _monitored_child_run(
    cmd, env, tracker, coordinator, heartbeat_s: float
):
    """Run one child incarnation under the elastic membership loop: drain
    heartbeat posts from the coordinator mailbox into the tracker while the
    child lives, and — once the child has proven it CAN beat — treat silence
    past ``heartbeat_s`` as a hang: terminate it so the restart loop can act
    (a wedged child is as dead as a killed one, it just doesn't know it).
    Returns ``(returncode, heartbeats, stalled)``."""
    # Discard beats a dying previous incarnation left in the mailbox (its
    # final poll window): a stale beat must not "prove" the FRESH child can
    # beat and arm the hang-kill against it mid-startup.
    coordinator.posts("heartbeat")
    proc = subprocess.Popen(cmd, env=env)
    beats = 0
    last_beat: Optional[float] = None
    stalled = False
    try:
        while True:
            posts = coordinator.posts("heartbeat")
            tracker.drain(posts)
            # Only THIS child's beats arm/feed the hang-kill deadline: a dead
            # predecessor's in-flight post landing after the pre-spawn
            # discard must not "prove" the fresh child can beat while it is
            # still compiling (the beat payload carries the sender's pid).
            n = sum(
                1
                for _rank, p in posts
                if isinstance(p, dict) and p.get("pid") == proc.pid
            )
            if n:
                beats += n
                last_beat = time.monotonic()
            rc = proc.poll()
            if rc is not None:
                return rc, beats, stalled
            if (
                not stalled
                and last_beat is not None
                and time.monotonic() - last_beat > heartbeat_s
            ):
                stalled = True
                FaultCounters.inc("elastic_stall_kills")
                proc.terminate()
                try:
                    proc.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    proc.kill()
            time.sleep(0.05)
    finally:
        if proc.poll() is None:  # never leak a child past the supervisor
            proc.kill()
            proc.wait()


def run_supervised(
    config,
    max_restarts: int = 3,
    logs_path: str = "./logs/",
    python: Optional[str] = None,
    extra_env: Optional[dict] = None,
) -> dict:
    """Run ``run_training(config)`` under a restart loop; returns the restart
    metadata dict (also persisted as ``logs/<name>/supervisor.json``).

    A child exiting 0 completes the run. Any other exit (including death by
    signal) consumes one restart; the next child resumes from the run's last
    periodic checkpoint. Exhausting ``max_restarts`` raises, with the full
    attempt log in the metadata file.

    With ``Training.elastic`` configured the supervisor additionally runs the
    graftelastic membership loop (docs/DISTRIBUTED.md "Elastic runbook"): a
    ``ProxyRendezvous`` coordinator whose address children receive via
    ``HYDRAGNN_ELASTIC_COORD`` (the epoch loop posts liveness beats), a
    hang-kill deadline of ``heartbeat_s`` once a child has proven it beats,
    and restart-with-new-world — each incarnation re-reads the scheduler env
    and a world-size change within ``[min_workers, max_workers]`` is recorded
    as an elastic transition (the child re-shards and resumes); outside the
    range, the supervisor fails loudly naming both worlds.
    """
    from ..utils.config_utils import get_log_name_config
    from ..utils.model import cleanup_stale_checkpoint_tmp

    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    cfg = _prepare_config(config)
    log_name = get_log_name_config(cfg)
    run_dir = os.path.join(logs_path, log_name)
    os.makedirs(run_dir, exist_ok=True)
    # A previous incarnation may have died mid-checkpoint-replace.
    cleanup_stale_checkpoint_tmp(run_dir)
    cfg_path = os.path.join(run_dir, "supervisor_config.json")
    _atomic_write_json(cfg_path, cfg)

    # graftmesh elastic-restart metadata (docs/DISTRIBUTED.md "Elastic
    # runbook"): the mesh/worker topology this supervised run was launched
    # under, persisted BEFORE the first child so a post-mortem (or an elastic
    # rejoin deciding whether a checkpoint's world shape matches) never has
    # to re-derive it from env archaeology.
    from ..parallel.distributed import init_comm_size_and_rank

    training_cfg = cfg.get("NeuralNetwork", {}).get("Training", {})
    world_size, _rank = init_comm_size_and_rank()
    meta = {
        "log_name": log_name,
        "max_restarts": int(max_restarts),
        "restarts": 0,
        "completed": False,
        "attempts": [],
        "mesh": {
            "world_size": world_size,
            "graph_axis": int(training_cfg.get("graph_axis") or 1),
            "grad_sync": training_cfg.get("grad_sync") or "single",
            "elastic": training_cfg.get("elastic") or None,
        },
    }
    meta_path = os.path.join(run_dir, SUPERVISOR_META)
    # graftelastic membership loop (docs/DISTRIBUTED.md "Elastic runbook"):
    # only armed when Training.elastic is configured — the plain supervisor
    # keeps its historical subprocess.run path byte-for-byte.
    from ..parallel.elastic import ElasticConfig

    elastic_cfg = ElasticConfig.from_training(training_cfg)
    coordinator = None
    tracker = None
    coord_addr = None
    if elastic_cfg is not None:
        from ..parallel.elastic import MembershipTracker
        from ..parallel.loopback import ProxyRendezvous

        meta["elastic_transitions"] = []
        coordinator = ProxyRendezvous(world_size=max(1, world_size))
        coord_addr = f"127.0.0.1:{coordinator.serve()}"
        tracker = MembershipTracker(elastic_cfg.heartbeat_s)
    # Children import hydragnn_tpu by module path regardless of the run's
    # cwd (training runs chdir'd into scratch dirs are the norm in tests).
    pkg_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    attempt = 0
    try:
        while True:
            # Restart-with-new-world: each incarnation re-reads the scheduler
            # env. A changed world size is an elastic transition when
            # Training.elastic admits it (the child re-shards its loader and
            # rebuilds its mesh at the new size, resuming from the last
            # periodic checkpoint); otherwise it is a topology contradiction
            # and the supervisor fails LOUDLY naming both worlds — ONE
            # admission rule (check_restart_topology) shared with the
            # resuming child, so the two can never disagree on legality.
            from ..parallel.elastic import check_restart_topology

            cur_world, _ = init_comm_size_and_rank()
            try:
                transition = check_restart_topology(
                    meta["mesh"],
                    cur_world,
                    meta["mesh"].get("graph_axis", 1),
                    elastic_cfg,
                )
            except RuntimeError as e:
                _write_meta(meta_path, meta)
                raise RuntimeError(
                    f"supervised restart (attempt {attempt}): {e}"
                ) from e
            if transition is not None:
                transition = dict(transition, attempt=attempt)
                meta.setdefault("elastic_transitions", []).append(transition)
                meta["mesh"]["world_size"] = cur_world
                # Persist BEFORE the child spawns: the resuming incarnation
                # consumes this block — it must see the post-transition
                # world (and not re-record the same transition itself).
                meta = _write_meta(meta_path, meta)
                from ..telemetry import graftel as telemetry

                telemetry.event("elastic/supervisor_transition", **transition)
            env = dict(os.environ)
            env[RESTART_ENV_VAR] = str(attempt)
            env["PYTHONPATH"] = pkg_root + (
                os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
            )
            if coord_addr is not None:
                env[ELASTIC_COORD_ENV_VAR] = coord_addr
                # The child's pump thread beats at heartbeat_s/4 — liveness
                # never depends on epoch cadence.
                env["HYDRAGNN_ELASTIC_HEARTBEAT_S"] = str(
                    elastic_cfg.heartbeat_s
                )
            if extra_env:
                env.update(extra_env)
            cmd = [
                python or sys.executable,
                "-m",
                "hydragnn_tpu.faults.supervisor",
                "--child",
                cfg_path,
            ]
            t0 = time.time()
            if tracker is not None:
                returncode, heartbeats, stalled = _monitored_child_run(
                    cmd, env, tracker, coordinator, elastic_cfg.heartbeat_s
                )
            else:
                returncode = subprocess.run(cmd, env=env).returncode
                heartbeats, stalled = None, None
            record = {
                "attempt": attempt,
                "returncode": returncode,
                "duration_s": round(time.time() - t0, 3),
                "ts_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            }
            if tracker is not None:
                record["world_size"] = meta["mesh"]["world_size"]
                record["heartbeats"] = heartbeats
                record["stalled"] = stalled
            meta["attempts"].append(record)
            if returncode == 0:
                meta["completed"] = True
                return _write_meta(meta_path, meta)
            if attempt >= max_restarts:
                _write_meta(meta_path, meta)
                raise RuntimeError(
                    f"supervised training failed after {attempt} restart(s) "
                    f"(max_restarts={max_restarts}); attempt log: {meta_path}"
                )
            attempt += 1
            meta["restarts"] = attempt
            FaultCounters.inc("restarts")
            # Flight-recorder trigger (docs/OBSERVABILITY.md): the
            # supervisor's own timeline (attempt events, fault counters) at
            # each child death — dumped into the run dir next to
            # supervisor.json so "why did it restart" and "what restarted"
            # live side by side.
            from ..telemetry import graftel as telemetry

            telemetry.event(
                "fault/supervisor_restart",
                attempt=attempt,
                returncode=meta["attempts"][-1]["returncode"],
            )
            telemetry.flight_dump(
                "supervisor_restart",
                run_dir=run_dir,
                extra={
                    "attempt": attempt,
                    "returncode": meta["attempts"][-1]["returncode"],
                    "max_restarts": int(max_restarts),
                },
            )
            meta = _write_meta(meta_path, meta)
    finally:
        if coordinator is not None:
            coordinator.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hydragnn_tpu.faults.supervisor",
        description="Crash-resume supervisor for hydragnn_tpu training runs.",
    )
    ap.add_argument("config", help="training config JSON path")
    ap.add_argument("--max-restarts", type=int, default=3)
    ap.add_argument(
        "--child",
        action="store_true",
        help="internal: run one training incarnation in THIS process",
    )
    args = ap.parse_args(argv)
    if args.child:
        import hydragnn_tpu

        hydragnn_tpu.run_training(args.config)
        return 0
    meta = run_supervised(args.config, max_restarts=args.max_restarts)
    print(json.dumps(meta))
    return 0


if __name__ == "__main__":
    sys.exit(main())
