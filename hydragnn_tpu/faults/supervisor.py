"""Crash-resume supervisor: restart-on-death around the periodic-checkpoint +
``Training.resume`` contract (docs/FAULT_TOLERANCE.md).

``run_training`` already resumes a killed run from its own periodic
checkpoint — but only when an operator reruns it. This module makes that loop
a first-class API::

    hydragnn_tpu.run_training(config, supervise=True, max_restarts=3)
    python -m hydragnn_tpu.faults.supervisor <config.json> [--max-restarts N]

The supervisor forces ``Training.resume = 1`` (and a periodic checkpoint
cadence if the config has none), snapshots the effective config into the run's
log dir, then runs the training as a CHILD PROCESS so any death — SIGKILL,
OOM, a segfaulting extension, an injected ``kill@K`` drill — is observable as
a nonzero/negative returncode rather than taking the supervisor down with it.
Each child gets ``HYDRAGNN_RESTART_COUNT`` in its environment (incarnation
index — fault plans use it to fire process-kill drills only once), and every
attempt is recorded in an atomically-updated ``logs/<name>/supervisor.json``
(restart counts, returncodes, durations) — the restart metadata the tests and
``bench.py --faults`` assert on.
"""

from __future__ import annotations

import argparse
import copy
import json
import os
import sys
import subprocess
import time
from typing import Optional

from .counters import FaultCounters
from .plan import RESTART_ENV_VAR

SUPERVISOR_META = "supervisor.json"


def _atomic_write_json(path: str, doc: dict) -> None:
    # Shared fsync'd unique-tmp install — one durability contract for every
    # checkpoint-adjacent sidecar (checkpoint/io.atomic_write_json).
    from ..checkpoint.io import atomic_write_json

    atomic_write_json(path, doc)


def _write_meta(meta_path: str, meta: dict) -> dict:
    """Atomic supervisor.json update that PRESERVES fields other writers own:
    the verified checkpoint loader records ``checkpoint_fallbacks`` into the
    same file from inside the CHILD process (docs/CHECKPOINTING.md), and a
    supervisor rewrite must not clobber them."""
    try:
        with open(meta_path) as f:
            existing = json.load(f)
    except (OSError, ValueError):
        existing = {}
    if existing.get("checkpoint_fallbacks"):
        meta = dict(meta, checkpoint_fallbacks=existing["checkpoint_fallbacks"])
    _atomic_write_json(meta_path, meta)
    return meta


def _prepare_config(config: dict) -> dict:
    """Supervised copy of the config: resume from this run's own checkpoint on
    every restart, and guarantee there IS a checkpoint to resume from. The
    graftcache executable store defaults ON under supervision (set
    ``compile_cache: 0`` to opt out): a restarted incarnation re-pays the
    whole compile wall otherwise, which is exactly the cold-start cost the
    store exists to absorb (docs/COMPILE_CACHE.md)."""
    cfg = copy.deepcopy(config)
    tr = cfg["NeuralNetwork"]["Training"]
    tr["resume"] = 1
    if not tr.get("periodic_checkpoint_every"):
        tr["periodic_checkpoint_every"] = 1
    if "compile_cache" not in tr:
        tr["compile_cache"] = 1
    return cfg


def read_supervisor_meta(log_name: str, path: str = "./logs/") -> dict:
    """The restart metadata of a supervised run ({} when none exists)."""
    meta_path = os.path.join(path, log_name, SUPERVISOR_META)
    if not os.path.exists(meta_path):
        return {}
    with open(meta_path) as f:
        return json.load(f)


def run_supervised(
    config,
    max_restarts: int = 3,
    logs_path: str = "./logs/",
    python: Optional[str] = None,
    extra_env: Optional[dict] = None,
) -> dict:
    """Run ``run_training(config)`` under a restart loop; returns the restart
    metadata dict (also persisted as ``logs/<name>/supervisor.json``).

    A child exiting 0 completes the run. Any other exit (including death by
    signal) consumes one restart; the next child resumes from the run's last
    periodic checkpoint. Exhausting ``max_restarts`` raises, with the full
    attempt log in the metadata file.
    """
    from ..utils.config_utils import get_log_name_config
    from ..utils.model import cleanup_stale_checkpoint_tmp

    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    cfg = _prepare_config(config)
    log_name = get_log_name_config(cfg)
    run_dir = os.path.join(logs_path, log_name)
    os.makedirs(run_dir, exist_ok=True)
    # A previous incarnation may have died mid-checkpoint-replace.
    cleanup_stale_checkpoint_tmp(run_dir)
    cfg_path = os.path.join(run_dir, "supervisor_config.json")
    _atomic_write_json(cfg_path, cfg)

    # graftmesh elastic-restart metadata (docs/DISTRIBUTED.md "Elastic
    # runbook"): the mesh/worker topology this supervised run was launched
    # under, persisted BEFORE the first child so a post-mortem (or an elastic
    # rejoin deciding whether a checkpoint's world shape matches) never has
    # to re-derive it from env archaeology.
    from ..parallel.distributed import init_comm_size_and_rank

    training_cfg = cfg.get("NeuralNetwork", {}).get("Training", {})
    world_size, _rank = init_comm_size_and_rank()
    meta = {
        "log_name": log_name,
        "max_restarts": int(max_restarts),
        "restarts": 0,
        "completed": False,
        "attempts": [],
        "mesh": {
            "world_size": world_size,
            "graph_axis": int(training_cfg.get("graph_axis") or 1),
            "grad_sync": training_cfg.get("grad_sync") or "single",
            "elastic": training_cfg.get("elastic") or None,
        },
    }
    meta_path = os.path.join(run_dir, SUPERVISOR_META)
    # Children import hydragnn_tpu by module path regardless of the run's
    # cwd (training runs chdir'd into scratch dirs are the norm in tests).
    pkg_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    attempt = 0
    while True:
        env = dict(os.environ)
        env[RESTART_ENV_VAR] = str(attempt)
        env["PYTHONPATH"] = pkg_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
        )
        if extra_env:
            env.update(extra_env)
        t0 = time.time()
        proc = subprocess.run(
            [
                python or sys.executable,
                "-m",
                "hydragnn_tpu.faults.supervisor",
                "--child",
                cfg_path,
            ],
            env=env,
        )
        meta["attempts"].append(
            {
                "attempt": attempt,
                "returncode": proc.returncode,
                "duration_s": round(time.time() - t0, 3),
                "ts_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            }
        )
        if proc.returncode == 0:
            meta["completed"] = True
            return _write_meta(meta_path, meta)
        if attempt >= max_restarts:
            _write_meta(meta_path, meta)
            raise RuntimeError(
                f"supervised training failed after {attempt} restart(s) "
                f"(max_restarts={max_restarts}); attempt log: {meta_path}"
            )
        attempt += 1
        meta["restarts"] = attempt
        FaultCounters.inc("restarts")
        # Flight-recorder trigger (docs/OBSERVABILITY.md): the supervisor's
        # own timeline (attempt events, fault counters) at each child death —
        # dumped into the run dir next to supervisor.json so "why did it
        # restart" and "what restarted" live side by side.
        from ..telemetry import graftel as telemetry

        telemetry.event(
            "fault/supervisor_restart",
            attempt=attempt,
            returncode=meta["attempts"][-1]["returncode"],
        )
        telemetry.flight_dump(
            "supervisor_restart",
            run_dir=run_dir,
            extra={
                "attempt": attempt,
                "returncode": meta["attempts"][-1]["returncode"],
                "max_restarts": int(max_restarts),
            },
        )
        meta = _write_meta(meta_path, meta)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hydragnn_tpu.faults.supervisor",
        description="Crash-resume supervisor for hydragnn_tpu training runs.",
    )
    ap.add_argument("config", help="training config JSON path")
    ap.add_argument("--max-restarts", type=int, default=3)
    ap.add_argument(
        "--child",
        action="store_true",
        help="internal: run one training incarnation in THIS process",
    )
    args = ap.parse_args(argv)
    if args.child:
        import hydragnn_tpu

        hydragnn_tpu.run_training(args.config)
        return 0
    meta = run_supervised(args.config, max_restarts=args.max_restarts)
    print(json.dumps(meta))
    return 0


if __name__ == "__main__":
    sys.exit(main())
