"""Deterministic fault injection — the drill half of the fault-tolerance layer
(docs/FAULT_TOLERANCE.md).

A :class:`FaultPlan` is a parsed, seeded description of WHICH faults fire and
WHEN, consulted by the code that must survive them: the training driver's
batch source (NaN batches, collation stalls, process kill), the device-feed
transfer stage (transient transfer crashes), and loader construction (corrupt
samples). Every failure mode the guards/retry/quarantine/supervisor machinery
handles has a reproducible drill here — ``bench.py --faults`` and the tier-1
fault suite (tests/test_faults.py) are built on it.

Spec grammar (comma-separated entries, driven by ``HYDRAGNN_FAULTS`` or the
``Training.faults`` config string)::

    seed=7                     # seeds the corrupt-sample draw
    nan_grad@5                 # NaN-fill the node features of fed batch 5
    nan_grad@12-14             # ... of fed batches 12..14 (inclusive)
    corrupt_sample:count=3     # NaN-corrupt 3 seeded dataset samples
    corrupt_sample:frac=0.05   # ... or a fraction of the dataset
    poison_labels:frac=0.5     # silently flip/scale targets of seeded samples
    poison_labels:count=8:scale=20  # ... fixed count, explicit scale
    slow_collate:ms=40         # sleep 40 ms before yielding every batch
    slow_collate@2:ms=40       # ... only before fed batch 2
    transfer_crash@3           # transfer 3 raises a TRANSIENT error, once
    kill@9                     # SIGKILL this process at fed batch 9
    corrupt_ckpt@2             # bit-flip a byte in the file of save 2
    truncate_ckpt@2            # truncate the file of save 2 to half
    kill@save1                 # SIGKILL right after save 1 completes

Batch/transfer indices are cumulative over the plan's lifetime (one plan per
TrainingDriver), counted on the pipeline's host/transfer threads in feed
order — deterministic for a seeded loader. Checkpoint-save indices count
completed ``save_model`` calls (periodic + final, sync or async) via the
checkpoint subsystem's post-save hook, which the TrainingDriver registers.
``kill``/``kill@save`` and the checkpoint-corruption kinds fire only in the
first incarnation of a supervised run (``HYDRAGNN_RESTART_COUNT`` unset or
0), so a restart drill terminates — and recovers through the fallback chain —
instead of corrupting/kill-looping forever.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Iterable, Optional, Set

import numpy as np

from .counters import FaultCounters

ENV_VAR = "HYDRAGNN_FAULTS"
RESTART_ENV_VAR = "HYDRAGNN_RESTART_COUNT"


class InjectedFault(RuntimeError):
    """Base class for exceptions raised by fault injection."""


class InjectedTransientError(InjectedFault):
    """Injected failure that SHOULD be survivable by a retry (the drill for
    the device feed's transient-transfer backoff). ``transient = True`` is the
    attribute the pipeline's retry predicate keys off, so the drill exercises
    exactly the production classification path."""

    transient = True


def _parse_steps(sel: str) -> Set[int]:
    """``"5"`` → {5}; ``"12-14"`` → {12, 13, 14}."""
    if "-" in sel:
        lo, hi = sel.split("-", 1)
        return set(range(int(lo), int(hi) + 1))
    return {int(sel)}


class FaultPlan:
    """Parsed fault schedule with the hooks instrumented code consults."""

    KINDS = (
        "nan_grad",
        "corrupt_sample",
        "poison_labels",
        "slow_collate",
        "transfer_crash",
        "kill",
        "corrupt_ckpt",
        "truncate_ckpt",
    )

    def __init__(self, spec: str = ""):
        self.spec = spec or ""
        self.seed = 0
        self.restart = int(os.environ.get(RESTART_ENV_VAR, "0") or 0)
        self._nan_steps: Set[int] = set()
        self._kill_steps: Set[int] = set()
        self._kill_saves: Set[int] = set()
        self._slow: list = []  # (steps | None meaning every batch, seconds)
        self._transfer_crashes: Set[int] = set()
        self._ckpt_corrupt: Set[int] = set()
        self._ckpt_truncate: Set[int] = set()
        self.corrupt_count = 0
        self.corrupt_frac = 0.0
        self.poison_count = 0
        self.poison_frac = 0.0
        self.poison_scale = 10.0
        self._batch_i = 0
        self._transfer_i = 0
        self._ckpt_save_i = 0
        self._lock = threading.Lock()
        for raw in filter(None, (p.strip() for p in self.spec.split(","))):
            self._parse_entry(raw)

    def _parse_entry(self, raw: str) -> None:
        if raw.startswith("seed="):
            self.seed = int(raw.split("=", 1)[1])
            return
        head, *params = raw.split(":")
        kind, _, sel = head.partition("@")
        if kind not in self.KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r} in {raw!r} "
                f"(known: {', '.join(self.KINDS)})"
            )
        kv = {}
        for p in params:
            k, _, v = p.partition("=")
            kv[k] = v
        if kind == "nan_grad":
            self._nan_steps |= _parse_steps(sel)
        elif kind == "kill":
            # kill@save / kill@saveK: indexed by completed checkpoint save,
            # not by fed batch — the drill for crash-during-checkpointing.
            if sel.startswith("save"):
                self._kill_saves |= _parse_steps(sel[len("save"):] or "0")
            else:
                self._kill_steps |= _parse_steps(sel)
        elif kind == "corrupt_ckpt":
            self._ckpt_corrupt |= _parse_steps(sel or "0")
        elif kind == "truncate_ckpt":
            self._ckpt_truncate |= _parse_steps(sel or "0")
        elif kind == "transfer_crash":
            self._transfer_crashes |= _parse_steps(sel)
        elif kind == "slow_collate":
            seconds = float(kv.get("ms", "20")) / 1000.0
            self._slow.append((_parse_steps(sel) if sel else None, seconds))
        elif kind == "corrupt_sample":
            if "count" in kv:
                self.corrupt_count = int(kv["count"])
            if "frac" in kv:
                self.corrupt_frac = float(kv["frac"])
        elif kind == "poison_labels":
            if "count" in kv:
                self.poison_count = int(kv["count"])
            if "frac" in kv:
                self.poison_frac = float(kv["frac"])
            if "scale" in kv:
                self.poison_scale = float(kv["scale"])

    # ------------------------------------------------------------ constructors
    @classmethod
    def from_env(cls) -> Optional["FaultPlan"]:
        spec = os.environ.get(ENV_VAR, "").strip()
        return cls(spec) if spec else None

    @property
    def active(self) -> bool:
        return bool(
            self._nan_steps
            or self._kill_steps
            or self._kill_saves
            or self._slow
            or self._transfer_crashes
            or self._ckpt_corrupt
            or self._ckpt_truncate
            or self.corrupt_count
            or self.corrupt_frac
            or self.poison_count
            or self.poison_frac
        )

    # ------------------------------------------------------- batch-source hook
    def wrap_batches(self, iterable: Iterable):
        """Wrap the driver's host batch source (runs on the pipeline's host
        thread): applies slow-collate stalls, process kill, and NaN-batch
        corruption at the scheduled fed-batch indices."""
        for batch in iterable:
            i = self._batch_i
            self._batch_i += 1
            for steps, seconds in self._slow:
                if steps is None or i in steps:
                    FaultCounters.inc("injected_slow_collate")
                    time.sleep(seconds)
            if i in self._kill_steps and self.restart == 0:
                FaultCounters.inc("injected_kill")
                os.kill(os.getpid(), signal.SIGKILL)
            if i in self._nan_steps:
                FaultCounters.inc("injected_nan_batches")
                batch = batch.replace(
                    node_features=np.full_like(batch.node_features, np.nan)
                )
            yield batch

    # --------------------------------------------------------- transfer hook
    def on_transfer(self) -> None:
        """Consulted once per transfer (pipeline transfer thread). Raises a
        TRANSIENT error at scheduled transfer indices; each index fires only
        once, so the retry that follows succeeds."""
        with self._lock:
            i = self._transfer_i
            self._transfer_i += 1
            fire = i in self._transfer_crashes
            if fire:
                self._transfer_crashes.discard(i)
        if fire:
            FaultCounters.inc("injected_transfer_crashes")
            raise InjectedTransientError(
                f"injected transient transfer failure at transfer {i}"
            )

    # ------------------------------------------------------- checkpoint hook
    def on_checkpoint_saved(self, path_name: str) -> None:
        """Consulted by the checkpoint subsystem (``set_post_save_hook``)
        after every COMPLETED save — sync path or async writer thread. At
        scheduled save indices, corrupts the just-written file (seeded
        bit-flip / truncation) or SIGKILLs the process: the drills for the
        verified loader's fallback chain and the supervisor's resume-through-
        corruption path. All three are gated to incarnation 0 so a supervised
        restart recovers instead of re-corrupting its own saves."""
        with self._lock:
            i = self._ckpt_save_i
            self._ckpt_save_i += 1
        if self.restart != 0:
            return
        if i in self._ckpt_corrupt:
            self._flip_byte(path_name, self.seed + i)
            FaultCounters.inc("injected_corrupt_ckpt")
        if i in self._ckpt_truncate:
            os.truncate(path_name, os.path.getsize(path_name) // 2)
            FaultCounters.inc("injected_truncate_ckpt")
        if i in self._kill_saves:
            FaultCounters.inc("injected_kill")
            os.kill(os.getpid(), signal.SIGKILL)

    @staticmethod
    def _flip_byte(path_name: str, seed: int) -> None:
        """XOR one seeded byte in the file body (past any magic prefix, so the
        drill exercises digest verification, not just format sniffing)."""
        size = os.path.getsize(path_name)
        rng = np.random.default_rng(seed)
        off = int(rng.integers(16, size)) if size > 17 else size - 1
        with open(path_name, "r+b") as f:
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ 0xFF]))

    # ---------------------------------------------------------- sample hooks
    def corrupt_sample_indices(self, n: int) -> Set[int]:
        """Seeded choice of dataset indices to corrupt (empty when the plan
        carries no corrupt_sample entry)."""
        count = self.corrupt_count
        if self.corrupt_frac:
            count = max(count, int(round(self.corrupt_frac * n)))
        count = min(count, n)
        if count <= 0:
            return set()
        rng = np.random.default_rng(self.seed)
        return set(int(i) for i in rng.choice(n, size=count, replace=False))

    @staticmethod
    def corrupt(sample):
        """Corrupted copy of a GraphSample: NaN node features — the canonical
        'unparseable/garbage record' stand-in the quarantine validator must
        catch."""
        bad = sample.clone()
        bad.x = np.full_like(np.asarray(bad.x, dtype=np.float32), np.nan)
        return bad

    def corrupt_dataset(self, dataset: list) -> int:
        """Corrupt the scheduled (seeded) samples IN PLACE; returns how many."""
        idxs = self.corrupt_sample_indices(len(dataset))
        for i in idxs:
            dataset[i] = self.corrupt(dataset[i])
        if idxs:
            FaultCounters.inc("injected_corrupt_samples", len(idxs))
        return len(idxs)

    # ---------------------------------------------------- label poisoning
    def poison_sample_indices(self, n: int) -> Set[int]:
        """Seeded choice of dataset indices to label-poison (empty when the
        plan carries no poison_labels entry). A distinct seed stream from
        the corrupt-sample draw, so the two injections compose."""
        count = self.poison_count
        if self.poison_frac:
            count = max(count, int(round(self.poison_frac * n)))
        count = min(count, n)
        if count <= 0:
            return set()
        rng = np.random.default_rng(self.seed + 0x9E37)
        return set(int(i) for i in rng.choice(n, size=count, replace=False))

    def poison(self, sample):
        """Label-poisoned copy of a GraphSample: finite, plausible-looking
        features with SCALED+FLIPPED targets. Unlike :meth:`corrupt`'s NaN
        garbage, nothing here is detectable by a record validator — a
        fine-tune on poisoned labels converges to confidently-wrong outputs,
        and only an output-comparison gate (the flywheel's shadow gate,
        docs/FLYWHEEL.md) can refuse the resulting candidate."""
        bad = sample.clone()
        # Only the packed target vector flips; y_loc (the int64 head-offset
        # index) must stay intact or collation breaks — and a broken record
        # would be detectable, defeating the point of this fault.
        if bad.y is not None:
            arr = np.asarray(bad.y, dtype=np.float32)
            bad.y = -self.poison_scale * arr - 1.0
        return bad

    def poison_dataset(self, dataset: list) -> int:
        """Label-poison the scheduled (seeded) samples IN PLACE; returns how
        many. The flywheel soak uses this on a fine-tune's training split to
        manufacture the poisoned candidate the shadow gate must catch."""
        idxs = self.poison_sample_indices(len(dataset))
        for i in idxs:
            dataset[i] = self.poison(dataset[i])
        if idxs:
            FaultCounters.inc("injected_poisoned_labels", len(idxs))
        return len(idxs)
