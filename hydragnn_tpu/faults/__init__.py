"""Fault-tolerance layer: deterministic fault injection, the non-finite step
guard's host policy, fault-event counters, and the crash-resume supervisor.

The mechanisms themselves are threaded through the layers they protect —
trainer (guarded compiled step), train_validate_test (guard policy + injection
hooks), pipeline (retrying transfers), dataloader (sample quarantine),
utils/model (checkpoint retention + stale-tmp cleanup), serve/engine
(batch-scoped failures, output guard, worker restarts). This package holds
what is shared: the plan, the policy, the counters, the supervisor.

See docs/FAULT_TOLERANCE.md for the fault taxonomy, the policy knobs
(``Training.fault_tolerance``), and the drill how-to (``HYDRAGNN_FAULTS``).
"""

from .counters import FaultCounters
from .guard import StepGuard
from .plan import (
    ENV_VAR,
    FaultPlan,
    InjectedFault,
    InjectedTransientError,
)
from .supervisor import read_supervisor_meta, run_supervised

__all__ = [
    "ENV_VAR",
    "FaultCounters",
    "FaultPlan",
    "InjectedFault",
    "InjectedTransientError",
    "StepGuard",
    "read_supervisor_meta",
    "run_supervised",
]
