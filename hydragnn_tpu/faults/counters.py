"""Process-wide fault-event counters (docs/FAULT_TOLERANCE.md).

The fault-tolerance layer's observability half: every survival mechanism
(guard skip, rollback, transfer retry, sample quarantine, supervised restart)
increments a named counter here when it fires, so "the run survived" is never
silent — `print_timers` appends the counts to the end-of-run report,
``bench.py --faults`` embeds the snapshot in the drill artifact, and the
serving layer mirrors its own engine-scoped counters into Prometheus.

Class-level API like ``Timer`` (utils/time_utils.py); since the graftel PR
the storage is the process-wide telemetry registry (telemetry/graftel.py,
``fault/<name>`` keys) — counters arrive from the pipeline's host/transfer
threads, the training driver, and loader construction, and every increment
also lands in the flight-recorder ring as an event, so a dump taken at a
guard trip shows WHICH survival mechanisms fired and when.
"""

from __future__ import annotations

from typing import Dict

from ..telemetry import graftel as telemetry

_PREFIX = "fault/"


class FaultCounters:
    """Accumulating named integer counters; graftel-backed registry."""

    @classmethod
    def inc(cls, name: str, n: int = 1) -> None:
        if n <= 0:
            return
        telemetry.counter(_PREFIX + name, int(n))
        telemetry.event(_PREFIX + name, n=int(n))

    @classmethod
    def get(cls, name: str) -> int:
        return int(telemetry.counter_value(_PREFIX + name))

    @classmethod
    def snapshot(cls) -> Dict[str, int]:
        return {
            k[len(_PREFIX):]: int(v)
            for k, v in telemetry.counters_snapshot(_PREFIX).items()
        }

    @classmethod
    def reset(cls) -> None:
        telemetry.clear_counters(_PREFIX)
