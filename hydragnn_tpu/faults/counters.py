"""Process-wide fault-event counters (docs/FAULT_TOLERANCE.md).

The fault-tolerance layer's observability half: every survival mechanism
(guard skip, rollback, transfer retry, sample quarantine, supervised restart)
increments a named counter here when it fires, so "the run survived" is never
silent — `print_timers` appends the counts to the end-of-run report,
``bench.py --faults`` embeds the snapshot in the drill artifact, and the
serving layer mirrors its own engine-scoped counters into Prometheus.

Class-level registry like ``Timer`` (utils/time_utils.py) — counters arrive
from the pipeline's host/transfer threads, the training driver, and loader
construction, so increments are lock-protected.
"""

from __future__ import annotations

import threading
from typing import Dict

from ..analysis import tsan


class FaultCounters:
    """Accumulating named integer counters; class-level registry."""

    _counts: Dict[str, int] = {}  # guarded-by: FaultCounters._lock
    _lock = tsan.instrument_lock(threading.Lock(), "FaultCounters._lock")

    @classmethod
    def inc(cls, name: str, n: int = 1) -> None:
        if n <= 0:
            return
        with cls._lock:
            cls._counts[name] = cls._counts.get(name, 0) + int(n)
            tsan.shared_access("FaultCounters.registry")

    @classmethod
    def get(cls, name: str) -> int:
        with cls._lock:
            return cls._counts.get(name, 0)

    @classmethod
    def snapshot(cls) -> Dict[str, int]:
        with cls._lock:
            return dict(cls._counts)

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._counts.clear()
