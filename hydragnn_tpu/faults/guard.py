"""Non-finite step-guard policy — the host half of the NaN/Inf survival
mechanism (docs/FAULT_TOLERANCE.md).

The compiled train step (trainer._step_body with ``guard=True``) already
SKIPPED the update on a non-finite step (params/opt_state/batch_stats keep
their old values inside the jit, the step's metrics carry zero weight) and
returned a ``bad`` flag. This policy consumes that flag on the host:

* count bad steps (FaultCounters ``bad_steps``),
* after ``max_bad_steps`` CONSECUTIVE bad steps, roll the driver back to a
  retained last-good device-side snapshot and optionally back off the
  injected learning rate — a *persistent* divergence recovers to known-good
  state instead of skip-looping forever,
* refresh the snapshot every epoch (and, optionally, every
  ``snapshot_every`` good steps).

Scan-path granularity: the chunked ``lax.scan`` epoch reports ``bad`` SUMMED
per chunk, so consecutive-bad accounting is chunk-level there (a clean chunk
resets the streak; a chunk with any bad steps extends it by its bad count).
The skip itself is always exact per step — it lives inside the jit.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..telemetry import graftel as telemetry
from ..utils.optimizer import get_learning_rate, set_learning_rate
from ..utils.print_utils import print_distributed
from .counters import FaultCounters


# ONE dispatch per snapshot: a jitted identity over the array leaves returns
# fresh output buffers (no donation), so the copy survives the donating train
# step consuming the originals — per-leaf jnp.array copies would cost a
# dispatch per leaf every epoch.
_jit_copy_leaves = jax.jit(lambda xs: [x for x in xs])


def _copy_state(state):
    """Fresh device buffers — the driver's donating steps consume the live
    state's buffers, so a retained snapshot must never alias them. Non-array
    leaves (python scalars some optimizer states carry) pass through
    untouched rather than being traced into arrays."""
    leaves, treedef = jax.tree_util.tree_flatten(state)
    arr_idx = [i for i, leaf in enumerate(leaves) if isinstance(leaf, jax.Array)]
    if arr_idx:
        copied = _jit_copy_leaves([leaves[i] for i in arr_idx])
        for i, c in zip(arr_idx, copied):
            leaves[i] = c
    return jax.tree_util.tree_unflatten(treedef, leaves)


class StepGuard:
    """Per-driver skip/rollback policy over the compiled step's ``bad`` flag.

    Parameters (the ``Training.fault_tolerance`` knobs):
      max_bad_steps:   consecutive bad steps tolerated before a rollback.
      lr_backoff:      multiply the injected LR by this on rollback
                       (None/1.0 disables; optimizers without an injected LR
                       — LBFGS — are left untouched).
      min_lr:          floor for the backoff.
      snapshot_every:  additionally refresh the last-good snapshot every N
                       good steps (0 = epoch-start snapshots only).
    """

    def __init__(
        self,
        max_bad_steps: int = 3,
        lr_backoff: Optional[float] = 0.5,
        min_lr: float = 1e-6,
        snapshot_every: int = 0,
        verbosity: int = 0,
    ):
        self.max_bad_steps = max(1, int(max_bad_steps))
        self.lr_backoff = lr_backoff
        self.min_lr = float(min_lr)
        self.snapshot_every = int(snapshot_every)
        self.verbosity = verbosity
        self.bad_steps = 0
        self.consecutive = 0.0
        self.rollbacks = 0
        self._snap = None
        self._good_since_snap = 0

    # ------------------------------------------------------------- lifecycle
    def begin_epoch(self, driver) -> None:
        """Epoch-start snapshot: the rollback target is never older than one
        epoch (taken BEFORE the first step can donate the buffers away)."""
        self.take_snapshot(driver.state)

    def take_snapshot(self, state) -> None:
        self._snap = _copy_state(state)
        self._good_since_snap = 0

    # ----------------------------------------------------------- the policy
    def after_update(self, driver, metrics) -> bool:
        """Consume one step's (or one scan chunk's summed) metrics; returns
        True when a rollback fired. Reads only ``metrics['bad']`` — already
        host-synced by the driver's metric accumulation, so the guard adds no
        extra device round-trip."""
        bad = float(metrics.get("bad", 0.0))
        if bad <= 0.0:
            self.consecutive = 0.0
            self._good_since_snap += 1
            if self.snapshot_every and self._good_since_snap >= self.snapshot_every:
                self.take_snapshot(driver.state)
            return False
        n = int(round(bad))
        streak_started = self.consecutive <= 0.0
        self.bad_steps += n
        FaultCounters.inc("bad_steps", n)
        if streak_started:
            # Flight-recorder trigger (docs/OBSERVABILITY.md): the ring holds
            # the offending step's collate/h2d/device spans right now — dump
            # once per bad streak, not once per skipped step, so a 3-step
            # divergence produces one timeline, not three near-copies.
            telemetry.flight_dump(
                "guard_trip",
                extra={
                    "bad_steps_this_update": n,
                    "bad_steps_total": self.bad_steps,
                    "max_bad_steps": self.max_bad_steps,
                },
            )
        print_distributed(
            self.verbosity,
            f"StepGuard: skipped {n} non-finite step(s) "
            f"(streak {self.consecutive + bad:.0f}/{self.max_bad_steps})",
        )
        self.consecutive += bad
        if self.consecutive >= self.max_bad_steps:
            self.rollback(driver)
            return True
        return False

    def rollback(self, driver) -> None:
        """Restore the retained last-good state (a fresh copy — the snapshot
        itself survives for the next rollback) and back off the LR."""
        # Mixed-precision interplay (docs/PRECISION.md): the loss-scale state
        # must SURVIVE the rollback. The snapshot predates the overflow, so
        # restoring its scale would re-raise the scale that just overflowed
        # and the next attempt would trip the guard again — a rollback storm.
        # The backed-off live scale is precisely the adaptation the policy
        # made; params/opt/batch_stats roll back, the scale does not.
        live_loss_scale = getattr(driver.state, "loss_scale", None)
        if self._snap is not None:
            driver.state = _copy_state(self._snap)
            if live_loss_scale is not None:
                driver.state = driver.state.replace(
                    loss_scale=live_loss_scale
                )
        if self.lr_backoff and self.lr_backoff != 1.0:
            lr = get_learning_rate(driver.state.opt_state)
            if lr is not None:
                new_lr = max(lr * float(self.lr_backoff), self.min_lr)
                if new_lr < lr:
                    driver.state = driver.state.replace(
                        opt_state=set_learning_rate(
                            driver.state.opt_state, new_lr
                        )
                    )
                    print_distributed(
                        self.verbosity,
                        f"StepGuard: rollback LR backoff {lr} -> {new_lr}",
                    )
        self.rollbacks += 1
        FaultCounters.inc("rollbacks")
        telemetry.event(
            "fault/guard_rollback",
            rollbacks=self.rollbacks,
            bad_steps=self.bad_steps,
        )
        self.consecutive = 0.0

    @classmethod
    def from_config(cls, cfg: Optional[dict], verbosity: int = 0):
        """``Training.fault_tolerance`` block → StepGuard, or None when the
        guard is disabled (absent block, or ``enabled`` false) — the default,
        keeping the compiled step bit-identical to the unguarded build."""
        if not cfg or not cfg.get("enabled"):
            return None
        return cls(
            max_bad_steps=cfg.get("max_bad_steps", 3),
            lr_backoff=cfg.get("lr_backoff", 0.5),
            min_lr=cfg.get("min_lr", 1e-6),
            snapshot_every=cfg.get("snapshot_every", 0),
            verbosity=verbosity,
        )
