"""graftswap model registry — versioned model identities over the checkpoint
layer (docs/SERVING.md "Live model lifecycle", docs/CHECKPOINTING.md
"Version identity").

A **model version IS a v2 digest-verified checkpoint**: its identity is the
sha256 over the container's verified per-section digest map
(``checkpoint/format.content_identity``) — deterministic serialization means
the same weights always carry the same identity, and nothing about a version
can be trusted before its digests verify. The registry tracks three ROLES
over one run directory's checkpoint set (``<name>.pk`` latest + the
``keep_last_k`` retention manifest, checkpoint/io.py):

* ``live``      — the version the serve tier currently answers with;
* ``candidate`` — a staged version awaiting shadow-gated promotion;
* ``previous``  — the last live version, kept addressable for instant
  rollback (which is why rollback requires ``keep_last_k >= 2`` —
  contracts.py ``bad-lifecycle``).

Role state persists in an atomically-installed ``<name>.lifecycle.json``
sidecar (same fsync'd unique-tmp contract as the retention manifest), so a
kill at ANY point during a promote/rollback leaves either the old or the new
role table — never a torn one. The kill-during-swap drill SIGKILLs a process
between weight publication and state persistence and asserts exactly that.

Every load path rides the checkpoint layer's verified machinery:

* live/candidate loads ride :func:`checkpoint.io.load_verified_chain` when
  they target the latest file — a corrupt candidate FALLS BACK LOUDLY
  (``ckpt_corrupt_detected`` counter, supervisor.json record, flight dump)
  and the registry then REFUSES the promotion because the recovered
  identity is not the staged candidate's (the live version stays
  untouched);
* explicit-file loads use :func:`checkpoint.io.load_checkpoint_file`
  (digest-verified, corrupt → loud raise, counted here).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import asdict, dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..analysis import tsan
from ..checkpoint import format as ckpt_format
from ..checkpoint.format import CheckpointCorruptError, CheckpointError
from ..checkpoint.io import (
    atomic_write_json,
    load_checkpoint_bytes,
    load_checkpoint_file,
    load_verified_chain,
)
from ..telemetry import graftel as telemetry

ROLE_LIVE = "live"
ROLE_CANDIDATE = "candidate"
ROLE_PREVIOUS = "previous"
ROLES = (ROLE_LIVE, ROLE_CANDIDATE, ROLE_PREVIOUS)

STATE_SUFFIX = ".lifecycle.json"


class LifecycleError(RuntimeError):
    """Base class for model-lifecycle failures (registry/manager/gate)."""


class CandidateVerificationError(LifecycleError):
    """The staged candidate could not be loaded AS ITSELF: the verified
    chain fell back to a different (intact) version, or the explicit file's
    identity changed since staging. Promotion is refused; the live version
    is untouched. ``loaded_version`` names what the chain recovered (None
    when nothing loaded)."""

    def __init__(self, message: str, loaded_version: Optional[str] = None):
        super().__init__(message)
        self.loaded_version = loaded_version


class SwapGateError(LifecycleError):
    """Promotion refused by a gate (shadow diff gate not green, or a
    post-swap tolerance gate failure already reverted the weights). Carries
    the gate ``report``."""

    def __init__(self, message: str, report: Optional[dict] = None):
        super().__init__(message)
        self.report = report or {}


@dataclass(frozen=True)
class ModelVersion:
    """One addressable model version: verified content identity + where its
    bytes live. ``fingerprint`` is the param-TREE fingerprint (architecture
    identity) the engine's swap validation compares against."""

    version: str
    file: str
    path: str
    epoch: Optional[int]
    fingerprint: str

    @property
    def short(self) -> str:
        """12-hex display/annotation form — what responses and /healthz
        carry (the full identity stays in the registry state)."""
        return self.version[:12]


# ------------------------------------------------------------------ drill hook
# Pre-persist hook (mirrors checkpoint/io.set_post_save_hook): invoked with
# the role-table dict RIGHT BEFORE each atomic state install. The
# kill-during-swap drill registers a SIGKILL here (incarnation-0 gated by the
# drill itself) to prove a death between weight publication and state
# persistence leaves a consistent registry.
_pre_persist_hook: Optional[Callable[[Dict[str, Any]], None]] = None


def set_pre_persist_hook(hook: Optional[Callable[[Dict[str, Any]], None]]) -> None:
    global _pre_persist_hook
    _pre_persist_hook = hook


class ModelRegistry:
    """Role-tracked model versions over one run directory.

    Thread-safety: the role table is read by serving-side status surfaces
    while the manager mutates it on promote/rollback — every access to
    ``_roles`` holds ``_lock`` (``# guarded-by:`` annotated, graftrace- and
    tsan-checked; the lock is registered with the runtime sanitizer)."""

    def __init__(self, run_dir: str, name: str):
        self.run_dir = run_dir
        self.name = name
        self._lock = tsan.instrument_lock(
            threading.Lock(), "ModelRegistry._lock"
        )
        # Role table: role -> ModelVersion dict (the sidecar's schema).
        self._roles: Dict[str, Optional[Dict[str, Any]]] = {  # guarded-by: self._lock
            r: None for r in ROLES
        }
        self._load_state()

    # -------------------------------------------------------------- identity
    def identify(self, path: str) -> ModelVersion:
        """Digest-verified :class:`ModelVersion` of one checkpoint file.
        Corruption is COUNTED (``ckpt_corrupt_detected``) and raised — an
        unverifiable file is never a version."""
        from ..faults import FaultCounters

        try:
            identity, header = ckpt_format.file_content_identity(path)
        except CheckpointCorruptError:
            FaultCounters.inc("ckpt_corrupt_detected")
            telemetry.event("swap/candidate_corrupt", file=os.path.basename(path))
            raise
        return ModelVersion(
            version=identity,
            file=os.path.basename(path),
            path=path,
            epoch=header.get("epoch"),
            fingerprint=header.get("param_fingerprint") or "",
        )

    def versions(self) -> List[ModelVersion]:
        """Every verifiable version addressable from this run dir (latest +
        manifest entries), newest first, deduplicated by identity. Corrupt
        entries are skipped here (scan is an inventory, not a load — the
        load paths fail loudly)."""
        import json

        seen: Dict[str, ModelVersion] = {}
        candidates = [os.path.join(self.run_dir, self.name + ".pk")]
        manifest_path = os.path.join(
            self.run_dir, self.name + ".manifest.json"
        )
        try:
            with open(manifest_path) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            manifest = {}
        entries = sorted(
            manifest.get("entries", []),
            key=lambda e: e.get("serial", 0),
            reverse=True,
        )
        candidates += [os.path.join(self.run_dir, e["file"]) for e in entries]
        for path in candidates:
            if not os.path.exists(path):
                continue
            try:
                mv = self.identify(path)
            except CheckpointError:
                continue
            seen.setdefault(mv.version, mv)
        return list(seen.values())

    def _stabilize(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        """Prefer a retained epoch-tagged hard link over the volatile
        ``<name>.pk`` path in ROLE records: the latest file is overwritten
        by every subsequent save, while the retained file is this exact
        version's stable address (same inode at retention time, same
        verified identity here). Candidates deliberately stay on the latest
        path — that is what routes their load through the fallback chain."""
        import json

        latest = os.path.join(self.run_dir, self.name + ".pk")
        if os.path.abspath(doc["path"]) != os.path.abspath(latest):
            return doc
        manifest_path = os.path.join(
            self.run_dir, self.name + ".manifest.json"
        )
        try:
            with open(manifest_path) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            return doc
        for entry in sorted(
            manifest.get("entries", []),
            key=lambda e: e.get("serial", 0),
            reverse=True,
        ):
            path = os.path.join(self.run_dir, entry["file"])
            if not os.path.exists(path):
                continue
            try:
                mv = self.identify(path)
            except CheckpointError:
                continue
            if mv.version == doc["version"]:
                return asdict(mv)
        return doc

    # ------------------------------------------------------------------ roles
    def _get_role(self, role: str) -> Optional[ModelVersion]:
        with self._lock:
            doc = self._roles.get(role)
        return ModelVersion(**doc) if doc else None

    @property
    def live(self) -> Optional[ModelVersion]:
        return self._get_role(ROLE_LIVE)

    @property
    def candidate(self) -> Optional[ModelVersion]:
        return self._get_role(ROLE_CANDIDATE)

    @property
    def previous(self) -> Optional[ModelVersion]:
        return self._get_role(ROLE_PREVIOUS)

    def state(self) -> Dict[str, Any]:
        """Locked snapshot of the role table (the /healthz-adjacent status
        surface and the drills' assertion target)."""
        with self._lock:
            roles = {r: dict(d) if d else None for r, d in self._roles.items()}
        return {"name": self.name, "run_dir": self.run_dir, "roles": roles}

    # ---------------------------------------------------------------- staging
    def set_live(self, path: Optional[str] = None) -> ModelVersion:
        """Declare the currently-served version (boot-time registration:
        engines built from a checkpoint call this once so promote/rollback
        have an anchored starting point)."""
        mv = self.identify(path or os.path.join(self.run_dir, self.name + ".pk"))
        doc = self._stabilize(asdict(mv))
        with self._lock:
            self._roles[ROLE_LIVE] = doc
        self._persist()
        return ModelVersion(**doc)

    def stage_candidate(self, path: Optional[str] = None) -> ModelVersion:
        """Verify + stage a candidate version (default: the run's latest
        ``<name>.pk`` — the checkpoint the trainer just wrote). A candidate
        identical to live is refused: promoting it would be a no-op swap
        that still churns the role table."""
        mv = self.identify(path or os.path.join(self.run_dir, self.name + ".pk"))
        live = self.live
        if live is not None and live.version == mv.version:
            raise LifecycleError(
                f"candidate {mv.short} is already the live version — "
                "nothing to promote"
            )
        with self._lock:
            self._roles[ROLE_CANDIDATE] = asdict(mv)
        self._persist()
        telemetry.event(
            "swap/candidate_staged", version=mv.short, file=mv.file
        )
        return mv

    def clear_candidate(self, reason: str = "") -> Optional[ModelVersion]:
        """Drop the staged candidate role (one atomic sidecar install) and
        return what was staged (None when nothing was). The flywheel's
        rejection path: a red shadow gate clears the candidate so the next
        checkpoint can stage cleanly — the live/previous roles are
        untouched, and the candidate's BYTES stay wherever they were (the
        flywheel quarantines a copy for forensics before calling this)."""
        with self._lock:
            doc = self._roles.get(ROLE_CANDIDATE)
            self._roles[ROLE_CANDIDATE] = None
        self._persist()
        if not doc:
            return None
        telemetry.event(
            "swap/candidate_cleared",
            version=doc["version"][:12],
            reason=reason or None,
        )
        return ModelVersion(**doc)

    # ------------------------------------------------------------------ loads
    def load_role(
        self, role: str, variables: Dict[str, Any]
    ) -> Tuple[Dict[str, Any], Dict[str, Any], ModelVersion]:
        """Verified load of the version holding ``role`` onto a variables
        template → ``(variables, meta, loaded_version)``.

        The latest file loads through :func:`load_verified_chain` (corrupt →
        loud fallback walk); any OTHER retained file loads directly
        (digest-verified). Either way the LOADED bytes' identity must match
        the role's staged identity — a mismatch (the chain recovered some
        other intact version) raises :class:`CandidateVerificationError`
        and the caller's live weights stay untouched."""
        want = self._get_role(role)
        if want is None:
            raise LifecycleError(
                f"no {role!r} version is registered for {self.name!r}"
            )
        latest = os.path.join(self.run_dir, self.name + ".pk")
        if os.path.abspath(want.path) == os.path.abspath(latest):
            # ONE read of the latest file: identity and deserialization
            # attest the same bytes (a trainer overwriting <name>.pk between
            # a load and a re-read could otherwise desync them). An intact
            # blob with the staged identity loads directly; anything else
            # goes through the loud machinery below.
            blob: Optional[bytes] = None
            identity: Optional[str] = None
            try:
                with open(latest, "rb") as f:
                    blob = f.read()
                identity, _header = ckpt_format.content_identity(blob, latest)
            except CheckpointCorruptError:
                pass  # counted + recovered via the verified chain below
            if blob is not None and identity == want.version:
                new_vars, _opt, meta = load_checkpoint_bytes(
                    variables, blob, latest
                )
                return new_vars, meta, want
            if identity is not None:
                # Intact but DIFFERENT bytes: the trainer overwrote the
                # latest since staging — not corruption, but not the staged
                # candidate either. Refuse; re-stage to pick up the new one.
                raise CandidateVerificationError(
                    f"{role} file {want.file} changed since staging "
                    f"(staged {want.short}, on disk {identity[:12]}) — "
                    "refusing to serve a version nobody staged",
                    loaded_version=identity,
                )
            # Corrupt latest: walk the verified chain LOUDLY (it counts
            # every corrupt entry into ckpt_corrupt_detected and records the
            # fallback in supervisor.json + a flight dump). Whatever intact
            # version it recovers cannot be the staged candidate, so the
            # promotion is refused — the point of the corrupt-candidate
            # drill.
            telemetry.event(
                "swap/candidate_corrupt", file=os.path.basename(latest)
            )
            new_vars, _opt, meta, report = load_verified_chain(
                variables, self.run_dir, self.name
            )
            loaded_path = (
                latest
                if report is None
                else os.path.join(self.run_dir, report["fallback_file"])
            )
            loaded = self.identify(loaded_path)
            raise CandidateVerificationError(
                f"{role} version {want.short} ({want.file}) failed "
                f"verification; the fallback chain recovered "
                f"{loaded.short} ({loaded.file}) instead — refusing to "
                f"serve a version nobody staged",
                loaded_version=loaded.version,
            )
        # Retained/explicit file: one verified read, no chain.
        try:
            loaded = self.identify(want.path)
        except CheckpointCorruptError as e:
            raise CandidateVerificationError(
                f"{role} version {want.short} ({want.file}) is corrupt: "
                f"{e.reason}",
            ) from e
        if loaded.version != want.version:
            raise CandidateVerificationError(
                f"{role} file {want.file} changed since staging "
                f"(staged {want.short}, on disk {loaded.short})",
                loaded_version=loaded.version,
            )
        new_vars, _opt, meta = load_checkpoint_file(variables, want.path)
        return new_vars, meta, loaded

    # ------------------------------------------------------------ role flips
    def commit_promote(self, version: ModelVersion) -> None:
        """candidate → live, live → previous — one atomic sidecar install.
        ``version`` must be the staged candidate (the manager passes the
        identity it actually loaded and swapped)."""
        with self._lock:
            cand = self._roles.get(ROLE_CANDIDATE)
            if not cand or cand["version"] != version.version:
                raise LifecycleError(
                    f"commit_promote({version.short}) does not match the "
                    "staged candidate"
                )
        # The new live's stable address (retained hard link, not the
        # soon-to-be-overwritten latest) — resolved outside the lock (file
        # reads), then committed.
        stable = self._stabilize(cand)
        with self._lock:
            if self._roles.get(ROLE_CANDIDATE) != cand:
                raise LifecycleError(
                    "candidate changed concurrently during commit_promote"
                )
            self._roles[ROLE_PREVIOUS] = self._roles.get(ROLE_LIVE)
            self._roles[ROLE_LIVE] = stable
            self._roles[ROLE_CANDIDATE] = None
        self._persist()
        telemetry.event("swap/promoted", version=version.short)

    def commit_rollback(self, version: ModelVersion) -> None:
        """live ↔ previous — one atomic sidecar install. Keeping the
        rolled-back version addressable as ``previous`` lets an operator
        roll FORWARD again after the underlying issue is fixed."""
        with self._lock:
            prev = self._roles.get(ROLE_PREVIOUS)
            if not prev or prev["version"] != version.version:
                raise LifecycleError(
                    f"commit_rollback({version.short}) does not match the "
                    "recorded previous version"
                )
            self._roles[ROLE_PREVIOUS] = self._roles.get(ROLE_LIVE)
            self._roles[ROLE_LIVE] = prev
        self._persist()
        telemetry.event("swap/rolled_back", version=version.short)

    # ------------------------------------------------------------ persistence
    def _state_path(self) -> str:
        return os.path.join(self.run_dir, self.name + STATE_SUFFIX)

    def _load_state(self) -> None:
        import json

        try:
            with open(self._state_path()) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return
        roles = doc.get("roles") or {}
        with self._lock:
            for role in ROLES:
                rec = roles.get(role)
                if isinstance(rec, dict) and rec.get("version"):
                    self._roles[role] = rec

    def _persist(self) -> None:
        with self._lock:
            roles = {r: dict(d) if d else None for r, d in self._roles.items()}
        doc = {
            "name": self.name,
            "roles": roles,
            "updated_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }
        hook = _pre_persist_hook
        if hook is not None:
            hook(doc)
        atomic_write_json(self._state_path(), doc)
