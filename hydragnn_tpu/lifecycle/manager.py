"""LifecycleManager — promotion as a metrics decision, not a restart
(docs/SERVING.md "Live model lifecycle"; ROADMAP item 4).

One manager owns a :class:`~hydragnn_tpu.lifecycle.registry.ModelRegistry`
and the live fleet's engines (optionally the front router, for the shadow
gate). The loop it closes::

    trainer writes checkpoint              (checkpoint/io.save_model)
      → stage_candidate()                  (digest-verified identity)
      → router.set_shadow(candidate arm)   (mirrored traffic, diff gate)
      → promote()                          (gate green → verified load →
                                            engine.swap_weights on every
                                            replica → registry role flip)
      → rollback()                         (previous ↔ live, one swap)

Every step is refusal-first: a corrupt candidate is caught by the verified
chain (the fleet keeps serving, ``ckpt_corrupt_detected`` counts it), a red
shadow gate raises :class:`SwapGateError`, a wrong-architecture candidate is
rejected by the engine's fingerprint check, and a quantized arm that fails
its post-swap tolerance gate reverts inside ``swap_weights``. Only after
every engine swapped does the registry's role table flip (atomic sidecar
install) — a kill anywhere in between leaves either the old or the new
table, which the kill-during-swap drill asserts.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

from ..telemetry import graftel as telemetry
from .registry import (
    LifecycleError,
    ModelRegistry,
    ModelVersion,
    SwapGateError,
)


class LifecycleManager:
    """Promote/rollback orchestration over a registry + engine fleet.

    Parameters
    ----------
    registry:
        The run's :class:`ModelRegistry`.
    engines:
        The live fleet's ``InferenceEngine`` objects (in-process replicas;
        an HTTP fleet drives the same API per-process). All must serve the
        same architecture — the swap validates it per engine.
    router:
        Optional front ``Router``. When it has a shadow arm configured,
        :meth:`promote` requires the shadow gate green (``force=True``
        overrides, loudly) and clears the shadow on success.
    """

    def __init__(
        self,
        registry: ModelRegistry,
        engines: Sequence[Any],
        router: Optional[Any] = None,
    ):
        if not engines:
            raise ValueError("LifecycleManager needs at least one engine")
        self.registry = registry
        self.engines: List[Any] = list(engines)
        self.router = router

    # ---------------------------------------------------------------- helpers
    @staticmethod
    def _is_engine(target: Any) -> bool:
        """In-process engines are driven by ``swap_weights(variables, ...)``;
        anything else (an ``HttpReplica``, a spawned fleet member) is driven
        by ``swap_checkpoint(path, ...)`` — the /swap admin endpoint, which
        re-verifies the staged identity server-side."""
        return hasattr(target, "swap_weights")

    def _inproc_engines(self) -> List[Any]:
        return [t for t in self.engines if self._is_engine(t)]

    def _template(self) -> Dict[str, Any]:
        """Variables template for verified loads — the first in-process
        engine's ``variables_template()`` (one definition with the /swap
        path, serve/engine.py). Pure-HTTP fleets never call this: their
        verified load happens replica-side through /swap's identity check."""
        return self._inproc_engines()[0].variables_template()

    def _load_role(self, role: str):
        """(variables, meta, loaded_version) for the role. With at least one
        in-process engine, the registry's verified template load runs here;
        a pure path-driven fleet instead re-verifies the role file's content
        identity (each replica's /swap verifies it AGAIN against the bytes
        it actually loads — ``expected_identity`` below)."""
        if self._inproc_engines():
            return self.registry.load_role(role, self._template())
        from ..checkpoint.format import file_content_identity
        from .registry import CandidateVerificationError

        mv = getattr(self.registry, role)
        if mv is None:
            raise LifecycleError(f"no version holds the {role!r} role")
        identity, _details = file_content_identity(mv.path)
        if identity != mv.version:
            raise CandidateVerificationError(
                f"{role} file {mv.file} no longer verifies as "
                f"{mv.short} (found {identity[:12]})",
                loaded_version=identity,
            )
        return None, {"epoch": mv.epoch}, mv

    def _swap_one(
        self, target: Any, variables: Optional[Dict[str, Any]], version: ModelVersion
    ) -> None:
        if self._is_engine(target):
            assert variables is not None  # guaranteed by _load_role
            target.swap_weights(variables, version.short)
        else:
            target.swap_checkpoint(
                version.path,
                version=version.short,
                expected_identity=version.version,
            )

    def _capture(self, target: Any):
        """Pre-swap restore point: the engine's weight triple in-process,
        the registry's CURRENT live version (a re-swappable path) for
        path-driven replicas."""
        if self._is_engine(target):
            return ("weights", target._current_weights())
        return ("version", self.registry.live)

    def _unwind_one(self, target: Any, captured) -> None:
        kind, val = captured
        if kind == "weights":
            target.restore_weights(val)
        elif val is not None:
            target.swap_checkpoint(
                val.path, version=val.short, expected_identity=val.version
            )
        else:
            # First-ever promote on a path-driven replica: there is no
            # previous version to restore — record it loudly; the replica
            # serves the candidate until the operator intervenes.
            telemetry.event(
                "swap/unwind_impossible",
                replica=getattr(target, "name", "?"),
            )

    def _unwind_fleet(self, targets, captured_states, version) -> None:
        """Best-effort unwind of EVERY listed member: since unwinding a
        path-driven replica is itself a fallible network call, one failing
        member must not abort the rest (that would leave members torn AND
        unlogged) nor mask the original error — each failure is swallowed
        into a ``swap/unwind_failed`` event and the loop continues."""
        for target, captured in zip(targets, captured_states):
            try:
                self._unwind_one(target, captured)
            except Exception:
                telemetry.event(
                    "swap/unwind_failed",
                    version=version.short,
                    replica=getattr(target, "name", "?"),
                )

    def _swap_all(
        self, variables: Optional[Dict[str, Any]], version: ModelVersion
    ) -> float:
        """Swap every fleet member, or none: a failure on replica k (worker
        death, per-engine gate refusal, an HTTP replica's /swap refusal)
        restores the pre-swap state on members 0..k-1 before re-raising —
        the fleet is never left version-torn against a role table that did
        not flip."""
        t0 = time.perf_counter()
        previous = [self._capture(target) for target in self.engines]
        done = 0
        try:
            for target in self.engines:
                self._swap_one(target, variables, version)
                done += 1
        except BaseException:
            self._unwind_fleet(
                self.engines[:done], previous[:done], version
            )
            # The member that FAILED may still have swapped server-side: an
            # HTTP timeout or connection reset after the replica received
            # /swap is client-ambiguous. Best-effort re-pin it to the
            # pre-swap state so a torn fleet is a loudly-logged anomaly,
            # never a silent one (in-process engines have exact exception
            # semantics and need no such repair).
            if done < len(self.engines) and not self._is_engine(
                self.engines[done]
            ):
                self._unwind_fleet(
                    [self.engines[done]], [previous[done]], version
                )
            telemetry.event(
                "swap/fleet_unwound", version=version.short, swapped=done
            )
            raise
        return time.perf_counter() - t0

    def shadow_report(self) -> Optional[Dict[str, Any]]:
        """The router's shadow-gate snapshot (None when no router or no
        shadow arm is configured)."""
        if self.router is None:
            return None
        report = self.router.shadow_report()
        return report if report.get("configured") else None

    # ------------------------------------------------------------------ steps
    def stage_candidate(self, path: Optional[str] = None) -> ModelVersion:
        """Verify + stage a candidate (default: the run's latest
        checkpoint). See :meth:`ModelRegistry.stage_candidate`."""
        return self.registry.stage_candidate(path)

    def promote(self, force: bool = False) -> Dict[str, Any]:
        """Flip live → candidate, gated and verified end to end. Returns
        {version, previous_version, swap_wall_s, gate, epochs}. Raises:

        * :class:`LifecycleError` — no candidate staged;
        * :class:`SwapGateError` — shadow gate configured but not green
          (``force=True`` promotes anyway, recorded in the report);
        * :class:`CandidateVerificationError` — the candidate's bytes no
          longer verify as the staged identity (corruption → the chain
          recovered something else; live weights untouched);
        * ``SwapFingerprintError`` / ``PrecisionToleranceError`` — engine
          refusals (architecture mismatch / failed post-swap gate).
        """
        candidate = self.registry.candidate
        if candidate is None:
            raise LifecycleError(
                "promote() with no staged candidate — call "
                "stage_candidate() first"
            )
        gate = self.shadow_report()
        if gate is not None and not gate.get("green") and not force:
            telemetry.event(
                "swap/promotion_refused",
                version=candidate.short,
                reason="shadow_gate_red",
            )
            raise SwapGateError(
                f"promotion of {candidate.short} refused: shadow gate is "
                f"not green ({gate.get('compared', 0)} compared, "
                f"{gate.get('failures', 0)} failure(s), "
                f"{gate.get('errors', 0)} error(s), need "
                f">= {gate.get('min_samples')} clean comparisons)",
                report=gate,
            )
        variables, meta, loaded = self._load_role("candidate")
        old_live = self.registry.live
        previous_state = [self._capture(e) for e in self.engines]
        wall = self._swap_all(variables, loaded)
        try:
            self.registry.commit_promote(loaded)
        except BaseException:
            # The role table did not flip (concurrent candidate change, a
            # failed sidecar install): un-publish the already-swapped fleet
            # — engines must never serve a version the registry does not
            # record as live.
            self._unwind_fleet(self.engines, previous_state, loaded)
            telemetry.event("swap/fleet_unwound", version=loaded.short)
            raise
        if self.router is not None:
            self.router.clear_shadow()
        report = {
            "version": loaded.short,
            "previous_version": old_live.short if old_live else None,
            "swap_wall_s": round(wall, 4),
            "gate": gate,
            "forced": bool(force and gate is not None and not gate.get("green")),
            "epoch": meta.get("epoch"),
        }
        telemetry.event(
            "swap/promote_complete",
            version=loaded.short,
            swap_wall_s=report["swap_wall_s"],
        )
        return report

    def rollback(self) -> Dict[str, Any]:
        """Restore the ``previous`` version in ONE swap (kept addressable by
        ``keep_last_k >= 2`` retention). Zero compiles by construction —
        same param tree, same executables. Returns the swap report."""
        previous = self.registry.previous
        if previous is None:
            raise LifecycleError(
                "rollback() with no previous version — nothing was ever "
                "promoted over the current live version (rollback also "
                "needs checkpoint_keep_last_k >= 2 so the previous file "
                "still exists; contracts.py flags bad-lifecycle otherwise)"
            )
        variables, meta, loaded = self._load_role("previous")
        old_live = self.registry.live
        previous_state = [self._capture(e) for e in self.engines]
        wall = self._swap_all(variables, loaded)
        try:
            self.registry.commit_rollback(loaded)
        except BaseException:
            self._unwind_fleet(self.engines, previous_state, loaded)
            telemetry.event("swap/fleet_unwound", version=loaded.short)
            raise
        report = {
            "version": loaded.short,
            "previous_version": old_live.short if old_live else None,
            "swap_wall_s": round(wall, 4),
            "epoch": meta.get("epoch"),
        }
        telemetry.event(
            "swap/rollback_complete",
            version=loaded.short,
            swap_wall_s=report["swap_wall_s"],
        )
        return report
