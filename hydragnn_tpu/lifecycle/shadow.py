"""Shadow diff gate — tolerance-gated live-vs-candidate output comparison
(docs/SERVING.md "Live model lifecycle", docs/OBSERVABILITY.md
"hydragnn_swap_*").

The router mirrors a sampled fraction of live traffic to a candidate-version
replica (route/router.py shadow mode); every mirrored call's outputs are
compared against the LIVE answer the caller already received, through the
same tolerance machinery the quantized serving arm and kernel certification
use (precision/tolerance.py — one definition of "within tolerance" across
the whole stack). This module holds the cross-thread accounting:

* :class:`ShadowGate` — the locked pass/fail record
  (``# guarded-by:``-annotated; observations arrive from the router's
  shadow worker thread, reads from caller threads and /metrics scrapes).
  The gate is **green** only once ``min_samples`` comparisons completed
  with ZERO tolerance failures — ``LifecycleManager.promote`` refuses a
  promotion whose gate is not green.
* :func:`compare_outputs` — per-graph per-head max-abs-diff verdict over a
  whole mirrored call (the worst head anywhere decides).

Shadow responses are NEVER returned to callers and NEVER counted against
SLO admission; a shadow replica that errors or a full mirror queue degrades
the GATE (errors/dropped counters), not live traffic.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence

from ..analysis import tsan
from ..precision.tolerance import tolerance_report


def compare_outputs(
    live: Sequence[Sequence[Any]],
    shadow: Sequence[Sequence[Any]],
    bound: float,
    names: Optional[Sequence[str]] = None,
) -> Dict[str, Any]:
    """One mirrored call's verdict: per-graph ``tolerance_report`` (heads
    vs heads), reduced to the worst graph. Shape disagreements raise — a
    candidate emitting different head shapes is a staging error the gate
    must surface loudly, not average away."""
    if len(live) != len(shadow):
        raise ValueError(
            f"mirrored call returned {len(shadow)} graphs for {len(live)} "
            "live answers"
        )
    worst: Optional[Dict[str, Any]] = None
    for live_heads, shadow_heads in zip(live, shadow):
        verdict = tolerance_report(shadow_heads, live_heads, bound, names=names)
        if worst is None or verdict["fwd_err"] > worst["fwd_err"]:
            worst = verdict
    assert worst is not None  # len(live) >= 1: engines reject empty calls
    worst["graphs"] = len(live)
    return worst


class ShadowGate:
    """Locked shadow-comparison record; green == promotion-safe."""

    def __init__(self, tolerance: float, min_samples: int = 8):
        if not (isinstance(tolerance, (int, float)) and tolerance > 0):
            raise ValueError(
                f"shadow gate needs a positive tolerance bound, got "
                f"{tolerance!r} (the bit-exactness contract is relaxed by "
                "an explicit bound, never silently)"
            )
        if min_samples < 1:
            raise ValueError(
                f"shadow gate min_samples must be >= 1, got {min_samples}"
            )
        self.tolerance = float(tolerance)
        self.min_samples = int(min_samples)
        self._lock = tsan.instrument_lock(threading.Lock(), "ShadowGate._lock")
        # Written by the shadow worker thread + router caller threads, read
        # by promotion checks and /metrics scrapes.
        self.mirrored_total = 0  # guarded-by: self._lock
        self.compared_total = 0  # guarded-by: self._lock
        self.failures_total = 0  # guarded-by: self._lock
        self.errors_total = 0  # guarded-by: self._lock
        self.dropped_total = 0  # guarded-by: self._lock
        self.diff_max = 0.0  # guarded-by: self._lock
        self._last_error: Optional[str] = None  # guarded-by: self._lock
        self._candidate_versions: set = set()  # guarded-by: self._lock

    # ------------------------------------------------------------- recorders
    def count_mirrored(self) -> None:
        with self._lock:
            self.mirrored_total += 1

    def count_dropped(self) -> None:
        with self._lock:
            self.dropped_total += 1

    def count_error(self, error: str) -> None:
        with self._lock:
            self.errors_total += 1
            self._last_error = error

    def record(
        self, verdict: Dict[str, Any], candidate_version: Optional[str] = None
    ) -> None:
        """Fold one :func:`compare_outputs` verdict into the gate."""
        with self._lock:
            self.compared_total += 1
            self.diff_max = max(self.diff_max, float(verdict.get("fwd_err", 0.0)))
            if not verdict.get("ok"):
                self.failures_total += 1
            if candidate_version:
                self._candidate_versions.add(str(candidate_version))

    # -------------------------------------------------------------- reporters
    def report(self) -> Dict[str, Any]:
        """Locked gate snapshot. ``green`` is the promotion predicate:
        enough comparisons, zero failures. Errors (shadow replica down) and
        drops don't fail the gate outright but do starve it of comparisons
        — a gate that never saw its quota stays red."""
        with self._lock:
            compared = self.compared_total
            failures = self.failures_total
            out = {
                "tolerance": self.tolerance,
                "min_samples": self.min_samples,
                "mirrored": self.mirrored_total,
                "compared": compared,
                "failures": failures,
                "errors": self.errors_total,
                "dropped": self.dropped_total,
                "diff_max": self.diff_max,
                "last_error": self._last_error,
                "candidate_versions": sorted(self._candidate_versions),
            }
        out["green"] = compared >= self.min_samples and failures == 0
        return out

    def render_prometheus(self) -> str:
        """The ``hydragnn_swap_*`` exposition family (appended to the
        router's /metrics payload while a shadow arm is configured)."""
        p = "hydragnn_swap"
        snap = self.report()
        lines: List[str] = []
        for name in ("mirrored", "compared", "failures", "errors", "dropped"):
            lines.append(f"# TYPE {p}_shadow_{name}_total counter")
            lines.append(f"{p}_shadow_{name}_total {snap[name]}")
        lines.append(f"# TYPE {p}_shadow_diff_max gauge")
        lines.append(f"{p}_shadow_diff_max {snap['diff_max']}")
        lines.append(f"# TYPE {p}_shadow_tolerance_bound gauge")
        lines.append(f"{p}_shadow_tolerance_bound {snap['tolerance']}")
        lines.append(f"# TYPE {p}_shadow_gate_green gauge")
        lines.append(f"{p}_shadow_gate_green {1 if snap['green'] else 0}")
        return "\n".join(lines) + "\n"
