"""graftswap — zero-downtime live model lifecycle (docs/SERVING.md "Live
model lifecycle"; ROADMAP item 4).

Model updates become a metrics decision instead of a restart:

* :mod:`.registry` — versioned model registry over the checkpoint layer: a
  model version IS a v2 digest-verified checkpoint (content identity =
  sha256 over the verified section digests), roles (live / candidate /
  previous) tracked over the ``keep_last_k`` manifest with an atomic
  ``<name>.lifecycle.json`` sidecar;
* :mod:`.shadow` — the tolerance-gated shadow diff gate the router's
  mirror arm feeds (``hydragnn_swap_*`` metrics); promotion requires it
  green;
* :mod:`.manager` — promote()/rollback() orchestration: verified load →
  ``engine.swap_weights`` (atomic, per-request-consistent, zero
  recompiles) on every replica → registry role flip.

The engine half (``InferenceEngine.swap_weights``, per-response
``model_version`` tags, the ``X-HydraGNN-Model-Version`` header) lives in
``hydragnn_tpu/serve``; the traffic-mirroring half (``Router.set_shadow``)
in ``hydragnn_tpu/route``.
"""

from .manager import LifecycleManager
from .registry import (
    ROLE_CANDIDATE,
    ROLE_LIVE,
    ROLE_PREVIOUS,
    CandidateVerificationError,
    LifecycleError,
    ModelRegistry,
    ModelVersion,
    SwapGateError,
    set_pre_persist_hook,
)
from .shadow import ShadowGate, compare_outputs

__all__ = [
    "ROLE_CANDIDATE",
    "ROLE_LIVE",
    "ROLE_PREVIOUS",
    "CandidateVerificationError",
    "LifecycleError",
    "LifecycleManager",
    "ModelRegistry",
    "ModelVersion",
    "ShadowGate",
    "SwapGateError",
    "compare_outputs",
    "set_pre_persist_hook",
]
