"""Result plotting (reference /root/reference/hydragnn/postprocess/visualizer.py:
24-735): parity/scatter plots per head, error histograms, loss-history dump
(pickled ``history_loss.pkl``) + curves, node-count histogram. matplotlib with the
Agg backend — file output only."""

from __future__ import annotations

import os
import pickle
from typing import List, Sequence

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt
import numpy as np


class Visualizer:
    def __init__(
        self,
        model_with_config_name: str,
        node_feature: Sequence = (),
        num_heads: int = 1,
        head_dims: Sequence[int] = (1,),
    ):
        self.true_values = []
        self.predicted_values = []
        self.model_with_config_name = model_with_config_name
        os.makedirs(self.model_with_config_name, exist_ok=True)
        self.node_feature = node_feature
        self.num_heads = num_heads
        self.head_dims = list(head_dims)

    # ----------------------------------------------------------- loss history
    def plot_history(self, history: dict) -> None:
        """Dump pickled history + train/val/test curves
        (visualizer.py:626-688)."""
        with open(
            os.path.join(self.model_with_config_name, "history_loss.pkl"), "wb"
        ) as f:
            pickle.dump(history, f)

        fig, axs = plt.subplots(1, 2, figsize=(12, 4.5))
        for key, label in (
            ("total_loss_train", "train"),
            ("total_loss_val", "validation"),
            ("total_loss_test", "test"),
        ):
            axs[0].plot(history[key], label=label)
        axs[0].set_xlabel("epoch")
        axs[0].set_ylabel("total loss")
        axs[0].set_yscale("log")
        axs[0].legend()

        task_train = np.asarray(history["task_loss_train"])
        if task_train.ndim == 2:
            for ih in range(task_train.shape[1]):
                axs[1].plot(task_train[:, ih], label=f"task {ih}")
            axs[1].set_xlabel("epoch")
            axs[1].set_ylabel("task RMSE (train)")
            axs[1].set_yscale("log")
            axs[1].legend()
        fig.tight_layout()
        fig.savefig(os.path.join(self.model_with_config_name, "history_loss.png"))
        plt.close(fig)

    # ----------------------------------------------------------- parity plots
    def create_parity_plots(
        self, true_values: List[np.ndarray], predicted_values: List[np.ndarray]
    ) -> None:
        """Per-head predicted-vs-true scatter (scalar plots,
        visualizer.py:280-383)."""
        for ihead, (tv, pv) in enumerate(zip(true_values, predicted_values)):
            tv = np.asarray(tv).reshape(-1)
            pv = np.asarray(pv).reshape(-1)
            fig, ax = plt.subplots(figsize=(5, 5))
            ax.scatter(tv, pv, s=6, alpha=0.5, edgecolors="none")
            lo = min(tv.min(), pv.min()) if tv.size else 0.0
            hi = max(tv.max(), pv.max()) if tv.size else 1.0
            ax.plot([lo, hi], [lo, hi], "r--", linewidth=1)
            ax.set_xlabel("true")
            ax.set_ylabel("predicted")
            ax.set_title(f"head {ihead}")
            fig.tight_layout()
            fig.savefig(
                os.path.join(
                    self.model_with_config_name, f"parity_head{ihead}.png"
                )
            )
            plt.close(fig)

    create_scatter_plots = create_parity_plots

    # ------------------------------------------------------- error histograms
    def create_error_histograms(
        self, true_values: List[np.ndarray], predicted_values: List[np.ndarray]
    ) -> None:
        """Per-head histogram of (pred − true) (visualizer.py:384-463)."""
        for ihead, (tv, pv) in enumerate(zip(true_values, predicted_values)):
            err = (np.asarray(pv) - np.asarray(tv)).reshape(-1)
            fig, ax = plt.subplots(figsize=(5, 4))
            ax.hist(err, bins=50)
            ax.set_xlabel("error (pred - true)")
            ax.set_ylabel("count")
            ax.set_title(f"head {ihead}")
            fig.tight_layout()
            fig.savefig(
                os.path.join(
                    self.model_with_config_name, f"error_hist_head{ihead}.png"
                )
            )
            plt.close(fig)

    # -------------------------------------------------------------- num nodes
    def num_nodes_plot(self, nodes_num_list: Sequence[int]) -> None:
        """Histogram of graph sizes in the test set (visualizer.py:727-735)."""
        fig, ax = plt.subplots(figsize=(5, 4))
        ax.hist(np.asarray(nodes_num_list), bins=30)
        ax.set_xlabel("num nodes")
        ax.set_ylabel("count")
        fig.tight_layout()
        fig.savefig(os.path.join(self.model_with_config_name, "num_nodes.png"))
        plt.close(fig)
