"""Result plotting (reference /root/reference/hydragnn/postprocess/visualizer.py:
24-735): per-head parity/scatter plots (scalar, vector, per-node), error
histograms, conditional-mean / error-PDF "global analysis", loss-history pickle
+ curves, and the test-set graph-size histogram. matplotlib with the Agg
backend — file output only.

The reference stores node-level head values as python lists-of-lists indexed
[sample][node] (which assumes a fixed graph size for the per-node plots,
visualizer.py:280-383). Here eval produces flat ``[rows, dim]`` arrays; node
heads are folded back to ``[samples, nodes]`` when the test set has a constant
graph size, and fall back to aggregate (scalar-style) plots otherwise — same
outputs where the reference works at all, no crash where it would."""

from __future__ import annotations

import json
import math
import os
import pickle
import warnings
from typing import List, Optional, Sequence

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt
import numpy as np


_pickle_history_warned = False


def load_history(output_dir: str) -> dict:
    """Read a run's loss-history sidecar: ``history_loss.json``, falling back
    to the retired ``history_loss.pkl`` for one release (one-time
    DeprecationWarning, mirroring the pickle-corpus and v1-checkpoint read
    paths)."""
    json_path = os.path.join(output_dir, "history_loss.json")
    if os.path.isfile(json_path):
        with open(json_path) as f:
            return json.load(f)
    pkl_path = os.path.join(output_dir, "history_loss.pkl")
    global _pickle_history_warned
    if not _pickle_history_warned:
        _pickle_history_warned = True
        warnings.warn(
            "reading the pickled loss-history sidecar is deprecated — "
            "re-running training writes history_loss.json instead",
            DeprecationWarning,
            stacklevel=2,
        )
    with open(pkl_path, "rb") as f:
        # graftlint: disable=pickle-load-outside-compat(v1 history sidecar shim — deprecated read path, DeprecationWarning issued above)
        return pickle.load(f)


def _identity_line(ax):
    lo = max(ax.get_xlim()[0], ax.get_ylim()[0])
    hi = min(ax.get_xlim()[1], ax.get_ylim()[1])
    ax.plot([lo, hi], [lo, hi], "r--", linewidth=1)


def _grid(n_panels: int, panel_w=3.0, panel_h=3.0):
    nrow = max(1, math.floor(math.sqrt(n_panels)))
    ncol = math.ceil(n_panels / nrow)
    fig, axs = plt.subplots(nrow, ncol, figsize=(ncol * panel_w, nrow * panel_h))
    axs = np.atleast_1d(axs).flatten()
    for ax in axs[n_panels:]:
        ax.axis("off")
    return fig, axs


class Visualizer:
    def __init__(
        self,
        output_dir: str,
        node_feature: Sequence = (),
        num_nodes_list: Sequence[int] = (),
        num_heads: int = 1,
        head_dims: Sequence[int] = (1,),
        head_types: Optional[Sequence[str]] = None,
    ):
        self.output_dir = output_dir
        os.makedirs(self.output_dir, exist_ok=True)
        # Flat per-node input features of the test set, [total_nodes] (the
        # reference collects data.x.tolist() per sample,
        # train_validate_test.py:62-66).
        self.node_feature = np.asarray(node_feature, dtype=np.float64).reshape(-1)
        self.num_nodes_list = [int(n) for n in num_nodes_list]
        self.num_heads = num_heads
        self.head_dims = list(head_dims)
        self.head_types = list(head_types) if head_types else ["graph"] * num_heads

    # back-compat alias (first-round API)
    @property
    def model_with_config_name(self):
        return self.output_dir

    def _path(self, stem: str, iepoch=None) -> str:
        if iepoch is not None:
            # Negative epoch = pre-training "initial solution" plots (the
            # reference passes iepoch=-1, train_validate_test.py:84); they must
            # not share a filename with the end-of-run plots (iepoch=None).
            suffix = "init" if iepoch < 0 else str(iepoch).zfill(4)
            stem = f"{stem}_{suffix}"
        return os.path.join(self.output_dir, stem + ".png")

    def _fixed_graph_size(self) -> Optional[int]:
        sizes = set(self.num_nodes_list)
        return sizes.pop() if len(sizes) == 1 else None

    def _fold_nodes(self, values: np.ndarray) -> Optional[np.ndarray]:
        """[total_nodes] → [samples, nodes] when graph size is constant."""
        n = self._fixed_graph_size()
        flat = np.asarray(values).reshape(-1)
        if n and flat.size % n == 0:
            return flat.reshape(-1, n)
        return None

    # ------------------------------------------------------------- primitives
    def _scatter(self, ax, x, y, s=None, c=None, marker=None, title=None,
                 x_label=None, y_label=None, xylim_equal=False):
        x = np.asarray(x).reshape(-1)
        y = np.asarray(y).reshape(-1)
        if c is not None:
            ax.scatter(x, y, s=s, c=np.asarray(c).reshape(-1), marker=marker)
        else:
            ax.scatter(x, y, s=s, edgecolor="b", marker=marker, facecolor="none")
        ax.set_title(title)
        ax.set_xlabel(x_label)
        ax.set_ylabel(y_label)
        if xylim_equal:
            ax.set_aspect("equal")
            lo = min(ax.get_xlim()[0], ax.get_ylim()[0])
            hi = max(ax.get_xlim()[1], ax.get_ylim()[1])
            ax.set_xlim(lo, hi)
            ax.set_ylim(lo, hi)
        _identity_line(ax)

    @staticmethod
    def _condmean(true, pred, weight=1.0, bins=50):
        """<weight·|true−pred|> conditioned on true, binned (reference
        __err_condmean, visualizer.py:93-105)."""
        true = np.asarray(true).reshape(-1)
        err = np.abs(true - np.asarray(pred).reshape(-1)) * weight
        sums, edges = np.histogram(true, bins=bins, weights=err)
        counts, _ = np.histogram(true, bins=bins)
        centers = 0.5 * (edges[:-1] + edges[1:])
        return centers, sums / np.maximum(counts, 1)

    @staticmethod
    def _error_pdf(true, pred, bins=40):
        hist, edges = np.histogram(
            np.asarray(pred).reshape(-1) - np.asarray(true).reshape(-1),
            bins=bins, density=True,
        )
        return 0.5 * (edges[:-1] + edges[1:]), hist

    def _pdf_panel(self, ax, true, pred, title=None):
        centers, pdf = self._error_pdf(true, pred)
        ax.plot(centers, pdf, "ro")
        ax.set_title(title)
        ax.set_xlabel("Error")
        ax.set_ylabel("PDF")

    def _condmean_panel(self, ax, true, pred, weight=1.0, title=None):
        xs, err = self._condmean(true, pred, weight)
        ax.plot(xs, err, "ro")
        ax.set_title(title)
        ax.set_xlabel("True")
        ax.set_ylabel("abs. error")

    # -------------------------------------------------------- global analysis
    def create_plot_global_analysis(self, varname, true_values, predicted_values,
                                    save_plot=True):
        """Scatter / conditional-mean / error-PDF triptych (reference
        visualizer.py:133-279). Node-level inputs [samples, nodes] additionally
        analyze the per-sample l2 length, sum, and raw components (3×3)."""
        tv = np.asarray(true_values, dtype=np.float64)
        pv = np.asarray(predicted_values, dtype=np.float64)
        if tv.ndim == 1 or tv.shape[1] == 1:
            fig, axs = plt.subplots(1, 3, figsize=(15, 4.5))
            self._scatter(axs[0], tv, pv, title="Scalar output", x_label="True",
                          y_label="Predicted", xylim_equal=True)
            self._condmean_panel(axs[1], tv, pv,
                                 title="Conditional mean abs. error")
            self._pdf_panel(axs[2], tv, pv, title="Scalar output: error PDF")
        else:
            ncomp = tv.shape[1]
            fig, axs = plt.subplots(3, 3, figsize=(18, 16))
            panels = (
                ("length", np.linalg.norm(tv, axis=1), np.linalg.norm(pv, axis=1),
                 1.0 / math.sqrt(ncomp)),
                ("sum", tv.sum(axis=1), pv.sum(axis=1), 1.0 / ncomp),
                ("components", tv, pv, 1.0),
            )
            for col, (label, t, p, w) in enumerate(panels):
                self._scatter(axs[0, col], t, p, title=f"Vector output: {label}",
                              x_label="True", y_label="Predicted", xylim_equal=True)
                self._condmean_panel(axs[1, col], t, p, weight=w)
                self._pdf_panel(axs[2, col], t, p)
        fig.tight_layout()
        if save_plot:
            fig.savefig(self._path(varname + "_scatter_condm_err"))
            plt.close(fig)

    # ------------------------------------------------------------ parity plots
    def create_parity_plot_and_error_histogram_scalar(
        self, varname, true_values, predicted_values, iepoch=None, save_plot=True
    ):
        """Scalar heads: parity + error-PDF pair; node-level heads (fixed graph
        size): per-node parity grid + SUM-over-nodes + mean-over-samples panels
        colored by the input node feature (reference visualizer.py:280-383)."""
        tv = np.asarray(true_values, dtype=np.float64)
        pv = np.asarray(predicted_values, dtype=np.float64)
        if tv.ndim == 1 or tv.shape[1] == 1:
            fig, axs = plt.subplots(1, 2, figsize=(12, 6))
            self._scatter(axs[0], tv, pv, title=varname, x_label="True",
                          y_label="Predicted", xylim_equal=True)
            self._pdf_panel(axs[1], tv, pv, title=varname + ": error PDF")
        else:
            nsamp, nnode = tv.shape
            feat = self._fold_nodes(self.node_feature)
            if feat is None or feat.shape != tv.shape:
                feat = np.zeros_like(tv)
            fig, axs = _grid(nnode + 2)
            for inode in range(nnode):
                self._scatter(axs[inode], tv[:, inode], pv[:, inode], s=6,
                              c=feat[:, inode], title=f"node:{inode}",
                              xylim_equal=True)
            self._scatter(axs[nnode], tv.sum(axis=1), pv.sum(axis=1), s=40,
                          c=feat.sum(axis=1), title="SUM", xylim_equal=True)
            self._scatter(axs[nnode + 1], tv.sum(axis=0), pv.sum(axis=0), s=40,
                          c=feat.sum(axis=0), title=f"SMP_Mean4sites:0-{nnode}",
                          xylim_equal=True)
        fig.tight_layout()
        if save_plot:
            fig.savefig(self._path(varname, iepoch))
            plt.close(fig)

    def create_error_histogram_per_node(
        self, varname, true_values, predicted_values, iepoch=None, save_plot=True
    ):
        """Per-node error-PDF grid (+ SUM and per-node-total panels); no-op for
        scalar heads (reference visualizer.py:384-463)."""
        tv = np.asarray(true_values, dtype=np.float64)
        pv = np.asarray(predicted_values, dtype=np.float64)
        if tv.ndim == 1 or tv.shape[1] == 1:
            return
        nsamp, nnode = tv.shape
        fig, axs = _grid(nnode + 2, 3.5, 3.2)
        for inode in range(nnode):
            self._pdf_panel(axs[inode], tv[:, inode], pv[:, inode],
                            title=f"node:{inode}")
        self._pdf_panel(axs[nnode], tv.sum(axis=1), pv.sum(axis=1), title="SUM")
        self._pdf_panel(axs[nnode + 1], tv.sum(axis=0), pv.sum(axis=0),
                        title=f"SMP_Mean4sites:0-{nnode}")
        fig.tight_layout()
        if save_plot:
            fig.savefig(self._path(varname + "_error_hist1d", iepoch))
            plt.close(fig)

    def create_parity_plot_vector(
        self, varname, true_values, predicted_values, head_dim, iepoch=None,
        save_plot=True
    ):
        """Component-wise parity grid for vector outputs (reference
        visualizer.py:464-515)."""
        tv = np.asarray(true_values, dtype=np.float64).reshape(-1, head_dim)
        pv = np.asarray(predicted_values, dtype=np.float64).reshape(-1, head_dim)
        markers = ["o", "s", "d"]
        fig, axs = _grid(head_dim, 4, 4)
        for icomp in range(head_dim):
            self._scatter(axs[icomp], tv[:, icomp], pv[:, icomp], s=6, c=None,
                          marker=markers[icomp % 3], title=f"comp:{icomp}",
                          xylim_equal=True)
        fig.tight_layout()
        if save_plot:
            fig.savefig(self._path(varname, iepoch))
            plt.close(fig)

    def create_parity_plot_per_node_vector(
        self, varname, true_values, predicted_values, iepoch=None, save_plot=True
    ):
        """Per-node parity for 3-vector node outputs (reference
        visualizer.py:516-610; unused there, kept for API parity)."""
        tv = np.asarray(true_values, dtype=np.float64)
        pv = np.asarray(predicted_values, dtype=np.float64)
        nsamp = tv.shape[0]
        tv = tv.reshape(nsamp, -1, 3)
        pv = pv.reshape(nsamp, -1, 3)
        nnode = tv.shape[1]
        feat = self._fold_nodes(self.node_feature)
        if feat is None or feat.shape[:1] != (nsamp,):
            feat = np.zeros((nsamp, nnode))
        markers = ["o", "s", "d"]
        fig, axs = _grid(nnode + 2)
        for inode in range(nnode):
            for icomp in range(3):
                self._scatter(axs[inode], tv[:, inode, icomp], pv[:, inode, icomp],
                              s=6, c=feat[:, inode], marker=markers[icomp],
                              title=f"node:{inode}", xylim_equal=True)
        for icomp in range(3):
            self._scatter(axs[nnode], tv[:, :, icomp].sum(axis=1),
                          pv[:, :, icomp].sum(axis=1), s=40, c=feat.sum(axis=1),
                          marker=markers[icomp], title="SUM", xylim_equal=True)
            self._scatter(axs[nnode + 1], tv[:, :, icomp].sum(axis=0),
                          pv[:, :, icomp].sum(axis=0), s=40, c=feat.sum(axis=0),
                          marker=markers[icomp],
                          title=f"SMP_Mean4sites:0-{nnode}", xylim_equal=True)
        fig.tight_layout()
        if save_plot:
            fig.savefig(self._path(varname, iepoch))
            plt.close(fig)

    # --------------------------------------------------------------- dispatch
    def _head_view(self, ihead: int, values) -> np.ndarray:
        """Per-head flat [rows, dim] → the shape each plotter expects: node
        heads fold to [samples, nodes] when possible."""
        arr = np.asarray(values, dtype=np.float64)
        if self.head_types[ihead] == "node" and self.head_dims[ihead] == 1:
            folded = self._fold_nodes(arr)
            if folded is not None:
                return folded
        return arr.reshape(-1, max(self.head_dims[ihead], 1))

    def create_scatter_plots(self, true_values, predicted_values,
                             output_names=None, iepoch=None):
        """Per-head dispatch (reference visualizer.py:689-716)."""
        names = output_names or [f"head{i}" for i in range(self.num_heads)]
        for ihead in range(self.num_heads):
            tv = self._head_view(ihead, true_values[ihead])
            pv = self._head_view(ihead, predicted_values[ihead])
            if self.head_dims[ihead] > 1:
                self.create_parity_plot_vector(
                    names[ihead], tv, pv, self.head_dims[ihead], iepoch
                )
            else:
                self.create_parity_plot_and_error_histogram_scalar(
                    names[ihead], tv, pv, iepoch
                )
                self.create_error_histogram_per_node(names[ihead], tv, pv, iepoch)

    # back-compat alias (first-round API took per-head lists directly)
    def create_parity_plots(self, true_values, predicted_values):
        self.create_scatter_plots(true_values, predicted_values)

    def create_error_histograms(self, true_values, predicted_values):
        for ihead in range(min(self.num_heads, len(true_values))):
            tv = self._head_view(ihead, true_values[ihead])
            pv = self._head_view(ihead, predicted_values[ihead])
            self.create_error_histogram_per_node(f"head{ihead}", tv, pv)

    def create_plot_global(self, true_values, predicted_values, output_names=None):
        """Global analysis for every head (reference visualizer.py:717-726)."""
        names = output_names or [f"head{i}" for i in range(self.num_heads)]
        for ihead in range(self.num_heads):
            self.create_plot_global_analysis(
                names[ihead],
                self._head_view(ihead, true_values[ihead]),
                self._head_view(ihead, predicted_values[ihead]),
            )

    # ----------------------------------------------------------- loss history
    def plot_history(self, history: dict, task_weights=None, task_names=None):
        """Write the history dict sidecar + plot total and per-task
        train/val/test curves (reference visualizer.py:626-688). The sidecar
        is JSON (``history_loss.json``) — bare pickle is write-retired;
        :func:`load_history` keeps pickle read-compat for one release."""
        doc = {
            k: (np.asarray(v).tolist() if not isinstance(v, (int, float)) else v)
            for k, v in history.items()
        }
        with open(os.path.join(self.output_dir, "history_loss.json"), "w") as f:
            json.dump(doc, f, sort_keys=True)

        task_train = np.atleast_2d(np.asarray(history["task_loss_train"], dtype=np.float64))
        task_val = np.atleast_2d(np.asarray(history["task_loss_val"], dtype=np.float64))
        task_test = np.atleast_2d(np.asarray(history["task_loss_test"], dtype=np.float64))
        num_tasks = task_train.shape[1] if task_train.size else 0

        ncol = max(num_tasks, 1)
        nrow = 2 if num_tasks else 1
        fig, axs = plt.subplots(nrow, ncol, figsize=(4.5 * ncol, 4.0 * nrow),
                                squeeze=False)
        ax = axs[0][0]
        ax.plot(history["total_loss_train"], "-", label="train")
        ax.plot(history["total_loss_val"], ":", label="validation")
        ax.plot(history["total_loss_test"], "--", label="test")
        ax.set_title("total loss")
        ax.set_xlabel("Epochs")
        ax.set_yscale("log")
        ax.legend()
        for iext in range(1, ncol):
            axs[0][iext].axis("off")
        for ivar in range(num_tasks):
            ax = axs[1][ivar]
            ax.plot(task_train[:, ivar], label="train")
            # Empty val/test splits yield (epochs, 0) task arrays — skip those
            # series instead of indexing out of range.
            if task_val.shape[1] > ivar:
                ax.plot(task_val[:, ivar], label="validation")
            if task_test.shape[1] > ivar:
                ax.plot(task_test[:, ivar], "--", label="test")
            name = task_names[ivar] if task_names else f"task {ivar}"
            if task_weights is not None:
                name += ", {:.4f}".format(task_weights[ivar])
            ax.set_title(name)
            ax.set_xlabel("Epochs")
            ax.set_yscale("log")
            if ivar == 0:
                ax.legend()
        fig.tight_layout()
        fig.savefig(os.path.join(self.output_dir, "history_loss.png"))
        plt.close(fig)

    # -------------------------------------------------------------- num nodes
    def num_nodes_plot(self, nodes_num_list: Optional[Sequence[int]] = None):
        """Histogram of test-set graph sizes (reference visualizer.py:727-735)."""
        sizes = np.asarray(
            nodes_num_list if nodes_num_list is not None else self.num_nodes_list
        )
        fig, ax = plt.subplots(figsize=(8, 8))
        ax.hist(sizes)
        ax.set_title("Histogram of graph size in test set")
        ax.set_xlabel("number of nodes")
        fig.savefig(os.path.join(self.output_dir, "num_nodes.png"))
        plt.close(fig)
