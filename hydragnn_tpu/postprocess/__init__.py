from .postprocess import (
    output_denormalize,
    unscale_features_by_num_nodes,
    unscale_features_by_num_nodes_config,
)
from .visualizer import Visualizer
