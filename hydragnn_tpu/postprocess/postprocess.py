"""Prediction post-processing (reference /root/reference/hydragnn/postprocess/
postprocess.py:13-54), vectorized (the reference's triple python loop is listed as
a hot spot in SURVEY.md §3.6)."""

from __future__ import annotations

from typing import List, Sequence

import numpy as np


def output_denormalize(y_minmax, true_values, predicted_values):
    """Undo per-head min-max normalization in place: v*(ymax-ymin)+ymin."""
    for ihead in range(len(y_minmax)):
        ymin = np.asarray(y_minmax[ihead][0])
        ymax = np.asarray(y_minmax[ihead][1])
        predicted_values[ihead] = predicted_values[ihead] * (ymax - ymin) + ymin
        true_values[ihead] = true_values[ihead] * (ymax - ymin) + ymin
    return true_values, predicted_values


def unscale_features_by_num_nodes(
    datasets_list, scaled_index_list: Sequence[int], nodes_num_list: Sequence[int]
):
    """Multiply ``*_scaled_num_nodes`` head values back by each sample's node
    count (postprocess.py:29-41). Values are [num_heads][num_samples][...]."""
    nodes = np.asarray(nodes_num_list)
    for dataset in datasets_list:
        for scaled_index in scaled_index_list:
            head_value = dataset[scaled_index]
            for isample in range(len(nodes)):
                head_value[isample] = head_value[isample] * nodes[isample]
    return datasets_list


def unscale_features_by_num_nodes_config(config, datasets_list, nodes_num_list):
    var_config = config["NeuralNetwork"]["Variables_of_interest"]
    output_names = var_config["output_names"]
    scaled_feature_index = [
        i for i, nm in enumerate(output_names) if "_scaled_num_nodes" in nm
    ]
    if scaled_feature_index:
        assert var_config[
            "denormalize_output"
        ], "Cannot unscale features without 'denormalize_output'"
        datasets_list = unscale_features_by_num_nodes(
            datasets_list, scaled_feature_index, nodes_num_list
        )
    return datasets_list
