from .batch import GraphBatch
from .sample import GraphSample
from .collate import collate_graphs, compute_pad_sizes, unpack_targets, round_up_pow2
