from .batch import GraphBatch
from .sample import GraphSample
from .collate import collate_graphs, compute_pad_sizes, unpack_targets, round_up_pow2
from .csr import build_graph_ptr, build_row_ptr, validate_csr
from .packing import (
    PackCaps,
    SizeHistogram,
    first_fit_decreasing,
    fit_ladder,
    histogram_distance,
    node_distribution,
    resolve_ladder_spec,
)

# NOTE: `python -m hydragnn_tpu.graphs.packing fit-ladder` prints a runpy
# double-import RuntimeWarning on stderr (the package root imports the
# preprocess layer, which already pulled in graphs.packing before runpy
# executes it). Harmless: the CLI's JSON contract is stdout-only.
