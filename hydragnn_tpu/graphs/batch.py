"""Padded, statically-shaped graph batch container — the TPU-native replacement for
torch_geometric's ragged ``Batch`` (reference: hydragnn/preprocess + Base.forward,
/root/reference/hydragnn/models/Base.py:225-269).

Design (jraph-style, but multi-head-target aware):

* A batch packs ``G`` real graphs into fixed-size node/edge/graph arrays
  ``(num_nodes_pad, num_edges_pad, num_graphs_pad)`` so XLA compiles one executable
  per bucket, not per batch.
* At least one padding node and one padding graph are ALWAYS reserved; every padding
  edge connects padding-node → padding-node, so message passing can run unmasked:
  garbage only ever lands on padding rows, which are excluded from batch-norm
  statistics, pooling denominators, and the loss by the masks carried here.
* Multi-head targets are dense per-head arrays (graph heads: ``[num_graphs_pad, dim]``,
  node heads: ``[num_nodes_pad, dim]``) with validity given by ``graph_mask`` /
  ``node_mask``. This replaces the reference's packed ``data.y`` + ``data.y_loc``
  prefix-offset contract (serialized_dataset_loader.py:220-261) and makes the
  per-batch python index math of ``get_head_indices``
  (train_validate_test.py:177-205) disappear into static shapes.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from flax import struct


@struct.dataclass
class GraphBatch:
    """A fixed-shape batch of graphs.

    Attributes:
      node_features:  [N_pad, F] float — input node features (padding rows zero).
      edge_features:  [E_pad, D] float or None — edge attributes (e.g. lengths).
      senders:        [E_pad] int32 — source node index of each edge.
      receivers:      [E_pad] int32 — destination node index of each edge.
      node_graph:     [N_pad] int32 — graph id owning each node; padding nodes point
                      at a padding graph slot.
      node_mask:      [N_pad] bool — True for real nodes.
      edge_mask:      [E_pad] bool — True for real edges.
      graph_mask:     [G_pad] bool — True for real graphs.
      targets:        tuple, one entry per head: graph-level heads are
                      [G_pad, dim]; node-level heads are [N_pad, dim].
      row_ptr:        [N_pad + 1] int32 or None — CSR boundaries over the
                      destination-sorted ``receivers`` (graphs/csr.py):
                      ``row_ptr[n]`` is the first edge targeting node >= n.
                      Computed once per batch at collation so the sorted-path
                      segment ops consume precomputed boundaries instead of
                      re-searching ids every layer.
      graph_ptr:      [G_pad + 1] int32 or None — the same boundaries over
                      ``node_graph`` (node→graph readout pooling).
      num_graphs_pad: static python int (G_pad). Needed as a static segment count.
    """

    node_features: jnp.ndarray
    edge_features: Optional[jnp.ndarray]
    senders: jnp.ndarray
    receivers: jnp.ndarray
    node_graph: jnp.ndarray
    node_mask: jnp.ndarray
    edge_mask: jnp.ndarray
    graph_mask: jnp.ndarray
    targets: Tuple[jnp.ndarray, ...] = ()
    row_ptr: Optional[jnp.ndarray] = None
    graph_ptr: Optional[jnp.ndarray] = None
    num_graphs_pad: int = struct.field(pytree_node=False, default=0)

    @property
    def num_nodes_pad(self) -> int:
        return self.node_features.shape[0]

    @property
    def num_edges_pad(self) -> int:
        return self.senders.shape[0]

    def count_real_nodes(self) -> jnp.ndarray:
        return jnp.sum(self.node_mask)

    def count_real_graphs(self) -> jnp.ndarray:
        return jnp.sum(self.graph_mask)
