"""Host-side collator: list[GraphSample] → padded GraphBatch numpy arrays.

Replaces torch_geometric's DataLoader collation (reference:
/root/reference/hydragnn/preprocess/load_data.py:53-86) with static-shape padding so
XLA compiles once per (N_pad, E_pad, G_pad) bucket. Also replaces the per-batch
``get_head_indices`` index math (/root/reference/hydragnn/train/train_validate_test.py:177-205):
targets are unpacked from the packed y/y_loc layout into dense per-head arrays here,
on the host, once per batch.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from .batch import GraphBatch
from .sample import GraphSample


def round_up_pow2(n: int, minimum: int = 8) -> int:
    """Round up to the next power of two (≥ minimum) to bound XLA recompiles."""
    v = max(int(n), minimum)
    return 1 << (v - 1).bit_length()


def unpack_targets(
    sample: GraphSample, head_types: Sequence[str], head_dims: Sequence[int]
) -> List[np.ndarray]:
    """Split a packed ``y`` (offsets in ``y_loc``) into per-head dense arrays:
    graph head → [dim]; node head → [n, dim] (row-major per node, matching the
    reshape(-1, 1) packing at serialized_dataset_loader.py:246-256)."""
    out = []
    y = np.asarray(sample.y).reshape(-1)
    y_loc = np.asarray(sample.y_loc).reshape(-1)
    n = sample.num_nodes
    for ihead, (htype, hdim) in enumerate(zip(head_types, head_dims)):
        sl = y[int(y_loc[ihead]) : int(y_loc[ihead + 1])]
        if htype == "graph":
            out.append(sl.reshape(hdim))
        elif htype == "node":
            out.append(sl.reshape(n, hdim))
        else:
            raise ValueError(f"Unknown head type {htype}")
    return out


def collate_graphs(
    graphs: Sequence[GraphSample],
    head_types: Sequence[str] = (),
    head_dims: Sequence[int] = (),
    num_nodes_pad: Optional[int] = None,
    num_edges_pad: Optional[int] = None,
    num_graphs_pad: Optional[int] = None,
    edge_dim: Optional[int] = None,
) -> GraphBatch:
    """Pack graphs into one padded GraphBatch (numpy arrays, host-side).

    Always reserves ≥1 padding node and ≥1 padding graph; padding edges connect
    padding nodes so unmasked message passing cannot touch real rows.
    """
    g = len(graphs)
    tot_nodes = sum(s.num_nodes for s in graphs)
    tot_edges = sum(s.num_edges for s in graphs)

    n_pad = num_nodes_pad if num_nodes_pad is not None else round_up_pow2(tot_nodes + 1)
    e_pad = num_edges_pad if num_edges_pad is not None else round_up_pow2(tot_edges + 1)
    g_pad = num_graphs_pad if num_graphs_pad is not None else g + 1
    if n_pad <= tot_nodes:
        raise ValueError(f"num_nodes_pad={n_pad} must exceed total nodes {tot_nodes}")
    if e_pad < tot_edges:
        raise ValueError(f"num_edges_pad={e_pad} must fit total edges {tot_edges}")
    if g_pad <= g:
        raise ValueError(f"num_graphs_pad={g_pad} must exceed num graphs {g}")

    feat_dim = graphs[0].x.shape[1]
    node_features = np.zeros((n_pad, feat_dim), dtype=np.float32)
    # Padding edges point at the last (always-padding) node.
    senders = np.full((e_pad,), n_pad - 1, dtype=np.int32)
    receivers = np.full((e_pad,), n_pad - 1, dtype=np.int32)
    # Padding nodes belong to the last (always-padding) graph slot.
    node_graph = np.full((n_pad,), g_pad - 1, dtype=np.int32)
    node_mask = np.zeros((n_pad,), dtype=bool)
    edge_mask = np.zeros((e_pad,), dtype=bool)
    graph_mask = np.zeros((g_pad,), dtype=bool)
    graph_mask[:g] = True

    if edge_dim is None:
        has_edge_attr = graphs[0].edge_attr is not None
        edge_dim_eff = graphs[0].edge_attr.shape[1] if has_edge_attr else 0
    else:
        has_edge_attr = edge_dim > 0
        edge_dim_eff = edge_dim
    edge_features = (
        np.zeros((e_pad, edge_dim_eff), dtype=np.float32) if has_edge_attr else None
    )

    targets = [
        np.zeros(
            (g_pad, hdim) if htype == "graph" else (n_pad, hdim), dtype=np.float32
        )
        for htype, hdim in zip(head_types, head_dims)
    ]

    node_off = 0
    edge_off = 0
    for gi, s in enumerate(graphs):
        n = s.num_nodes
        e = s.num_edges
        node_features[node_off : node_off + n] = s.x
        node_graph[node_off : node_off + n] = gi
        node_mask[node_off : node_off + n] = True
        if e:
            senders[edge_off : edge_off + e] = s.edge_index[0] + node_off
            receivers[edge_off : edge_off + e] = s.edge_index[1] + node_off
            edge_mask[edge_off : edge_off + e] = True
            if edge_features is not None and s.edge_attr is not None:
                edge_features[edge_off : edge_off + e] = s.edge_attr[:, :edge_dim_eff]
        if head_types:
            per_head = unpack_targets(s, head_types, head_dims)
            for ih, (htype, tval) in enumerate(zip(head_types, per_head)):
                if htype == "graph":
                    targets[ih][gi] = tval
                else:
                    targets[ih][node_off : node_off + n] = tval
        node_off += n
        edge_off += e

    return GraphBatch(
        node_features=node_features,
        edge_features=edge_features,
        senders=senders,
        receivers=receivers,
        node_graph=node_graph,
        node_mask=node_mask,
        edge_mask=edge_mask,
        graph_mask=graph_mask,
        targets=tuple(targets),
        num_graphs_pad=g_pad,
    )


def compute_pad_sizes(
    graphs: Sequence[GraphSample], batch_size: int
) -> Tuple[int, int, int]:
    """Dataset-level static pad sizes so every batch of ``batch_size`` graphs from
    this dataset fits one compiled shape: a worst-case batch is the ``batch_size``
    largest graphs."""
    nodes = sorted((s.num_nodes for s in graphs), reverse=True)[:batch_size]
    edges = sorted((s.num_edges for s in graphs), reverse=True)[:batch_size]
    n_pad = round_up_pow2(sum(nodes) + 1)
    e_pad = round_up_pow2(max(sum(edges), 1) + 1)
    return n_pad, e_pad, batch_size + 1
