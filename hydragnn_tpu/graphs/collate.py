"""Host-side collator: list[GraphSample] → padded GraphBatch numpy arrays.

Replaces torch_geometric's DataLoader collation (reference:
/root/reference/hydragnn/preprocess/load_data.py:53-86) with static-shape padding so
XLA compiles once per (N_pad, E_pad, G_pad) bucket. Also replaces the per-batch
``get_head_indices`` index math (/root/reference/hydragnn/train/train_validate_test.py:177-205):
targets are unpacked from the packed y/y_loc layout into dense per-head arrays here,
on the host, once per batch.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from .batch import GraphBatch
from .csr import build_graph_ptr, build_row_ptr, csr_debug_enabled, validate_csr
from .sample import GraphSample


def round_up_pow2(n: int, minimum: int = 8, mode: str = "pow2") -> int:
    """Round up to the next compiled-shape boundary (≥ minimum) to bound XLA
    recompiles. ``mode="pow2"`` (default) is the historical next-power-of-two
    ladder; ``mode="mult64"`` switches to multiples of 64 above 256 so a
    520-node batch pads to 576 instead of 1024 (``Dataset.ladder_step`` in
    the JSON config; graphs/packing.py:round_up_step holds the arithmetic)."""
    if mode == "pow2":
        v = max(int(n), minimum)
        return 1 << (v - 1).bit_length()
    from .packing import round_up_step

    return round_up_step(n, minimum=minimum, mode=mode)


def unpack_targets(
    sample: GraphSample, head_types: Sequence[str], head_dims: Sequence[int]
) -> List[np.ndarray]:
    """Split a packed ``y`` (offsets in ``y_loc``) into per-head dense arrays:
    graph head → [dim]; node head → [n, dim] (row-major per node, matching the
    reshape(-1, 1) packing at serialized_dataset_loader.py:246-256)."""
    out = []
    y = np.asarray(sample.y).reshape(-1)
    y_loc = np.asarray(sample.y_loc).reshape(-1)
    n = sample.num_nodes
    for ihead, (htype, hdim) in enumerate(zip(head_types, head_dims)):
        sl = y[int(y_loc[ihead]) : int(y_loc[ihead + 1])]
        if htype == "graph":
            out.append(sl.reshape(hdim))
        elif htype == "node":
            out.append(sl.reshape(n, hdim))
        else:
            raise ValueError(f"Unknown head type {htype}")
    return out


def collate_graphs(
    graphs: Sequence[GraphSample],
    head_types: Sequence[str] = (),
    head_dims: Sequence[int] = (),
    num_nodes_pad: Optional[int] = None,
    num_edges_pad: Optional[int] = None,
    num_graphs_pad: Optional[int] = None,
    edge_dim: Optional[int] = None,
) -> GraphBatch:
    """Pack graphs into one padded GraphBatch (numpy arrays, host-side).

    Always reserves >=1 padding node and >=1 padding graph; padding edges
    connect padding nodes so unmasked message passing cannot touch real rows.
    One-off convenience over the single packing implementation, GraphArena —
    loaders build the arena once and reuse it per batch.
    """
    return GraphArena(graphs).collate(
        np.arange(len(graphs)),
        head_types=head_types,
        head_dims=head_dims,
        num_nodes_pad=num_nodes_pad,
        num_edges_pad=num_edges_pad,
        num_graphs_pad=num_graphs_pad,
        edge_dim=edge_dim,
    )


class GraphArena:
    """Dataset-level contiguous buffers for zero-Python-loop batch packing.

    Per-sample Python packing (property calls, tiny reshapes per graph) costs
    ~2 ms for a 256-graph batch — a single prefetch thread then feeds a TPU
    ~8x slower than the chip trains. The arena concatenates every sample's
    fields ONCE per dataset; a batch is then a handful of numpy gathers
    (~0.4 ms for the same 256 graphs), independent of graph count in Python
    terms. Trade-off: the arena holds a second, contiguous copy of the
    dataset's arrays (float32/int32) for the loader's lifetime — datasets are
    host-RAM sized in this framework (the reference holds them on the
    accelerator, serialized_dataset_loader.py:137-140), so ~2x host arrays is
    the cost of feeding the chip at line rate.

    Edge-feature semantics: presence and width are resolved ONCE at arena
    (dataset) level from the first edge-bearing sample carrying ``edge_attr``
    — not per batch. A batch whose own graphs all lack ``edge_attr`` still
    gets zero-filled ``edge_features`` (not None) when any other sample in
    the dataset has them, keeping the batch pytree structure identical across
    batches (one jit trace per pad shape instead of two)."""

    def __init__(self, graphs: Sequence[GraphSample]):
        g = len(graphs)
        self.ns = np.fromiter((s.num_nodes for s in graphs), np.int64, g)
        self.es = np.fromiter((s.num_edges for s in graphs), np.int64, g)
        self.node_start = np.zeros(g + 1, np.int64)
        np.cumsum(self.ns, out=self.node_start[1:])
        self.edge_start = np.zeros(g + 1, np.int64)
        np.cumsum(self.es, out=self.edge_start[1:])

        self.x_all = np.concatenate(
            [np.asarray(s.x, dtype=np.float32) for s in graphs]
        )
        with_edges = [s for s in graphs if s.num_edges]
        if with_edges:
            self.ei_all = np.concatenate(
                [np.asarray(s.edge_index, dtype=np.int32) for s in with_edges],
                axis=1,
            )
            first_attr = next(
                (s.edge_attr for s in with_edges if s.edge_attr is not None), None
            )
            if first_attr is not None:
                # Samples missing edge_attr contribute zero rows (same as the
                # historical per-sample packer: attrs that exist are packed).
                width = np.asarray(first_attr).shape[1]
                self.ea_all = np.concatenate(
                    [
                        np.asarray(s.edge_attr, dtype=np.float32)[:, :width]
                        if s.edge_attr is not None
                        else np.zeros((s.num_edges, width), np.float32)
                        for s in with_edges
                    ]
                )
            else:
                self.ea_all = None
        else:
            self.ei_all = np.zeros((2, 0), np.int32)
            self.ea_all = None

        # Sort each graph's edges by receiver (stable, one-time): message
        # passing is permutation-invariant over edges, and per-graph sorted
        # runs + ascending batch node offsets + top-index padding edges make
        # every collated batch's receivers globally non-decreasing — the
        # contract the scatter-free sorted segment path requires
        # (ops/segment_sorted.py). edge_attr rows ride the same permutation.
        if self.ei_all.shape[1]:
            graph_of_edge = np.repeat(
                np.arange(g, dtype=np.int64), self.es
            )
            order = np.lexsort((self.ei_all[1], graph_of_edge))
            self.ei_all = self.ei_all[:, order]
            if self.ea_all is not None:
                self.ea_all = self.ea_all[order]
        # CSR batch contract (graphs/csr.py): the sort above is what makes
        # every collated batch's receivers globally non-decreasing, so the
        # row pointers collate() emits are valid. Validated ONCE per arena
        # (first collate) — or every batch under HYDRAGNN_DEBUG_LAYOUT=1.
        self._csr_validated = False

        # Unlabeled datasets (inference-only: y/y_loc absent) simply carry no
        # target arenas; requesting head_types at collate then raises.
        if any(s.y is None or s.y_loc is None for s in graphs):
            self.y_all = None
            self.y_start = None
            self.y_loc = None
        else:
            ys = [np.asarray(s.y, dtype=np.float32).reshape(-1) for s in graphs]
            self.y_start = np.zeros(g + 1, np.int64)
            np.cumsum(
                np.fromiter((y.size for y in ys), np.int64, g),
                out=self.y_start[1:],
            )
            self.y_all = np.concatenate(ys) if ys else np.zeros(0, np.float32)
            self.y_loc = np.stack(
                [np.asarray(s.y_loc, dtype=np.int64).reshape(-1) for s in graphs]
            )

    @staticmethod
    def _ragged_rows(starts: np.ndarray, lens: np.ndarray) -> np.ndarray:
        """Flat arena row indices for per-sample ranges [start, start+len)."""
        total = int(lens.sum())
        intra = np.arange(total, dtype=np.int64)
        intra -= np.repeat(np.cumsum(lens) - lens, lens)
        return np.repeat(starts, lens) + intra

    def collate(
        self,
        idx,
        head_types: Sequence[str] = (),
        head_dims: Sequence[int] = (),
        num_nodes_pad: Optional[int] = None,
        num_edges_pad: Optional[int] = None,
        num_graphs_pad: Optional[int] = None,
        edge_dim: Optional[int] = None,
    ) -> GraphBatch:
        """Pack the samples at ``idx`` — same output as ``collate_graphs`` on
        the corresponding GraphSample list (parity-tested)."""
        idx = np.asarray(idx, dtype=np.int64)
        g = len(idx)
        ns, es = self.ns[idx], self.es[idx]
        tot_nodes = int(ns.sum())
        tot_edges = int(es.sum())

        n_pad = num_nodes_pad if num_nodes_pad is not None else round_up_pow2(tot_nodes + 1)
        e_pad = num_edges_pad if num_edges_pad is not None else round_up_pow2(tot_edges + 1)
        g_pad = num_graphs_pad if num_graphs_pad is not None else g + 1
        if n_pad <= tot_nodes:
            raise ValueError(f"num_nodes_pad={n_pad} must exceed total nodes {tot_nodes}")
        if e_pad < tot_edges:
            raise ValueError(f"num_edges_pad={e_pad} must fit total edges {tot_edges}")
        if g_pad <= g:
            raise ValueError(f"num_graphs_pad={g_pad} must exceed num graphs {g}")

        feat_dim = self.x_all.shape[1]
        node_features = np.zeros((n_pad, feat_dim), dtype=np.float32)
        senders = np.full((e_pad,), n_pad - 1, dtype=np.int32)
        receivers = np.full((e_pad,), n_pad - 1, dtype=np.int32)
        node_graph = np.full((n_pad,), g_pad - 1, dtype=np.int32)
        node_mask = np.zeros((n_pad,), dtype=bool)
        edge_mask = np.zeros((e_pad,), dtype=bool)
        graph_mask = np.zeros((g_pad,), dtype=bool)
        graph_mask[:g] = True

        node_rows = self._ragged_rows(self.node_start[idx], ns)
        node_features[:tot_nodes] = self.x_all[node_rows]
        node_graph[:tot_nodes] = np.repeat(np.arange(g, dtype=np.int32), ns)
        node_mask[:tot_nodes] = True

        if edge_dim is None:
            has_edge_attr = self.ea_all is not None
            edge_dim_eff = self.ea_all.shape[1] if has_edge_attr else 0
        else:
            has_edge_attr = edge_dim > 0
            edge_dim_eff = edge_dim
        edge_features = (
            np.zeros((e_pad, edge_dim_eff), dtype=np.float32)
            if has_edge_attr
            else None
        )
        if tot_edges:
            edge_rows = self._ragged_rows(self.edge_start[idx], es)
            new_node_off = np.zeros(g, np.int64)
            np.cumsum(ns[:-1], out=new_node_off[1:])
            shift = np.repeat(new_node_off, es)
            senders[:tot_edges] = self.ei_all[0, edge_rows] + shift
            receivers[:tot_edges] = self.ei_all[1, edge_rows] + shift
            edge_mask[:tot_edges] = True
            if edge_features is not None and self.ea_all is not None:
                edge_features[:tot_edges] = self.ea_all[edge_rows, :edge_dim_eff]

        targets = [
            np.zeros(
                (g_pad, hdim) if htype == "graph" else (n_pad, hdim),
                dtype=np.float32,
            )
            for htype, hdim in zip(head_types, head_dims)
        ]
        if head_types and self.y_all is None:
            raise ValueError(
                "targets requested but the dataset has unlabeled samples "
                "(y/y_loc is None)"
            )
        for ih, (htype, hdim) in enumerate(zip(head_types, head_dims)):
            starts = self.y_start[idx] + self.y_loc[idx, ih]
            spans = self.y_loc[idx, ih + 1] - self.y_loc[idx, ih]
            if htype == "graph":
                if not (spans == hdim).all():
                    raise ValueError(
                        f"head {ih}: y_loc spans {np.unique(spans)} != "
                        f"declared graph dim {hdim}"
                    )
                targets[ih][:g] = self.y_all[starts[:, None] + np.arange(hdim)]
            elif htype == "node":
                if not (spans == ns * hdim).all():
                    raise ValueError(
                        f"head {ih}: y_loc spans don't match num_nodes * "
                        f"{hdim} (declared node dim)"
                    )
                rows = self._ragged_rows(starts, ns * hdim)
                targets[ih][:tot_nodes] = self.y_all[rows].reshape(tot_nodes, hdim)
            else:
                raise ValueError(f"Unknown head type {htype}")

        # Precomputed CSR boundaries — one O(E) host pass per batch replaces
        # two searchsorted calls per op per conv layer in the compiled step.
        row_ptr = build_row_ptr(receivers, n_pad)
        graph_ptr = build_graph_ptr(node_graph, g_pad)
        if not self._csr_validated or csr_debug_enabled():
            # Structural O(E) checks only (deep=False): the pointers were
            # bincount-built from these very ids two lines up, so for
            # sorted in-range ids they provably equal the searchsorted
            # boundaries — and serving builds one arena PER micro-batch
            # flush, putting this on the collate hot path. The deep
            # cross-check runs in the debug mode and the check_config gate.
            deep = csr_debug_enabled()
            validate_csr(receivers, row_ptr, n_pad, what="receivers", deep=deep)
            validate_csr(
                node_graph, graph_ptr, g_pad, what="node_graph", deep=deep
            )
            self._csr_validated = True

        return GraphBatch(
            node_features=node_features,
            edge_features=edge_features,
            senders=senders,
            receivers=receivers,
            node_graph=node_graph,
            node_mask=node_mask,
            edge_mask=edge_mask,
            graph_mask=graph_mask,
            targets=tuple(targets),
            row_ptr=row_ptr,
            graph_ptr=graph_ptr,
            num_graphs_pad=g_pad,
        )


def compute_pad_sizes(
    graphs: Sequence[GraphSample], batch_size: int, ladder_step: str = "pow2"
) -> Tuple[int, int, int]:
    """Dataset-level static pad sizes so every batch of ``batch_size`` graphs from
    this dataset fits one compiled shape: a worst-case batch is the ``batch_size``
    largest graphs. ``ladder_step`` picks the round-up ladder (see
    ``round_up_pow2``)."""
    return compute_pad_sizes_from_counts(
        [s.num_nodes for s in graphs],
        [s.num_edges for s in graphs],
        batch_size,
        ladder_step=ladder_step,
    )


def compute_pad_sizes_from_counts(
    ns, es, batch_size: int, ladder_step: str = "pow2"
) -> Tuple[int, int, int]:
    """``compute_pad_sizes`` from per-sample (num_nodes, num_edges) count
    arrays alone — the form the loaders use (their ``_ns``/``_es`` arrays are
    the single source of truth) and the only form the out-of-core streaming
    loader CAN use: its pad shapes come from the GSHD index without decoding
    a single shard (docs/DATA_PLANE.md)."""
    nodes = sorted((int(n) for n in ns), reverse=True)[:batch_size]
    edges = sorted((int(e) for e in es), reverse=True)[:batch_size]
    n_pad = round_up_pow2(sum(nodes) + 1, mode=ladder_step)
    e_pad = round_up_pow2(max(sum(edges), 1) + 1, mode=ladder_step)
    return n_pad, e_pad, batch_size + 1
