"""CSR batch contract: destination-sorted edges + precomputed row pointers.

Collation already sorts every graph's edges by receiver (graphs/collate.py:
GraphArena), which makes batch receivers globally non-decreasing — the layout
the scatter-free sorted segment path (ops/segment_sorted.py) requires. Until
PR 7 that layout was a CONVENTION: every conv layer re-derived its segment
boundaries with two ``searchsorted`` calls per op per layer, and nothing
checked the assumption.

This module promotes the layout to a first-class contract:

* :func:`build_row_ptr` — ``row_ptr[N_pad + 1]`` over the padded receiver
  array (``row_ptr[n]`` = first edge whose receiver is ``>= n``;
  ``row_ptr[n + 1] - row_ptr[n]`` = in-degree of node ``n``). Computed ONCE
  per batch on the host (O(E) bincount + cumsum) and carried on
  :class:`~hydragnn_tpu.graphs.batch.GraphBatch` so every conv layer of
  every op consumes precomputed boundaries — zero in-step binary searches.
* :func:`build_graph_ptr` — the same pointers over ``node_graph`` (nodes are
  contiguous per graph by collation), consumed by the node→graph mean-pool
  readout.
* :func:`validate_csr` — the one checkable definition of the contract
  (length, endpoints, monotonicity, agreement with the actual sorted ids),
  run once per arena at first collation and by the ``check_config``
  eval_shape gate; ``HYDRAGNN_DEBUG_LAYOUT=1`` re-validates every batch.

Padding edges connect padding nodes at the TOP index (receiver
``N_pad - 1``), so the padding node's row simply absorbs them — identical
boundaries to what ``searchsorted`` derived in-step, which is why the
precomputed path is bit-exact against the historical one (tests).

Everything here is deterministic by contract (graftlint's
collation-deterministic rule applies): pure numpy on (ids, shapes) only.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np


def csr_debug_enabled() -> bool:
    """Re-validate the CSR contract on EVERY collated batch (host-side) and
    insert runtime layout assertions into the sorted-path ops
    (ops/segment_sorted.attach_layout_check). Off by default: the contract
    is validated once per arena; this flag is the loud diagnostic for
    suspected layout regressions."""
    return os.environ.get("HYDRAGNN_DEBUG_LAYOUT", "0") not in (
        "0",
        "false",
        "False",
    )


def build_row_ptr(ids: np.ndarray, num_segments: int) -> np.ndarray:
    """``row_ptr[num_segments + 1]`` int32 for NON-DECREASING ``ids`` [E].

    ``row_ptr[s] = searchsorted(ids, s, side="left")`` computed in O(E) via
    bincount + exclusive cumsum. The result is only meaningful under the
    sorted contract — callers that cannot guarantee it must
    :func:`validate_csr` (the arena does, once)."""
    ids = np.asarray(ids)
    counts = np.bincount(ids, minlength=num_segments)
    if len(counts) > num_segments:
        raise ValueError(
            f"ids reference segment {int(ids.max())} >= num_segments "
            f"{num_segments}"
        )
    row_ptr = np.zeros(num_segments + 1, dtype=np.int32)
    np.cumsum(counts, out=row_ptr[1:])
    return row_ptr


def build_graph_ptr(node_graph: np.ndarray, num_graphs: int) -> np.ndarray:
    """``graph_ptr[num_graphs + 1]`` over the (sorted) node→graph ids — the
    readout pooling's CSR boundaries."""
    return build_row_ptr(node_graph, num_graphs)


def validate_csr(
    ids: np.ndarray,
    row_ptr: np.ndarray,
    num_segments: int,
    what: str = "receivers",
    num_rows: Optional[int] = None,
    deep: bool = True,
) -> None:
    """Raise ``ValueError`` unless ``(ids, row_ptr)`` satisfies the CSR batch
    contract:

    * ``row_ptr`` has ``num_segments + 1`` entries, starts at 0, ends at
      ``len(ids)`` (every edge owned by exactly one segment), and is
      monotonically non-decreasing;
    * ``ids`` is globally non-decreasing and in ``[0, num_segments)``;
    * (``deep`` only) the pointers agree with the ids: ``row_ptr[s]`` is
      exactly the first position with ``ids >= s`` for every segment.

    ``deep=False`` skips the O(N log E) searchsorted cross-check — for
    sorted, in-range ids a bincount-built ``row_ptr`` (build_row_ptr) IS the
    searchsorted boundary set, so callers validating pointers they just
    built from the same ids (the collation hot path: serving builds one
    arena per micro-batch flush) only need the O(E) structural checks. Keep
    the default for pointers of unknown provenance (the check_config gate,
    tests)."""
    ids = np.asarray(ids)
    row_ptr = np.asarray(row_ptr)
    e = len(ids) if num_rows is None else int(num_rows)
    if row_ptr.shape != (num_segments + 1,):
        raise ValueError(
            f"CSR contract violated for {what}: row_ptr shape "
            f"{row_ptr.shape} != ({num_segments + 1},)"
        )
    if row_ptr[0] != 0 or row_ptr[-1] != e:
        raise ValueError(
            f"CSR contract violated for {what}: row_ptr endpoints "
            f"({int(row_ptr[0])}, {int(row_ptr[-1])}) != (0, {e})"
        )
    if (np.diff(row_ptr) < 0).any():
        raise ValueError(
            f"CSR contract violated for {what}: row_ptr is not monotone"
        )
    if len(ids):
        if (np.diff(ids) < 0).any():
            k = int(np.argmax(np.diff(ids) < 0))
            raise ValueError(
                f"CSR contract violated for {what}: ids not sorted at row "
                f"{k} ({int(ids[k])} -> {int(ids[k + 1])})"
            )
        if int(ids.min()) < 0 or int(ids.max()) >= num_segments:
            raise ValueError(
                f"CSR contract violated for {what}: ids outside "
                f"[0, {num_segments})"
            )
    if not deep:
        return
    expect = np.searchsorted(ids, np.arange(num_segments + 1)).astype(
        row_ptr.dtype
    )
    if not np.array_equal(row_ptr, expect):
        bad = int(np.argmax(row_ptr != expect))
        raise ValueError(
            f"CSR contract violated for {what}: row_ptr[{bad}] = "
            f"{int(row_ptr[bad])}, ids say {int(expect[bad])}"
        )
