"""Graph packing + occupancy-aware bucket ladders (ROADMAP item 1).

The padded-arena contract compiles one executable per ``(N_pad, E_pad,
G_pad)`` bucket — but a bucket sized for the worst-case batch burns most of
the chip on padding when traffic is small (SERVE_r06: occupancy 0.06–0.5,
padding waste 75–97% of nodes/edges). This module is the shared layer both
hot paths use to stop that:

* :func:`first_fit_decreasing` — bin-pack many small graphs into one arena
  slot under joint ``(nodes, edges, graphs)`` capacity constraints BEFORE
  padding, so each compiled batch carries more real rows. Used by the
  serving micro-batcher (``serve/engine.py``) and the training collator plan
  (``preprocess/dataloader.py``).
* :class:`SizeHistogram` — per-run record of observed graph and batch sizes
  (serve metrics layer + training loader), serialized to JSON so production
  observations feed the next deploy's ladder.
* :func:`fit_ladder` — derive a small set of ``(N_pad, E_pad)`` bucket
  shapes from an observed size histogram under a bounded compile budget
  (``max_rungs``), minimizing expected padded-row waste instead of rounding
  everything to the next power of two.
* :func:`resolve_ladder_spec` — one parser for every ladder form the CLIs
  accept: ``"NxE,NxE"`` literals, ``auto:<histogram.json>`` (fit now), and
  ``auto:<ladder.json>`` (pre-fitted, e.g. by ``fit-ladder`` below).

CLI::

    python -m hydragnn_tpu.graphs.packing fit-ladder --hist HIST.json \
        [--max-rungs 4] [--mode mult64] [--out LADDER.json]

Everything here is deterministic by contract (graftlint's
collation-deterministic rule applies): no wall clock, no global RNG —
batches must be a pure function of (dataset, seed, epoch) or crash-resume
replay and the device-cache epochs diverge from the streamed path.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

HISTOGRAM_SCHEMA = "hydragnn-size-histogram/v1"
LADDER_SCHEMA = "hydragnn-bucket-ladder/v1"

# Default compile budget for fitted ladders: each rung is one XLA compile at
# warmup (~tens of seconds each on the bucketed path, BENCH_r05_hw), so the
# fitter trades padding waste against a handful of executables, not dozens.
DEFAULT_MAX_RUNGS = 4

LADDER_STEP_MODES = ("pow2", "mult64")


# --------------------------------------------------------------------- packer
@dataclasses.dataclass(frozen=True)
class PackCaps:
    """Joint capacity of ONE arena slot (one padded batch).

    ``nodes``/``edges`` are REAL-row capacities: the padded batch needs
    ``N_pad > total nodes`` (>= 1 padding node is always reserved), so a slot
    destined for shape ``(N_pad, E_pad)`` has ``nodes = N_pad - 1`` and
    ``edges = E_pad``. ``graphs`` caps bin cardinality so ``G_pad`` stays a
    static compiled dimension.
    """

    nodes: int
    edges: int
    graphs: int

    def fits(self, n: int, e: int, g: int = 1) -> bool:
        return n <= self.nodes and e <= self.edges and g <= self.graphs


def first_fit_decreasing(
    node_sizes: Sequence[int],
    edge_sizes: Sequence[int],
    caps: PackCaps,
    order: Optional[Sequence[int]] = None,
) -> List[List[int]]:
    """Pack items (graphs) into bins (arena slots) by first-fit-decreasing.

    Items are visited largest-first (by nodes, then edges) and each placed
    into the FIRST open bin with room under every capacity; no fit opens a
    new bin. Returns bins as lists of item indices, in bin-creation order.

    ``order`` is an optional permutation of item indices used as the scan
    order among EQUAL-size items (and the within-bin emission order): callers
    with a per-epoch shuffle pass it so ties rotate across epochs while the
    packing itself stays deterministic in (sizes, order).

    An item exceeding ``caps`` on its own is returned as a singleton bin —
    the caller's fallback path (pow2 round-up) owns oversize graphs; packing
    must never drop or reorder them out of existence.
    """
    ns = np.asarray(node_sizes, dtype=np.int64)
    es = np.asarray(edge_sizes, dtype=np.int64)
    if ns.shape != es.shape or ns.ndim != 1:
        raise ValueError("node_sizes and edge_sizes must be equal-length 1-D")
    count = len(ns)
    if order is None:
        order = np.arange(count, dtype=np.int64)
    else:
        order = np.asarray(order, dtype=np.int64)
        if sorted(order.tolist()) != list(range(count)):
            raise ValueError("order must be a permutation of range(len(items))")
    # Decreasing by (nodes, edges); ties follow the caller's order. Sorting
    # the caller-ordered items with a stable sort gives exactly that.
    rank = np.lexsort((-es[order], -ns[order]))
    visit = order[rank]

    bins: List[List[int]] = []
    bin_nodes: List[int] = []
    bin_edges: List[int] = []
    for i in visit.tolist():
        n, e = int(ns[i]), int(es[i])
        if not caps.fits(n, e):
            bins.append([i])  # oversize: isolated, caller falls back
            bin_nodes.append(n)
            bin_edges.append(e)
            continue
        for b, members in enumerate(bins):
            if (
                bin_nodes[b] + n <= caps.nodes
                and bin_edges[b] + e <= caps.edges
                and len(members) < caps.graphs
                # An oversize singleton is CLOSED: feeding it more graphs
                # would push the fallback shape even further past the ladder.
                and caps.fits(bin_nodes[b], bin_edges[b])
            ):
                members.append(i)
                bin_nodes[b] += n
                bin_edges[b] += e
                break
        else:
            bins.append([i])
            bin_nodes.append(n)
            bin_edges.append(e)
    return bins


# ------------------------------------------------------------------ histogram
class SizeHistogram:
    """Joint size counts for graphs and batches, JSON-serializable.

    ``graphs``: {(nodes, edges): count} of individual graphs (requests /
    dataset samples). ``batches``: {(nodes, edges, graphs): count} of REAL
    batch totals at collation time — what the ladder fitter consumes. Counts
    are plain ints; recording is O(1) per observation.
    """

    def __init__(self):
        # Single-threaded on the training path (loader-owned); under serving
        # the owning ServeMetrics records into it holding ITS lock.
        self.graphs: Dict[Tuple[int, int], int] = {}  # guarded-by: external(callers synchronize; ServeMetrics records under ServeMetrics._lock, the training loader is single-threaded)
        self.batches: Dict[Tuple[int, int, int], int] = {}  # guarded-by: external(callers synchronize; ServeMetrics records under ServeMetrics._lock, the training loader is single-threaded)

    def record_graph(self, nodes: int, edges: int, weight: int = 1) -> None:
        key = (int(nodes), int(edges))
        self.graphs[key] = self.graphs.get(key, 0) + int(weight)

    def record_batch(
        self, nodes: int, edges: int, graphs: int, weight: int = 1
    ) -> None:
        key = (int(nodes), int(edges), int(graphs))
        self.batches[key] = self.batches.get(key, 0) + int(weight)

    @property
    def num_graphs(self) -> int:
        return sum(self.graphs.values())

    @property
    def num_batches(self) -> int:
        return sum(self.batches.values())

    def merge(self, other: "SizeHistogram") -> "SizeHistogram":
        for (n, e), w in other.graphs.items():
            self.record_graph(n, e, w)
        for (n, e, g), w in other.batches.items():
            self.record_batch(n, e, g, w)
        return self

    # -- serialization (sorted keys => byte-stable files for identical data)
    def to_json(self) -> dict:
        return {
            "schema": HISTOGRAM_SCHEMA,
            "graph_sizes": [
                [n, e, w] for (n, e), w in sorted(self.graphs.items())
            ],
            "batch_sizes": [
                [n, e, g, w] for (n, e, g), w in sorted(self.batches.items())
            ],
        }

    @classmethod
    def from_json(cls, doc: dict) -> "SizeHistogram":
        if doc.get("schema") != HISTOGRAM_SCHEMA:
            raise ValueError(
                f"not a size histogram (schema {doc.get('schema')!r}, "
                f"expected {HISTOGRAM_SCHEMA!r})"
            )
        hist = cls()
        for n, e, w in doc.get("graph_sizes", ()):
            hist.record_graph(n, e, w)
        for n, e, g, w in doc.get("batch_sizes", ()):
            hist.record_batch(n, e, g, w)
        return hist

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=2, sort_keys=True)

    @classmethod
    def load(cls, path: str) -> "SizeHistogram":
        with open(path) as f:
            return cls.from_json(json.load(f))


# -------------------------------------------------------------- ladder fitter
def round_up_step(
    n: int, minimum: int = 8, mode: str = "pow2", step: int = 64
) -> int:
    """Round a size up to a compiled-shape boundary.

    ``mode="pow2"``: next power of two (the historical ladder — at most 2x
    waste, but a 520-node batch pads to 1024). ``mode="mult64"``: next power
    of two up to ``4*step`` (tiny shapes stay sparse), then the next multiple
    of ``step`` — a 520-node batch pads to 576, and 64 is the TPU lane width
    so every rung stays tiling-aligned.
    """
    if mode not in LADDER_STEP_MODES:
        raise ValueError(
            f"unknown ladder-step mode {mode!r} (expected one of "
            f"{LADDER_STEP_MODES})"
        )
    v = max(int(n), int(minimum))
    p = 1 << (v - 1).bit_length()
    if mode == "pow2" or p <= 4 * step:
        return p
    return -(-v // step) * step


def fit_ladder(
    hist: "SizeHistogram | Sequence[Tuple[int, int, int]]",
    max_rungs: int = DEFAULT_MAX_RUNGS,
    mode: str = "mult64",
    step: int = 64,
    min_nodes: int = 8,
) -> List[Tuple[int, int]]:
    """Fit an occupancy-aware bucket ladder to observed batch sizes.

    Input is a :class:`SizeHistogram` (its ``batches`` table; single-graph
    ``graphs`` observations stand in when no batches were recorded — the
    1-request flush shape) or a raw ``[(nodes, edges, weight)]`` sequence.
    Returns at most ``max_rungs`` ``(N_pad, E_pad)`` shapes, ascending, with
    ``E_pad`` non-decreasing alongside ``N_pad`` so the TOP rung dominates
    every observation — the packers' capacity guarantee.

    Method: exact weighted interval DP over the (quantized) sorted node
    totals. Splitting the observations into K contiguous segments, each
    segment's rung is the rounded-up segment maximum and its cost is the
    weighted padded-node waste ``sum_i w_i * (N_seg - n_i)``; the DP picks
    the K-segmentation minimizing total waste. Edge pads are the rounded-up
    per-segment edge maxima (cummax'd) — edges ride the node segmentation
    because node counts drive both in molecular graphs, and an edge overflow
    still resolves to a higher rung at batch time rather than an error.
    """
    if isinstance(hist, SizeHistogram):
        rows = [(n, e, w) for (n, e, g), w in sorted(hist.batches.items())]
        if not rows:
            rows = [(n, e, w) for (n, e), w in sorted(hist.graphs.items())]
    else:
        rows = [(int(n), int(e), int(w)) for n, e, w in hist]
    rows = [(n, e, w) for n, e, w in rows if w > 0]
    if not rows:
        raise ValueError("cannot fit a ladder from an empty histogram")
    max_rungs = max(1, int(max_rungs))

    # Aggregate per unique node total; carry max-edges and summed weight.
    by_n: Dict[int, List[int]] = {}
    for n, e, w in rows:
        cur = by_n.setdefault(n, [0, 0])
        cur[0] += w
        cur[1] = max(cur[1], e)
    ns = np.array(sorted(by_n), dtype=np.int64)
    ws = np.array([by_n[int(n)][0] for n in ns], dtype=np.float64)
    es = np.array([by_n[int(n)][1] for n in ns], dtype=np.int64)

    # Bound the DP: quantize to at most 512 support points by merging each
    # chunk into its maximum (conservative: rungs only grow, never shrink).
    if len(ns) > 512:
        chunks = np.array_split(np.arange(len(ns)), 512)
        ns = np.array([ns[c].max() for c in chunks])
        ws = np.array([ws[c].sum() for c in chunks])
        es = np.array([es[c].max() for c in chunks])

    m = len(ns)
    k = min(max_rungs, m)
    w_pref = np.concatenate([[0.0], np.cumsum(ws)])
    wn_pref = np.concatenate([[0.0], np.cumsum(ws * ns)])

    def seg_cost(i: int, j: int) -> float:
        """Weighted padded-node waste of one rung covering ns[i..j]."""
        rung = round_up_step(int(ns[j]) + 1, minimum=min_nodes, mode=mode, step=step)
        return rung * (w_pref[j + 1] - w_pref[i]) - (wn_pref[j + 1] - wn_pref[i])

    inf = float("inf")
    dp = np.full((k + 1, m + 1), inf)
    cut = np.zeros((k + 1, m + 1), dtype=np.int64)
    dp[0][0] = 0.0
    for r in range(1, k + 1):
        for j in range(1, m + 1):
            for i in range(r - 1, j):
                c = dp[r - 1][i] + seg_cost(i, j - 1)
                if c < dp[r][j]:
                    dp[r][j] = c
                    cut[r][j] = i
    # Fewer segments can never cost less here (each rung is a segment max),
    # but rungs can COLLIDE after rounding — dedup below handles that.
    bounds = []
    j = m
    for r in range(k, 0, -1):
        i = int(cut[r][j])
        bounds.append((i, j - 1))
        j = i
    bounds.reverse()

    ladder: List[Tuple[int, int]] = []
    e_floor = 0
    for i, j in bounds:
        n_pad = round_up_step(int(ns[j]) + 1, minimum=min_nodes, mode=mode, step=step)
        e_pad = round_up_step(
            max(int(es[i : j + 1].max()), 1), minimum=min_nodes, mode=mode, step=step
        )
        e_floor = max(e_floor, e_pad)  # cummax: top rung dominates on edges
        if ladder and ladder[-1][0] == n_pad:
            ladder[-1] = (n_pad, max(ladder[-1][1], e_floor))
        else:
            ladder.append((n_pad, e_floor))
    return ladder


def ladder_waste(
    ladder: Sequence[Tuple[int, int]],
    hist: "SizeHistogram | Sequence[Tuple[int, int, int]]",
) -> float:
    """Mean padded-node waste fraction of ``hist``'s batches under ``ladder``
    (tightest-fitting rung per batch; oversize batches fall back pow2) —
    the fitter's objective, exposed for reporting and tests."""
    if isinstance(hist, SizeHistogram):
        rows = [(n, e, w) for (n, e, g), w in sorted(hist.batches.items())]
        if not rows:
            rows = [(n, e, w) for (n, e), w in sorted(hist.graphs.items())]
    else:
        rows = list(hist)
    rungs = sorted((int(n), int(e)) for n, e in ladder)
    total_w = total_waste = 0.0
    for n, e, w in rows:
        n_pad = next(
            (rn for rn, re in rungs if rn > n and re >= e),
            round_up_step(n + 1, mode="pow2"),
        )
        total_w += w
        total_waste += w * (1.0 - n / n_pad)
    return total_waste / total_w if total_w else 0.0


# ------------------------------------------------------------- drift distance
def node_distribution(
    hist: "SizeHistogram | Sequence[Tuple[int, int, int]]",
    mode: str = "mult64",
    step: int = 64,
    min_nodes: int = 8,
) -> Dict[int, float]:
    """Normalized node-size distribution over compiled-shape bins.

    Each observed graph size is quantized to its :func:`round_up_step`
    boundary (the same quantization the ladder fitter pads to), then the
    per-bin weights are normalized to sum to 1. Quantizing BEFORE comparing
    is what makes the drift detector's distance mean something operational:
    two traffic mixes that land in the same compiled shapes are, for the
    batcher, the same distribution — only mass moving across a shape
    boundary can change occupancy or trigger fallback.
    """
    if isinstance(hist, SizeHistogram):
        rows = [(n, e, w) for (n, e), w in sorted(hist.graphs.items())]
        if not rows:
            rows = [(n, e, w) for (n, e, g), w in sorted(hist.batches.items())]
    else:
        rows = [(int(n), int(e), int(w)) for n, e, w in hist]
    rows = [(n, e, w) for n, e, w in rows if w > 0]
    if not rows:
        raise ValueError("cannot build a distribution from an empty histogram")
    bins: Dict[int, float] = {}
    total = 0.0
    for n, _e, w in rows:
        b = round_up_step(n, minimum=min_nodes, mode=mode, step=step)
        bins[b] = bins.get(b, 0.0) + w
        total += w
    return {b: v / total for b, v in sorted(bins.items())}


def histogram_distance(
    p: "SizeHistogram | Sequence[Tuple[int, int, int]]",
    q: "SizeHistogram | Sequence[Tuple[int, int, int]]",
    mode: str = "mult64",
    step: int = 64,
    min_nodes: int = 8,
) -> float:
    """Total-variation distance in [0, 1] between two histograms'
    :func:`node_distribution`\\ s — the flywheel drift detector's metric.
    0 means the two traffic mixes occupy compiled shapes identically; 1
    means disjoint support (every request would hit a different rung)."""
    pd = node_distribution(p, mode=mode, step=step, min_nodes=min_nodes)
    qd = node_distribution(q, mode=mode, step=step, min_nodes=min_nodes)
    keys = set(pd) | set(qd)
    return 0.5 * sum(abs(pd.get(k, 0.0) - qd.get(k, 0.0)) for k in keys)


# ----------------------------------------------------------------- spec forms
def parse_ladder_literal(spec: str) -> List[Tuple[int, int]]:
    """``"512x4096,1024x8192"`` → ``[(512, 4096), (1024, 8192)]``."""
    ladder = []
    for part in filter(None, (p.strip() for p in spec.split(","))):
        n, _, e = part.partition("x")
        if not e:
            raise ValueError(
                f"bucket ladder rung {part!r} is not of the form NxE"
            )
        ladder.append((int(n), int(e)))
    if not ladder:
        raise ValueError(f"empty bucket ladder spec {spec!r}")
    return ladder


def resolve_ladder_spec(
    spec: str,
    max_rungs: int = DEFAULT_MAX_RUNGS,
    mode: str = "mult64",
) -> List[Tuple[int, int]]:
    """Resolve any CLI/config ladder form to ``[(N_pad, E_pad)]``.

    * ``"NxE,NxE,..."`` — literal shapes, as before.
    * ``"auto:<path>"`` — ``<path>`` is either a fitted ladder JSON (the
      ``fit-ladder`` CLI output: its ladder is used verbatim) or a size
      histogram JSON (a ladder is fitted NOW with the given budget).
    """
    if spec.startswith("auto:"):
        path = spec[len("auto:") :]
        if not path:
            raise ValueError("auto: ladder spec is missing the file path")
        with open(path) as f:
            doc = json.load(f)
        if doc.get("schema") == LADDER_SCHEMA:
            ladder = [(int(n), int(e)) for n, e in doc["ladder"]]
            if not ladder:
                raise ValueError(f"{path}: fitted ladder is empty")
            return ladder
        return fit_ladder(
            SizeHistogram.from_json(doc), max_rungs=max_rungs, mode=mode
        )
    return parse_ladder_literal(spec)


def ladder_to_json(
    ladder: Sequence[Tuple[int, int]], meta: Optional[dict] = None
) -> dict:
    return {
        "schema": LADDER_SCHEMA,
        "ladder": [[int(n), int(e)] for n, e in ladder],
        "meta": dict(meta or {}),
    }


# ------------------------------------------------------------------------ CLI
def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m hydragnn_tpu.graphs.packing",
        description="Graph-packing utilities (docs/SERVING.md runbook).",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    fit = sub.add_parser(
        "fit-ladder",
        help="fit an occupancy-aware bucket ladder from a size histogram",
    )
    fit.add_argument(
        "--hist",
        required=True,
        help="size-histogram JSON (serve: SERVE_rNN_hist.json; training: "
        "logs/<name>/size_histogram.json)",
    )
    fit.add_argument("--max-rungs", type=int, default=DEFAULT_MAX_RUNGS)
    fit.add_argument("--mode", choices=LADDER_STEP_MODES, default="mult64")
    fit.add_argument("--step", type=int, default=64)
    fit.add_argument(
        "--out",
        default=None,
        help="write the fitted ladder JSON here (default: stdout only); "
        "consumed by --bucket-ladder auto:<path>",
    )
    args = ap.parse_args(argv)

    hist = SizeHistogram.load(args.hist)
    ladder = fit_ladder(
        hist, max_rungs=args.max_rungs, mode=args.mode, step=args.step
    )
    doc = ladder_to_json(
        ladder,
        meta={
            "source": args.hist,
            "max_rungs": args.max_rungs,
            "mode": args.mode,
            "step": args.step,
            "observed_batches": hist.num_batches,
            "observed_graphs": hist.num_graphs,
            "mean_padding_waste_nodes": round(ladder_waste(ladder, hist), 4),
        },
    )
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
    print(json.dumps(doc))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
