"""Host-side graph sample — the numpy replacement for torch_geometric.data.Data as
used by the reference loaders (/root/reference/hydragnn/preprocess/*.py).

A ``GraphSample`` lives on the host, in the input pipeline, only. Device arrays are
produced by the collator (hydragnn_tpu/graphs/collate.py). The packed-``y`` +
``y_loc`` layout of the reference (serialized_dataset_loader.py:220-261) is kept on
this host object for config/data compatibility; it is unpacked into dense per-head
arrays at batch time.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


@dataclasses.dataclass
class GraphSample:
    """One graph (atomic structure).

    x:    [n, F] node features.
    pos:  [n, 3] node positions.
    y:    packed target vector (graph features then per-head slices once
          ``update_predicted_values`` has run).
    y_loc: [1, num_heads+1] int64 prefix offsets of each head's slice in ``y``.
    edge_index: [2, E] int (senders row 0, receivers row 1).
    edge_attr:  [E, D] float edge attributes (e.g. lengths).
    supercell_size: [3, 3] lattice vectors for periodic structures.
    """

    x: Optional[np.ndarray] = None
    pos: Optional[np.ndarray] = None
    y: Optional[np.ndarray] = None
    y_loc: Optional[np.ndarray] = None
    edge_index: Optional[np.ndarray] = None
    edge_attr: Optional[np.ndarray] = None
    supercell_size: Optional[np.ndarray] = None

    @property
    def num_nodes(self) -> int:
        if self.x is not None:
            return int(self.x.shape[0])
        return int(self.pos.shape[0])

    @property
    def num_edges(self) -> int:
        if self.edge_index is None:
            return 0
        return int(self.edge_index.shape[1])

    def clone(self) -> "GraphSample":
        return GraphSample(
            **{
                f.name: (None if getattr(self, f.name) is None else np.array(getattr(self, f.name)))
                for f in dataclasses.fields(self)
            }
        )
