"""Scatter-free segment aggregation for SORTED segment ids.

Collation owns edge order (message passing is permutation-invariant over
edges), so GraphArena sorts each graph's edges by receiver once at arena
build; batch receivers are then globally non-decreasing (per-graph sorted
runs + ascending node offsets + padding edges at the top index). That turns
segment_sum — TPU's worst op as a scatter — into pure prefix sums and
gathers:

    P[k]   = sum(data[:k])                       (compensated prefix, below)
    out[s] = P[right_s] - P[left_s]
    cnt[s] = right_s - left_s                    (EXACT, integer)

where left/right come from the batch's precomputed CSR ``row_ptr``
(graphs/csr.py — collation builds and validates it once per batch) or, when
no boundaries were provided, from two in-step ``searchsorted`` calls (the
pre-PR-7 derivation, kept for callers outside the batch contract and for
edge-sharded graph parallelism where global offsets don't apply).

Cost: one O(E·F) chunked cumsum (HBM-bound, log-depth on TPU), a short
TwoSum carry scan over chunk totals, two gathers [N, F] — and zero binary
searches when ``row_ptr`` rides along. Zero MXU work, zero scatter, no
O(N·E) one-hot.

Accuracy: a raw f32 prefix difference cancels against the magnitude of the
WHOLE prefix (worst ~1e-3 at E=16k), so the prefix is two-level: f32 cumsum
within chunks (error bounded by local magnitudes) and carries accumulated
across chunks as an UNEVALUATED hi+err pair via error-free TwoSum — no f64,
so no dependence on jax_enable_x64. The segment value is recovered as
(hi_r - hi_l) + (err_r - err_l) + (local_r - local_l): the hi cancellation
is exactly rounded and its accumulated rounding error lives in err.
Certified against the same f64 ground truth as the Pallas kernel (tests).

OPT-IN (HYDRAGNN_SEGMENT_SORTED=1) until measured on TPU hardware — the
sorted arm rides along automatically whenever ``certify_pallas`` runs on
contiguous ids (bench.py each round; benchmarks/tune_kernel.py's first sweep
arm; benchmarks/hw_watchdog.sh's bench_sorted step measures it in the real
train step). Convs request it via ``sorted_ids=True`` (+ the batch's
``row_ptr``) on the fused_* wrappers — since PR 7 that includes GAT, whose
self-loops became an explicit self-attention term instead of the
sort-breaking ``[edges; self-loops]`` concat (models/convs.py:GATv2Conv).
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

# Trace-time spy: number of searchsorted boundary derivations traced by this
# module. The CSR batch contract (graphs/csr.py) exists to drive this to ZERO
# in the compiled step — collation precomputes ``row_ptr`` once per batch and
# every sorted-path op consumes it. tests/test_csr_contract.py asserts a full
# model trace with row_ptr present increments this by 0.
SEARCHSORTED_CALLS = 0


def searchsorted_calls() -> int:
    return SEARCHSORTED_CALLS


def _host_assert_sorted(ids, what="segment ids"):
    """jax.debug.callback target: loud failure on a layout regression."""
    import numpy as np

    arr = np.asarray(ids)
    if len(arr) and (np.diff(arr) < 0).any():
        k = int(np.argmax(np.diff(arr) < 0))
        raise RuntimeError(
            f"sorted-layout contract violated: {what} decrease at row {k} "
            f"({int(arr[k])} -> {int(arr[k + 1])}) — a caller passed "
            "sorted_ids=True on an unsorted layout (HYDRAGNN_DEBUG_LAYOUT "
            "check)"
        )


def attach_layout_check(ids: jnp.ndarray, what: str = "segment ids") -> None:
    """Debug-mode runtime assertion that ``ids`` really is non-decreasing.

    The ``fused_*`` wrappers accept ``sorted_ids=True`` on the caller's word;
    collation validates its own batches once per arena (graphs/csr.py), but a
    NEW caller with a broken layout would silently corrupt aggregation. Under
    ``HYDRAGNN_DEBUG_LAYOUT=1`` (read at trace time, like every other gate
    here) each sorted-path op embeds a host callback that raises on the first
    unsorted batch; default off — zero cost in production steps."""
    from ..graphs.csr import csr_debug_enabled

    if csr_debug_enabled():
        jax.debug.callback(functools.partial(_host_assert_sorted, what=what), ids)


def sorted_enabled() -> bool:
    """Trace-time gate, like HYDRAGNN_PALLAS (set before the first step).

    DEFAULT ON for TPU execution since round 5: the first full hardware
    bench of the three aggregation candidates (BENCH_r05_sorted.json, TPU
    v5e) measured the sorted path at 926,028 graphs/s/chip on the flagship
    workload vs the 812,122 XLA-scatter baseline pin (+14%; steady step
    0.276 ms vs 0.315 ms; the hidden=256 model stepped 1.65x faster), with
    hardware-certified accuracy (CERTIFY_r05.json sorted arm: fwd 3.0e-5,
    grad 1.5e-4 — the only arm that met every gate before the kernel fix).
    Off-TPU the default stays the XLA scatter bundle (CPU scatters are
    cheap and the exact-gate reference-parity tests pin that path).
    HYDRAGNN_SEGMENT_SORTED=1/0 overrides either way."""
    env = os.environ.get("HYDRAGNN_SEGMENT_SORTED")
    if env is not None:
        return env not in ("0", "false", "False")
    from . import segment as seg

    return seg.execution_platform() == "tpu"


def _chunk_rows(e: int) -> int:
    """Chunk size: >=128 (lane-friendly), sized so the carry scan stays short
    (<=512 sequential steps) while local f32 cumsum error stays bounded."""
    c = 128
    while e // c > 512:
        c *= 2
    return c


def _two_sum(a, b):
    """Error-free transformation: a + b = s + err exactly (Knuth)."""
    s = a + b
    bb = s - a
    err = (a - bb) + (b - (s - bb))
    return s, err


def _prefix_open(data32: jnp.ndarray):
    """Two-level inclusive prefix of [E, F] f32 data.

    Returns (local, hi, err, chunk): P[k] = hi[k // chunk] + err[k // chunk]
    + local[k], where (hi, err) is the compensated EXCLUSIVE sum of chunks
    before k's and local the f32 cumsum inside it."""
    e, f = data32.shape
    chunk = _chunk_rows(e)
    e_pad = (e + chunk - 1) // chunk * chunk
    padded = jnp.zeros((e_pad, f), jnp.float32).at[:e].set(data32)
    chunks = padded.reshape(e_pad // chunk, chunk, f)
    local = jnp.cumsum(chunks, axis=1)
    totals = local[:, -1, :]  # [C, F]

    def step(carry, t):
        s, err = carry
        s2, e2 = _two_sum(s, t)
        return (s2, err + e2), (s, err)  # emit EXCLUSIVE prefix

    zeros = jnp.zeros((f,), jnp.float32)
    _, (hi, err) = jax.lax.scan(step, (zeros, zeros), totals)
    return local.reshape(e_pad, f), hi, err, chunk


def _sum_count_sorted(data, ids, num_segments: int, row_ptr=None):
    data32 = data.astype(jnp.float32)
    if data32.shape[0] == 0:
        # Drop-in parity with segment_sum on an empty edge set: exact zeros
        # (jnp.mean over the empty axis would otherwise inject NaN via mu).
        return (
            jnp.zeros((num_segments, data32.shape[1]), jnp.float32),
            jnp.zeros((num_segments,), jnp.float32),
        )
    # Mean-center before the prefix: a mean-shifted stream grows the prefix
    # linearly and the within-chunk f32 cumsum rounds at ulp(prefix) — ~5e-4
    # absolute at E=16k, 100x the scatter path. Centered, the prefix is a
    # random walk (~sqrt scale); the exact row count restores count*mu after
    # the difference (masked rows contribute -mu then get +mu back: net 0).
    mu = jnp.mean(data32, axis=0)
    local, hi, err, chunk = _prefix_open(data32 - mu)
    if row_ptr is not None:
        # CSR batch contract: collation precomputed the boundaries once per
        # batch (graphs/csr.py). Identical values to the searchsorted
        # derivation below (validated at collation), so the two paths are
        # bit-exact — tests/test_csr_contract.py pins that.
        row_ptr = row_ptr.astype(jnp.int32)
        left, right = row_ptr[:-1], row_ptr[1:]
    else:
        ids = ids.astype(jnp.int32)
        seg = jnp.arange(num_segments, dtype=jnp.int32)
        global SEARCHSORTED_CALLS
        SEARCHSORTED_CALLS += 1
        left = jnp.searchsorted(ids, seg, side="left").astype(jnp.int32)
        right = jnp.searchsorted(ids, seg, side="right").astype(jnp.int32)

    def parts(k):
        """(hi, err, local) components of P[k] = sum(data[:k]); k in [0, E]."""
        km1 = jnp.maximum(k - 1, 0)
        nz = (k > 0)[:, None]
        c = km1 // chunk
        return (
            jnp.where(nz, hi[c], 0.0),
            jnp.where(nz, err[c], 0.0),
            jnp.where(nz, local[km1], 0.0),
        )

    hi_r, err_r, loc_r = parts(right)
    hi_l, err_l, loc_l = parts(left)
    # hi_r - hi_l is exactly rounded; the carries' accumulated rounding error
    # is (err_r - err_l); within-chunk contributions cancel at local scale.
    count = (right - left).astype(jnp.float32)
    total = (
        (hi_r - hi_l) + (err_r - err_l) + (loc_r - loc_l)
        + count[:, None] * mu
    )
    return total, count


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def segment_sum_count_sorted(data, ids, num_segments: int):
    """(segment_sum, segment_count) for non-decreasing ``ids`` — see module
    docstring. ``data`` [E, F] float; masked rows must already be zeroed and
    their ids kept sort-compatible (collation's padding contract)."""
    return _sum_count_sorted(data, ids, num_segments)


def _fwd(data, ids, num_segments):
    # Zero-size carrier keeps the input dtype in the residuals (a raw dtype
    # object is not a JAX type) — same trick as pallas_segment's VJP.
    carrier = jnp.zeros((0,), data.dtype)
    return _sum_count_sorted(data, ids, num_segments), (ids, carrier)


def _bwd(num_segments, res, cots):
    ids, carrier = res
    d_total, _ = cots  # count is effectively non-differentiable (integer)
    idx = jnp.clip(ids.astype(jnp.int32), 0, num_segments - 1)
    d_data = jnp.take(d_total, idx, axis=0).astype(carrier.dtype)
    return d_data, jnp.zeros(ids.shape, jax.dtypes.float0)


segment_sum_count_sorted.defvjp(_fwd, _bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def segment_sum_count_csr(data, row_ptr, ids, num_segments: int):
    """(segment_sum, segment_count) from PRECOMPUTED CSR boundaries — the
    zero-searchsorted twin of :func:`segment_sum_count_sorted`. ``row_ptr``
    [num_segments + 1] comes from collation (graphs/csr.py); ``ids`` is kept
    only for the gather backward (it never enters the forward)."""
    return _sum_count_sorted(data, ids, num_segments, row_ptr=row_ptr)


def _csr_fwd(data, row_ptr, ids, num_segments):
    carrier = jnp.zeros((0,), data.dtype)
    out = _sum_count_sorted(data, ids, num_segments, row_ptr=row_ptr)
    return out, (row_ptr, ids, carrier)


def _csr_bwd(num_segments, res, cots):
    row_ptr, ids, carrier = res
    d_total, _ = cots
    idx = jnp.clip(ids.astype(jnp.int32), 0, num_segments - 1)
    d_data = jnp.take(d_total, idx, axis=0).astype(carrier.dtype)
    return (
        d_data,
        jnp.zeros(row_ptr.shape, jax.dtypes.float0),
        jnp.zeros(ids.shape, jax.dtypes.float0),
    )


segment_sum_count_csr.defvjp(_csr_fwd, _csr_bwd)


def segment_sum_count_auto(data, ids, num_segments: int, row_ptr=None):
    """Dispatch between the precomputed-boundary and searchsorted variants —
    the single entry the fused wrappers route sorted traffic through."""
    if row_ptr is not None:
        return segment_sum_count_csr(data, row_ptr, ids, num_segments)
    return segment_sum_count_sorted(data, ids, num_segments)


def segment_sum_sorted(
    data, ids, num_segments: int, mask: Optional[jnp.ndarray] = None,
    row_ptr=None,
):
    """Masked drop-in segment_sum for sorted ids ([E, ...] data)."""
    shape = data.shape
    flat = data.reshape(shape[0], -1) if data.ndim != 2 else data
    if mask is not None:
        flat = jnp.where(mask[:, None], flat, 0)
    total, _ = segment_sum_count_auto(flat, ids, num_segments, row_ptr=row_ptr)
    out = total.astype(data.dtype)
    if data.ndim != 2:
        out = out.reshape((num_segments,) + shape[1:])
    return out
