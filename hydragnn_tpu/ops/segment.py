"""Masked segment ops — the XLA replacement for torch-scatter/-sparse kernels that
PyTorch-Geometric message passing leans on (reference conv calls:
/root/reference/hydragnn/models/Base.py:236-243, global_mean_pool at Base.py:250).

All ops take a static ``num_segments`` so shapes are compile-time constants, and an
optional boolean mask marking valid rows. Under the GraphBatch padding contract
(padding edges connect padding nodes) masks are usually only needed for statistics
(mean/std/min/max/softmax) where identity elements differ from zero.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

_BIG = 1e30


def _expand(mask: jnp.ndarray, like: jnp.ndarray) -> jnp.ndarray:
    """Broadcast a [N] mask against [N, ...] data."""
    return mask.reshape(mask.shape + (1,) * (like.ndim - mask.ndim))


def segment_sum(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    if mask is not None:
        data = jnp.where(_expand(mask, data), data, 0)
    return jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)


def segment_count(
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    ones = jnp.ones(segment_ids.shape[0], dtype=jnp.float32)
    if mask is not None:
        ones = jnp.where(mask, ones, 0.0)
    return jax.ops.segment_sum(ones, segment_ids, num_segments=num_segments)


def segment_mean(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    total = segment_sum(data, segment_ids, num_segments, mask)
    count = segment_count(segment_ids, num_segments, mask)
    return total / jnp.maximum(count, 1.0).reshape(
        count.shape + (1,) * (total.ndim - count.ndim)
    )


def segment_max(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    fill: float = 0.0,
) -> jnp.ndarray:
    if mask is not None:
        data = jnp.where(_expand(mask, data), data, -_BIG)
    out = jax.ops.segment_max(data, segment_ids, num_segments=num_segments)
    # Empty segments come back as -inf/-BIG: replace with `fill` so downstream
    # matmuls stay finite (isolated nodes have no incoming messages).
    return jnp.where(out <= -_BIG / 2, fill, out)


def segment_min(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    fill: float = 0.0,
) -> jnp.ndarray:
    if mask is not None:
        data = jnp.where(_expand(mask, data), data, _BIG)
    out = jax.ops.segment_min(data, segment_ids, num_segments=num_segments)
    return jnp.where(out >= _BIG / 2, fill, out)


def segment_std(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    eps: float = 1e-5,
) -> jnp.ndarray:
    """Per-segment standard deviation, sqrt(relu(E[x^2]-E[x]^2) + eps) like PyG's
    PNA 'std' aggregator (uses a small eps for a finite gradient at zero)."""
    mean = segment_mean(data, segment_ids, num_segments, mask)
    mean_sq = segment_mean(jnp.square(data), segment_ids, num_segments, mask)
    var = jax.nn.relu(mean_sq - jnp.square(mean))
    return jnp.sqrt(var + eps)


def segment_softmax(
    logits: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Numerically-stable softmax normalized within each segment (GATv2 attention
    over incoming edges). Masked-out rows get weight 0."""
    if mask is not None:
        logits = jnp.where(_expand(mask, logits), logits, -_BIG)
    seg_max = jax.ops.segment_max(logits, segment_ids, num_segments=num_segments)
    seg_max = jnp.where(seg_max <= -_BIG / 2, 0.0, seg_max)
    shifted = logits - seg_max[segment_ids]
    exp = jnp.exp(shifted)
    if mask is not None:
        exp = jnp.where(_expand(mask, exp), exp, 0.0)
    denom = jax.ops.segment_sum(exp, segment_ids, num_segments=num_segments)
    return exp / jnp.maximum(denom[segment_ids], 1e-16)


def masked_mean(data: jnp.ndarray, mask: jnp.ndarray, axis=None) -> jnp.ndarray:
    """Mean over rows where mask is True (for batch-norm statistics over padded
    node arrays)."""
    m = jnp.broadcast_to(_expand(mask, data), data.shape).astype(data.dtype)
    total = jnp.sum(data * m, axis=axis)
    count = jnp.sum(m, axis=axis)
    return total / jnp.maximum(count, 1.0)
