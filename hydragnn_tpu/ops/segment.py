"""Masked segment ops — the XLA replacement for torch-scatter/-sparse kernels that
PyTorch-Geometric message passing leans on (reference conv calls:
/root/reference/hydragnn/models/Base.py:236-243, global_mean_pool at Base.py:250).

All ops take a static ``num_segments`` so shapes are compile-time constants, and an
optional boolean mask marking valid rows.

Graph parallelism (the long-context analog axis, SURVEY.md §5.7): every op accepts
an optional ``axis_name``. When set, the edge/data rows are assumed sharded across
that mesh axis (nodes replicated); each device reduces its local shard and the
partial segment results are combined with the matching XLA collective
(psum / pmax / pmin) over ICI. This turns large-graph message passing into
edge-partitioned SPMD with one collective per aggregation.
"""

from __future__ import annotations

import contextlib
from contextvars import ContextVar
from typing import Optional

import jax
import jax.numpy as jnp

_BIG = 1e30

# Platform the op-gating decisions see (fused-kernel and sorted-path
# defaults). jax.default_backend() is process-global and WRONG in
# mixed-platform environments (a TPU-attached host tracing a step for a CPU
# mesh): the gate must reflect the devices that will execute the op. Step
# builders pin it for the duration of tracing via platform_override().
# Defined here (the lowest-level ops module) so pallas_segment and
# segment_sorted share one source of truth without a circular import.
_PLATFORM_OVERRIDE: ContextVar[Optional[str]] = ContextVar(
    "hydragnn_execution_platform", default=None
)


@contextlib.contextmanager
def platform_override(platform: Optional[str]):
    token = _PLATFORM_OVERRIDE.set(platform)
    try:
        yield
    finally:
        _PLATFORM_OVERRIDE.reset(token)


def execution_platform() -> str:
    return _PLATFORM_OVERRIDE.get() or jax.default_backend()


def _pmax(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Differentiable cross-device max (lax.pmax has no VJP rule)."""
    return jnp.max(jax.lax.all_gather(x, axis_name), axis=0)


def _pmin(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    return jnp.min(jax.lax.all_gather(x, axis_name), axis=0)


def _expand(mask: jnp.ndarray, like: jnp.ndarray) -> jnp.ndarray:
    """Broadcast a [N] mask against [N, ...] data."""
    return mask.reshape(mask.shape + (1,) * (like.ndim - mask.ndim))


def segment_sum(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    axis_name: Optional[str] = None,
) -> jnp.ndarray:
    if mask is not None:
        data = jnp.where(_expand(mask, data), data, 0)
    out = jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)
    if axis_name is not None:
        out = jax.lax.psum(out, axis_name)
    return out


def segment_count(
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    axis_name: Optional[str] = None,
) -> jnp.ndarray:
    ones = jnp.ones(segment_ids.shape[0], dtype=jnp.float32)
    if mask is not None:
        ones = jnp.where(mask, ones, 0.0)
    return segment_sum(ones, segment_ids, num_segments, axis_name=axis_name)


def segment_mean(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    axis_name: Optional[str] = None,
) -> jnp.ndarray:
    total = segment_sum(data, segment_ids, num_segments, mask, axis_name)
    count = segment_count(segment_ids, num_segments, mask, axis_name)
    return total / jnp.maximum(count, 1.0).reshape(
        count.shape + (1,) * (total.ndim - count.ndim)
    )


def segment_max(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    fill: float = 0.0,
    axis_name: Optional[str] = None,
) -> jnp.ndarray:
    if mask is not None:
        data = jnp.where(_expand(mask, data), data, -_BIG)
    out = jax.ops.segment_max(data, segment_ids, num_segments=num_segments)
    if axis_name is not None:
        out = _pmax(out, axis_name)
    # Empty segments come back as -inf/-BIG: replace with `fill` so downstream
    # matmuls stay finite (isolated nodes have no incoming messages).
    return jnp.where(out <= -_BIG / 2, fill, out)


def segment_min(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    fill: float = 0.0,
    axis_name: Optional[str] = None,
) -> jnp.ndarray:
    if mask is not None:
        data = jnp.where(_expand(mask, data), data, _BIG)
    out = jax.ops.segment_min(data, segment_ids, num_segments=num_segments)
    if axis_name is not None:
        out = _pmin(out, axis_name)
    return jnp.where(out >= _BIG / 2, fill, out)


def segment_std(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    eps: float = 1e-5,
    axis_name: Optional[str] = None,
) -> jnp.ndarray:
    """Per-segment standard deviation, sqrt(relu(E[x^2]-E[x]^2) + eps) like PyG's
    PNA 'std' aggregator (uses a small eps for a finite gradient at zero)."""
    mean = segment_mean(data, segment_ids, num_segments, mask, axis_name)
    mean_sq = segment_mean(
        jnp.square(data), segment_ids, num_segments, mask, axis_name
    )
    var = jax.nn.relu(mean_sq - jnp.square(mean))
    return jnp.sqrt(var + eps)


def segment_softmax(
    logits: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    axis_name: Optional[str] = None,
    sum_fn=None,
) -> jnp.ndarray:
    """Numerically-stable softmax normalized within each segment (GATv2 attention
    over incoming edges). Masked-out rows get weight 0. Under graph parallelism
    the per-segment max and denominator are reduced globally; the returned
    weights are for the LOCAL edge shard.

    ``sum_fn(data, ids, n, mask=, axis_name=)`` overrides the denominator's
    segment sum (must return the globally-reduced sum) — the hook the fused
    Pallas kernel plugs into so both paths share ONE stabilization body."""
    if mask is not None:
        logits = jnp.where(_expand(mask, logits), logits, -_BIG)
    seg_max = jax.ops.segment_max(logits, segment_ids, num_segments=num_segments)
    if axis_name is not None:
        seg_max = _pmax(seg_max, axis_name)
    seg_max = jnp.where(seg_max <= -_BIG / 2, 0.0, seg_max)
    # Softmax is shift-invariant, so the max is analytically a constant:
    # stop_gradient gives the identical gradient while skipping
    # segment_max's scatter-heavy TPU VJP (jax.nn.softmax does the same).
    seg_max = jax.lax.stop_gradient(seg_max)
    shifted = logits - seg_max[segment_ids]
    exp = jnp.exp(shifted)
    if mask is not None:
        exp = jnp.where(_expand(mask, exp), exp, 0.0)
    if sum_fn is not None:
        denom = sum_fn(
            exp, segment_ids, num_segments, mask=mask, axis_name=axis_name
        )
    else:
        denom = jax.ops.segment_sum(exp, segment_ids, num_segments=num_segments)
        if axis_name is not None:
            denom = jax.lax.psum(denom, axis_name)
    return exp / jnp.maximum(denom[segment_ids], 1e-16)


def masked_mean(data: jnp.ndarray, mask: jnp.ndarray, axis=None) -> jnp.ndarray:
    """Mean over rows where mask is True (for batch-norm statistics over padded
    node arrays)."""
    m = jnp.broadcast_to(_expand(mask, data), data.shape).astype(data.dtype)
    total = jnp.sum(data * m, axis=axis)
    count = jnp.sum(m, axis=axis)
    return total / jnp.maximum(count, 1.0)
