"""Pallas TPU kernel for fused segment aggregation — the hot op of PNA.

The reference's PNA conv (via PyG ``PNAConv``, /root/reference/hydragnn/models/
PNAStack.py:28-53) aggregates per-edge messages with four aggregators
(mean/min/max/std). Composed from XLA segment ops that is five scatter passes
over the [E, F] edge-message array (sum, count, sum-of-squares, min, max) —
and XLA's TPU scatter-add serializes updates instead of using the MXU.

This kernel turns the scatter into one-hot matmuls on the 128x128 MXU systolic
array: for a [BN]-node block and [BE]-edge block,

    onehot[n, e] = (receiver[e] == n)        # built in-register, exact in bf16
    sum   += onehot @ data                    # MXU
    count += rowsum(onehot)                   # VPU

TPU matmuls run bf16 multiplies by default (~0.4% relative error — the
bfloat16-first design point for TPU training). That is fine for sum/mean but
catastrophic for variance via E[x^2]-E[x]^2 (cancellation); so ``std`` is
computed with a SECOND fused pass over *centered* values,
var = mean((x - mean[ids])^2), which has no cancellation and keeps bf16-class
relative accuracy. Two passes over the edge data instead of five, with the
scatters on the MXU.

Measured on TPU v5e (E=16k, F=64, N=4k) on the ROUND-2 kernel: XLA
mean/min/max/std/count bundle ~88us; the fused path ~50us with min/max still
on XLA ``segment_max/min`` (elementwise extrema cannot ride the MXU and their
scatters are not the bottleneck). The round-4 rework (f-packing + block-skip)
did NOT hold that win on its first hardware contact (TUNE_KERNEL_r05:
0.41-0.98x vs XLA, certification failing) — hence the opt-in default; see
pallas_enabled.

The custom VJP keeps the backward on plain XLA gathers (gathers are fast on
TPU; only scatter is slow): for (sum, count) the data cotangent is
``d_sum[ids]``, and the stats bundle has an analytic scatter-free backward.
A side benefit of the centered formulation: the std value AND gradient are
~1000x more accurate than XLA's ``sqrt(relu(E[x²]−E[x]²)+eps)`` on
near-degenerate segments (values clustered around a large offset), where the
uncentered form cancels catastrophically in f32 (measured 6.6e-6 vs 5.8e-3
max grad error against an f64 reference).

On non-TPU backends the public entry points fall back to the masked XLA
segment ops in ``hydragnn_tpu.ops.segment`` (tests exercise the kernel via the
Pallas interpreter for exact parity with what compiles on TPU). Set
``HYDRAGNN_PALLAS=0`` to force the XLA path everywhere.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from . import segment as seg
from . import segment_sorted as srt

_BN = 128  # node-block rows (one MXU tile edge)
# Edge-block columns per grid step. Env-overridable (HYDRAGNN_PALLAS_BE) so
# benchmarks/tune_kernel.py can sweep block sizes on hardware without code
# edits; must be a multiple of 128 (lane count).
# A malformed value must not abort unrelated imports (code that never touches
# Pallas, or runs with HYDRAGNN_PALLAS=0): record the error here and raise it
# from _sum_count_pallas when the kernel is actually requested.
_BE_ERROR: Optional[str] = None
try:
    _BE = int(os.environ.get("HYDRAGNN_PALLAS_BE", "512"))
except ValueError:
    _BE, _BE_ERROR = 512, (
        "HYDRAGNN_PALLAS_BE must be an integer multiple of 128, got "
        f"{os.environ['HYDRAGNN_PALLAS_BE']!r}"
    )
if _BE_ERROR is None and (_BE <= 0 or _BE % 128 != 0):
    _BE, _BE_ERROR = 512, (
        f"HYDRAGNN_PALLAS_BE={_BE} must be a positive multiple of 128 (lanes)"
    )

# Platform gating lives in ops/segment.py (shared with segment_sorted's
# TPU-default gate — one source of truth, no circular import). Re-exported
# here under the names the trainer and tests have always used.
pallas_platform = seg.platform_override
_platform = seg.execution_platform


def pallas_enabled() -> bool:
    """True when the fused kernel should run. OPT-IN (HYDRAGNN_PALLAS=1)
    since round 5: the first on-hardware measurements of the reworked kernel
    (TPU v5e, 2026-07-31, TUNE_KERNEL_r05) showed it both failing its f64
    certification (ok=false at every swept block size) and slower than the
    XLA segment bundle (0.41-0.98x). The certification failure was
    root-caused (and fixed) later in r05: DEFAULT-precision MXU dots
    truncate f32 operands to bf16 on hardware only, so the std's
    single-pass sum-of-squares carried ~8e-3 error (16x the gate) and the
    un-rounded lo residual lost its low bits — see _stats_forward_pallas
    and _sum_count_pallas. Post-fix the kernel certifies ok=true ON
    HARDWARE at every block size (CERTIFY_r05.json, TUNE_KERNEL_r05.jsonl)
    with interpreter certification now hardware-faithful. It nevertheless
    STAYS opt-in: the end-to-end three-way race (BENCH_r05_*.json) was won
    by the scatter-free sorted path (ops/segment_sorted.py, the TPU
    default), with the kernel at ~parity with the XLA bundle. The kernel
    remains the candidate for workloads the sorted contract cannot cover
    (unsorted ids at scale); tests/test_pallas_tpu.py stays the hardware
    canary."""
    env = os.environ.get("HYDRAGNN_PALLAS")
    if env is not None:
        return env not in ("0", "false", "False")
    return False


def csr_kernel_enabled() -> bool:
    """Route Pallas traffic that carries precomputed CSR boundaries
    (``row_ptr`` — the PR-7 batch contract, graphs/csr.py) through the
    CSR-blocked kernel instead of the legacy one-hot scatter matmul. Rides
    UNDER the HYDRAGNN_PALLAS opt-in (pallas_enabled): with the kernel arm
    enabled, HYDRAGNN_PALLAS_CSR=0 forces the legacy one-hot kernel — the
    A/B pin benchmarks/pallas_matrix.py and tune_kernel.py use to race the
    two kernel generations on hardware. Default on: when a caller has CSR
    boundaries the run-walk kernel does strictly less work (no id compares,
    exact empty-block skip from the pointers)."""
    return pallas_enabled() and os.environ.get(
        "HYDRAGNN_PALLAS_CSR", "1"
    ) not in ("0", "false", "False")


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _round_bf16(v: jnp.ndarray) -> jnp.ndarray:
    """Round f32 to the nearest bf16-representable f32 via integer bit math.

    NOT ``v.astype(bfloat16).astype(float32)``: XLA:TPU runs with excess
    precision allowed and folds that f32->bf16->f32 convert pair to the
    IDENTITY, which silently turned the hi/lo accuracy split into hi = x,
    lo = 0 — the kernel ran single-pass bf16 on hardware (measured r05:
    split=True output bit-identical to split=False, ~5e-2 error) while the
    interpreter, which does not fold the pair, certified ~1e-4. Bit masking
    can't be folded. Round-half-up: adding 0x8000 before masking carries
    into the exponent exactly when rounding up to the next binade should.
    Finite inputs only (NaN payloads may change; we never feed NaN/inf)."""
    u = jax.lax.bitcast_convert_type(v, jnp.uint32)
    u = (u + jnp.uint32(0x8000)) & jnp.uint32(0xFFFF0000)
    return jax.lax.bitcast_convert_type(u, jnp.float32)


def _wants_split(dtype) -> bool:
    """Single source of the hi/lo accuracy-split policy: the split only buys
    accuracy when the input has more mantissa bits than bf16 — for bf16
    activations (mixed precision) lo == 0 and the extra pass is pure waste."""
    return dtype != jnp.bfloat16


def _sum_count_kernel(ids_ref, data_ref, sum_ref, cnt_ref):
    import jax.experimental.pallas as pl

    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        sum_ref[:] = jnp.zeros_like(sum_ref)
        cnt_ref[:] = jnp.zeros_like(cnt_ref)

    base = pl.program_id(0) * _BN
    rows = jax.lax.broadcasted_iota(jnp.int32, (_BN, _BE), 0) + base
    ids = ids_ref[:]  # (1, BE); padded/masked edges carry id -1 → no row matches
    onehot = (rows == ids).astype(jnp.float32)  # (BN, BE)
    sum_ref[:] += jnp.dot(onehot, data_ref[:], preferred_element_type=jnp.float32)
    cnt_ref[:] += jnp.sum(onehot, axis=1, keepdims=True)


def _sum_count_split_kernel(ids_ref, hi_ref, lo_ref, sum_ref, cnt_ref):
    """Accuracy variant: the TPU MXU multiplies in bf16, but the one-hot factor
    is exact in bf16, so splitting data into a bf16 hi/lo pair and doing two
    matmuls recovers ~f32 accuracy at 2x the MXU work (the bf16x2 trick; XLA's
    HIGH precision would spend 3 passes because it must also split the one-hot
    operand, which for us is exact)."""
    import jax.experimental.pallas as pl

    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        sum_ref[:] = jnp.zeros_like(sum_ref)
        cnt_ref[:] = jnp.zeros_like(cnt_ref)

    base = pl.program_id(0) * _BN
    rows = jax.lax.broadcasted_iota(jnp.int32, (_BN, _BE), 0) + base
    onehot = (rows == ids_ref[:]).astype(jnp.float32)
    sum_ref[:] += jnp.dot(
        onehot, hi_ref[:], preferred_element_type=jnp.float32
    ) + jnp.dot(onehot, lo_ref[:], preferred_element_type=jnp.float32)
    cnt_ref[:] += jnp.sum(onehot, axis=1, keepdims=True)


def pallas_skip_enabled() -> bool:
    """Block-skip variant (HYDRAGNN_PALLAS_SKIP=1): collation packs graphs
    contiguously, so each edge block's receivers span a narrow node window and
    most (node-block, edge-block) grid pairs provably cannot interact. The
    variant scalar-prefetches per-edge-block receiver ranges, predicates the
    one-hot matmul away for non-overlapping pairs (pl.when), and clamps the
    skipped pairs' DMA index to block 0 so revisited blocks do not re-fetch —
    on a diagonal-ish pattern this cuts both MXU work and HBM traffic by
    ~E_blocks/overlap. Default OFF until measured on hardware (the accelerator
    tunnel was down the round this landed); correctness is interpreter-tested
    either way and benchmarks/tune_kernel.py can sweep it via the env.

    Read at TRACE time: like HYDRAGNN_PALLAS / HYDRAGNN_PALLAS_BE, this flag
    must be set before the process traces its first step — a later env toggle
    does not affect already-cached traces under jit."""
    return os.environ.get("HYDRAGNN_PALLAS_SKIP", "0") not in ("0", "false", "False")


def _block_overlap(i, j, lo_ref, hi_ref):
    """Can edge block j's receivers touch node block i? ONE definition shared
    by the skip kernel's compute predicate and the DMA index maps — if these
    ever diverged, a pair the index map clamps to block 0 could still compute,
    silently accumulating the wrong edge data."""
    base = i * _BN
    return (hi_ref[j] >= base) & (lo_ref[j] < base + _BN)


def _skip_kernel():
    """Block-skip twin of _sum_count_kernel/_sum_count_split_kernel (any
    operand count): same accumulation math, guarded by the prefetched
    receiver-range overlap test."""
    import jax.experimental.pallas as pl

    def kern(lo_ref, hi_ref, ids_ref, *args):
        ops, sum_ref, cnt_ref = args[:-2], args[-2], args[-1]
        i = pl.program_id(0)
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _():
            sum_ref[:] = jnp.zeros_like(sum_ref)
            cnt_ref[:] = jnp.zeros_like(cnt_ref)

        base = i * _BN

        @pl.when(_block_overlap(i, j, lo_ref, hi_ref))
        def _():
            rows = jax.lax.broadcasted_iota(jnp.int32, (_BN, _BE), 0) + base
            onehot = (rows == ids_ref[:]).astype(jnp.float32)
            acc = jnp.dot(onehot, ops[0][:], preferred_element_type=jnp.float32)
            for op in ops[1:]:
                acc = acc + jnp.dot(
                    onehot, op[:], preferred_element_type=jnp.float32
                )
            sum_ref[:] += acc
            cnt_ref[:] += jnp.sum(onehot, axis=1, keepdims=True)

    return kern


def _sum_count_pallas(
    data: jnp.ndarray,
    ids: jnp.ndarray,
    num_segments: int,
    interpret: bool,
    split: bool = True,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    import jax.experimental.pallas as pl

    if _BE_ERROR is not None:
        raise ValueError(_BE_ERROR)
    e, f = data.shape
    e_pad = _round_up(max(e, _BE), _BE)
    n_pad = _round_up(max(num_segments, _BN), _BN)
    ids_p = jnp.full((1, e_pad), -1, jnp.int32).at[0, :e].set(ids.astype(jnp.int32))

    data32 = data.astype(jnp.float32)
    # f-packing: at f <= 64 the hi/lo pair fits side-by-side in one 128-lane
    # tile (hi in lanes [0:f], lo lane-aligned at [64:64+f]), so the accuracy
    # split costs ZERO extra MXU work — the un-packed split path pays 2x. The
    # one-hot factor is shared, so one matmul yields both column groups and the
    # final hi+lo add happens in f32 outside the kernel.
    packed = split and 2 * f <= 128
    # hi and lo are rounded to bf16 HERE (via _round_bf16 — bit math the
    # compiler cannot fold; see its docstring for the excess-precision trap
    # that silently zeroed lo on hardware), not left for the MXU: a
    # DEFAULT-precision dot truncates f32 operands to bf16 on hardware but
    # not in interpreter mode. With every operand bf16-representable the
    # hardware dot is EXACT (one-hot x bf16 products), so interpreter and
    # TPU now compute the same split to ~accumulation order.
    if packed:
        f_pad = 128
        hi = _round_bf16(data32)
        lo = _round_bf16(data32 - hi)
        data_p = (
            jnp.zeros((e_pad, f_pad), jnp.float32)
            .at[:e, :f].set(hi)
            .at[:e, 64 : 64 + f].set(lo)
        )
        operands = (data_p,)
        kernel = _sum_count_kernel
    else:
        f_pad = _round_up(max(f, 128), 128)
        data_p = jnp.zeros((e_pad, f_pad), jnp.float32).at[:e, :f].set(data32)
        if split:
            hi = _round_bf16(data_p)
            lo = _round_bf16(data_p - hi)
            operands = (hi, lo)
            kernel = _sum_count_split_kernel
        else:
            operands = (data_p,)
            kernel = _sum_count_kernel

    grid = (n_pad // _BN, e_pad // _BE)
    edge_spec = pl.BlockSpec((_BE, f_pad), lambda i, j: (j, 0))
    out_specs = [
        pl.BlockSpec((_BN, f_pad), lambda i, j: (i, 0)),
        pl.BlockSpec((_BN, 1), lambda i, j: (i, 0)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((n_pad, f_pad), jnp.float32),
        jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
    ]
    ids_spec = pl.BlockSpec((1, _BE), lambda i, j: (0, j))
    if pallas_skip_enabled():
        from jax.experimental.pallas import tpu as pltpu

        nblk_e = e_pad // _BE
        blk = ids_p[0].reshape(nblk_e, _BE)
        valid = blk >= 0
        lo = jnp.where(valid, blk, jnp.int32(2147483647)).min(axis=1)
        hi = jnp.where(valid, blk, jnp.int32(-1)).max(axis=1)

        def _edge_idx(i, j, lo_ref, hi_ref):
            # Skipped pairs re-address block 0: an unchanged block index means
            # the pipeline skips the DMA, so skipped iterations cost no HBM.
            return (jnp.where(_block_overlap(i, j, lo_ref, hi_ref), j, 0), 0)

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (1, _BE),
                    lambda i, j, lo_ref, hi_ref: (
                        0,
                        _edge_idx(i, j, lo_ref, hi_ref)[0],
                    ),
                )
            ]
            + [pl.BlockSpec((_BE, f_pad), _edge_idx)] * len(operands),
            out_specs=[
                pl.BlockSpec((_BN, f_pad), lambda i, j, lo_ref, hi_ref: (i, 0)),
                pl.BlockSpec((_BN, 1), lambda i, j, lo_ref, hi_ref: (i, 0)),
            ],
        )
        out_sum, out_cnt = pl.pallas_call(
            _skip_kernel(),
            grid_spec=grid_spec,
            out_shape=out_shape,
            interpret=interpret,
        )(lo, hi, ids_p, *operands)
    else:
        out_sum, out_cnt = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[ids_spec] + [edge_spec] * len(operands),
            out_specs=out_specs,
            out_shape=out_shape,
            interpret=interpret,
        )(ids_p, *operands)
    total = out_sum[:num_segments, :f]
    if packed:
        total = total + out_sum[:num_segments, 64 : 64 + f]
    return total, out_cnt[:num_segments, 0]


# ----------------------------------------------------------- CSR-blocked kernel
def _csr_kernel():
    """CSR run-walk twin of the one-hot kernels (any operand count): the
    one-hot factor is built from ROW POINTERS, not id comparisons —
    ``onehot[n, e] = row_start[n] <= e_global < row_end[n]`` — so the kernel
    never loads the edge-id array at all, and contiguous receiver runs give
    an EXACT empty-block skip (the scalar-prefetched per-node-block edge
    ranges come straight from ``row_ptr``, no id scan to derive them)."""
    import jax.experimental.pallas as pl

    def kern(lo_ref, hi_ref, rs_ref, re_ref, *args):
        ops, sum_ref, cnt_ref = args[:-2], args[-2], args[-1]
        i = pl.program_id(0)
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _():
            sum_ref[:] = jnp.zeros_like(sum_ref)
            cnt_ref[:] = jnp.zeros_like(cnt_ref)

        @pl.when((j >= lo_ref[i]) & (j <= hi_ref[i]))
        def _():
            cols = jax.lax.broadcasted_iota(jnp.int32, (_BN, _BE), 1) + j * _BE
            # rs/re blocks are (BN, 1): broadcast against the (BN, BE) iota.
            onehot = ((cols >= rs_ref[:]) & (cols < re_ref[:])).astype(
                jnp.float32
            )
            acc = jnp.dot(onehot, ops[0][:], preferred_element_type=jnp.float32)
            for op in ops[1:]:
                acc = acc + jnp.dot(
                    onehot, op[:], preferred_element_type=jnp.float32
                )
            sum_ref[:] += acc
            cnt_ref[:] += jnp.sum(onehot, axis=1, keepdims=True)

    return kern


def _csr_sum_count_pallas(
    data: jnp.ndarray,
    row_ptr: jnp.ndarray,
    num_segments: int,
    interpret: bool,
    split: bool = True,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fused (sum, count) over contiguous receiver runs given by ``row_ptr``
    [num_segments + 1] (the CSR batch contract). Masked rows must arrive
    pre-zeroed with their edges owned by padding segments — exactly the
    collation contract the sorted prefix path already relies on. Same
    hi/lo bf16x2 accuracy split and f-packing as the one-hot kernel."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if _BE_ERROR is not None:
        raise ValueError(_BE_ERROR)
    e, f = data.shape
    e_pad = _round_up(max(e, _BE), _BE)
    n_pad = _round_up(max(num_segments, _BN), _BN)
    rp = row_ptr.astype(jnp.int32)
    # Rows beyond num_segments own no edges: empty runs [e, e).
    row_start = jnp.full((n_pad, 1), e, jnp.int32).at[:num_segments, 0].set(
        rp[:-1]
    )
    row_end = jnp.full((n_pad, 1), e, jnp.int32).at[:num_segments, 0].set(
        rp[1:]
    )

    data32 = data.astype(jnp.float32)
    packed = split and 2 * f <= 128
    if packed:
        f_pad = 128
        hi = _round_bf16(data32)
        lo = _round_bf16(data32 - hi)
        data_p = (
            jnp.zeros((e_pad, f_pad), jnp.float32)
            .at[:e, :f].set(hi)
            .at[:e, 64 : 64 + f].set(lo)
        )
        operands = (data_p,)
    else:
        f_pad = _round_up(max(f, 128), 128)
        data_p = jnp.zeros((e_pad, f_pad), jnp.float32).at[:e, :f].set(data32)
        if split:
            hi = _round_bf16(data_p)
            lo = _round_bf16(data_p - hi)
            operands = (hi, lo)
        else:
            operands = (data_p,)

    # Per-node-block edge-block ranges, straight from the pointers: block i's
    # edges live in [row_ptr[i*BN], row_ptr[min((i+1)*BN, N)]) — contiguous
    # by the CSR contract. hi_blk = -1 marks an empty block (predicate and
    # DMA clamp both fail j <= hi).
    n_blocks = n_pad // _BN
    lo_edge = row_start.reshape(n_blocks, _BN).min(axis=1)
    hi_edge = row_end.reshape(n_blocks, _BN).max(axis=1)  # exclusive
    nonempty = hi_edge > lo_edge
    lo_blk = jnp.where(nonempty, lo_edge // _BE, 0).astype(jnp.int32)
    hi_blk = jnp.where(
        nonempty, (jnp.maximum(hi_edge, 1) - 1) // _BE, -1
    ).astype(jnp.int32)

    def _edge_idx(i, j, lo_ref, hi_ref):
        # Skipped pairs re-address block 0: an unchanged block index means
        # the pipeline skips the DMA (same trick as the skip kernel).
        return (jnp.where((j >= lo_ref[i]) & (j <= hi_ref[i]), j, 0), 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_blocks, e_pad // _BE),
        in_specs=[
            pl.BlockSpec((_BN, 1), lambda i, j, lo_ref, hi_ref: (i, 0)),
            pl.BlockSpec((_BN, 1), lambda i, j, lo_ref, hi_ref: (i, 0)),
        ]
        + [pl.BlockSpec((_BE, f_pad), _edge_idx)] * len(operands),
        out_specs=[
            pl.BlockSpec((_BN, f_pad), lambda i, j, lo_ref, hi_ref: (i, 0)),
            pl.BlockSpec((_BN, 1), lambda i, j, lo_ref, hi_ref: (i, 0)),
        ],
    )
    out_shape = [
        jax.ShapeDtypeStruct((n_pad, f_pad), jnp.float32),
        jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
    ]
    out_sum, out_cnt = pl.pallas_call(
        _csr_kernel(),
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(lo_blk, hi_blk, row_start, row_end, *operands)
    total = out_sum[:num_segments, :f]
    if packed:
        total = total + out_sum[:num_segments, 64 : 64 + f]
    return total, out_cnt[:num_segments, 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _csr_sum_count_vjp(data, row_ptr, ids, num_segments, interpret, split, dtype_name):
    return _csr_sum_count_pallas(data, row_ptr, num_segments, interpret, split)


def _csr_sum_count_fwd(data, row_ptr, ids, num_segments, interpret, split, dtype_name):
    out = _csr_sum_count_pallas(data, row_ptr, num_segments, interpret, split)
    return out, (row_ptr, ids)


def _csr_sum_count_bwd(num_segments, interpret, split, dtype_name, res, cots):
    row_ptr, ids = res
    d_sum, d_cnt = cots
    del d_cnt  # count has no data dependence
    # CSR contract: data arrives pre-zeroed at masked rows, ids RAW (masked
    # rows target padding segments) — masking composes through the caller's
    # jnp.where, so the backward is a plain gather like the sorted path's.
    idx = jnp.clip(ids.astype(jnp.int32), 0, num_segments - 1)
    d_data = jnp.take(d_sum, idx, axis=0)
    return (
        d_data.astype(dtype_name),
        jnp.zeros(row_ptr.shape, jax.dtypes.float0),
        jnp.zeros(ids.shape, jax.dtypes.float0),
    )


_csr_sum_count_vjp.defvjp(_csr_sum_count_fwd, _csr_sum_count_bwd)


def csr_segment_sum_count(
    data, row_ptr, ids, num_segments: int, interpret: bool = False,
    split: bool = True,
):
    """Fused (sum, count) per segment over precomputed CSR boundaries — the
    run-walk kernel behind every conv family's CSR-path aggregation
    (sum/mean for SAGE/GIN/CGCNN, sum+count for MFC, both passes of the PNA
    stats bundle). ``ids`` is only consumed by the gather backward; the
    forward walks ``row_ptr`` alone."""
    return _csr_sum_count_vjp(
        data, row_ptr, ids, num_segments, interpret, split, str(data.dtype)
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _sum_count_vjp(data, ids, num_segments, interpret, split, dtype_name):
    return _sum_count_pallas(data, ids, num_segments, interpret, split)


def _sum_count_fwd(data, ids, num_segments, interpret, split, dtype_name):
    out = _sum_count_pallas(data, ids, num_segments, interpret, split)
    return out, ids


def _sum_count_bwd(num_segments, interpret, split, dtype_name, ids, cots):
    d_sum, d_cnt = cots
    del d_cnt  # count has no data dependence
    valid = (ids >= 0)[:, None]
    idx = jnp.clip(ids, 0, num_segments - 1)
    d_data = jnp.where(valid, d_sum[idx], 0.0)
    return d_data.astype(dtype_name), jnp.zeros(ids.shape, jax.dtypes.float0)


_sum_count_vjp.defvjp(_sum_count_fwd, _sum_count_bwd)


def segment_sum_count(
    data, ids, num_segments: int, interpret: bool = False, split: bool = True
):
    """Fused (sum, count) per segment via one-hot MXU matmuls.

    ``ids`` < 0 marks masked/padding rows (excluded from both outputs).
    ``data``: [E, F] float; ``ids``: [E] int. Returns ``(sum [N,F], count [N])``.
    ``split=True`` uses the bf16 hi/lo trick for ~f32 accuracy — free when
    f <= 64 (hi/lo pack side-by-side into one 128-lane tile and share the
    one-hot matmul), two matmuls otherwise; ``split=False`` is single-pass
    bf16 — use it ONLY for data that is already bf16-representable: on
    hardware the MXU truncates f32 operands to bf16 regardless of
    cancellation structure (~2^-9 relative error; skipping the split on the
    "no cancellation" argument for sums of squares is exactly what failed
    the r05 on-chip certification at 16x the gate).
    Differentiable w.r.t. ``data`` (gather backward).

    The primal dtype rides as a STATIC argument — a zero-size carrier array in
    the residuals (the previous design) picks up an inconsistent sharding
    under ``shard_map`` and breaks the graph-parallel backward.
    """
    return _sum_count_vjp(
        data, ids, num_segments, interpret, split, str(data.dtype)
    )


def _stats_forward(
    data, ids, num_segments, eps, axis_name, interpret, want_std,
    sorted_route=False, row_ptr=None,
):
    if sorted_route:
        # Scatter-free path: data arrives pre-zeroed at masked rows and ids
        # RAW (sorted; masked rows target padding segments). The centered
        # second pass needs no mask handling — masked rows have data 0 and
        # a ~0 padding-segment mean, and padding outputs are never consumed.
        # With CSR boundaries (row_ptr) the segment bounds are precomputed
        # at collation — zero searchsorted calls in the traced step.
        total, count = srt.segment_sum_count_auto(
            data, ids, num_segments, row_ptr=row_ptr
        )
        if axis_name is not None:
            total = jax.lax.psum(total, axis_name)
            count = jax.lax.psum(count, axis_name)
        safe = jnp.maximum(count, 1.0)[:, None]
        mean = total / safe
        if not want_std:
            return total, mean, jnp.zeros_like(mean), count
        idx = jnp.clip(ids, 0, num_segments - 1)
        # sumsq via a CENTERED XLA scatter, not the prefix path: squares are
        # tiny exactly where 1/std^2 amplifies error (near-degenerate
        # segments), and prefix-difference noise (~1e-5 abs) there costs
        # ~5e-3 in the std GRADIENT — 8x worse than even XLA's uncentered
        # formula at some shapes. The centered scatter has no cancellation
        # (~1e-6 fwd, ~1e-5 grad, same as the Pallas arm). Masked rows are
        # exactly zero here (data pre-zeroed, padding-segment mean is 0), so
        # no mask argument is needed. Net: 4 of 5 scatters still eliminated;
        # only PNA's std pass keeps one.
        sumsq = jax.ops.segment_sum(
            jnp.square(data - mean[idx]), ids, num_segments=num_segments
        )
        if axis_name is not None:
            sumsq = jax.lax.psum(sumsq, axis_name)
        # Single-element segments have sumsq == 0 identically; pin them to
        # sqrt(eps) (the bwd already treats their dstd as 0).
        std = jnp.where(
            count[:, None] > 1.0,
            jnp.sqrt(sumsq / safe + eps),
            jnp.full_like(mean, jnp.sqrt(eps)),
        )
        return total, mean, std, count
    return _stats_forward_pallas(
        data, ids, num_segments, eps, axis_name, interpret, want_std,
        row_ptr=row_ptr,
    )


def _stats_forward_pallas(data, ids, num_segments, eps, axis_name, interpret,
                          want_std, row_ptr=None):
    def _sum_count(d, i):
        # CSR route (row_ptr present under the HYDRAGNN_PALLAS opt-in): the
        # run-walk kernel — raw sorted ids, data pre-zeroed at masked rows
        # (the caller enforced the CSR contract before dispatching here).
        if row_ptr is not None:
            return csr_segment_sum_count(
                d, row_ptr, i, num_segments, interpret,
                _wants_split(data.dtype),
            )
        return segment_sum_count(
            d, i, num_segments, interpret, _wants_split(data.dtype)
        )

    total, count = _sum_count(data, ids)
    if axis_name is not None:
        total = jax.lax.psum(total, axis_name)
        count = jax.lax.psum(count, axis_name)
    safe = jnp.maximum(count, 1.0)[:, None]
    mean = total / safe
    if not want_std:
        return total, mean, jnp.zeros_like(mean), count
    # Centered second pass. This MUST take the hi/lo accuracy split: on the
    # real MXU a DEFAULT-precision f32 dot truncates its operands to bf16
    # (jax/_src/pallas/mosaic/lowering.py precision handling), capping each
    # square at ~2^-9 relative error — ~8e-3 absolute on the std at certify
    # magnitudes, 15x over the 5e-4 gate. This single-pass shortcut (the
    # "squares don't cancel" argument missed operand truncation) is what
    # failed the r05 on-hardware certification at every block size while the
    # interpreter (true-f32 dots) passed. With the split the simulated-MXU
    # std error is ~1.4e-5; at f <= 64 the packed layout makes it free.
    idx = jnp.clip(ids, 0, num_segments - 1)
    centered = jnp.where((ids >= 0)[:, None], data - mean[idx], 0.0)
    if row_ptr is not None:
        sumsq, _ = csr_segment_sum_count(
            jnp.square(centered), row_ptr, ids, num_segments, interpret, True
        )
    else:
        sumsq, _ = segment_sum_count(
            jnp.square(centered), ids, num_segments, interpret, True
        )
    if axis_name is not None:
        sumsq = jax.lax.psum(sumsq, axis_name)
    std = jnp.sqrt(sumsq / safe + eps)
    return total, mean, std, count


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7))
def _stats(data, ids, num_segments, eps, axis_name, interpret, want_std,
           sorted_route=False, row_ptr=None):
    return _stats_forward(
        data, ids, num_segments, eps, axis_name, interpret, want_std,
        sorted_route, row_ptr,
    )


def _stats_fwd(data, ids, num_segments, eps, axis_name, interpret, want_std,
               sorted_route=False, row_ptr=None):
    out = _stats_forward(
        data, ids, num_segments, eps, axis_name, interpret, want_std,
        sorted_route, row_ptr,
    )
    total, mean, std, count = out
    return out, (data, ids, mean, std, count, row_ptr)


def _stats_bwd(num_segments, eps, axis_name, interpret, want_std, sorted_route,
               res, cots):
    """Analytic scatter-free backward. With s=Σx, μ=s/n, σ=sqrt(Σ(x-μ)²/n+eps):
    since Σ_e (x_e - μ) = 0 exactly, the μ-coupling inside σ vanishes and

        dx_e = ds̄[i] + dμ̄[i]/n[i] + dσ̄[i]·(x_e − μ[i])/(σ[i]·n[i]),  i=id(e)

    — pure gathers, no scatter (scatter is the slow op on TPU). Under graph
    parallelism the incoming cotangents are per-device shares of the global
    outputs, so they are psum'd first (VJP of the forward psum)."""
    data, ids, mean, std, count, row_ptr = res
    d_total, d_mean, d_std, d_count = cots
    del d_count  # no data dependence
    if axis_name is not None:
        d_total = jax.lax.psum(d_total, axis_name)
        d_mean = jax.lax.psum(d_mean, axis_name)
        d_std = jax.lax.psum(d_std, axis_name)
    safe = jnp.maximum(count, 1.0)[:, None]
    per_seg_lin = d_total + d_mean / safe  # [N, F]
    valid = (ids >= 0)[:, None]
    idx = jnp.clip(ids, 0, num_segments - 1)
    d_data = per_seg_lin[idx]
    if want_std:
        # Single-element segments have x ≡ μ, so dσ/dx is identically 0; guard
        # the 1/σ=1/sqrt(eps) amplification against residual rounding in x−μ.
        per_seg_quad = jnp.where(count[:, None] > 1.0, d_std / (std * safe), 0.0)
        d_data = d_data + per_seg_quad[idx] * (data - mean[idx])
    d_data = jnp.where(valid, d_data, 0.0)
    d_row_ptr = (
        None if row_ptr is None
        else jnp.zeros(row_ptr.shape, jax.dtypes.float0)
    )
    return (
        d_data.astype(data.dtype),
        jnp.zeros(ids.shape, jax.dtypes.float0),
        d_row_ptr,
    )


_stats.defvjp(_stats_fwd, _stats_bwd)


def fused_segment_stats(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: Optional[jnp.ndarray] = None,
    eps: float = 1e-5,
    axis_name: Optional[str] = None,
    interpret: Optional[bool] = None,
    want_std: bool = True,
    sorted_ids: bool = False,
    row_ptr: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(sum, mean, std, count) per segment from two fused passes — the PNA
    sum/mean/std aggregator family (drop-in for segment_sum + segment_mean +
    segment_std + segment_count), with an analytic scatter-free backward.
    ``want_std=False`` skips the centered second pass (std comes back as
    zeros) when only the sum/mean family is needed.

    ``row_ptr`` (the CSR batch contract, graphs/csr.py) supplies precomputed
    segment boundaries: the sorted prefix path then runs zero searchsorted
    calls, and under HYDRAGNN_PALLAS the CSR run-walk kernel replaces the
    one-hot scatter matmul for both fused passes.

    Under edge-sharded graph parallelism (``axis_name``) the raw partial sums
    are psum'd across the shard axis before the mean/std are formed — the same
    cross-device composition as the scatter path, but two collectives total.
    Per-shard edge slices keep the sorted order but NOT the global ``row_ptr``
    offsets, so the boundaries are re-derived locally in that mode.
    """
    ids = segment_ids.astype(jnp.int32)
    if interpret is None:
        interpret = _platform() != "tpu"
    use_sorted, use_csr_kernel, row_ptr = _sorted_route(
        sorted_ids, row_ptr, axis_name, num_local_edges=segment_ids.shape[0]
    )
    if use_sorted or use_csr_kernel:
        # Sorted/CSR contract: zero masked rows, keep RAW (sorted) ids — a -1
        # marker would break the non-decreasing order the path requires.
        srt.attach_layout_check(ids)
        if mask is not None:
            data = jnp.where(mask[:, None], data, 0)
        return _stats(
            data.astype(jnp.float32), ids, num_segments, eps, axis_name,
            interpret, want_std, use_sorted, row_ptr,
        )
    if mask is not None:
        ids = jnp.where(mask, ids, -1)
    return _stats(
        data, ids, num_segments, eps, axis_name, interpret, want_std, False,
        None,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def segment_extrema(data, ids, num_segments: int, axis_name: Optional[str] = None):
    """(min, max) per segment with a gather-based backward: the cotangent flows
    to every row equal to its segment's extremum (the standard subgradient),
    avoiding XLA's scatter-heavy segment_min/max VJP on TPU. ``ids`` < 0 marks
    masked rows; empty segments yield 0."""
    mask = ids >= 0
    safe_ids = jnp.where(mask, ids, 0)
    mn = seg.segment_min(data, safe_ids, num_segments, mask=mask, axis_name=axis_name)
    mx = seg.segment_max(data, safe_ids, num_segments, mask=mask, axis_name=axis_name)
    return mn, mx


def _extrema_fwd(data, ids, num_segments, axis_name):
    mn, mx = segment_extrema(data, ids, num_segments, axis_name)
    return (mn, mx), (data, ids, mn, mx)


def _extrema_bwd(num_segments, axis_name, res, cots):
    data, ids, mn, mx = res
    d_mn, d_mx = cots
    if axis_name is not None:
        d_mn = jax.lax.psum(d_mn, axis_name)
        d_mx = jax.lax.psum(d_mx, axis_name)
    valid = (ids >= 0)[:, None]
    idx = jnp.clip(ids, 0, num_segments - 1)
    d_data = jnp.where(valid & (data == mn[idx]), d_mn[idx], 0.0) + jnp.where(
        valid & (data == mx[idx]), d_mx[idx], 0.0
    )
    return d_data.astype(data.dtype), jnp.zeros(ids.shape, jax.dtypes.float0)


segment_extrema.defvjp(_extrema_fwd, _extrema_bwd)


def certify_pallas(
    e: int = 16384,
    f: int = 64,
    n: int = 4096,
    reps: int = 20,
    seed: int = 0,
    contiguous: bool = False,
    sorted_arm: bool = True,
    csr_arm: bool = True,
) -> dict:
    """On-device certification of the fused kernel against the XLA segment
    ops: forward + gradient parity on the PNA aggregation workload (reference
    shape: /root/reference/hydragnn/models/PNAStack.py:28-53) and measured
    speedup of the compiled sum/mean/std bundle. Run by bench.py on every
    benchmark invocation and by tests/test_pallas_tpu.py on TPU.

    Errors are measured against an f64 numpy ground truth (comparing fused to
    XLA directly would mis-attribute XLA's own E[x²]−E[x]² cancellation error
    in the std gradient to the kernel). Returns {backend, max_err_fwd,
    max_err_grad, xla_err_fwd, xla_err_grad, speedup, pallas_ms, xla_ms}.
    Uses whatever platform pallas gating currently resolves to (pin with
    ``pallas_platform`` / HYDRAGNN_PALLAS as needed).

    ``contiguous=True`` SORTS the segment ids — the production pattern
    (collation packs graphs contiguously, so receivers ascend across the edge
    array). This is the shape on which the block-skip variant
    (HYDRAGNN_PALLAS_SKIP) can skip work; with uniformly random ids every
    edge block spans all nodes and nothing is skippable, so a skip-vs-base
    comparison on random ids is structurally meaningless.
    """
    import time

    import numpy as np

    def _problem(e_, f_, n_, seed_):
        key = jax.random.PRNGKey(seed_)
        k1, k2, k3 = jax.random.split(key, 3)
        data = jax.random.normal(k1, (e_, f_), jnp.float32) * 2.0 + 1.0
        ids = jax.random.randint(k2, (e_,), 0, n_)
        if contiguous:
            ids = jnp.sort(ids)
        mask = jax.random.uniform(k3, (e_,)) > 0.1
        return data, ids, mask

    def _bundles(ids, mask, n_):
        def fused_bundle(d):
            return fused_segment_stats(d, ids, n_, mask=mask)

        def xla_bundle(d):
            safe = jnp.where(mask, ids, 0)
            return (
                seg.segment_sum(d, safe, n_, mask=mask),
                seg.segment_mean(d, safe, n_, mask=mask),
                seg.segment_std(d, safe, n_, mask=mask),
                seg.segment_count(safe, n_, mask=mask),
            )

        def scalarize(bundle):
            def fn(d):
                total, mean, std, count = bundle(d)
                # All three differentiable outputs contribute to the cotangent.
                return jnp.sum(total * 0.3 + mean * 1.7 - std * 0.9)

            return fn

        return fused_bundle, xla_bundle, scalarize

    def _accuracy(data, ids, mask, n_):
        """(fused fwd/grad err, xla fwd/grad err) vs an f64 host ground truth."""
        e_, f_ = data.shape
        fused_bundle, xla_bundle, scalarize = _bundles(ids, mask, n_)
        f_fused = jax.jit(fused_bundle)
        f_xla = jax.jit(xla_bundle)
        g_fused = jax.jit(jax.grad(scalarize(fused_bundle)))
        g_xla = jax.jit(jax.grad(scalarize(xla_bundle)))

        d64 = np.asarray(data, np.float64)
        ids_h = np.asarray(ids)
        mask_h = np.asarray(mask)
        total64 = np.zeros((n_, f_))
        count64 = np.zeros(n_)
        np.add.at(total64, ids_h[mask_h], d64[mask_h])
        np.add.at(count64, ids_h[mask_h], 1.0)
        safe64 = np.maximum(count64, 1.0)[:, None]
        mean64 = total64 / safe64
        centered = np.where(mask_h[:, None], d64 - mean64[ids_h], 0.0)
        sumsq64 = np.zeros((n_, f_))
        np.add.at(sumsq64, ids_h[mask_h], np.square(centered)[mask_h])
        std64 = np.sqrt(sumsq64 / safe64 + 1e-5)
        # grad of S = Σ 0.3·total + 1.7·mean − 0.9·std w.r.t. data:
        per_seg = 0.3 + 1.7 / safe64
        grad64 = np.where(
            mask_h[:, None], np.broadcast_to(per_seg[ids_h], (e_, f_)), 0.0
        )
        quad = np.where(count64[:, None] > 1.0, -0.9 / (std64 * safe64), 0.0)
        grad64 += np.where(mask_h[:, None], quad[ids_h] * centered, 0.0)
        truth = (total64, mean64, std64, count64)

        def errs(outs, grad):
            # Per-output decomposition (kept in the artifact): the r05
            # hardware failure was only diagnosable once the max was split
            # into components (raw-sum error implicated the matmul itself).
            comp = {
                name: float(np.max(np.abs(np.asarray(o, np.float64) - t)))
                for name, o, t in zip(
                    ("total", "mean", "std", "count"), outs, truth
                )
            }
            grad_err = float(
                np.max(np.abs(np.asarray(grad, np.float64) - grad64))
            )
            return max(comp.values()), grad_err, comp

        fused_errs = errs(
            jax.block_until_ready(f_fused(data)), jax.block_until_ready(g_fused(data))
        )
        xla_errs = errs(
            jax.block_until_ready(f_xla(data)), jax.block_until_ready(g_xla(data))
        )
        return fused_errs, xla_errs

    # Certification must measure the KERNEL even now that the production
    # default is the XLA path (fused_* gates on pallas_enabled, which would
    # otherwise compare XLA to itself). Force-enable for the duration.
    _saved_env = os.environ.get("HYDRAGNN_PALLAS")
    os.environ["HYDRAGNN_PALLAS"] = "1"
    try:
        data, ids, mask = _problem(e, f, n, seed)
        (
            (max_err_fwd, max_err_grad, err_components),
            (xla_err_fwd, xla_err_grad, xla_components),
        ) = _accuracy(data, ids, mask, n)
        # The split=True kernel forks on the packing boundary (2f <= 128 packs
        # hi/lo into one tile; wider shapes run the two-matmul kernel). Certify
        # BOTH sides: the flagship f (packed when <= 64) above, and a wide shape
        # exercising _sum_count_split_kernel here — production takes that path
        # whenever hidden_dim > 64.
        f_wide = max(2 * f, 96)
        wide = _problem(e // 4, f_wide, max(n // 4, _BN), seed + 1)
        (wide_err_fwd, wide_err_grad, _), _ = _accuracy(*wide, max(n // 4, _BN))

        fused_bundle, xla_bundle, _ = _bundles(ids, mask, n)
        f_fused = jax.jit(fused_bundle)
        f_xla = jax.jit(xla_bundle)

        def best_ms(fn):
            times = []
            for _ in range(reps):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(data))
                times.append(time.perf_counter() - t0)
            return 1000.0 * min(times)

        pallas_ms = best_ms(f_fused)
        xla_ms = best_ms(f_xla)

        # Further arms on contiguous ids: the scatter-free sorted path
        # (ops/segment_sorted.py) and the CSR run-walk kernel
        # (csr_segment_sum_count — the row_ptr batch contract). Measured
        # UNMASKED — certify's random mask violates the sorted contract
        # (masked rows must target padding segments), so their accuracy is
        # checked against their own f64 truth. Forward AND gradient, like
        # the other arms.
        # Shared tolerance gate (precision/tolerance.py): the same fwd/grad
        # bounds every consumer of "within tolerance" uses.
        from ..precision.tolerance import KERNEL_CERT_GATE as _gate

        sorted_res = None
        if contiguous and (sorted_arm or csr_arm):
            d64 = np.asarray(data, np.float64)
            ids_h = np.asarray(ids)
            tot64 = np.zeros((n, f))
            np.add.at(tot64, ids_h, d64)
            cnt64 = np.bincount(ids_h, minlength=n).astype(np.float64)
            safe64 = np.maximum(cnt64, 1.0)[:, None]
            mean64 = tot64 / safe64
            sq64 = np.zeros((n, f))
            np.add.at(sq64, ids_h, np.square(d64 - mean64[ids_h]))
            std64 = np.sqrt(sq64 / safe64 + 1e-5)
            truths = (tot64, mean64, std64, cnt64)
            # Same cotangent as the other arms' scalarize; dstd at
            # single-count segments is identically 0 (std pinned there).
            per_lin = 0.3 + 1.7 / safe64
            quad = np.where(
                cnt64[:, None] > 1.0, -0.9 / (std64 * safe64), 0.0
            )
            g64 = per_lin[ids_h] + quad[ids_h] * (d64 - mean64[ids_h])
            row_ptr = jnp.asarray(
                np.searchsorted(ids_h, np.arange(n + 1)).astype(np.int32)
            )

            def _measure_arm(tag, env, row_ptr_arg):
                saved = {k: os.environ.get(k) for k in env}
                os.environ.update(env)
                try:
                    def bundle(d):
                        return fused_segment_stats(
                            d, ids, n, sorted_ids=True, row_ptr=row_ptr_arg
                        )

                    f_arm = jax.jit(bundle)

                    def _scalar(d):
                        total, mean, std, _ = bundle(d)
                        return jnp.sum(total * 0.3 + mean * 1.7 - std * 0.9)

                    g_arm = jax.jit(jax.grad(_scalar))
                    outs = jax.block_until_ready(f_arm(data))
                    grad = jax.block_until_ready(g_arm(data))
                    err = max(
                        float(np.max(np.abs(np.asarray(o, np.float64) - t)))
                        for o, t in zip(outs, truths)
                    )
                    err_grad = float(
                        np.max(np.abs(np.asarray(grad, np.float64) - g64))
                    )
                    arm_ms = best_ms(f_arm)
                    return err, err_grad, arm_ms
                finally:
                    for k, v in saved.items():
                        if v is None:
                            os.environ.pop(k, None)
                        else:
                            os.environ[k] = v

            sorted_res = {}
            if sorted_arm:
                err, err_grad, sorted_ms = _measure_arm(
                    "sorted", {"HYDRAGNN_SEGMENT_SORTED": "1"}, None
                )
                # Gradient gate: no regression vs the INCUMBENT default (the
                # XLA bundle) rather than the kernel-grade 5e-4 — the sorted
                # std grad inherits ~1/std^2 amplification at near-degenerate
                # segments from its ~1e-5 sumsq noise (measured ~5e-3), while
                # the XLA path production trains on today carries ~9e-2 from
                # its E[x^2]-E[x]^2 cancellation. Promotion must not lose
                # accuracy; it need not beat the Pallas kernel's.
                sorted_res.update(
                    sorted_ms=round(sorted_ms, 4),
                    sorted_err_fwd=err,
                    sorted_err_grad=err_grad,
                    sorted_ok=err < _gate.fwd
                    and err_grad <= max(_gate.fwd, xla_err_grad),
                    sorted_speedup_vs_xla=round(
                        sorted_ms and xla_ms / sorted_ms, 3
                    ),
                )
            if csr_arm:
                # CSR kernel arm: HYDRAGNN_PALLAS is already forced on for
                # the whole certification; pin the sorted prefix path OFF so
                # the row_ptr route resolves to the run-walk kernel, not the
                # prefix-sum arm (the TPU default).
                err, err_grad, csr_ms = _measure_arm(
                    "csr",
                    {
                        "HYDRAGNN_SEGMENT_SORTED": "0",
                        "HYDRAGNN_PALLAS_CSR": "1",
                    },
                    row_ptr,
                )
                # Same gates as the one-hot kernel (KERNEL_CERT_GATE): the
                # CSR kernel shares its bf16x2 split and analytic backward,
                # so kernel-grade 5e-4 fwd / 5e-3 grad apply unchanged.
                sorted_res.update(
                    csr_ms=round(csr_ms, 4),
                    csr_err_fwd=err,
                    csr_err_grad=err_grad,
                    csr_ok=_gate.check(err, err_grad)["ok"],
                    csr_speedup_vs_xla=round(csr_ms and xla_ms / csr_ms, 3),
                )
    finally:
        if _saved_env is None:
            os.environ.pop("HYDRAGNN_PALLAS", None)
        else:
            os.environ["HYDRAGNN_PALLAS"] = _saved_env
    # Single source of truth for the certification tolerances is now the
    # SHARED gate in precision/tolerance.py (KERNEL_CERT_GATE) — one
    # implementation for kernel certification and the quantized serving arm,
    # so the two can never drift on what "within tolerance" means. Forward:
    # strict 5e-4. Gradient: 5e-3 — the ANALYTIC worst case of an
    # accurate-mean kernel, not slack. The sigma cotangent at a count-n
    # segment contributes d_std/(std*n)*(x-mu); at near-degenerate pairs
    # (std -> sqrt(eps) = 3.16e-3, the floor the forward pins) the factor
    # |quad| reaches 0.9/(2*sqrt(eps)) ~ 142, which amplifies the bf16x2
    # mean's ~1e-5 rounding to ~4e-3 in isolated elements regardless of
    # kernel quality (measured on v5e: 1.3e-3, located exactly at count-2
    # std~3.5e-3 segments; the XLA incumbent carries 0.11 at the same
    # elements). Anything above 5e-3 therefore indicates a real defect,
    # while a uniform 5e-4 would reject every f32-mean-based formula.
    from ..precision.tolerance import KERNEL_CERT_GATE

    verdict = KERNEL_CERT_GATE.check(
        max(max_err_fwd, wide_err_fwd), max(max_err_grad, wide_err_grad)
    )
    return {
        "backend": _platform(),
        "pallas_enabled": pallas_enabled(),
        "pallas_skip": pallas_skip_enabled(),
        "contiguous_ids": contiguous,
        "ok": verdict["ok"],
        "tol": KERNEL_CERT_GATE.fwd,
        "tol_grad": KERNEL_CERT_GATE.grad,
        "max_err_fwd": max_err_fwd,
        "max_err_grad": max_err_grad,
        "err_components": err_components,
        "xla_err_components": xla_components,
        "wide_f": f_wide,
        "wide_err_fwd": wide_err_fwd,
        "wide_err_grad": wide_err_grad,
        "xla_err_fwd": xla_err_fwd,
        "xla_err_grad": xla_err_grad,
        "pallas_ms": round(pallas_ms, 4),
        "xla_ms": round(xla_ms, 4),
        "speedup": round(xla_ms / pallas_ms, 3),
        **(sorted_res or {}),
    }


def _flatten_trailing(data):
    """[E, ...] → ([E, F], unflatten) for the 2-D kernel."""
    if data.ndim == 2:
        return data, lambda x: x
    shape = data.shape
    if data.ndim == 1:
        return data[:, None], lambda x: x[:, 0]
    return data.reshape(shape[0], -1), lambda x: x.reshape(
        (x.shape[0],) + shape[1:]
    )


def localize_row_ptr(row_ptr, axis_name, num_local_edges: int):
    """Global CSR boundaries → THIS edge shard's local boundaries (graftmesh
    halo/edge-cut contract, docs/DISTRIBUTED.md).

    Edge-sharded graph parallelism slices the destination-sorted edge list
    into equal contiguous shards (shard_map's even split over the edge axis),
    so shard ``s`` owns global rows ``[s*E_loc, (s+1)*E_loc)`` and a node's
    local run is the global run clamped into that window::

        local_row_ptr[n] = clip(global_row_ptr[n] - s*E_loc, 0, E_loc)

    Nodes whose edges live entirely on another shard get an empty local run
    (left == right), nodes cut by the shard boundary get exactly their local
    rows — the subsequent psum over ``axis_name`` is the halo exchange that
    sums each node's per-shard partial aggregates. Must be called INSIDE the
    sharded computation (``lax.axis_index`` needs the bound axis)."""
    start = jax.lax.axis_index(axis_name).astype(jnp.int32) * jnp.int32(
        num_local_edges
    )
    return jnp.clip(
        row_ptr.astype(jnp.int32) - start, 0, jnp.int32(num_local_edges)
    )


def _sorted_route(sorted_ids: bool, row_ptr, axis_name, num_local_edges=None):
    """ONE resolution of the sorted/CSR dispatch every fused wrapper uses.

    Returns ``(use_sorted, use_csr_kernel, row_ptr)``: the sorted prefix
    path when enabled (precedence unchanged from r05), else the CSR
    run-walk kernel when the caller supplied boundaries under the
    HYDRAGNN_PALLAS opt-in. Under an ``axis_name`` the global ``row_ptr``
    offsets are wrong for a local edge shard: since graftmesh they are
    LOCALIZED per shard (:func:`localize_row_ptr` — the caller passes its
    local edge count) so graph-partitioned steps stay zero-searchsorted;
    a caller that cannot name its local edge count falls back to the local
    re-derivation (row_ptr nulled). Centralized so a routing change cannot
    silently diverge between wrappers (a missed site would send that
    wrapper's traffic back to the scatter path — the 0.47x regression class
    the contract checker guards against)."""
    if axis_name is not None and row_ptr is not None:
        if num_local_edges is None:
            row_ptr = None
        else:
            row_ptr = localize_row_ptr(row_ptr, axis_name, num_local_edges)
    use_sorted = sorted_ids and srt.sorted_enabled()
    use_csr_kernel = (
        not use_sorted
        and sorted_ids
        and row_ptr is not None
        and csr_kernel_enabled()
    )
    return use_sorted, use_csr_kernel, row_ptr


def fused_segment_sum(
    data, segment_ids, num_segments: int, mask=None, axis_name=None,
    sorted_ids: bool = False, row_ptr=None,
):
    """Drop-in masked ``segment_sum`` used by every conv family's aggregation:
    the scatter-free sorted path when the caller guarantees non-decreasing
    ids AND HYDRAGNN_SEGMENT_SORTED=1 (with ``row_ptr`` — the CSR batch
    contract — consuming precomputed boundaries instead of searching), the
    CSR run-walk or one-hot MXU kernel when opted in (HYDRAGNN_PALLAS=1 —
    see pallas_enabled for why the default is the XLA path since r05), the
    masked XLA segment op otherwise. Accepts any [E, ...] float data
    (trailing dims flattened for the kernel)."""
    total, _ = fused_segment_sum_count(
        data, segment_ids, num_segments, mask=mask, axis_name=axis_name,
        sorted_ids=sorted_ids, row_ptr=row_ptr,
    )
    return total


def fused_segment_sum_count(
    data, segment_ids, num_segments: int, mask=None, axis_name=None,
    sorted_ids: bool = False, row_ptr=None,
):
    """Masked (segment_sum, segment_count) in ONE fused pass — callers that
    need both (MFC's degree lookup) save a whole scatter. Falls back to the
    two XLA ops off-TPU.

    ``sorted_ids=True`` declares the collation contract: non-decreasing ids
    with masked rows targeting padding segments (whose outputs are unused) —
    the sorted path's count includes masked rows, which is only correct
    under that contract. ``row_ptr`` carries the contract's precomputed CSR
    boundaries (LOCALIZED per shard under ``axis_name`` — graftmesh's
    halo/edge-cut contract, see :func:`localize_row_ptr`)."""
    use_sorted, use_csr_kernel, row_ptr = _sorted_route(
        sorted_ids, row_ptr, axis_name, num_local_edges=segment_ids.shape[0]
    )
    if use_sorted or use_csr_kernel:
        # Sorted/CSR contract prep: zero masked rows, RAW (sorted) ids.
        srt.attach_layout_check(segment_ids)
        flat, unflatten = _flatten_trailing(data)
        if mask is not None:
            flat = jnp.where(mask[:, None], flat, 0)
        if use_sorted:
            total, count = srt.segment_sum_count_auto(
                flat.astype(jnp.float32), segment_ids.astype(jnp.int32),
                num_segments, row_ptr=row_ptr,
            )
            if axis_name is not None:
                total = jax.lax.psum(total, axis_name)
                count = jax.lax.psum(count, axis_name)
        else:
            # CSR run-walk kernel (HYDRAGNN_PALLAS opt-in, row_ptr present).
            total, count = csr_segment_sum_count(
                flat.astype(jnp.float32), row_ptr,
                segment_ids.astype(jnp.int32), num_segments,
                _platform() != "tpu", _wants_split(flat.dtype),
            )
        return unflatten(total.astype(data.dtype)), count
    if not pallas_enabled():
        return (
            seg.segment_sum(
                data, segment_ids, num_segments, mask=mask, axis_name=axis_name
            ),
            seg.segment_count(
                segment_ids, num_segments, mask=mask, axis_name=axis_name
            ),
        )
    flat, unflatten = _flatten_trailing(data)
    ids = segment_ids.astype(jnp.int32)
    if mask is not None:
        ids = jnp.where(mask, ids, -1)
    total, count = segment_sum_count(
        flat, ids, num_segments, _platform() != "tpu", _wants_split(flat.dtype)
    )
    if axis_name is not None:
        total = jax.lax.psum(total, axis_name)
        count = jax.lax.psum(count, axis_name)
    return unflatten(total.astype(data.dtype)), count


def fused_segment_mean(
    data, segment_ids, num_segments: int, mask=None, axis_name=None,
    sorted_ids: bool = False, row_ptr=None,
):
    """Drop-in masked ``segment_mean`` over the fused kernel (SAGE neighbor
    mean, the global mean-pool readout). Both paths return ``data.dtype`` so
    CPU-fallback and TPU runs agree on dtype flow."""
    # Route decision only — the UN-localized row_ptr forwards to
    # fused_segment_sum_count, which performs the per-shard localization
    # itself (localizing here too would shift the boundaries twice).
    use_sorted, use_csr_kernel, _ = _sorted_route(
        sorted_ids, row_ptr, axis_name, num_local_edges=segment_ids.shape[0]
    )
    if use_sorted or use_csr_kernel:
        total, count = fused_segment_sum_count(
            data, segment_ids, num_segments, mask=mask, axis_name=axis_name,
            sorted_ids=True, row_ptr=row_ptr,
        )
        safe = jnp.maximum(count, 1.0).reshape(
            count.shape + (1,) * (total.ndim - count.ndim)
        )
        return (total / safe).astype(data.dtype)
    if not pallas_enabled():
        return seg.segment_mean(
            data, segment_ids, num_segments, mask=mask, axis_name=axis_name
        ).astype(data.dtype)
    total, count = fused_segment_sum_count(
        data, segment_ids, num_segments, mask=mask, axis_name=axis_name
    )
    safe = jnp.maximum(count, 1.0).reshape(
        count.shape + (1,) * (total.ndim - count.ndim)
    )
    return (total / safe).astype(data.dtype)


def fused_segment_softmax(
    logits, segment_ids, num_segments: int, mask=None, axis_name=None,
    sorted_ids: bool = False, row_ptr=None,
):
    """Generic segment softmax with the denominator's scatter on the fused
    MXU kernel or the scatter-free sorted/CSR path — one shared
    stabilization body (seg.segment_softmax) with the sum injected, so the
    TPU and fallback paths cannot drift. The per-segment max stays on XLA
    ``segment_max`` (extrema can't ride the MXU) under stop_gradient, so no
    scatter appears in the backward either.

    NOTE: GATv2Conv no longer routes through here — its softmax runs over
    {incoming edges} ∪ {self} and is built inline from seg.segment_max +
    fused_segment_sum so the dense self term can join the denominator
    (models/convs.py:GATv2Conv). This stays the entry point for plain
    edge-only segment softmaxes; ``sorted_ids``/``row_ptr`` declare the CSR
    batch contract for the denominator sum."""
    use_sorted, use_csr_kernel, _ = _sorted_route(
        sorted_ids, row_ptr, axis_name, num_local_edges=segment_ids.shape[0]
    )
    use_fast = pallas_enabled() or use_sorted or use_csr_kernel
    sum_fn = None
    if use_fast:
        def sum_fn(d, i, n, mask=None, axis_name=None):
            return fused_segment_sum(
                d, i, n, mask=mask, axis_name=axis_name,
                sorted_ids=sorted_ids, row_ptr=row_ptr,
            )
    return seg.segment_softmax(
        logits, segment_ids, num_segments, mask=mask, axis_name=axis_name,
        sum_fn=sum_fn,
    )


def pna_aggregate(
    msg: jnp.ndarray,
    receivers: jnp.ndarray,
    num_segments: int,
    aggregators: Tuple[str, ...],
    mask: Optional[jnp.ndarray] = None,
    axis_name: Optional[str] = None,
    sorted_ids: bool = False,
    row_ptr=None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """PNA multi-aggregator bundle → (stacked [N, A, F] aggregates, count [N]).

    Routes the sum/mean/std family through the scatter-free sorted path
    (precomputed CSR boundaries when ``row_ptr`` is present) or the fused
    Pallas kernel when enabled; min/max always via XLA segment extrema.
    Falls back entirely to the masked XLA segment ops otherwise.
    """
    n = num_segments
    use_sorted = sorted_ids and srt.sorted_enabled()
    if pallas_enabled() or use_sorted:
        fused = {}
        count = None
        if any(a in ("mean", "std", "sum") for a in aggregators):
            total, mean, std, count = fused_segment_stats(
                msg, receivers, n, mask=mask, axis_name=axis_name,
                want_std="std" in aggregators, sorted_ids=sorted_ids,
                row_ptr=row_ptr,
            )
            fused = {"mean": mean, "std": std, "sum": total}
        if "min" in aggregators or "max" in aggregators:
            ids = receivers.astype(jnp.int32)
            if mask is not None:
                ids = jnp.where(mask, ids, -1)
            mn, mx = segment_extrema(msg, ids, n, axis_name)
            fused["min"], fused["max"] = mn, mx
    else:
        fused = {}
        count = None
    aggs = []
    for a in aggregators:
        if a in fused:
            aggs.append(fused[a])
        elif a == "mean":
            aggs.append(seg.segment_mean(msg, receivers, n, mask=mask, axis_name=axis_name))
        elif a == "sum":
            aggs.append(seg.segment_sum(msg, receivers, n, mask=mask, axis_name=axis_name))
        elif a == "std":
            aggs.append(seg.segment_std(msg, receivers, n, mask=mask, axis_name=axis_name))
        elif a == "min":
            aggs.append(seg.segment_min(msg, receivers, n, mask=mask, axis_name=axis_name))
        elif a == "max":
            aggs.append(seg.segment_max(msg, receivers, n, mask=mask, axis_name=axis_name))
        else:
            raise ValueError(f"Unknown aggregator {a}")
    if count is None:
        count = seg.segment_count(receivers, n, mask=mask, axis_name=axis_name)
    return jnp.stack(aggs, axis=1), count
