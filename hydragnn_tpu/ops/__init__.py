from . import segment
