from . import pallas_segment, segment
