"""Host-side radius-graph construction — flat (cKDTree) and periodic (own cell-image
neighbor list; the reference delegates to torch-cluster RadiusGraph and
ase.neighborlist.neighbor_list, /root/reference/hydragnn/preprocess/utils.py:51-123).

Graph building stays OUT of the XLA graph: it is ragged, data-dependent work that
belongs in the prefetching input pipeline (SURVEY.md §2.9).
"""

from __future__ import annotations

import numpy as np
from scipy.spatial import cKDTree

from ..graphs.sample import GraphSample
from .. import native


def radius_graph(
    pos: np.ndarray, radius: float, max_neighbours: int, loop: bool = False
):
    """Edges (j → i) for all j within `radius` of i, nearest-first, capped at
    `max_neighbours` per receiver (torch-cluster radius_graph semantics).

    Uses the native C++ cell-list builder when available (hydragnn_tpu/native),
    falling back to the numpy/cKDTree path below."""
    if native.available():
        return native.radius_graph(pos, radius, max_neighbours, loop), None
    pos = np.asarray(pos, dtype=np.float64)
    tree = cKDTree(pos)
    senders, receivers = [], []
    for i, nbrs in enumerate(tree.query_ball_point(pos, r=radius)):
        nbrs = [j for j in nbrs if loop or j != i]
        if len(nbrs) > max_neighbours:
            d = np.linalg.norm(pos[nbrs] - pos[i], axis=1)
            nbrs = [nbrs[k] for k in np.argsort(d, kind="stable")[:max_neighbours]]
        senders.extend(nbrs)
        receivers.extend([i] * len(nbrs))
    return (
        np.asarray([senders, receivers], dtype=np.int64).reshape(2, -1),
        None,
    )


def periodic_radius_graph(
    pos: np.ndarray,
    cell: np.ndarray,
    radius: float,
    max_neighbours: int | None = None,
    loop: bool = False,
):
    """Periodic neighbor list over cell images (ase.neighborlist.neighbor_list("ijd")
    equivalent). Returns (edge_index [2,E], lengths [E]).

    Self-pairs across nonzero images ARE included (an atom sees its own periodic
    copy); the zero-image self pair only with loop=True. The image search range per
    axis is ceil(radius / cell-height) with cell heights from the reciprocal cell.

    Uses the native C++ builder when available (hydragnn_tpu/native), falling
    back to the numpy/cKDTree path below.
    """
    if native.available():
        return native.periodic_radius_graph(pos, cell, radius, max_neighbours, loop)
    pos = np.asarray(pos, dtype=np.float64)
    cell = np.asarray(cell, dtype=np.float64).reshape(3, 3)
    n = pos.shape[0]

    # Height of the cell along each reciprocal direction bounds how many images
    # can fall within `radius`.
    volume = abs(np.linalg.det(cell))
    heights = np.empty(3)
    for k in range(3):
        cross = np.cross(cell[(k + 1) % 3], cell[(k + 2) % 3])
        heights[k] = volume / np.linalg.norm(cross)
    n_images = np.ceil(radius / heights).astype(int)

    shifts = [
        np.array([i, j, k], dtype=np.float64)
        for i in range(-n_images[0], n_images[0] + 1)
        for j in range(-n_images[1], n_images[1] + 1)
        for k in range(-n_images[2], n_images[2] + 1)
    ]

    src, dst, lengths = [], [], []
    tree = cKDTree(pos)
    for shift in shifts:
        offset = shift @ cell
        zero_shift = not shift.any()
        # neighbors of (pos_j + offset) around each i: pairs (i, j) with
        # |pos_i - pos_j - offset| <= radius.
        shifted_tree = cKDTree(pos + offset)
        pairs = tree.query_ball_tree(shifted_tree, r=radius)
        for i, js in enumerate(pairs):
            for j in js:
                if zero_shift and i == j and not loop:
                    continue
                d = np.linalg.norm(pos[i] - pos[j] - offset)
                src.append(j)
                dst.append(i)
                lengths.append(d)

    edge_index = np.asarray([src, dst], dtype=np.int64).reshape(2, -1)
    lengths = np.asarray(lengths, dtype=np.float64)

    # Reference asserts no duplicate (i, j) pairs after coalescing — multiple
    # images of the same pair within the cutoff mean radius/cell are inconsistent
    # (preprocess/utils.py:108-116).
    if edge_index.shape[1]:
        uniq = len({(int(a), int(b)) for a, b in edge_index.T})
        assert uniq == edge_index.shape[1], (
            "Adding periodic boundary conditions would result in duplicate edges. "
            "Cutoff radius must be reduced or system size increased."
        )
    if max_neighbours is not None:
        keep = _cap_neighbors(edge_index, lengths, max_neighbours)
        edge_index, lengths = edge_index[:, keep], lengths[keep]
    return edge_index, lengths


def _cap_neighbors(edge_index, lengths, max_neighbours):
    keep = []
    by_receiver = {}
    for e, r in enumerate(edge_index[1]):
        by_receiver.setdefault(int(r), []).append(e)
    for r, edges in by_receiver.items():
        if len(edges) > max_neighbours:
            order = np.argsort(lengths[edges], kind="stable")[:max_neighbours]
            edges = [edges[k] for k in order]
        keep.extend(edges)
    return np.sort(np.asarray(keep, dtype=np.int64))


def compute_edges(sample: GraphSample, radius, max_neighbours, periodic=False):
    """Build edges on a sample in place, mirroring RadiusGraph / RadiusGraphPBC:
    PBC also stores edge lengths in edge_attr (utils.py:118)."""
    if periodic:
        assert sample.supercell_size is not None, (
            "The data must contain the size of the supercell to apply periodic "
            "boundary conditions."
        )
        ei, lengths = periodic_radius_graph(
            sample.pos, sample.supercell_size, radius, max_neighbours
        )
        sample.edge_index = ei
        sample.edge_attr = lengths.reshape(-1, 1).astype(np.float32)
    else:
        ei, _ = radius_graph(sample.pos, radius, max_neighbours)
        sample.edge_index = ei
        sample.edge_attr = None
    return sample


def get_radius_graph_config(arch_config: dict):
    """Closure building edges from an Architecture config block, the analog of
    the reference's transform factory (preprocess/utils.py:51-80) used by the
    md17 example (examples/md17/md17.py:64)."""

    def transform(sample: GraphSample) -> GraphSample:
        compute_edges(
            sample,
            radius=arch_config["radius"],
            max_neighbours=arch_config["max_neighbours"],
            periodic=arch_config.get("periodic_boundary_conditions", False),
        )
        if "lengths" in arch_config.get("edge_features", []) or arch_config.get(
            "periodic_boundary_conditions", False
        ):
            if sample.edge_attr is None:
                add_edge_lengths(sample)
        return sample

    return transform


def add_edge_lengths(sample: GraphSample) -> GraphSample:
    """torch_geometric.transforms.Distance(norm=False, cat=True): append |p_r - p_s|
    to edge_attr."""
    ei = sample.edge_index
    d = np.linalg.norm(
        np.asarray(sample.pos)[ei[1]] - np.asarray(sample.pos)[ei[0]], axis=1
    ).reshape(-1, 1).astype(np.float32)
    if sample.edge_attr is None:
        sample.edge_attr = d
    else:
        sample.edge_attr = np.concatenate(
            [np.asarray(sample.edge_attr, dtype=np.float32), d], axis=1
        )
    return sample


def normalize_rotation(sample: GraphSample) -> GraphSample:
    """torch_geometric.transforms.NormalizeRotation(max_points=-1, sort=False):
    rotate positions onto the eigenbasis of their covariance (centered)."""
    pos = np.asarray(sample.pos, dtype=np.float64)
    centered = pos - pos.mean(axis=0, keepdims=True)
    cov = centered.T @ centered
    _, eigvecs = np.linalg.eigh(cov)
    sample.pos = (centered @ eigvecs).astype(pos.dtype)
    return sample


def check_if_graph_size_variable(*datasets) -> bool:
    sizes = set()
    for ds in datasets:
        for s in ds:
            sizes.add(s.num_nodes)
            if len(sizes) > 1:
                return True
    return False


def check_data_samples_equivalence(sample1: GraphSample, sample2: GraphSample,
                                   tol: float) -> bool:
    """Same shapes and the same edge set (edges may be listed in any order),
    with edge attributes matching within ``tol`` (reference
    /root/reference/hydragnn/preprocess/utils.py:32-48 — its O(E²) python loop
    replaced by a lexicographic sort of both edge lists)."""
    if (
        np.shape(sample1.x) != np.shape(sample2.x)
        or np.shape(sample1.pos) != np.shape(sample2.pos)
        or np.shape(sample1.y) != np.shape(sample2.y)
    ):
        return False
    e1 = np.asarray(sample1.edge_index)
    e2 = np.asarray(sample2.edge_index)
    if e1.shape != e2.shape:
        return False
    o1 = np.lexsort((e1[1], e1[0]))
    o2 = np.lexsort((e2[1], e2[0]))
    if not np.array_equal(e1[:, o1], e2[:, o2]):
        return False
    if (sample1.edge_attr is None) != (sample2.edge_attr is None):
        return False
    if sample1.edge_attr is not None:
        a1 = np.asarray(sample1.edge_attr)[o1]
        a2 = np.asarray(sample2.edge_attr)[o2]
        if not np.all(np.linalg.norm(a1 - a2, axis=-1) < tol):
            return False
    return True
