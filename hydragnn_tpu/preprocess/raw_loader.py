"""Raw file readers → normalized serialized datasets
(reference /root/reference/hydragnn/preprocess/raw_dataset_loader.py:29-388).

Formats:
  * LSMS / unit_test — text tables: line 0 = graph features, lines 1+ =
    per-node rows [feature, index, x, y, z, outputs...] (raw_dataset_loader.py:226-274).
  * CFG — AtomEye (extended) CFG crystal files + optional ``.bulk`` sidecar with
    graph features (raw_dataset_loader.py:161-224). The reference reads CFG via
    ase.io.cfg; ase is not available here, so ``cfg_io.read_cfg`` is our own parser.

Output contract (identical to reference, raw_dataset_loader.py:140-148): one pickle
file per split with three sequential dumps: minmax_node_feature [2, nfeat],
minmax_graph_feature [2, nfeat], then the list of samples. Min-max normalization is
computed globally across ALL splits (raw_dataset_loader.py:319-388).
"""

from __future__ import annotations

import os
import pickle
from typing import Dict, List

import numpy as np

from ..graphs.sample import GraphSample
from .cfg_io import read_cfg


def np_divide(x1, x2):
    return np.divide(x1, x2, out=np.zeros_like(x1), where=x2 != 0)


class RawDataLoader:
    """Parses raw files, normalizes, and pickles serialized splits (rank-0 only by
    the orchestration layer)."""

    def __init__(self, config: Dict):
        self.dataset_list: List[List[GraphSample]] = []
        self.serial_data_name_list: List[str] = []
        self.node_feature_name = config["node_features"]["name"]
        self.node_feature_dim = config["node_features"]["dim"]
        self.node_feature_col = config["node_features"]["column_index"]
        self.graph_feature_name = config["graph_features"]["name"]
        self.graph_feature_dim = config["graph_features"]["dim"]
        self.graph_feature_col = config["graph_features"]["column_index"]
        self.raw_dataset_name = config["name"]
        self.data_format = config["format"]
        self.path_dictionary = config["path"]

        assert len(self.node_feature_name) == len(self.node_feature_dim)
        assert len(self.node_feature_name) == len(self.node_feature_col)
        assert len(self.graph_feature_name) == len(self.graph_feature_dim)
        assert len(self.graph_feature_name) == len(self.graph_feature_col)

    # ---------------------------------------------------------------- public
    def load_raw_data(self) -> None:
        serialized_dir = os.path.join(
            os.environ["SERIALIZED_DATA_PATH"], "serialized_dataset"
        )
        os.makedirs(serialized_dir, exist_ok=True)

        for dataset_type, raw_data_path in self.path_dictionary.items():
            if not os.path.isabs(raw_data_path):
                raw_data_path = os.path.join(os.getcwd(), raw_data_path)
            if not os.path.exists(raw_data_path):
                raise ValueError("Folder not found: " + raw_data_path)
            files = sorted(os.listdir(raw_data_path))
            assert len(files) > 0, f"No data files provided in {raw_data_path}!"

            dataset = []
            for name in files:
                if name == ".DS_Store":
                    continue
                full = os.path.join(raw_data_path, name)
                if os.path.isfile(full):
                    obj = self._parse_file(full)
                    if obj is not None:
                        dataset.append(obj)
                elif os.path.isdir(full):
                    for sub in sorted(os.listdir(full)):
                        subf = os.path.join(full, sub)
                        if os.path.isfile(subf):
                            obj = self._parse_file(subf)
                            if obj is not None:
                                dataset.append(obj)

            if self.data_format == "LSMS":
                for s in dataset:
                    self._charge_density_update_for_lsms(s)
            dataset = self._scale_features_by_num_nodes(dataset)

            if dataset_type == "total":
                serial_data_name = self.raw_dataset_name + ".pkl"
            else:
                serial_data_name = f"{self.raw_dataset_name}_{dataset_type}.pkl"
            self.dataset_list.append(dataset)
            self.serial_data_name_list.append(serial_data_name)

        self._normalize_dataset()

        for serial_data_name, dataset in zip(
            self.serial_data_name_list, self.dataset_list
        ):
            with open(os.path.join(serialized_dir, serial_data_name), "wb") as f:
                pickle.dump(self.minmax_node_feature, f)
                pickle.dump(self.minmax_graph_feature, f)
                pickle.dump(dataset, f)

    # --------------------------------------------------------------- parsing
    def _parse_file(self, filepath):
        if self.data_format in ("LSMS", "unit_test"):
            return self._parse_lsms(filepath)
        if self.data_format == "CFG":
            return self._parse_cfg(filepath)
        raise ValueError(f"Unknown raw data format {self.data_format}")

    def _parse_lsms(self, filepath) -> GraphSample:
        with open(filepath, "r", encoding="utf-8") as f:
            lines = f.readlines()
        graph_feat = lines[0].split(None, 2)
        g_feature = []
        for item in range(len(self.graph_feature_dim)):
            for icomp in range(self.graph_feature_dim[item]):
                it_comp = self.graph_feature_col[item] + icomp
                g_feature.append(float(graph_feat[it_comp].strip()))

        node_feature_matrix = []
        node_position_matrix = []
        for line in lines[1:]:
            node_feat = line.split(None, 11)
            node_position_matrix.append(
                [float(node_feat[c].strip()) for c in (2, 3, 4)]
            )
            row = []
            for item in range(len(self.node_feature_dim)):
                for icomp in range(self.node_feature_dim[item]):
                    it_comp = self.node_feature_col[item] + icomp
                    row.append(float(node_feat[it_comp].strip()))
            node_feature_matrix.append(row)

        return GraphSample(
            x=np.asarray(node_feature_matrix, dtype=np.float32),
            pos=np.asarray(node_position_matrix, dtype=np.float32),
            y=np.asarray(g_feature, dtype=np.float32),
        )

    def _parse_cfg(self, filepath):
        if not filepath.endswith(".cfg"):
            return None
        cfg = read_cfg(filepath)
        sample = GraphSample(
            pos=cfg.positions.astype(np.float32),
            supercell_size=cfg.cell.astype(np.float32),
        )
        cols = [
            cfg.numbers.reshape(-1, 1),
            cfg.masses.reshape(-1, 1),
        ]
        for aux in ("c_peratom", "fx", "fy", "fz"):
            cols.append(cfg.aux[aux].reshape(-1, 1))
        sample.x = np.concatenate(cols, axis=1).astype(np.float32)

        bulk_path = os.path.splitext(filepath)[0] + ".bulk"
        if os.path.exists(bulk_path):
            with open(bulk_path, "r", encoding="utf-8") as f:
                graph_feat = f.readlines()[0].split(None, 2)
            g_feature = []
            for item in range(len(self.graph_feature_dim)):
                for icomp in range(self.graph_feature_dim[item]):
                    it_comp = self.graph_feature_col[item] + icomp
                    g_feature.append(float(graph_feat[it_comp].strip()))
            sample.y = np.asarray(g_feature, dtype=np.float32)
        return sample

    # ------------------------------------------------------------ transforms
    @staticmethod
    def _charge_density_update_for_lsms(sample: GraphSample) -> GraphSample:
        """Charge density column ← charge density − num protons
        (raw_dataset_loader.py:276-292)."""
        sample.x[:, 1] = sample.x[:, 1] - sample.x[:, 0]
        return sample

    def _scale_features_by_num_nodes(self, dataset):
        """Divide any ``*_scaled_num_nodes`` feature by the node count
        (raw_dataset_loader.py:294-317)."""
        g_idx = [
            i
            for i, nm in enumerate(self.graph_feature_name)
            if "_scaled_num_nodes" in nm
        ]
        n_idx = [
            i
            for i, nm in enumerate(self.node_feature_name)
            if "_scaled_num_nodes" in nm
        ]
        for s in dataset:
            if s.y is not None and g_idx:
                s.y[g_idx] = s.y[g_idx] / s.num_nodes
            if s.x is not None and n_idx:
                s.x[:, n_idx] = s.x[:, n_idx] / s.num_nodes
        return dataset

    def _normalize_dataset(self):
        """Global min-max across all splits; per logical feature (which may span
        multiple columns), matching raw_dataset_loader.py:319-388."""
        num_node_features = len(self.node_feature_dim)
        num_graph_features = len(self.graph_feature_dim)
        self.minmax_graph_feature = np.full((2, num_graph_features), np.inf)
        self.minmax_node_feature = np.full((2, num_node_features), np.inf)
        self.minmax_graph_feature[1, :] *= -1
        self.minmax_node_feature[1, :] *= -1

        for dataset in self.dataset_list:
            for s in dataset:
                g_start = 0
                for ifeat in range(num_graph_features):
                    g_end = g_start + self.graph_feature_dim[ifeat]
                    self.minmax_graph_feature[0, ifeat] = min(
                        float(s.y[g_start:g_end].min()),
                        self.minmax_graph_feature[0, ifeat],
                    )
                    self.minmax_graph_feature[1, ifeat] = max(
                        float(s.y[g_start:g_end].max()),
                        self.minmax_graph_feature[1, ifeat],
                    )
                    g_start = g_end
                n_start = 0
                for ifeat in range(num_node_features):
                    n_end = n_start + self.node_feature_dim[ifeat]
                    self.minmax_node_feature[0, ifeat] = min(
                        float(s.x[:, n_start:n_end].min()),
                        self.minmax_node_feature[0, ifeat],
                    )
                    self.minmax_node_feature[1, ifeat] = max(
                        float(s.x[:, n_start:n_end].max()),
                        self.minmax_node_feature[1, ifeat],
                    )
                    n_start = n_end

        for dataset in self.dataset_list:
            for s in dataset:
                g_start = 0
                for ifeat in range(num_graph_features):
                    g_end = g_start + self.graph_feature_dim[ifeat]
                    lo, hi = (
                        self.minmax_graph_feature[0, ifeat],
                        self.minmax_graph_feature[1, ifeat],
                    )
                    s.y[g_start:g_end] = np_divide(s.y[g_start:g_end] - lo, hi - lo)
                    g_start = g_end
                n_start = 0
                for ifeat in range(num_node_features):
                    n_end = n_start + self.node_feature_dim[ifeat]
                    lo, hi = (
                        self.minmax_node_feature[0, ifeat],
                        self.minmax_node_feature[1, ifeat],
                    )
                    s.x[:, n_start:n_end] = np_divide(s.x[:, n_start:n_end] - lo, hi - lo)
                    n_start = n_end
