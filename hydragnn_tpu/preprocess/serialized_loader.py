"""Serialized (pickled) dataset → training-ready GraphSamples
(reference /root/reference/hydragnn/preprocess/serialized_dataset_loader.py:31-261).

Pipeline per split: optional rotation normalization → radius-graph edges (flat or
PBC) → edge lengths → GLOBAL max-edge-length normalization → target packing
(update_predicted_values) → input-feature column selection → optional stratified
subsample. One deliberate divergence: samples stay host-side numpy (the reference
moves the whole dataset to the accelerator at load time,
serialized_dataset_loader.py:137-140 — SURVEY.md §7 quirks list says stream
instead, which our DataLoader does).
"""

from __future__ import annotations

import pickle
import warnings
from typing import List, Sequence

import numpy as np
from sklearn.model_selection import StratifiedShuffleSplit

from ..graphs.sample import GraphSample
from .graph_build import add_edge_lengths, compute_edges, normalize_rotation


_pickle_warned = False


def warn_pickle_corpus_once() -> None:
    """One-time DeprecationWarning for the raw-pickle corpus read path
    (mirrors the v1-checkpoint read precedent in checkpoint/io.py): pickle
    corpora still load this release, but GSHD is the supported data plane —
    it is digest-verified, sharded, and streamable (docs/DATA_PLANE.md)."""
    global _pickle_warned
    if _pickle_warned:
        return
    _pickle_warned = True
    from ..datasets.shards import CONVERT_CMD

    warnings.warn(
        "reading a raw-pickle dataset corpus is deprecated — migrate to the "
        f"GSHD streaming format with `{CONVERT_CMD}` (docs/DATA_PLANE.md)",
        DeprecationWarning,
        stacklevel=3,
    )


class SerializedDataLoader:
    def __init__(self, config: dict):
        self.verbosity = config["Verbosity"]["level"]
        ds = config["Dataset"]
        self.node_feature_name = ds["node_features"]["name"]
        self.node_feature_dim = ds["node_features"]["dim"]
        self.node_feature_col = ds["node_features"]["column_index"]
        self.graph_feature_name = ds["graph_features"]["name"]
        self.graph_feature_dim = ds["graph_features"]["dim"]
        self.graph_feature_col = ds["graph_features"]["column_index"]
        # Defaulted when absent (divergence from the reference, which requires
        # both keys — serialized_dataset_loader.py:49 — even though its own
        # ising_model.json omits them).
        self.rotational_invariance = ds.get("rotational_invariance", False)
        arch = config["NeuralNetwork"]["Architecture"]
        self.periodic_boundary_conditions = arch.get(
            "periodic_boundary_conditions", False
        )
        self.radius = arch["radius"]
        self.max_neighbours = arch["max_neighbours"]
        voi = config["NeuralNetwork"]["Variables_of_interest"]
        self.variables = voi
        self.variables_type = voi["type"]
        self.output_index = voi["output_index"]
        self.input_node_features = voi["input_node_features"]

        assert len(self.node_feature_name) == len(self.node_feature_dim)
        assert len(self.node_feature_name) == len(self.node_feature_col)
        assert len(self.graph_feature_name) == len(self.graph_feature_dim)
        assert len(self.graph_feature_name) == len(self.graph_feature_col)

    def load_serialized_data(self, dataset_path: str) -> List[GraphSample]:
        warn_pickle_corpus_once()
        with open(dataset_path, "rb") as f:
            _ = pickle.load(f)  # graftlint: disable=pickle-load-outside-compat(legacy HydraGNN .pkl loader shim gated behind warn_pickle_corpus_once)
            _ = pickle.load(f)  # graftlint: disable=pickle-load-outside-compat(legacy loader shim, see above)
            dataset = pickle.load(f)  # graftlint: disable=pickle-load-outside-compat(legacy loader shim, see above)

        if self.rotational_invariance:
            dataset = [normalize_rotation(s) for s in dataset]

        for s in dataset:
            compute_edges(
                s,
                self.radius,
                self.max_neighbours,
                periodic=self.periodic_boundary_conditions,
            )
            if not self.periodic_boundary_conditions:
                # PBC already stored lengths in edge_attr.
                add_edge_lengths(s)

        # Global max-edge-length normalization across the split
        # (serialized_dataset_loader.py:128-135).
        max_edge_length = -np.inf
        for s in dataset:
            if s.edge_attr is not None and s.edge_attr.size:
                max_edge_length = max(max_edge_length, float(s.edge_attr.max()))
        if np.isfinite(max_edge_length) and max_edge_length > 0:
            for s in dataset:
                if s.edge_attr is not None:
                    s.edge_attr = s.edge_attr / max_edge_length

        for s in dataset:
            update_predicted_values(
                self.variables_type,
                self.output_index,
                self.graph_feature_dim,
                self.node_feature_dim,
                s,
            )
            s.x = s.x[:, list(self.input_node_features)]

        if "subsample_percentage" in self.variables:
            return stratified_subsample(
                dataset, self.variables["subsample_percentage"]
            )
        return dataset


def update_predicted_values(
    type: Sequence[str],
    index: Sequence[int],
    graph_feature_dim: Sequence[int],
    node_feature_dim: Sequence[int],
    sample: GraphSample,
) -> None:
    """THE packed-y data contract (serialized_dataset_loader.py:220-261): y becomes
    the concatenation of the selected per-head slices (graph slices then per-node
    column slices, each flattened row-major); y_loc[0, i] is the prefix offset of
    head i."""
    output_feature = []
    sample.y_loc = np.zeros((1, len(type) + 1), dtype=np.int64)
    for item in range(len(type)):
        if type[item] == "graph":
            start = sum(graph_feature_dim[: index[item]])
            feat = np.asarray(sample.y).reshape(-1)[
                start : start + graph_feature_dim[index[item]]
            ].reshape(-1, 1)
        elif type[item] == "node":
            start = sum(node_feature_dim[: index[item]])
            feat = np.asarray(sample.x)[
                :, start : start + node_feature_dim[index[item]]
            ].reshape(-1, 1)
        else:
            raise ValueError("Unknown output type", type[item])
        output_feature.append(feat)
        sample.y_loc[0, item + 1] = sample.y_loc[0, item] + feat.shape[0]
    sample.y = np.concatenate(output_feature, axis=0).astype(np.float32).reshape(-1)


def stratified_subsample(
    dataset: List[GraphSample], subsample_percentage: float
) -> List[GraphSample]:
    """Stratified (by composition category) subsample of the dataset
    (serialized_dataset_loader.py:172-217). Divergence from the reference, on
    purpose: categories come from splitting.create_dataset_categories, which
    handles min-max-normalized float element ids via np.unique — the reference's
    bincount(int(x)) collapses all normalized elements except the max into one
    bin, making its 'stratified' subsample effectively random."""
    from .splitting import create_dataset_categories

    categories = create_dataset_categories(dataset)
    sss = StratifiedShuffleSplit(
        n_splits=1, train_size=subsample_percentage, random_state=0
    )
    for keep_idx, _rest in sss.split(dataset, categories):
        return [dataset[i] for i in keep_idx.tolist()]
    return dataset
