"""Dataset splitting: sequential and compositional-stratified
(reference /root/reference/hydragnn/preprocess/load_data.py:89-107 and
compositional_data_splitting.py:26-152).

The compositional category encodes the per-element atom counts of a structure as
digits in base 10^ceil(log10(max_graph_size)), so each composition maps to a unique
integer and sklearn's StratifiedShuffleSplit keeps all three splits
composition-balanced. Singleton categories are duplicated first so sklearn can
split them (the reference's "data augmentation" trick,
compositional_data_splitting.py:75-90).
"""

from __future__ import annotations

import collections
import math
from typing import List, Sequence, Tuple

import numpy as np
from sklearn.model_selection import StratifiedShuffleSplit

from ..graphs.sample import GraphSample


def get_max_graph_size(dataset: Sequence[GraphSample]) -> int:
    return max(int(s.num_nodes) for s in dataset)


def create_dataset_categories(dataset: Sequence[GraphSample]) -> List[int]:
    max_graph_size = get_max_graph_size(dataset)
    power_ten = math.ceil(math.log10(max_graph_size))
    elements = sorted(
        {float(e) for s in dataset for e in np.unique(np.asarray(s.x)[:, 0])}
    )
    element_rank = {e: i for i, e in enumerate(elements)}

    categories = []
    for s in dataset:
        elems, freqs = np.unique(np.asarray(s.x)[:, 0], return_counts=True)
        category = 0
        for e, f in zip(elems, freqs):
            category += int(f) * (10 ** (power_ten * element_rank[float(e)]))
        categories.append(category)
    return categories


def duplicate_unique_data_samples(dataset, categories):
    counter = collections.Counter(categories)
    singletons = {k for k, v in counter.items() if v == 1}
    extra, extra_cat = [], []
    for s, c in zip(dataset, categories):
        if c in singletons:
            extra.append(s.clone())
            extra_cat.append(c)
    return list(dataset) + extra, list(categories) + extra_cat


def _partition(dataset, categories, train_size):
    sss = StratifiedShuffleSplit(n_splits=1, train_size=train_size, random_state=0)
    for a_idx, b_idx in sss.split(dataset, categories):
        return (
            [dataset[i] for i in a_idx.tolist()],
            [dataset[i] for i in b_idx.tolist()],
        )


def compositional_stratified_splitting(
    dataset: Sequence[GraphSample], perc_train: float
) -> Tuple[List[GraphSample], List[GraphSample], List[GraphSample]]:
    categories = create_dataset_categories(dataset)
    dataset, categories = duplicate_unique_data_samples(list(dataset), categories)
    trainset, val_test = _partition(dataset, categories, perc_train)

    vt_categories = create_dataset_categories(val_test)
    val_test, vt_categories = duplicate_unique_data_samples(val_test, vt_categories)
    valset, testset = _partition(val_test, vt_categories, 0.5)
    return trainset, valset, testset


def split_dataset(
    dataset: Sequence[GraphSample], perc_train: float, stratify_splitting: bool
):
    """Sequential head/middle/tail split, or compositional stratified
    (load_data.py:89-107)."""
    if not stratify_splitting:
        perc_val = (1 - perc_train) / 2
        n = len(dataset)
        trainset = dataset[: int(n * perc_train)]
        valset = dataset[int(n * perc_train) : int(n * (perc_train + perc_val))]
        testset = dataset[int(n * (perc_train + perc_val)) :]
    else:
        trainset, valset, testset = compositional_stratified_splitting(
            dataset, perc_train
        )
    return trainset, valset, testset
