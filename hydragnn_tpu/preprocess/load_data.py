"""Dataset loading & splitting orchestration
(reference /root/reference/hydragnn/preprocess/load_data.py:34-183).

Flow: raw→serialized conversion if paths are not .pkl (rank 0 + barrier) →
"total"→train/val/test pkl split → per-split SerializedDataLoader →
GraphDataLoader construction (sharded per process when running multi-process,
replacing DistributedSampler)."""

from __future__ import annotations

import os
import pickle
from typing import Dict, Tuple

from ..parallel.distributed import barrier, get_comm_size_and_rank
from ..utils.time_utils import Timer
from .dataloader import GraphDataLoader
from .raw_loader import RawDataLoader
from .serialized_loader import SerializedDataLoader
from .splitting import split_dataset


def dataset_loading_and_splitting(config: Dict):
    # Streaming data plane (docs/DATA_PLANE.md): when every split path is a
    # GSHD dataset, nothing is materialized in host RAM — the loaders stream
    # shards through the decode-ahead ring. This branch must precede the
    # raw/pickle plumbing below, which assumes pickle-era paths.
    paths = config["Dataset"]["path"]
    from ..datasets.shards import is_gshd_path

    if "total" not in paths and all(is_gshd_path(p) for p in paths.values()):
        return create_streaming_dataloaders(config)
    if not list(config["Dataset"]["path"].values())[0].endswith(".pkl"):
        transform_raw_data_to_serialized(config["Dataset"])
    if "total" in config["Dataset"]["path"].keys():
        total_to_train_val_test_pkls(config)
    trainset, valset, testset = load_train_val_test_sets(config)
    # Config-driven fault drills (Training.faults) must reach the LOADERS
    # too — corrupt_sample injection happens at loader construction, and the
    # loaders only consult the HYDRAGNN_FAULTS env on their own. Env wins
    # when both are set (same precedence as run_training's driver plan).
    import os as _os

    from ..faults.plan import FaultPlan

    fault_plan = None
    spec = config["NeuralNetwork"]["Training"].get("faults")
    if spec and not _os.environ.get("HYDRAGNN_FAULTS"):
        fault_plan = FaultPlan(spec)
    return create_dataloaders(
        trainset,
        valset,
        testset,
        batch_size=config["NeuralNetwork"]["Training"]["batch_size"],
        num_buckets=config["Dataset"].get("num_buckets", 1),
        reshuffle=config["NeuralNetwork"]["Training"].get("reshuffle", "sample"),
        # Corrupt-sample quarantine budget (docs/FAULT_TOLERANCE.md); 0 =
        # no validation, the historical behavior.
        skip_budget=config["Dataset"].get("skip_budget", 0),
        fault_plan=fault_plan,
        # Graph packing + pad round-up ladder (docs/INPUT_PIPELINE.md
        # "Graph packing"): packing densifies train batches by FFD
        # bin-packing; ladder_step picks pow2 vs multiples-of-64 pads.
        packing=bool(config["Dataset"].get("packing", False)),
        ladder_step=config["Dataset"].get("ladder_step", "pow2"),
    )


def create_dataloaders(trainset, valset, testset, batch_size, num_buckets=1,
                       reshuffle="sample", skip_budget=0, fault_plan=None,
                       packing=False, ladder_step="pow2"):
    """Three GraphDataLoaders; multi-process runs shard every split by process
    (the DistributedSampler analog). Returns (train, val, test, sampler_list) for
    reference API parity — the loaders are their own samplers here.

    Documented divergence: the reference shuffles val/test too
    (load_data.py:75-84), which silently misaligns its Visualizer's
    dataset-order node features with eval-order predictions. Eval loaders
    here keep dataset order — shuffling eval batches has no training effect.

    Documented divergence: ``batch_size`` is the GLOBAL batch — each process
    takes batch_size/world_size graphs per step, so the optimizer trajectory
    (steps per epoch, gradient noise scale) is invariant under the process
    count. The reference's batch_size is per-rank (DistributedSampler halves
    steps and doubles the effective batch at 2 ranks), which shifts
    convergence for the same config as ranks change."""
    world_size, rank = get_comm_size_and_rank()
    shard_batch = max(1, -(-batch_size // world_size))
    if shard_batch * world_size != batch_size:
        print(
            f"WARNING: batch_size {batch_size} is not divisible by "
            f"{world_size} processes; using {shard_batch}/process "
            f"(effective global batch {shard_batch * world_size})"
        )
    loaders = []
    for ds, shuffle in ((trainset, True), (valset, False), (testset, False)):
        loaders.append(
            GraphDataLoader(
                ds,
                batch_size=shard_batch,
                shuffle=shuffle,
                num_shards=world_size,
                shard_rank=rank,
                # Bucketing reorders iteration bucket-major; only the train
                # loader may do that — eval loaders keep exact dataset order
                # (run_prediction rows must align with the test set).
                num_buckets=num_buckets if shuffle else 1,
                # Per-epoch reshuffle granularity (Training.reshuffle):
                # "sample" = reference DistributedSampler parity; "batch"
                # freezes membership so collation + device transfer cache
                # across epochs (train loader only — eval never shuffles).
                reshuffle=reshuffle if shuffle else "sample",
                skip_budget=skip_budget,
                fault_plan=fault_plan,
                # Packing reorders batch membership by size — train only;
                # eval loaders must keep exact dataset order
                # (run_prediction rows align with the test set).
                packing=packing if shuffle else False,
                ladder_step=ladder_step,
            )
        )
    train_loader, val_loader, test_loader = loaders
    sampler_list = loaders if world_size > 1 else []
    return train_loader, val_loader, test_loader, sampler_list


def create_streaming_dataloaders(config: Dict):
    """Three StreamingGraphLoaders over GSHD split datasets — the out-of-core
    analog of ``create_dataloaders``, with identical split/sharding/knob
    semantics (global batch divided across processes, train-only buckets/
    packing/reshuffle, eval loaders in exact dataset order). Corruption
    handling is shard-granular (``Dataset.skip_budget`` counts shards);
    ``Training.faults`` corrupt_sample injection is an in-memory-loader drill
    and does not apply — on-disk corruption is drilled by flipping real shard
    bytes (benchmarks/stream_bench.py)."""
    from ..datasets.stream import StreamingGraphLoader

    world_size, rank = get_comm_size_and_rank()
    batch_size = config["NeuralNetwork"]["Training"]["batch_size"]
    shard_batch = max(1, -(-batch_size // world_size))
    if shard_batch * world_size != batch_size:
        print(
            f"WARNING: batch_size {batch_size} is not divisible by "
            f"{world_size} processes; using {shard_batch}/process "
            f"(effective global batch {shard_batch * world_size})"
        )
    ds = config["Dataset"]
    reshuffle = config["NeuralNetwork"]["Training"].get("reshuffle", "sample")
    loaders = []
    for split, shuffle in (("train", True), ("validate", False), ("test", False)):
        loaders.append(
            StreamingGraphLoader(
                ds["path"][split],
                batch_size=shard_batch,
                shuffle=shuffle,
                num_shards=world_size,
                shard_rank=rank,
                num_buckets=ds.get("num_buckets", 1) if shuffle else 1,
                reshuffle=reshuffle if shuffle else "sample",
                skip_budget=ds.get("skip_budget", 0),
                packing=bool(ds.get("packing", False)) if shuffle else False,
                ladder_step=ds.get("ladder_step", "pow2"),
                ring_depth=ds.get("ring_depth", 2),
                resident_shards=ds.get("resident_shards", 8),
            )
        )
    train_loader, val_loader, test_loader = loaders
    sampler_list = loaders if world_size > 1 else []
    return train_loader, val_loader, test_loader, sampler_list


def load_train_val_test_sets(config: Dict):
    timer = Timer("load_data")
    timer.start()
    dataset_list = []
    datasetname_list = []
    for dataset_name, raw_data_path in config["Dataset"]["path"].items():
        if raw_data_path.endswith(".pkl"):
            files_dir = raw_data_path
        else:
            files_dir = (
                f"{os.environ['SERIALIZED_DATA_PATH']}/serialized_dataset/"
                f"{config['Dataset']['name']}_{dataset_name}.pkl"
            )
        loader = SerializedDataLoader(config)
        dataset_list.append(loader.load_serialized_data(dataset_path=files_dir))
        datasetname_list.append(dataset_name)
    trainset = dataset_list[datasetname_list.index("train")]
    valset = dataset_list[datasetname_list.index("validate")]
    testset = dataset_list[datasetname_list.index("test")]
    timer.stop()
    return trainset, valset, testset


def transform_raw_data_to_serialized(dataset_config: Dict):
    _, rank = get_comm_size_and_rank()
    if rank == 0:
        loader = RawDataLoader(dataset_config)
        loader.load_raw_data()
    barrier("raw_to_serialized")


def total_to_train_val_test_pkls(config: Dict):
    _, rank = get_comm_size_and_rank()
    if list(config["Dataset"]["path"].values())[0].endswith(".pkl"):
        file_dir = config["Dataset"]["path"]["total"]
    else:
        file_dir = (
            f"{os.environ['SERIALIZED_DATA_PATH']}/serialized_dataset/"
            f"{config['Dataset']['name']}.pkl"
        )
    from .serialized_loader import warn_pickle_corpus_once

    warn_pickle_corpus_once()
    with open(file_dir, "rb") as f:
        minmax_node_feature = pickle.load(f)  # graftlint: disable=pickle-load-outside-compat(legacy HydraGNN corpus shim gated behind warn_pickle_corpus_once — the GSHD shard path is the supported reader)
        minmax_graph_feature = pickle.load(f)  # graftlint: disable=pickle-load-outside-compat(legacy corpus shim, see above)
        dataset_total = pickle.load(f)  # graftlint: disable=pickle-load-outside-compat(legacy corpus shim, see above)

    trainset, valset, testset = split_dataset(
        dataset=dataset_total,
        perc_train=config["NeuralNetwork"]["Training"]["perc_train"],
        stratify_splitting=config["Dataset"]["compositional_stratified_splitting"],
    )
    serialized_dir = os.path.dirname(file_dir)
    config["Dataset"]["path"] = {}
    for dataset_type, dataset in zip(
        ["train", "validate", "test"], [trainset, valset, testset]
    ):
        serial_data_name = config["Dataset"]["name"] + "_" + dataset_type + ".pkl"
        config["Dataset"]["path"][dataset_type] = (
            serialized_dir + "/" + serial_data_name
        )
        if rank == 0:
            with open(os.path.join(serialized_dir, serial_data_name), "wb") as f:
                pickle.dump(minmax_node_feature, f)
                pickle.dump(minmax_graph_feature, f)
                pickle.dump(dataset, f)
    barrier("total_split")
