from .dataloader import GraphDataLoader
from .dataset_descriptors import AtomFeatures, StructureFeatures
from .graph_build import (
    add_edge_lengths,
    check_data_samples_equivalence,
    check_if_graph_size_variable,
    compute_edges,
    get_radius_graph_config,
    normalize_rotation,
    periodic_radius_graph,
    radius_graph,
)
from .load_data import (
    create_dataloaders,
    dataset_loading_and_splitting,
    load_train_val_test_sets,
    total_to_train_val_test_pkls,
    transform_raw_data_to_serialized,
)
from .raw_loader import RawDataLoader
from .serialized_loader import SerializedDataLoader, update_predicted_values
from .splitting import compositional_stratified_splitting, split_dataset
