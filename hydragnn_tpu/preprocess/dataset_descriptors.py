"""Column-semantics enums for LSMS-format atomistic datasets
(reference /root/reference/hydragnn/preprocess/dataset_descriptors.py:15-32)."""

from enum import IntEnum


class AtomFeatures(IntEnum):
    NUM_OF_PROTONS = 0
    CHARGE_DENSITY = 1
    MAGNETIC_MOMENT = 2


class StructureFeatures(IntEnum):
    FREE_ENERGY = 0
    CHARGE_DENSITY = 1
    MAGNETIC_MOMENT = 2
