"""Minimal AtomEye (extended) CFG reader/writer.

The reference reads CFG via ase.io.cfg.read_cfg
(/root/reference/hydragnn/preprocess/raw_dataset_loader.py:183-207); ase is not in
this environment, so this module implements the subset of the format the EAM
example datasets use: extended CFG with ``.NO_VELOCITY.``, ``entry_count``,
``auxiliary[i]`` declarations, per-species ``mass`` + element-symbol lines, and
reduced coordinates scaled by ``A`` · H0.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict

import numpy as np

_SYMBOLS = (
    "H He Li Be B C N O F Ne Na Mg Al Si P S Cl Ar K Ca Sc Ti V Cr Mn Fe Co Ni "
    "Cu Zn Ga Ge As Se Br Kr Rb Sr Y Zr Nb Mo Tc Ru Rh Pd Ag Cd In Sn Sb Te I "
    "Xe Cs Ba La Ce Pr Nd Pm Sm Eu Gd Tb Dy Ho Er Tm Yb Lu Hf Ta W Re Os Ir Pt "
    "Au Hg Tl Pb Bi Po At Rn Fr Ra Ac Th Pa U Np Pu"
).split()
ATOMIC_NUMBERS: Dict[str, int] = {s: i + 1 for i, s in enumerate(_SYMBOLS)}
SYMBOLS_BY_NUMBER: Dict[int, str] = {v: k for k, v in ATOMIC_NUMBERS.items()}


@dataclasses.dataclass
class CfgData:
    positions: np.ndarray  # [n, 3] cartesian
    cell: np.ndarray  # [3, 3]
    numbers: np.ndarray  # [n] atomic numbers
    masses: np.ndarray  # [n]
    aux: Dict[str, np.ndarray]  # name → [n]


def read_cfg(filepath: str) -> CfgData:
    with open(filepath, "r", encoding="utf-8") as f:
        lines = [ln.strip() for ln in f if ln.strip() and not ln.startswith("#")]

    n = None
    scale = 1.0
    h0 = np.eye(3)
    entry_count = None
    aux_names = []
    body_start = None
    for idx, ln in enumerate(lines):
        if ln.startswith("Number of particles"):
            n = int(ln.split("=")[1])
        elif ln.startswith("A ") or ln.startswith("A="):
            scale = float(ln.split("=")[1].split()[0])
        elif ln.startswith("H0("):
            m = re.match(r"H0\((\d),(\d)\)\s*=\s*([-\d.eE+]+)", ln)
            h0[int(m.group(1)) - 1, int(m.group(2)) - 1] = float(m.group(3))
        elif ln.startswith("entry_count"):
            entry_count = int(ln.split("=")[1])
        elif ln.startswith("auxiliary["):
            m = re.match(r"auxiliary\[(\d+)\]\s*=\s*(\S+)", ln)
            aux_names.append(m.group(2))
        elif ln == ".NO_VELOCITY.":
            pass
        else:
            first_tokens = ln.split()
            if body_start is None and re.match(r"^[-\d.]", first_tokens[0]):
                # Header lines all start with a keyword; the body starts at the
                # first bare number (a per-species mass, or a legacy atom row).
                if idx > 0 and n is not None:
                    body_start = idx
                    break
    assert n is not None, f"{filepath}: missing 'Number of particles'"
    cell = scale * h0

    positions, numbers, masses = [], [], []
    aux_vals = {name: [] for name in aux_names}
    extended = entry_count is not None
    if extended:
        naux = entry_count - 3
        cur_mass, cur_z = None, None
        i = body_start
        while i < len(lines):
            tokens = lines[i].split()
            if len(tokens) == 1 and re.match(r"^[\d.]", tokens[0]):
                cur_mass = float(tokens[0])
                cur_z = ATOMIC_NUMBERS[lines[i + 1].split()[0]]
                i += 2
                continue
            frac = np.array([float(t) for t in tokens[:3]])
            positions.append(frac @ cell)
            masses.append(cur_mass)
            numbers.append(cur_z)
            for k in range(naux):
                name = aux_names[k] if k < len(aux_names) else f"aux{k}"
                aux_vals.setdefault(name, []).append(float(tokens[3 + k]))
            i += 1
    else:
        # Legacy rows: mass type x y z [vx vy vz]
        for ln in lines[body_start:]:
            tokens = ln.split()
            masses.append(float(tokens[0]))
            numbers.append(ATOMIC_NUMBERS[tokens[1]])
            frac = np.array([float(t) for t in tokens[2:5]])
            positions.append(frac @ cell)

    return CfgData(
        positions=np.asarray(positions, dtype=np.float64),
        cell=cell,
        numbers=np.asarray(numbers, dtype=np.int64),
        masses=np.asarray(masses, dtype=np.float64),
        aux={k: np.asarray(v, dtype=np.float64) for k, v in aux_vals.items()},
    )


def write_cfg(filepath: str, data: CfgData) -> None:
    """Extended-CFG writer (used by examples/tests to fabricate datasets)."""
    n = len(data.numbers)
    aux_names = list(data.aux.keys())
    inv_cell = np.linalg.inv(data.cell)
    with open(filepath, "w", encoding="utf-8") as f:
        f.write(f"Number of particles = {n}\n")
        f.write("A = 1.0 Angstrom\n")
        for i in range(3):
            for j in range(3):
                f.write(f"H0({i + 1},{j + 1}) = {data.cell[i, j]:.8f}\n")
        f.write(".NO_VELOCITY.\n")
        f.write(f"entry_count = {3 + len(aux_names)}\n")
        for k, name in enumerate(aux_names):
            f.write(f"auxiliary[{k}] = {name} [au]\n")
        order = np.argsort(data.numbers, kind="stable")
        last_z = None
        for i in order:
            z = int(data.numbers[i])
            if z != last_z:
                f.write(f"{data.masses[i]:.4f}\n{SYMBOLS_BY_NUMBER[z]}\n")
                last_z = z
            frac = data.positions[i] @ inv_cell
            row = " ".join(f"{v:.8f}" for v in frac)
            for name in aux_names:
                row += f" {data.aux[name][i]:.8f}"
            f.write(row + "\n")
