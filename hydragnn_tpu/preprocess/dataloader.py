"""Batch iterator over GraphSamples → padded GraphBatches.

Replaces torch_geometric DataLoader + torch DistributedSampler (reference
/root/reference/hydragnn/preprocess/load_data.py:53-86). Sharding follows
DistributedSampler semantics: indices are globally shuffled with a per-epoch seed
(the ``sampler.set_epoch`` contract, train_validate_test.py:96-97), padded to a
multiple of the shard count by wrapping around, then dealt round-robin so every
shard sees the same number of batches.

Recompilation control vs padding waste (SURVEY.md §7 hard part #4): with
``num_buckets=1`` the whole dataset shares one worst-case pad shape (one XLA
compile). Datasets mixing small and large graphs can set ``num_buckets=K``:
samples are partitioned into K node-count quantile buckets, each with its own
pad shape — K compiles, far less padding FLOP waste. Batches are formed within
buckets and the batch order is shuffled across buckets per epoch.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import numpy as np

from ..graphs.batch import GraphBatch
from ..graphs.collate import GraphArena, compute_pad_sizes_from_counts
from ..graphs.packing import PackCaps, SizeHistogram, first_fit_decreasing
from ..graphs.sample import GraphSample


def invalid_sample_reason(s: GraphSample) -> Optional[str]:
    """Why a sample must not reach collation (None = valid). The quarantine
    validator (docs/FAULT_TOLERANCE.md): catches corrupt/unparseable records
    — non-finite features, out-of-range edge indices, inconsistent packed
    targets — BEFORE they poison a whole padded batch (one bad sample
    otherwise NaNs the loss of every batch-mate, or crashes the collator
    mid-epoch).

    The serving admission check (serve/engine.py:InferenceEngine._validate)
    overlaps on the structural edge/x checks but is a different contract —
    request-facing, model-width-aware, no y/y_loc or finiteness (non-finite
    OUTPUTS are guarded there instead); a change to either's shared
    structural checks should be mirrored in the other."""
    x = s.x
    if x is None or np.ndim(x) != 2:
        return "x is not a [num_nodes, F] array"
    if not np.isfinite(np.asarray(x, dtype=np.float64)).all():
        return "non-finite node features"
    if s.pos is not None and not np.isfinite(
        np.asarray(s.pos, dtype=np.float64)
    ).all():
        return "non-finite node positions"
    n = int(np.shape(x)[0])
    if s.edge_index is not None:
        ei = np.asarray(s.edge_index)
        if ei.ndim != 2 or ei.shape[0] != 2:
            return "edge_index is not [2, num_edges]"
        if ei.size and (ei.min() < 0 or ei.max() >= n):
            return "edge_index references nodes outside the graph"
        if s.edge_attr is not None and np.shape(s.edge_attr)[0] != ei.shape[1]:
            return "edge_attr row count does not match num_edges"
    if s.edge_attr is not None and not np.isfinite(
        np.asarray(s.edge_attr, dtype=np.float64)
    ).all():
        return "non-finite edge attributes"
    if (s.y is None) != (s.y_loc is None):
        return "y and y_loc must be present together"
    if s.y is not None:
        y = np.asarray(s.y).reshape(-1)
        if not np.isfinite(y.astype(np.float64)).all():
            return "non-finite targets"
        y_loc = np.asarray(s.y_loc).reshape(-1)
        if y_loc.size < 2 or (np.diff(y_loc) < 0).any() or y_loc[-1] > y.size:
            return "y_loc offsets are not a valid prefix of y"
    return None


class GraphDataLoader:
    def __init__(
        self,
        dataset: Sequence[GraphSample],
        batch_size: int,
        shuffle: bool = True,
        seed: int = 0,
        num_shards: int = 1,
        shard_rank: int = 0,
        head_types: Optional[Sequence[str]] = None,
        head_dims: Optional[Sequence[int]] = None,
        edge_dim: Optional[int] = None,
        num_buckets: int = 1,
        reshuffle: str = "sample",
        skip_budget: int = 0,
        fault_plan=None,
        packing: bool = False,
        ladder_step: str = "pow2",
    ):
        """``reshuffle`` picks the per-epoch shuffling granularity:

        - ``"sample"`` (default, reference parity): batch MEMBERSHIP is
          redrawn every epoch (DistributedSampler ``set_epoch`` semantics) —
          every epoch re-collates and re-feeds fresh host batches.
        - ``"batch"``: membership is frozen at epoch 0; epochs reshuffle only
          the ORDER batches are visited. Collated batches are then cached
          after the first epoch (and the TrainingDriver additionally caches
          the stacked epoch chunks on DEVICE), so steady-state epochs do no
          host collation and no host->device transfer — the win is large
          when the device link is slow (the tunneled-TPU bucketed path) or
          the host is collation-bound. A mild SGD semantics change, which is
          why it is opt-in (``Training.reshuffle`` in the JSON config).

        ``skip_budget > 0`` enables the corrupt-sample quarantine
        (docs/FAULT_TOLERANCE.md): samples failing ``invalid_sample_reason``
        are dropped into ``self.quarantined`` (index + reason) up to the
        budget; exceeding it fails loudly WITH the quarantine log. The
        default 0 performs no validation at all — identical to the
        historical loader. ``fault_plan`` (default: HYDRAGNN_FAULTS env)
        injects seeded sample corruption for the drills.

        ``packing=True`` (``Dataset.packing``) bin-packs graphs into arena
        slots by first-fit-decreasing (graphs/packing.py) instead of cutting
        the shuffled stream every ``batch_size`` graphs: a batch then holds
        as many graphs as fit the bucket's node/edge capacity (up to 4x
        ``batch_size``), so streamed epochs run far fewer, far denser padded
        batches. Batch MEMBERSHIP becomes size-driven (ties and batch order
        still reshuffle per epoch) — a mild SGD semantics change like
        ``reshuffle="batch"``, which is why it is opt-in; same-seed
        convergence parity is locked by tests/test_packing.py.
        ``ladder_step`` picks the pad round-up ladder (``"pow2"`` historical,
        ``"mult64"``: multiples of 64 above 256 — docs/INPUT_PIPELINE.md).
        """
        if reshuffle not in ("sample", "batch"):
            raise ValueError(
                f"reshuffle must be 'sample' or 'batch', got {reshuffle!r}"
            )
        self.dataset = list(dataset)
        self.skip_budget = int(skip_budget)
        self.quarantined: List[tuple] = []
        self._apply_fault_plan(fault_plan)
        if self.skip_budget > 0:
            self._quarantine_invalid_samples()
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.num_shards = num_shards
        self.shard_rank = shard_rank
        self.head_types = tuple(head_types) if head_types else None
        self.head_dims = tuple(head_dims) if head_dims else None
        self.edge_dim = edge_dim
        self.reshuffle = reshuffle
        self.packing = bool(packing)
        self.ladder_step = ladder_step
        self.epoch = 0
        # Head-spec generation: bumped by set_head_spec so EXTERNAL caches of
        # collated/device batches (TrainingDriver._scan_cache/_eval_cache)
        # can detect staleness — the loader's own _batch_cache is cleared
        # directly, and this counter keeps the two invalidation contracts
        # symmetric.
        self.generation = 0
        self._arena = None
        self._frozen_plan = None  # reshuffle="batch": membership drawn once
        self._plan_memo = None  # (epoch, generation) -> last computed plan
        self._batch_cache: dict = {}  # plan position -> collated GraphBatch
        # Host-RAM cap for the collation cache (padded batches can be several
        # times the raw dataset): once exceeded, later positions are simply
        # re-collated each epoch. Distinct from the driver's device-cache
        # budget (HYDRAGNN_DEVICE_CACHE_MB) — different resource.
        import os as _os

        self._cache_budget = int(
            _os.environ.get("HYDRAGNN_HOST_CACHE_MB", "1024")
        ) * (1 << 20)
        self._cache_bytes = 0
        # Per-sample size arrays (packing + per-batch accounting) and the
        # per-run size record the ladder fitter consumes
        # (``python -m hydragnn_tpu.graphs.packing fit-ladder``).
        self._ns = np.fromiter(
            (s.num_nodes for s in self.dataset), np.int64, len(self.dataset)
        )
        self._es = np.fromiter(
            (s.num_edges for s in self.dataset), np.int64, len(self.dataset)
        )
        self.size_histogram = SizeHistogram()
        for n, e in zip(self._ns.tolist(), self._es.tolist()):
            self.size_histogram.record_graph(n, e)
        self._pad_stats = self._zero_pad_stats()
        self._num_buckets_requested = max(1, int(num_buckets))
        self._build_buckets(self._num_buckets_requested)

    def _apply_fault_plan(self, fault_plan) -> None:
        """Seeded corrupt-sample injection (the quarantine drill). Runs
        BEFORE validation so the loader both injects and catches its own
        drill corruption in one construction."""
        from ..faults.plan import FaultPlan

        plan = fault_plan if fault_plan is not None else FaultPlan.from_env()
        if plan is not None and (plan.corrupt_count or plan.corrupt_frac):
            plan.corrupt_dataset(self.dataset)

    def _quarantine_invalid_samples(self) -> None:
        """Drop invalid samples (bounded by ``skip_budget``) before buckets
        and pad shapes are computed, so the surviving dataset is exactly what
        every later stage sees. Exceeding the budget raises with the log —
        a dataset that corrupt can only be fixed upstream, and silently
        training on its remainder would misreport coverage."""
        from ..faults.counters import FaultCounters

        kept = []
        for i, s in enumerate(self.dataset):
            reason = invalid_sample_reason(s)
            if reason is None:
                kept.append(s)
            else:
                self.quarantined.append((i, reason))
        if len(self.quarantined) > self.skip_budget:
            log = "; ".join(
                f"sample {i}: {r}" for i, r in self.quarantined[:10]
            )
            raise RuntimeError(
                f"quarantine budget exceeded: {len(self.quarantined)} corrupt "
                f"samples > skip_budget={self.skip_budget} — {log}"
                + (" ..." if len(self.quarantined) > 10 else "")
            )
        if self.quarantined:
            FaultCounters.inc("quarantined_samples", len(self.quarantined))
            self.dataset = kept

    def _build_buckets(self, num_buckets: int) -> None:
        """Partition dataset indices into node-count quantile buckets, each
        with its own static pad shape."""
        n = int(self._ns.size)
        if n == 0:
            self._buckets = []
            self._bucket_pads = []
            self._pack_caps = []
            return
        sizes = self._ns  # one source of truth for per-sample node counts
        num_buckets = min(num_buckets, n)
        order = np.argsort(sizes, kind="stable")
        splits = np.array_split(order, num_buckets)
        # Merge buckets that collapsed to identical size ranges (uniform data).
        buckets: List[np.ndarray] = []
        for part in splits:
            if len(part) == 0:
                continue
            if buckets and sizes[part].max() == sizes[buckets[-1]].max() and (
                sizes[part].min() == sizes[buckets[-1]].min()
            ):
                buckets[-1] = np.concatenate([buckets[-1], part])
            else:
                buckets.append(part)
        # Keep ascending dataset order WITHIN each bucket: with shuffle=False
        # and num_buckets=1 iteration order is exactly dataset order (the
        # eval-loader guarantee documented in load_data.create_dataloaders).
        self._buckets = [np.sort(b) for b in buckets]
        # Pad shapes from the count arrays alone (not the sample objects):
        # the streaming subclass (datasets/stream.py) shares this method with
        # nothing but the GSHD index in RAM.
        self._bucket_pads = [
            compute_pad_sizes_from_counts(
                self._ns[b],
                self._es[b],
                self.batch_size,
                ladder_step=self.ladder_step,
            )
            for b in self._buckets
        ]
        # Packing: the bucket's worst-case pad shape becomes a CAPACITY the
        # packer fills with however many graphs fit (bounded at 4x batch_size
        # so G_pad stays a sane static dimension); G_pad grows to the graph
        # capacity + the reserved padding graph.
        self._pack_caps = []
        if self.packing:
            pads = []
            for b, (n_pad, e_pad, _) in zip(self._buckets, self._bucket_pads):
                min_n = max(1, int(sizes[b].min()))
                g_cap = int(
                    min(
                        max(self.batch_size, (n_pad - 1) // min_n),
                        4 * self.batch_size,
                    )
                )
                self._pack_caps.append(
                    PackCaps(nodes=n_pad - 1, edges=e_pad, graphs=g_cap)
                )
                pads.append((n_pad, e_pad, g_cap + 1))
            self._bucket_pads = pads

    # -- reference parity: sampler.set_epoch reshuffles DP shards each epoch.
    def set_epoch(self, epoch: int) -> None:
        self.epoch = int(epoch)

    def set_head_spec(
        self, head_types: Sequence[str], head_dims: Sequence[int]
    ) -> None:
        """Called by config completion once output heads are inferred from data."""
        self.head_types = tuple(head_types)
        self.head_dims = tuple(head_dims)
        self._batch_cache.clear()  # cached collations baked the old spec
        self._cache_bytes = 0
        self.generation += 1  # external (driver) caches key on this

    def set_packing(
        self, enabled: bool, ladder_step: Optional[str] = None
    ) -> None:
        """Toggle graph packing (and optionally the round-up ladder) after
        construction: rebuilds bucket pads/capacities, drops cached
        collations and the frozen plan, and bumps ``generation`` so external
        caches of collated/device batches (TrainingDriver scan/eval caches)
        detect the shape change — the same invalidation contract as
        ``set_head_spec``."""
        self.packing = bool(enabled)
        if ladder_step is not None:
            self.ladder_step = ladder_step
        self._frozen_plan = None
        self._batch_cache.clear()
        self._cache_bytes = 0
        self.generation += 1
        self._build_buckets(self._num_buckets_requested)

    @staticmethod
    def _zero_pad_stats() -> dict:
        return {
            "batches": 0,
            "real_nodes": 0,
            "pad_nodes": 0,
            "real_edges": 0,
            "pad_edges": 0,
            "real_graphs": 0,
            "pad_graphs": 0,
        }

    def reset_padding_stats(self) -> None:
        self._pad_stats = self._zero_pad_stats()

    def padding_stats(self) -> dict:
        """Padded-row accounting over every batch yielded since the last
        reset: waste = share of compiled rows that carried no real
        node/edge/graph (the serving metrics' ``padding_waste_*`` definition,
        on the training side). Surfaced by ``bench.py --packing``."""
        st = dict(self._pad_stats)
        for kind in ("nodes", "edges", "graphs"):
            pad = st[f"pad_{kind}"]
            st[f"padding_waste_{kind}"] = (
                round(1.0 - st[f"real_{kind}"] / pad, 4) if pad else None
            )
        return st

    def write_size_histogram(self, path: str) -> None:
        """Persist this run's observed sizes for the ladder fitter
        (``python -m hydragnn_tpu.graphs.packing fit-ladder --hist <path>``)."""
        self.size_histogram.save(path)

    @property
    def pad_sizes(self):
        """Worst-case pad shape every batch fits (elementwise max over
        buckets — the largest-node bucket need not have the most edges)."""
        if not self._bucket_pads:
            return (0, 0, 0)
        return tuple(max(p[i] for p in self._bucket_pads) for i in range(3))

    @property
    def num_buckets(self) -> int:
        return len(self._buckets)

    def _shard(self, idx: np.ndarray, rng: Optional[np.random.Generator]):
        if self.shuffle and rng is not None:
            idx = idx.copy()
            rng.shuffle(idx)
        if self.num_shards > 1:
            # Wrap-pad so all shards get equal counts (DistributedSampler does
            # the same duplication), then deal round-robin.
            per_shard = -(-len(idx) // self.num_shards)
            padded = np.resize(idx, per_shard * self.num_shards)
            idx = padded[self.shard_rank :: self.num_shards]
        return idx

    def _batch_plan(self) -> List[tuple]:
        """[(plan_pos, bucket_id, [sample indices])] for this epoch.

        reshuffle="sample": membership redrawn per epoch from
        rng(seed+epoch); batch order shuffled across buckets.
        reshuffle="batch": membership drawn ONCE from rng(seed) and frozen
        (plan_pos is a stable identity — the collation cache and the
        driver's device cache key on it); only the visit ORDER reshuffles
        per epoch.

        The plan is a pure function of (epoch, generation), so it is
        memoized per epoch: ``__len__`` + ``__iter__`` in the same epoch
        pay the shuffle/packing planning cost once (the FFD packer is
        O(items x bins) Python — cheap at this framework's host-RAM dataset
        sizes, but not free to re-run casually)."""
        key = (self.epoch, self.generation)
        if self._plan_memo is not None and self._plan_memo[0] == key:
            return self._plan_memo[1]
        plan = self._compute_batch_plan()
        self._plan_memo = (key, plan)
        return plan

    def _compute_batch_plan(self) -> List[tuple]:
        if self.reshuffle == "batch" and self.shuffle:
            if self._frozen_plan is None:
                rng = np.random.default_rng(self.seed)
                plan = []
                for bi, bucket in enumerate(self._buckets):
                    idx = self._shard(np.asarray(bucket), rng)
                    for members in self._plan_bucket(bi, idx):
                        plan.append((bi, members))
                self._frozen_plan = [
                    (pos, bi, idx) for pos, (bi, idx) in enumerate(plan)
                ]
            order = np.random.default_rng(self.seed + self.epoch).permutation(
                len(self._frozen_plan)
            )
            return [self._frozen_plan[i] for i in order]
        rng = (
            np.random.default_rng(self.seed + self.epoch)
            if self.shuffle
            else None
        )
        plan = []
        for bi, bucket in enumerate(self._buckets):
            idx = self._shard(np.asarray(bucket), rng)
            for members in self._plan_bucket(bi, idx):
                plan.append((bi, members))
        # Packed plans come out of FFD largest-bin-first; restore random
        # visit order (multi-bucket plans always reshuffled, as before).
        if rng is not None and (len(self._buckets) > 1 or self.packing):
            rng.shuffle(plan)
        return [(None, bi, idx) for bi, idx in plan]

    def _plan_bucket(self, bi: int, idx: np.ndarray) -> List[np.ndarray]:
        """Split one bucket's (sharded, shuffled) index stream into batch
        membership arrays: fixed ``batch_size`` cuts, or — with packing —
        first-fit-decreasing bins under the bucket's (nodes, edges, graphs)
        capacity. The shuffled ``idx`` order is the packer's tie-break, so
        equal-size graphs still migrate between batches across epochs."""
        if not self.packing:
            return [
                idx[start : start + self.batch_size]
                for start in range(0, len(idx), self.batch_size)
            ]
        bins = first_fit_decreasing(
            self._ns[idx], self._es[idx], self._pack_caps[bi]
        )
        return [idx[members] for members in bins]

    def __len__(self) -> int:
        return len(self._batch_plan())

    def __iter__(self) -> Iterator[GraphBatch]:
        if self._arena is None and self.dataset:
            # Built once per dataset: batches become pure numpy gathers over
            # contiguous arenas (the per-sample Python walk in collate_graphs
            # caps a prefetch thread well below TPU consumption rate).
            self._arena = GraphArena(self.dataset)
        for pos, bi, sample_idx in self._batch_plan():
            n_pad, e_pad, g_pad = self._bucket_pads[bi]
            # Per-batch size record + padded-row accounting (cached yields
            # included — the device executes the same padded shape either
            # way). Feeds the ladder fitter and bench.py --packing.
            tot_n = int(self._ns[sample_idx].sum())
            tot_e = int(self._es[sample_idx].sum())
            self.size_histogram.record_batch(tot_n, tot_e, len(sample_idx))
            st = self._pad_stats
            st["batches"] += 1
            st["real_nodes"] += tot_n
            st["pad_nodes"] += n_pad
            st["real_edges"] += tot_e
            st["pad_edges"] += e_pad
            st["real_graphs"] += len(sample_idx)
            st["pad_graphs"] += g_pad
            if pos is not None and pos in self._batch_cache:
                yield self._batch_cache[pos]
                continue
            batch = self._arena.collate(
                sample_idx,
                head_types=self.head_types or (),
                head_dims=self.head_dims or (),
                num_nodes_pad=n_pad,
                num_edges_pad=e_pad,
                num_graphs_pad=g_pad,
                edge_dim=self.edge_dim,
            )
            if pos is not None:
                # Frozen membership (reshuffle="batch"): the collation is
                # deterministic per position, so cache it — up to the host
                # byte budget. Invalidated when the head spec changes.
                import jax as _jax

                nbytes = sum(
                    getattr(l, "nbytes", 0)
                    for l in _jax.tree_util.tree_leaves(batch)
                )
                if self._cache_bytes + nbytes <= self._cache_budget:
                    self._batch_cache[pos] = batch
                    self._cache_bytes += nbytes
            yield batch
