"""Batch iterator over GraphSamples → padded GraphBatches.

Replaces torch_geometric DataLoader + torch DistributedSampler (reference
/root/reference/hydragnn/preprocess/load_data.py:53-86). Sharding follows
DistributedSampler semantics: indices are globally shuffled with a per-epoch seed
(the ``sampler.set_epoch`` contract, train_validate_test.py:96-97), padded to a
multiple of the shard count by wrapping around, then dealt round-robin so every
shard sees the same number of batches. Pad sizes are computed once over the whole
dataset so every shard/batch compiles to the same XLA shapes.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import numpy as np

from ..graphs.batch import GraphBatch
from ..graphs.collate import collate_graphs, compute_pad_sizes
from ..graphs.sample import GraphSample


class GraphDataLoader:
    def __init__(
        self,
        dataset: Sequence[GraphSample],
        batch_size: int,
        shuffle: bool = True,
        seed: int = 0,
        num_shards: int = 1,
        shard_rank: int = 0,
        head_types: Optional[Sequence[str]] = None,
        head_dims: Optional[Sequence[int]] = None,
        edge_dim: Optional[int] = None,
    ):
        self.dataset = list(dataset)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.num_shards = num_shards
        self.shard_rank = shard_rank
        self.head_types = tuple(head_types) if head_types else None
        self.head_dims = tuple(head_dims) if head_dims else None
        self.edge_dim = edge_dim
        self.epoch = 0
        if self.dataset:
            self._n_pad, self._e_pad, self._g_pad = compute_pad_sizes(
                self.dataset, batch_size
            )
        else:
            self._n_pad = self._e_pad = self._g_pad = 0

    # -- reference parity: sampler.set_epoch reshuffles DP shards each epoch.
    def set_epoch(self, epoch: int) -> None:
        self.epoch = int(epoch)

    def set_head_spec(
        self, head_types: Sequence[str], head_dims: Sequence[int]
    ) -> None:
        """Called by config completion once output heads are inferred from data."""
        self.head_types = tuple(head_types)
        self.head_dims = tuple(head_dims)

    @property
    def pad_sizes(self):
        return self._n_pad, self._e_pad, self._g_pad

    def _shard_indices(self) -> List[int]:
        n = len(self.dataset)
        idx = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(idx)
        if self.num_shards > 1:
            # Wrap-pad so all shards get equal counts (DistributedSampler does
            # the same duplication), then deal round-robin.
            per_shard = -(-n // self.num_shards)
            padded = np.resize(idx, per_shard * self.num_shards)
            idx = padded[self.shard_rank :: self.num_shards]
        return idx.tolist()

    def __len__(self) -> int:
        n = len(self._shard_indices())
        return -(-n // self.batch_size) if n else 0

    def __iter__(self) -> Iterator[GraphBatch]:
        idx = self._shard_indices()
        for start in range(0, len(idx), self.batch_size):
            chunk = [self.dataset[i] for i in idx[start : start + self.batch_size]]
            yield collate_graphs(
                chunk,
                head_types=self.head_types or (),
                head_dims=self.head_dims or (),
                num_nodes_pad=self._n_pad,
                num_edges_pad=self._e_pad,
                num_graphs_pad=self._g_pad,
                edge_dim=self.edge_dim,
            )
