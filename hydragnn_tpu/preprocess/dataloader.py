"""Batch iterator over GraphSamples → padded GraphBatches.

Replaces torch_geometric DataLoader + torch DistributedSampler (reference
/root/reference/hydragnn/preprocess/load_data.py:53-86). Sharding follows
DistributedSampler semantics: indices are globally shuffled with a per-epoch seed
(the ``sampler.set_epoch`` contract, train_validate_test.py:96-97), padded to a
multiple of the shard count by wrapping around, then dealt round-robin so every
shard sees the same number of batches.

Recompilation control vs padding waste (SURVEY.md §7 hard part #4): with
``num_buckets=1`` the whole dataset shares one worst-case pad shape (one XLA
compile). Datasets mixing small and large graphs can set ``num_buckets=K``:
samples are partitioned into K node-count quantile buckets, each with its own
pad shape — K compiles, far less padding FLOP waste. Batches are formed within
buckets and the batch order is shuffled across buckets per epoch.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import numpy as np

from ..graphs.batch import GraphBatch
from ..graphs.collate import GraphArena, compute_pad_sizes
from ..graphs.sample import GraphSample


class GraphDataLoader:
    def __init__(
        self,
        dataset: Sequence[GraphSample],
        batch_size: int,
        shuffle: bool = True,
        seed: int = 0,
        num_shards: int = 1,
        shard_rank: int = 0,
        head_types: Optional[Sequence[str]] = None,
        head_dims: Optional[Sequence[int]] = None,
        edge_dim: Optional[int] = None,
        num_buckets: int = 1,
    ):
        self.dataset = list(dataset)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.num_shards = num_shards
        self.shard_rank = shard_rank
        self.head_types = tuple(head_types) if head_types else None
        self.head_dims = tuple(head_dims) if head_dims else None
        self.edge_dim = edge_dim
        self.epoch = 0
        self._arena = None
        self._build_buckets(max(1, int(num_buckets)))

    def _build_buckets(self, num_buckets: int) -> None:
        """Partition dataset indices into node-count quantile buckets, each
        with its own static pad shape."""
        n = len(self.dataset)
        if n == 0:
            self._buckets = []
            self._bucket_pads = []
            return
        sizes = np.array([s.num_nodes for s in self.dataset])
        num_buckets = min(num_buckets, n)
        order = np.argsort(sizes, kind="stable")
        splits = np.array_split(order, num_buckets)
        # Merge buckets that collapsed to identical size ranges (uniform data).
        buckets: List[np.ndarray] = []
        for part in splits:
            if len(part) == 0:
                continue
            if buckets and sizes[part].max() == sizes[buckets[-1]].max() and (
                sizes[part].min() == sizes[buckets[-1]].min()
            ):
                buckets[-1] = np.concatenate([buckets[-1], part])
            else:
                buckets.append(part)
        # Keep ascending dataset order WITHIN each bucket: with shuffle=False
        # and num_buckets=1 iteration order is exactly dataset order (the
        # eval-loader guarantee documented in load_data.create_dataloaders).
        self._buckets = [np.sort(b) for b in buckets]
        self._bucket_pads = [
            compute_pad_sizes([self.dataset[i] for i in b], self.batch_size)
            for b in self._buckets
        ]

    # -- reference parity: sampler.set_epoch reshuffles DP shards each epoch.
    def set_epoch(self, epoch: int) -> None:
        self.epoch = int(epoch)

    def set_head_spec(
        self, head_types: Sequence[str], head_dims: Sequence[int]
    ) -> None:
        """Called by config completion once output heads are inferred from data."""
        self.head_types = tuple(head_types)
        self.head_dims = tuple(head_dims)

    @property
    def pad_sizes(self):
        """Worst-case pad shape every batch fits (elementwise max over
        buckets — the largest-node bucket need not have the most edges)."""
        if not self._bucket_pads:
            return (0, 0, 0)
        return tuple(max(p[i] for p in self._bucket_pads) for i in range(3))

    @property
    def num_buckets(self) -> int:
        return len(self._buckets)

    def _shard(self, idx: np.ndarray, rng: Optional[np.random.Generator]):
        if self.shuffle and rng is not None:
            idx = idx.copy()
            rng.shuffle(idx)
        if self.num_shards > 1:
            # Wrap-pad so all shards get equal counts (DistributedSampler does
            # the same duplication), then deal round-robin.
            per_shard = -(-len(idx) // self.num_shards)
            padded = np.resize(idx, per_shard * self.num_shards)
            idx = padded[self.shard_rank :: self.num_shards]
        return idx

    def _batch_plan(self) -> List[tuple]:
        """[(bucket_id, [sample indices])] for this epoch, batch order shuffled
        across buckets."""
        rng = (
            np.random.default_rng(self.seed + self.epoch)
            if self.shuffle
            else None
        )
        plan = []
        for bi, bucket in enumerate(self._buckets):
            idx = self._shard(np.asarray(bucket), rng)
            for start in range(0, len(idx), self.batch_size):
                plan.append((bi, idx[start : start + self.batch_size]))
        if rng is not None and len(self._buckets) > 1:
            rng.shuffle(plan)
        return plan

    def __len__(self) -> int:
        return len(self._batch_plan())

    def __iter__(self) -> Iterator[GraphBatch]:
        if self._arena is None and self.dataset:
            # Built once per dataset: batches become pure numpy gathers over
            # contiguous arenas (the per-sample Python walk in collate_graphs
            # caps a prefetch thread well below TPU consumption rate).
            self._arena = GraphArena(self.dataset)
        for bi, sample_idx in self._batch_plan():
            n_pad, e_pad, g_pad = self._bucket_pads[bi]
            yield self._arena.collate(
                sample_idx,
                head_types=self.head_types or (),
                head_dims=self.head_dims or (),
                num_nodes_pad=n_pad,
                num_edges_pad=e_pad,
                num_graphs_pad=g_pad,
                edge_dim=self.edge_dim,
            )
