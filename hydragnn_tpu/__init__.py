"""hydragnn_tpu — TPU-native (JAX/XLA/pjit) multi-headed graph neural network
trainer with the capabilities of HydraGNN (reference: /root/reference).

Public API mirrors the reference (hydragnn/__init__.py:1-3): two entry functions
driven by one JSON config, plus the composable mid-level pieces."""

from . import (
    datasets,
    graphs,
    models,
    ops,
    parallel,
    postprocess,
    preprocess,
    tools,
    train,
    utils,
)
from .run_training import run_training
from .run_prediction import run_prediction

# Imported after the subpackages above: serve builds on models/train/graphs;
# faults threads through train/preprocess/serve (fault injection, non-finite
# guard policy, crash-resume supervisor); analysis is the static-analysis
# layer (graftlint, check-config, recompile sentinel — docs/STATIC_ANALYSIS.md).
from . import analysis, faults, serve

__version__ = "0.1.0"
