"""Replica backends behind one interface (docs/SERVING.md "Multi-replica
tier").

The router dispatches to :class:`Replica` objects and never sees what is
behind them:

* :class:`InProcessReplica` — an ``InferenceEngine`` in this process (the
  test/bench topology, and the ``--replicas N`` CLI mode where one host
  runs several engines over one shared graftcache store);
* :class:`HttpReplica` — a ``python -m hydragnn_tpu.serve`` process reached
  over HTTP (same host via :func:`spawn_serve_replica`, or any remote
  host). Correlation ids ride the ``X-HydraGNN-Request-Id`` header both
  ways, so a request keeps one id across replica hops.

Error taxonomy (what the router's retry logic keys on):

* :class:`ReplicaBackpressureError` — the replica shed load (engine 429
  path); carries the replica's own retry-after hint and queue depth. The
  replica is HEALTHY; the router may retry elsewhere within the request's
  deadline or surface the hint fleet-wide.
* :class:`ReplicaDownError` — the replica cannot serve (poisoned/closed
  engine, connection refused, 503). The router retries elsewhere and the
  health loop confirms ejection.

Anything else (per-request validation errors, timeouts) propagates: a
malformed graph is malformed on every replica — retrying would amplify it.
"""

from __future__ import annotations

import json
import re
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..graphs.sample import GraphSample
from ..serve.server import MODEL_VERSION_HEADER, REQUEST_ID_HEADER


class ReplicaError(RuntimeError):
    """Base class for dispatch failures the router knows how to handle."""


class ReplicaBackpressureError(ReplicaError):
    """The replica shed this request (its bounded queue is full)."""

    def __init__(
        self,
        message: str,
        retry_after_s: float,
        queue_depth: Optional[int] = None,
    ):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)
        self.queue_depth = queue_depth


class ReplicaDownError(ReplicaError):
    """The replica cannot serve at all (poisoned, closed, unreachable)."""


class Replica:
    """One engine replica the router can dispatch to.

    Implementations must be safe to call from multiple router caller
    threads concurrently (both backends are: the engine's submit path and
    one-urllib-connection-per-call are thread-safe).
    """

    name: str = ""

    def predict(
        self,
        samples: Sequence[GraphSample],
        timeout: float = 60.0,
        request_id: Optional[str] = None,
    ) -> List[List[np.ndarray]]:
        """One synchronous prediction call; per-graph per-head outputs,
        numerically identical to a direct ``InferenceEngine.predict``."""
        raise NotImplementedError

    def predict_versioned(
        self,
        samples: Sequence[GraphSample],
        timeout: float = 60.0,
        request_id: Optional[str] = None,
    ) -> Tuple[List[List[np.ndarray]], Optional[str]]:
        """``(results, model_version)`` — the version tag the lifecycle
        layer threads through RouteResult and the response header
        (docs/SERVING.md "Live model lifecycle"). Backends that cannot
        report a version return None; both shipped backends can."""
        return self.predict(samples, timeout=timeout, request_id=request_id), None

    def health(self) -> Dict[str, Any]:
        """The replica's /healthz view (ok, degraded, queue depth, compiled
        buckets, fault counters, hydration counters). Raising == down."""
        raise NotImplementedError

    def swap_checkpoint(
        self,
        path: str,
        version: Optional[str] = None,
        expected_identity: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Hot-swap this replica onto the v2 checkpoint at ``path`` (a
        shared-storage path the replica's own process can read) — the
        fleet-orchestration surface ``LifecycleManager`` drives for replicas
        it holds no engine object for (docs/SERVING.md "Live model
        lifecycle"). Same refusal semantics as ``engine.swap_weights``:
        identity/fingerprint/tolerance mismatches raise and the replica
        keeps serving its current version."""
        raise NotImplementedError

    def close(self) -> None:  # pragma: no cover - interface default
        pass


class InProcessReplica(Replica):
    """An ``InferenceEngine`` in this process."""

    def __init__(self, name: str, engine):
        self.name = str(name)
        self.engine = engine

    def predict(
        self,
        samples: Sequence[GraphSample],
        timeout: float = 60.0,
        request_id: Optional[str] = None,
    ) -> List[List[np.ndarray]]:
        return self.predict_versioned(
            samples, timeout=timeout, request_id=request_id
        )[0]

    def predict_versioned(
        self,
        samples: Sequence[GraphSample],
        timeout: float = 60.0,
        request_id: Optional[str] = None,
    ) -> Tuple[List[List[np.ndarray]], Optional[str]]:
        from ..serve.engine import (
            BackpressureError,
            EngineClosedError,
            EngineFailedError,
        )

        try:
            results, versions = self.engine.predict_versioned(
                samples, timeout=timeout, request_id=request_id
            )
        except BackpressureError as e:
            raise ReplicaBackpressureError(
                str(e),
                retry_after_s=e.retry_after_s,
                queue_depth=self.engine._queue.qsize(),
            ) from e
        except (EngineClosedError, EngineFailedError) as e:
            raise ReplicaDownError(
                f"replica {self.name}: {e}"
            ) from e
        tagged = [v for v in versions if v]
        return results, tagged[-1] if tagged else None

    def health(self) -> Dict[str, Any]:
        engine = self.engine
        counters = engine.metrics.read_counters(
            "bad_batches_total",
            "nonfinite_total",
            "engine_restarts_total",
            "exec_cache_hydrated_total",
            "cache_misses_total",
            "weight_swaps_total",
            "swap_rejected_total",
        )
        # Mirrors the HTTP /healthz payload (serve/server.py) so the router
        # consumes ONE schema regardless of backend.
        return {
            "ok": engine.running,
            "degraded": engine.degraded,
            "degraded_events": engine.degraded_events,
            "queue_depth": engine._queue.qsize(),
            "queue_limit": engine.queue_limit,
            "compiled_buckets": engine.compiled_buckets,
            "precision": engine.precision,
            "model_version": engine.model_version,
            "weight_swaps": counters["weight_swaps_total"],
            "swaps_rejected": counters["swap_rejected_total"],
            "bad_batches": counters["bad_batches_total"],
            "nonfinite_outputs": counters["nonfinite_total"],
            "restarts": counters["engine_restarts_total"],
            "hydrated_buckets": counters["exec_cache_hydrated_total"],
            "compiled_fresh_buckets": counters["cache_misses_total"],
            "replica": self.name,
        }

    def swap_checkpoint(
        self,
        path: str,
        version: Optional[str] = None,
        expected_identity: Optional[str] = None,
    ) -> Dict[str, Any]:
        from ..serve.engine import swap_from_checkpoint

        return swap_from_checkpoint(
            self.engine, path, version=version,
            expected_identity=expected_identity,
        )

    def close(self) -> None:
        self.engine.close()


def graph_doc(sample: GraphSample) -> Dict[str, Any]:
    """One GraphSample as the /predict request-graph JSON object (the
    inverse of serve/server.py ``parse_graph``)."""
    doc: Dict[str, Any] = {"x": np.asarray(sample.x).tolist()}
    if sample.edge_index is not None:
        doc["edge_index"] = np.asarray(sample.edge_index).tolist()
    if sample.edge_attr is not None:
        doc["edge_attr"] = np.asarray(sample.edge_attr).tolist()
    if sample.pos is not None:
        doc["pos"] = np.asarray(sample.pos).tolist()
    return doc


class HttpReplica(Replica):
    """A serve process reached over HTTP (subprocess or remote host).

    Numerical note: /predict serializes float32 outputs via ``tolist()``
    (repr round-trip, exact for float32) and this class casts back to
    float32 — HTTP replicas stay bit-exact with in-process ones.

    ``health_timeout_s`` bounds the /healthz probe separately from request
    traffic: the router's health loop polls replicas SEQUENTIALLY, so a
    wedged replica holding a 60 s request timeout would freeze the whole
    fleet's drain/eject/readmit cadence — a health probe that cannot answer
    in a few seconds IS the down signal.
    """

    def __init__(
        self,
        name: str,
        base_url: str,
        timeout_s: float = 60.0,
        health_timeout_s: float = 5.0,
    ):
        self.name = str(name)
        self.base_url = base_url.rstrip("/")
        self.timeout_s = float(timeout_s)
        self.health_timeout_s = float(health_timeout_s)

    def _read_json(self, resp) -> Dict[str, Any]:
        try:
            return json.loads(resp.read() or b"{}")
        except (ValueError, OSError):
            return {}

    def predict(
        self,
        samples: Sequence[GraphSample],
        timeout: float = 60.0,
        request_id: Optional[str] = None,
    ) -> List[List[np.ndarray]]:
        return self.predict_versioned(
            samples, timeout=timeout, request_id=request_id
        )[0]

    def predict_versioned(
        self,
        samples: Sequence[GraphSample],
        timeout: float = 60.0,
        request_id: Optional[str] = None,
    ) -> Tuple[List[List[np.ndarray]], Optional[str]]:
        body = json.dumps(
            {"graphs": [graph_doc(s) for s in samples]}
        ).encode()
        headers = {"Content-Type": "application/json"}
        if request_id:
            headers[REQUEST_ID_HEADER] = request_id
        req = urllib.request.Request(
            self.base_url + "/predict", data=body, headers=headers
        )
        version: Optional[str] = None
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                doc = self._read_json(resp)
                version = (
                    doc.get("model_version")
                    or resp.headers.get(MODEL_VERSION_HEADER)
                )
        except urllib.error.HTTPError as e:
            payload = self._read_json(e)
            if e.code == 429:
                raise ReplicaBackpressureError(
                    payload.get("error", "replica backpressure"),
                    retry_after_s=float(
                        payload.get("retry_after_s")
                        or e.headers.get("Retry-After")
                        or 1.0
                    ),
                ) from e
            if e.code in (502, 503):
                raise ReplicaDownError(
                    f"replica {self.name}: HTTP {e.code}: "
                    f"{payload.get('error', '')}"
                ) from e
            if e.code == 400:
                raise ValueError(
                    payload.get("error", f"replica rejected request: {e}")
                ) from e
            if e.code == 504:
                raise TimeoutError(
                    payload.get("error", "replica request timed out")
                ) from e
            raise ReplicaError(
                f"replica {self.name}: HTTP {e.code}"
            ) from e
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            raise ReplicaDownError(f"replica {self.name}: {e}") from e
        return [
            [np.asarray(h, dtype=np.float32) for h in per_graph]
            for per_graph in doc["predictions"]
        ], version

    def swap_checkpoint(
        self,
        path: str,
        version: Optional[str] = None,
        expected_identity: Optional[str] = None,
    ) -> Dict[str, Any]:
        """POST /swap on the replica (it must run with ``--admin``): the
        replica loads ``path`` from ITS filesystem — a fleet shares the
        checkpoint store the same way it shares the graftcache store."""
        doc: Dict[str, Any] = {"checkpoint": path}
        if version:
            doc["version"] = version
        if expected_identity:
            doc["expected_identity"] = expected_identity
        req = urllib.request.Request(
            self.base_url + "/swap",
            data=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                return self._read_json(resp)
        except urllib.error.HTTPError as e:
            payload = self._read_json(e)
            err = payload.get("error", f"HTTP {e.code}")
            if e.code in (502, 503):
                raise ReplicaDownError(f"replica {self.name}: {err}") from e
            # 403 (admin disabled), 409 (refused swap), 400 (bad file): the
            # replica is healthy and KEPT its version — surface the refusal.
            raise ReplicaError(
                f"replica {self.name}: swap refused (HTTP {e.code}): {err}"
            ) from e
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            raise ReplicaDownError(f"replica {self.name}: {e}") from e

    def health(self) -> Dict[str, Any]:
        try:
            with urllib.request.urlopen(
                self.base_url + "/healthz", timeout=self.health_timeout_s
            ) as resp:
                return self._read_json(resp)
        except urllib.error.HTTPError as e:
            if e.code == 503:  # down-but-answering: the payload is honest
                doc = self._read_json(e)
                doc.setdefault("ok", False)
                return doc
            raise ReplicaDownError(
                f"replica {self.name}: healthz HTTP {e.code}"
            ) from e
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            raise ReplicaDownError(
                f"replica {self.name}: healthz {e}"
            ) from e


_LISTEN_RE = re.compile(r"listening on (http://[\w.:\-]+)")


def spawn_serve_replica(
    name: str,
    serve_args: Sequence[str],
    startup_timeout_s: float = 300.0,
) -> Tuple[HttpReplica, "subprocess.Popen[str]"]:
    """Spawn ``python -m hydragnn_tpu.serve <serve_args>`` as a subprocess
    replica and return (HttpReplica, process) once its listen line appears.

    Pass ``--port 0`` in ``serve_args`` for an ephemeral port — the bound
    address is parsed from the server's startup line. Point every spawned
    replica's ``--compile-cache`` at the shared graftcache store so spin-up
    hydrates instead of compiling (docs/COMPILE_CACHE.md). The caller owns
    the process (terminate it after ``replica.close()``)."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "hydragnn_tpu.serve", *serve_args],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    # The pipe is scanned on a reader thread: readline() has no timeout, so
    # a child that stays alive but never prints (wedged checkpoint load,
    # silent hang) must not block the caller past startup_timeout_s — the
    # deadline is enforced on the Event wait, and the reader dies with the
    # killed process's EOF.
    lines: List[str] = []
    url_box: List[str] = []
    found = threading.Event()

    def _scan() -> None:
        assert proc.stdout is not None
        for line in proc.stdout:
            if not found.is_set():
                lines.append(line)
                m = _LISTEN_RE.search(line)
                if m:
                    url_box.append(m.group(1))
                    found.set()
            # After startup keep DRAINING (and discarding) the merged
            # stdout/stderr pipe for the replica's lifetime: a child that
            # keeps logging into a full 64 KB pipe would block mid-write
            # and wedge the serve process.
        found.set()  # EOF without a listen line: stop waiting

    reader = threading.Thread(
        target=_scan, name="hydragnn-route-spawn-reader", daemon=True
    )
    reader.start()
    t0 = time.monotonic()
    while time.monotonic() - t0 < startup_timeout_s:
        if found.wait(timeout=0.25):
            break
        if proc.poll() is not None:
            found.wait(timeout=2.0)  # let the reader drain the final output
            break
    if url_box:
        return HttpReplica(name, url_box[0]), proc
    proc.kill()
    raise RuntimeError(
        f"spawned replica {name!r} never printed its listen line within "
        f"{startup_timeout_s:g}s; output:\n" + "".join(lines[-20:])
    )
