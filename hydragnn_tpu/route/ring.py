"""Weighted consistent-hash ring for the front router (docs/SERVING.md
"Multi-replica tier").

Why consistent hashing at all: the serve engines behind the router keep
per-bucket compiled-executable caches AND micro-batch across requests, so
steady request->replica affinity (same correlation-id prefix lands on the
same replica) keeps each replica's working set of bucket shapes small and
its micro-batches full. A plain round-robin would spray every bucket shape
across every replica. The ring makes membership changes cheap too: adding
or removing one replica moves only ~1/N of the keyspace (locked by
tests/test_route.py's bounded-movement test), so a drain or a warm
spin-up does not reshuffle the whole fleet's affinity.

The ring is deliberately NOT thread-safe: the owning ``Router`` mutates and
queries it exclusively under its own ``_lock`` (graftrace-checked there).
"""

from __future__ import annotations

import bisect
import hashlib
import math
from typing import Dict, List, Optional


def _point(label: str) -> int:
    """Stable 64-bit ring position for a label (sha256 prefix — no Python
    ``hash()``: ring layout must agree across processes and restarts)."""
    return int.from_bytes(hashlib.sha256(label.encode()).digest()[:8], "big")


class HashRing:
    """Consistent-hash ring with per-member weights via virtual nodes.

    ``vnodes`` virtual points per unit of weight; a weight-2 replica owns
    ~2x the keyspace of a weight-1 replica. ``owners(key)`` returns ALL
    members in ring-walk order from the key's position — the router's
    primary-then-spill candidate list.
    """

    def __init__(self, vnodes: int = 64):
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self.vnodes = int(vnodes)
        self._weights: Dict[str, float] = {}  # guarded-by: external(the owning Router mutates and queries the ring only under Router._lock)
        self._points: List[int] = []  # guarded-by: external(the owning Router mutates and queries the ring only under Router._lock)
        self._names: List[str] = []  # guarded-by: external(the owning Router mutates and queries the ring only under Router._lock)

    def __contains__(self, name: str) -> bool:
        return name in self._weights

    def __len__(self) -> int:
        return len(self._weights)

    @property
    def members(self) -> List[str]:
        return sorted(self._weights)

    def weight(self, name: str) -> Optional[float]:
        return self._weights.get(name)

    def add(self, name: str, weight: float = 1.0) -> None:
        """Add (or re-weight) a member. Weight must be positive and finite —
        the contracts checker rejects nonsense weights before a router is
        even built (analysis/contracts.py ``bad-router``); this is the
        runtime backstop."""
        weight = float(weight)
        if not math.isfinite(weight) or weight <= 0:
            raise ValueError(
                f"replica weight must be a positive finite number, got "
                f"{weight!r} for {name!r}"
            )
        self._weights[str(name)] = weight
        self._rebuild()

    def remove(self, name: str) -> None:
        """Remove a member (no-op when absent — drain paths call this
        idempotently)."""
        if self._weights.pop(name, None) is not None:
            self._rebuild()

    def _rebuild(self) -> None:
        pts = []
        for name, w in self._weights.items():
            for i in range(max(1, round(self.vnodes * w))):
                pts.append((_point(f"{name}#{i}"), name))
        pts.sort()
        self._points = [p for p, _ in pts]
        self._names = [n for _, n in pts]

    def owners(self, key: str, n: Optional[int] = None) -> List[str]:
        """Distinct members in ring order starting at ``key``'s position:
        ``owners(key)[0]`` is the primary, the rest are the bounded-load
        spill candidates in preference order. ``n`` truncates the walk."""
        if not self._points:
            return []
        want = len(self._weights) if n is None else min(n, len(self._weights))
        i = bisect.bisect_left(self._points, _point(key)) % len(self._points)
        out: List[str] = []
        for j in range(len(self._points)):
            name = self._names[(i + j) % len(self._points)]
            if name not in out:
                out.append(name)
                if len(out) == want:
                    break
        return out
