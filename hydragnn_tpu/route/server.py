"""Stdlib HTTP front end for the router (docs/SERVING.md "Multi-replica
tier").

Same shape as serve/server.py (``http.server`` is all the container has),
but the handler threads never touch an engine directly — they call
``Router.predict`` and block on the chosen replica. Endpoints:

  POST /predict  — same request schema as the single-engine server plus an
                   optional ``"class"`` field (admission class; default
                   "fast"). Responses carry the per-request hop log.
                   429 (RouterBusyError) includes the jittered Retry-After,
                   the router queue depth, and the shedding replica's own
                   hint; 503 (NoReplicaAvailableError) is explicit and
                   retryable.
  GET  /healthz  — fleet view: per-replica lifecycle states + last health.
  GET  /metrics  — hydragnn_route_* + the process-wide graftel registry.

Correlation ids: ``X-HydraGNN-Request-Id`` is honored/generated exactly
like the engine server (same safe-charset rule) and handed to the router,
which forwards it on every replica hop — one id, end to end.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from ..serve.server import REQUEST_ID_HEADER, RequestPlumbing
from ..telemetry import render_prometheus
from .admission import NoReplicaAvailableError, RouterBusyError
from .router import Router


class _Handler(RequestPlumbing, BaseHTTPRequestHandler):
    # Request-id hygiene + response emission are the shared RequestPlumbing
    # (serve/server.py) — ONE implementation of the PR-9 echo contract for
    # both front ends. BaseHTTPRequestHandler stays an explicit base so
    # graftrace's handler-thread-root discovery sees this class.
    protocol_version = "HTTP/1.1"

    @property
    def router(self) -> Router:
        return self.server.router  # type: ignore[attr-defined]

    # ---------------------------------------------------------------- routes
    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
        self._begin_request()
        if self.path == "/healthz":
            router = self.router
            states = router.states()
            admitted = sum(
                1 for s in states.values() if s["state"] == "admitted"
            )
            self._send_json(
                200 if admitted else 503,
                {
                    "ok": admitted > 0,
                    "admitted": admitted,
                    "replicas": states,
                    "queue_depth": router.queue_depth(),
                    # Shadow/canary arm status (docs/SERVING.md "Live model
                    # lifecycle"): the diff-gate record promotion gates on.
                    "shadow": router.shadow_report(),
                    "classes": {
                        name: {"deadline_s": c.deadline_s, "priority": c.priority}
                        for name, c in sorted(router.classes.items())
                    },
                },
            )
        elif self.path == "/metrics":
            self._send_text(
                200,
                self.router.metrics.render_prometheus()
                + self.router.shadow_prometheus()
                + render_prometheus(),
                "text/plain; version=0.0.4",
            )
        else:
            self._send_json(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):  # noqa: N802
        rid = self._begin_request()
        length = int(self.headers.get("Content-Length", "0"))
        body = self.rfile.read(length) if length else b""
        if self.path != "/predict":
            self._send_json(404, {"error": f"unknown path {self.path}"})
            return
        from ..serve.server import parse_graph

        try:
            doc = json.loads(body or b"{}")
            graphs_doc = doc.get("graphs")
            if not isinstance(graphs_doc, list) or not graphs_doc:
                raise ValueError('body must be {"graphs": [<graph>, ...]}')
            samples = [parse_graph(g) for g in graphs_doc]
            # No "class" field -> the router's default class, so the
            # single-engine request schema works against custom-class fleets.
            klass = doc.get("class")
            if klass is None:
                klass = self.router.default_class
            if not isinstance(klass, str):
                raise ValueError('"class" must be an admission-class name')
            # Optional tenant tag: routes into the tenant's bulkhead
            # namespace when an autopilot attached one (pilot/tenants.py);
            # ignored by a router with no bulkheads.
            tenant = doc.get("tenant")
            if tenant is not None and not isinstance(tenant, str):
                raise ValueError('"tenant" must be a string tenant name')
        except (ValueError, TypeError, json.JSONDecodeError) as e:
            self._send_json(400, {"error": str(e), "request_id": rid})
            return

        router = self.router
        try:
            res = router.predict(
                samples,
                klass=klass,
                timeout=getattr(self.server, "request_timeout_s", 60.0),
                request_id=rid,
                tenant=tenant,
            )
        except RouterBusyError as e:
            # Tenant-tagged 429 (TenantQuotaError): the shed names the
            # noisy tenant so clients/operators can attribute it.
            self._send_json(
                429,
                {
                    "error": str(e),
                    "retry_after_s": e.retry_after_s,
                    "replica_retry_after_s": e.replica_retry_after_s,
                    "queue_depth": e.queue_depth,
                    "class": e.klass,
                    "tenant": getattr(e, "tenant", None),
                    "hops": e.hops,
                    "request_id": rid,
                },
                headers={"Retry-After": f"{max(1, round(e.retry_after_s))}"},
            )
            return
        except NoReplicaAvailableError as e:
            self._send_json(
                503,
                {
                    "error": str(e),
                    "retryable": True,
                    "retry_after_s": e.retry_after_s,
                    "hops": e.hops,
                    "request_id": rid,
                },
                headers={"Retry-After": f"{max(1, round(e.retry_after_s))}"},
            )
            return
        except (ValueError, TypeError) as e:
            self._send_json(400, {"error": str(e), "request_id": rid})
            return
        except TimeoutError as e:
            self._send_json(504, {"error": str(e), "request_id": rid})
            return
        except RuntimeError as e:
            self._send_json(503, {"error": str(e), "request_id": rid})
            return

        # The answering replica's model version rides the same echo contract
        # as the request id (RequestPlumbing._model_version override).
        self._mv_override = res.model_version
        self._send_json(
            200,
            {
                "request_id": res.request_id,
                "replica": res.replica,
                "class": res.klass,
                "model_version": res.model_version,
                "hops": res.hops,
                "predictions": [
                    [np.asarray(h).tolist() for h in per_graph]
                    for per_graph in res.results
                ],
            },
        )


class RouterServer:
    """ThreadingHTTPServer wrapper owning one router (mirrors
    serve/server.py's InferenceServer lifecycle)."""

    def __init__(
        self,
        router: Router,
        host: str = "127.0.0.1",
        port: int = 8100,
        request_timeout_s: float = 60.0,
        verbose: bool = False,
    ):
        self.router = router
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.router = router  # type: ignore[attr-defined]
        self._httpd.verbose = verbose  # type: ignore[attr-defined]
        self._httpd.request_timeout_s = request_timeout_s  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def serve_forever(self) -> None:
        self._httpd.serve_forever()

    def start_background(self) -> "RouterServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="hydragnn-route-http",
            daemon=True,
        )
        self._thread.start()
        return self

    def shutdown(self, close_router: bool = True) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(5.0)
        if close_router:
            self.router.close()
