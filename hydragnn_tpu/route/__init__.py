"""graftroute — multi-replica serving tier (docs/SERVING.md "Multi-replica
tier"; ROADMAP item 1).

A stdlib-only front router over N ``InferenceEngine`` replicas: consistent
request hashing with bounded-load spill (ring.py), per-class SLO-aware
admission and deadline-based load shedding (admission.py), a health loop
consuming each replica's /healthz sticky-degraded states to
drain/eject/readmit, and warm scale-up over the shared graftcache store
(router.py). Replica backends — in-process engines and HTTP/subprocess
serve processes — sit behind one ``Replica`` interface (replica.py); the
HTTP front end (server.py) and the ``hydragnn_route_*`` metric family
(metrics.py) mirror the single-engine serve layer.

CLI: ``python -m hydragnn_tpu.serve router --config ... --replicas N``
(also reachable as ``python -m hydragnn_tpu.route``).
"""

from .admission import (
    DEFAULT_CLASSES,
    AdmissionClass,
    NoReplicaAvailableError,
    RouterBusyError,
    TenantQuotaError,
    build_classes,
)
from .metrics import RouteMetrics
from .replica import (
    HttpReplica,
    InProcessReplica,
    Replica,
    ReplicaBackpressureError,
    ReplicaDownError,
    ReplicaError,
    spawn_serve_replica,
)
from .ring import HashRing
from .router import RouteResult, Router
from .server import RouterServer

__all__ = [
    "DEFAULT_CLASSES",
    "AdmissionClass",
    "HashRing",
    "HttpReplica",
    "InProcessReplica",
    "NoReplicaAvailableError",
    "Replica",
    "ReplicaBackpressureError",
    "ReplicaDownError",
    "ReplicaError",
    "RouteMetrics",
    "RouteResult",
    "Router",
    "RouterBusyError",
    "RouterServer",
    "TenantQuotaError",
    "build_classes",
    "spawn_serve_replica",
]
