"""Admission classes and SLO-aware load shedding for the front router
(docs/SERVING.md "Multi-replica tier").

The single-engine 429 path says "my queue is full, retry in ~Ns". Fleet-wide
that hint is meaningless: one replica's queue says nothing about the tier's
capacity, and N synchronized clients retrying at exactly +Ns thundering-herd
whichever replica their keys hash to. This module generalizes it:

* every request belongs to an **admission class** with a deadline — the SLO
  the caller actually cares about. Admission compares the tier's estimated
  wait against the CLASS deadline, so a 15 s ``ensemble`` request is
  admitted at queue depths where a 2 s ``fast`` request is shed (the
  "ensemble vs fast" split is the SLO-tier hook ROADMAP item 6's
  uncertainty serving plugs into);
* shedding raises :class:`RouterBusyError` carrying a **jittered**
  retry-after (uniform 0.5x–1.5x) plus the router's own queue depth and,
  when a replica's 429 was the proximate cause, that replica's hint — the
  caller sees the honest fleet picture and retries desynchronized.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass
from typing import Any, Dict, List, Mapping, Optional


@dataclass(frozen=True)
class AdmissionClass:
    """One SLO tier: requests of this class must resolve within
    ``deadline_s`` of admission or be shed/failed explicitly. ``priority``
    is reserved as the tie-breaker for ROADMAP item 6's ensemble tier
    (admission today differentiates classes purely by deadline)."""

    name: str
    deadline_s: float
    priority: int = 0


#: Default tiers: ``fast`` is the single-model low-latency path; ``ensemble``
#: is the accurate/uncertainty tier (longer deadline — it tolerates deeper
#: queues and, once item 6 lands, N-model fan-out).
DEFAULT_CLASSES = (
    AdmissionClass("fast", deadline_s=2.0, priority=0),
    AdmissionClass("ensemble", deadline_s=15.0, priority=1),
)


def build_classes(
    spec: "Optional[Mapping[str, Any]]" = None,
) -> Dict[str, AdmissionClass]:
    """Admission-class table from a config mapping
    ``{name: {"deadline_s": float, "priority": int?}}`` (or
    ``{name: float}`` shorthand). ``None`` -> :data:`DEFAULT_CLASSES`.
    Validation mirrors the static checker (analysis/contracts.py
    ``bad-router``): a class without a positive finite deadline is refused
    here too — an SLO class with no SLO is meaningless."""
    if spec is None:
        return {c.name: c for c in DEFAULT_CLASSES}
    out: Dict[str, AdmissionClass] = {}
    for name, val in spec.items():
        if isinstance(val, Mapping):
            deadline = val.get("deadline_s")
            priority = int(val.get("priority", 0))
        else:
            deadline, priority = val, 0
        try:
            deadline_f = float(deadline)
        except (TypeError, ValueError):
            deadline_f = float("nan")
        if not math.isfinite(deadline_f) or deadline_f <= 0:
            raise ValueError(
                f"admission class {name!r} needs a positive finite "
                f"deadline_s, got {deadline!r}"
            )
        out[str(name)] = AdmissionClass(str(name), deadline_f, priority)
    if not out:
        raise ValueError("admission class table must not be empty")
    return out


def jittered(hint_s: float, rng: random.Random) -> float:
    """De-synchronize client retries: uniform 0.5x–1.5x around the hint.
    Without it every client that saw the same shed retries on the same
    tick and the hash ring lands the herd on one replica."""
    return max(0.05, float(hint_s)) * (0.5 + rng.random())


class RouterBusyError(RuntimeError):
    """The tier cannot meet this request's class deadline — the fleet-wide
    429. ``retry_after_s`` is already jittered; ``replica_retry_after_s``
    is the raw hint from the replica whose shed triggered this (None when
    admission itself shed); ``queue_depth`` is the router's in-flight count
    at shed time; ``hops`` is the per-request hop log up to the shed."""

    retryable = True

    def __init__(
        self,
        message: str,
        retry_after_s: float,
        queue_depth: int = 0,
        replica_retry_after_s: Optional[float] = None,
        klass: str = "fast",
        hops: "Optional[List[dict]]" = None,
    ):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)
        self.queue_depth = int(queue_depth)
        self.replica_retry_after_s = replica_retry_after_s
        self.klass = klass
        self.hops = list(hops or [])


class TenantQuotaError(RouterBusyError):
    """A tenant bulkhead shed (pilot/tenants.py): THIS tenant's in-flight
    quota or retry budget is exhausted — the fleet itself may be healthy.
    Same retryable-429 contract as :class:`RouterBusyError`, but the shed
    is tenant-tagged so the front end and the ``hydragnn_pilot_*`` metrics
    attribute it to the noisy tenant instead of the tier."""

    def __init__(
        self,
        message: str,
        retry_after_s: float,
        tenant: str,
        queue_depth: int = 0,
        klass: str = "fast",
    ):
        super().__init__(
            message,
            retry_after_s=retry_after_s,
            queue_depth=queue_depth,
            klass=klass,
        )
        self.tenant = str(tenant)


class NoReplicaAvailableError(RuntimeError):
    """Every candidate replica is down/draining — explicit retryable
    failure (HTTP 503 + Retry-After at the front end). Accepted requests
    are NEVER silently dropped: a request that cannot be completed gets
    this, a :class:`RouterBusyError`, or a TimeoutError — all explicit."""

    retryable = True

    def __init__(
        self,
        message: str,
        retry_after_s: float = 1.0,
        hops: "Optional[List[dict]]" = None,
    ):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)
        self.hops = list(hops or [])
