"""CLI entry: ``python -m hydragnn_tpu.serve router ...`` (also
``python -m hydragnn_tpu.route``).

Builds a router over N replicas and serves the fleet /predict, /healthz,
/metrics until interrupted. Three replica modes, mixable:

* ``--replicas N`` — N in-process engines built from ``--config``/
  ``--ckpt`` (one process, one shared graftcache store: the single-host
  multi-engine topology);
* ``--replica-url URL`` (repeatable) — attach running
  ``python -m hydragnn_tpu.serve`` processes over HTTP;
* ``--spawn N`` — spawn N serve subprocesses on ephemeral ports (each
  pointed at the shared ``--compile-cache`` store so spin-up hydrates).

Config validation rides the same ``gate_config`` path as every other entry
point — router findings (replica weights, admission-class deadlines,
replica-count-vs-ladder-memory) are ``bad-router`` lines BEFORE any
checkpoint loads (docs/STATIC_ANALYSIS.md).
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def parse_classes(spec: str) -> Optional[dict]:
    """``--classes "fast=2.0,ensemble=15.0"`` -> {name: {deadline_s}}."""
    if not spec:
        return None
    out = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f'--classes entries are "name=deadline_s", got {part!r}'
            )
        name, deadline = part.split("=", 1)
        out[name.strip()] = {"deadline_s": float(deadline)}
    return out or None


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m hydragnn_tpu.serve router",
        description="Multi-replica front router for HydraGNN serving.",
    )
    ap.add_argument("--config", required=True, help="COMPLETED config JSON")
    ap.add_argument("--ckpt", default=None)
    ap.add_argument(
        "--ckpt-format", choices=("auto", "native", "torch"), default="auto"
    )
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8100)
    ap.add_argument(
        "--replicas",
        type=int,
        default=0,
        help="in-process engine replicas to build from --config",
    )
    ap.add_argument(
        "--replica-url",
        action="append",
        default=[],
        metavar="URL",
        help="attach a running serve process (repeatable)",
    )
    ap.add_argument(
        "--spawn",
        type=int,
        default=0,
        help="serve subprocesses to spawn on ephemeral ports",
    )
    ap.add_argument(
        "--classes",
        default="",
        help='admission classes as "name=deadline_s,..." '
        '(default: fast=2.0,ensemble=15.0)',
    )
    ap.add_argument("--load-factor", type=float, default=1.25)
    ap.add_argument("--vnodes", type=int, default=64)
    ap.add_argument("--health-interval", type=float, default=0.5)
    ap.add_argument("--max-hops", type=int, default=3)
    ap.add_argument("--bucket-ladder", default="")
    ap.add_argument("--max-ladder-rungs", type=int, default=4)
    ap.add_argument("--packing", action="store_true")
    ap.add_argument("--max-batch-graphs", type=int, default=32)
    ap.add_argument("--max-delay-ms", type=float, default=5.0)
    ap.add_argument("--queue-limit", type=int, default=256)
    ap.add_argument(
        "--compile-cache",
        default=None,
        metavar="DIR",
        help="SHARED graftcache store for every replica (warm spin-up "
        "hydrates the whole ladder from here — docs/COMPILE_CACHE.md)",
    )
    ap.add_argument("--verbose", action="store_true")
    return ap


def _build_replicas(args, ladder, replicas, procs) -> None:
    """Build the fleet in the caller-provided lists (so a mid-build failure
    leaves the already-spawned subprocesses visible for cleanup)."""
    from ..serve.engine import InferenceEngine
    from . import HttpReplica, InProcessReplica
    from .replica import spawn_serve_replica

    for i in range(args.replicas):
        engine = InferenceEngine.from_config(
            args.config,
            checkpoint=args.ckpt,
            checkpoint_format=args.ckpt_format,
            max_batch_graphs=args.max_batch_graphs,
            max_delay_ms=args.max_delay_ms,
            queue_limit=args.queue_limit,
            bucket_ladder=ladder,
            warmup=ladder is not None,
            packing=args.packing,
            compile_cache=args.compile_cache,
        )
        replicas.append(InProcessReplica(f"local-{i}", engine))
    for i, url in enumerate(args.replica_url):
        replicas.append(HttpReplica(f"http-{i}", url))
    for i in range(args.spawn):
        # Forward the full engine shape: a fleet must be HOMOGENEOUS —
        # spawned replicas that batched/shed/packed differently from the
        # in-process ones would break the matched-buckets contract.
        serve_args = [
            "--config", args.config, "--port", "0",
            "--replica-id", f"spawn-{i}",
            "--ckpt-format", args.ckpt_format,
            "--max-batch-graphs", str(args.max_batch_graphs),
            "--max-delay-ms", str(args.max_delay_ms),
            "--queue-limit", str(args.queue_limit),
        ]
        if args.ckpt:
            serve_args += ["--ckpt", args.ckpt]
        if args.bucket_ladder:
            serve_args += ["--bucket-ladder", args.bucket_ladder]
        if args.packing:
            serve_args += ["--packing"]
        if args.compile_cache:
            serve_args += ["--compile-cache", args.compile_cache]
        replica, proc = spawn_serve_replica(f"spawn-{i}", serve_args)
        replicas.append(replica)
        procs.append(proc)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    n_replicas = args.replicas + len(args.replica_url) + args.spawn
    if n_replicas < 1:
        print(
            "router needs at least one replica "
            "(--replicas / --replica-url / --spawn)",
            file=sys.stderr,
        )
        return 2

    from ..analysis.contracts import gate_config
    from ..graphs.packing import resolve_ladder_spec

    ladder = None
    parse_error = None
    if args.bucket_ladder:
        try:
            ladder = resolve_ladder_spec(
                args.bucket_ladder, max_rungs=args.max_ladder_rungs
            )
        except Exception as e:  # noqa: BLE001 — checker diagnoses it below
            parse_error = e
    classes = parse_classes(args.classes)
    gate_config(
        args.config,
        mode="serving",
        bucket_ladder=ladder
        if ladder is not None
        else (args.bucket_ladder or None),
        router={
            "replicas": n_replicas,
            "classes": classes,
            "load_factor": args.load_factor,
            "vnodes": args.vnodes,
        },
    )
    if parse_error is not None:
        raise parse_error

    from . import Router, RouterServer

    replicas: List = []
    procs = []
    try:
        _build_replicas(args, ladder, replicas, procs)
    except BaseException:
        # A failed spawn/build must not orphan the already-spawned serve
        # subprocesses (they outlive this process; in-process engines die
        # with it).
        for proc in procs:
            proc.terminate()
        raise

    router = Router(
        replicas,
        classes=classes,
        load_factor=args.load_factor,
        vnodes=args.vnodes,
        health_interval_s=args.health_interval,
        max_hops=args.max_hops,
        expected_rungs=len(ladder) if ladder else 0,
    )
    server = RouterServer(
        router, host=args.host, port=args.port, verbose=args.verbose
    )
    print(
        f"hydragnn_tpu.route listening on http://{server.host}:{server.port} "
        f"(replicas: {', '.join(r.name for r in replicas)})",
        flush=True,
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
        router.close(close_replicas=True)
        for proc in procs:
            proc.terminate()
    return 0


if __name__ == "__main__":
    sys.exit(main())
