"""The front router: consistent-hash dispatch over N engine replicas with
SLO-aware admission, replica health, and warm spin-up (docs/SERVING.md
"Multi-replica tier"; ROADMAP item 1).

One ``Router`` owns the routing table (hash ring + per-replica lifecycle
records). Requests are admitted against their class deadline
(admission.py), hashed to a primary replica, spilled to the next ring owner
when the primary is over the bounded-load limit, and retried on another
replica when one sheds (429) or dies mid-dispatch — always within the
request's deadline, never silently: every admitted request resolves to a
result or an explicit retryable error.

Replica lifecycle (the health loop's state machine, one poll per
``health_interval_s``)::

    warming --hydrated--> admitted --degraded counters moved--> draining
    draining --quiet for readmit_polls--> admitted
    (admitted|draining) --eject_after failed polls--> ejected
    ejected --healthz ok again--> warming   (re-verifies hydration)
    any --scale_down()--> retiring --in-flight quiet--> reap_retired()

``draining``/``ejected`` replicas leave the hash ring (no NEW requests;
in-flight ones finish) but keep being polled so recovery readmits them.
``retiring`` (graftpilot scale-down) also leaves the ring but takes NO
health transitions — the autopilot shrank the fleet, the replica is not
sick — and exits the table only through ``reap_retired()``.
"degraded counters moved" means the replica's sticky /healthz fault
counters (bad batches, non-finite outputs, worker restarts) INCREASED
since the previous poll — the sticky bit alone cannot drive draining or a
once-degraded replica could never come back.

Warm spin-up (``scale_up``): the factory builds a replica pointed at the
shared graftcache store on a spawner thread; the new replica enters the
table as ``warming`` and is only admitted once its /healthz reports the
expected bucket-ladder rungs compiled — on a warm store that is hydration
(milliseconds-scale deserialize, zero XLA compiles), locked by
tests/test_route.py's compile-spy test.

Concurrency: ``_table``/``_ring``/``_inflight_total`` are cross-thread
state (caller threads dispatch, the health loop transitions, the spawner
publishes) — all access is under ``_lock`` with ``# guarded-by:``
annotations, graftrace-checked, and the dispatch path carries a tsan yield
point (``route.dispatch.pre_send``) for the schedule-fuzz drill. No JAX
from router threads: dispatch blocks on engine futures, the device work
stays on each engine's own sanctioned dispatch thread.
"""

from __future__ import annotations

import math
import queue
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..analysis import tsan
from ..lifecycle.shadow import ShadowGate, compare_outputs
from ..telemetry import graftel as telemetry
from ..serve.metrics import LatencyHistogram
from .admission import (
    AdmissionClass,
    NoReplicaAvailableError,
    RouterBusyError,
    TenantQuotaError,
    build_classes,
    jittered,
)
from .metrics import RouteMetrics
from .replica import (
    Replica,
    ReplicaBackpressureError,
    ReplicaDownError,
)
from .ring import HashRing

WARMING = "warming"
ADMITTED = "admitted"
DRAINING = "draining"
EJECTED = "ejected"
RETIRING = "retiring"


class RouteResult:
    """One routed prediction: per-graph per-head outputs plus the hop log
    (which replicas were tried, in order, with outcomes) — the response's
    routing provenance (docs/OBSERVABILITY.md "Serve request correlation")."""

    __slots__ = (
        "results", "request_id", "replica", "hops", "klass", "model_version"
    )

    def __init__(
        self, results, request_id, replica, hops, klass, model_version=None
    ):
        self.results = results
        self.request_id = request_id
        self.replica = replica
        self.hops = hops
        self.klass = klass
        # Which model version answered (docs/SERVING.md "Live model
        # lifecycle") — surfaced as X-HydraGNN-Model-Version by the front.
        self.model_version = model_version


class _ReplicaEntry:
    """One replica's routing-table record. Fields are mutated by the caller
    threads (inflight), the health loop (state machine), and the spawner
    (replica publication) — every access goes through the owning Router's
    ``_lock``; the per-field declarations below record that contract."""

    __slots__ = (
        "replica",
        "weight",
        "state",
        "inflight",
        "fails",
        "healthy_polls",
        "deg_baseline",
        "expected_rungs",
        "last_health",
        "spawn_wall_s",
    )

    def __init__(
        self,
        replica: Optional[Replica],
        weight: float,
        state: str,
        expected_rungs: Optional[int],
    ):
        self.replica = replica  # guarded-by: external(every access holds the owning Router._lock)
        self.weight = float(weight)  # guarded-by: external(every access holds the owning Router._lock)
        self.state = state  # guarded-by: external(every access holds the owning Router._lock)
        self.inflight = 0  # guarded-by: external(every access holds the owning Router._lock)
        self.fails = 0  # guarded-by: external(every access holds the owning Router._lock)
        self.healthy_polls = 0  # guarded-by: external(every access holds the owning Router._lock)
        self.deg_baseline: Optional[int] = None  # guarded-by: external(every access holds the owning Router._lock)
        self.expected_rungs = expected_rungs  # guarded-by: external(every access holds the owning Router._lock)
        self.last_health: Optional[dict] = None  # guarded-by: external(every access holds the owning Router._lock)
        self.spawn_wall_s: Optional[float] = None  # guarded-by: external(every access holds the owning Router._lock)


class Router:
    """Consistent-hash front router over N :class:`Replica` backends.

    Parameters
    ----------
    replicas:
        Initial replicas (already warm — built/warmed by the caller);
        admitted immediately. Accepts ``Replica`` objects or
        ``(Replica, weight)`` pairs.
    classes:
        Admission-class spec (admission.build_classes). Default: ``fast``
        (2 s) + ``ensemble`` (15 s, reserved for ROADMAP item 6).
    load_factor:
        Bounded-load consistent hashing: a replica whose in-flight count
        exceeds ``ceil(load_factor * (total_inflight + 1) / admitted)``
        spills to the next ring owner. Must be >= 1.
    health_interval_s, eject_after, readmit_polls:
        Health-loop cadence; consecutive failed polls before ejection;
        consecutive quiet polls before a draining replica readmits.
    expected_rungs:
        Bucket-ladder rungs a warming replica must report compiled before
        admission (per-replica override on ``add_replica``/``scale_up``).
        0/None accepts the first healthy poll with >= 1 compiled bucket.
    max_hops:
        Dispatch attempts (primary + retries) per request, deadline
        permitting.
    jitter_seed:
        Seeds the retry-after jitter stream (tests pin it; production
        leaves it None for OS entropy).
    """

    def __init__(
        self,
        replicas: Sequence[Any] = (),
        *,
        classes: Optional[dict] = None,
        load_factor: float = 1.25,
        vnodes: int = 64,
        health_interval_s: float = 0.5,
        eject_after: int = 2,
        readmit_polls: int = 2,
        expected_rungs: int = 0,
        max_hops: int = 3,
        default_timeout_s: float = 60.0,
        metrics: Optional[RouteMetrics] = None,
        jitter_seed: Optional[int] = None,
        autostart_health: bool = True,
    ):
        if load_factor < 1.0 or not math.isfinite(load_factor):
            raise ValueError(
                f"load_factor must be a finite number >= 1, got {load_factor}"
            )
        self.classes: Dict[str, AdmissionClass] = build_classes(classes)
        self.load_factor = float(load_factor)
        self.health_interval_s = float(health_interval_s)
        self.eject_after = int(eject_after)
        self.readmit_polls = int(readmit_polls)
        self.expected_rungs = int(expected_rungs or 0)
        self.max_hops = int(max_hops)
        self.default_timeout_s = float(default_timeout_s)
        self.metrics = (
            metrics
            if metrics is not None
            else RouteMetrics(class_names=list(self.classes))
        )
        self._lock = tsan.instrument_lock(threading.Lock(), "Router._lock")
        # The routing table: replica name -> lifecycle record. Written by
        # add/scale/dispatch/health threads.
        self._table: Dict[str, _ReplicaEntry] = {}  # guarded-by: self._lock
        # Ring membership == ADMITTED replicas only; mutated and queried
        # exclusively under the lock (ring.py is not thread-safe itself).
        self._ring = HashRing(vnodes)  # guarded-by: self._lock, dirty-reads(the attribute cell is bound once here; every mutation and owners() lookup runs under the lock)
        self._inflight_total = 0  # guarded-by: self._lock
        # Brownout degradation state (graftpilot's ladder actuates it via
        # set_degradation; _admit consults it): classes shed outright, the
        # factor per-class deadlines are tightened by, and the hard
        # in-flight cap ("shrink the bounded queue"). Every step is
        # reversible; (set(), 1.0, None) is the healthy level-0 state.
        self._deg_shed: set = set()  # guarded-by: self._lock
        self._deg_deadline_scale = 1.0  # guarded-by: self._lock
        self._deg_queue_cap: Optional[int] = None  # guarded-by: self._lock
        # Tenant bulkheads (pilot/tenants.py duck type: acquire/release/
        # allow_retry) — None until an autopilot attaches them.
        self._bulkheads: Optional[Any] = None  # guarded-by: self._lock
        # Per-class latency bucket counts at the PREVIOUS control_snapshot
        # — the rolling fleet-p99 window anchor (deltas between successive
        # snapshots are the window).
        self._ctl_hist_seen: Dict[str, List[int]] = {}  # guarded-by: self._lock
        # Retry-jitter stream; Random() is internally locked, the seed makes
        # shed hints reproducible in tests.
        self._rng = random.Random(jitter_seed)
        self._stop = threading.Event()
        self._health_thread: Optional[threading.Thread] = None
        self._health_ctx: Optional[Any] = None
        # Shadow map (graftswap, docs/SERVING.md "Live model lifecycle"):
        # one optional {replica, fraction, gate} record. Written by
        # set_shadow/clear_shadow (operator threads), read by every caller
        # thread's mirror decision and the shadow worker. Mirrored work
        # rides a bounded self-sync queue so a slow candidate can never
        # block live traffic (full queue -> dropped, counted on the gate).
        self._shadow: Optional[Dict[str, Any]] = None  # guarded-by: self._lock
        # Most recent DISARMED gate record: clear_shadow() used to drop the
        # gate entirely, which made the counters operators need to judge a
        # verdict (mirrored vs dropped vs compared) vanish from /healthz and
        # /metrics the instant the arm came down. Kept until the next
        # set_shadow so a scrape between gate cycles still sees the last
        # cycle's evidence.
        self._last_shadow: Optional[Dict[str, Any]] = None  # guarded-by: self._lock
        self._shadow_queue: "queue.Queue" = queue.Queue(maxsize=64)
        self._shadow_thread: Optional[threading.Thread] = None
        self._shadow_ctx: Optional[Any] = None
        for item in replicas:
            if isinstance(item, tuple):
                self.add_replica(item[0], weight=item[1])
            else:
                self.add_replica(item)
        if autostart_health:
            self.start_health_loop()

    # ------------------------------------------------------------- lifecycle
    def add_replica(
        self,
        replica: Replica,
        weight: float = 1.0,
        warm: bool = False,
        expected_rungs: Optional[int] = None,
    ) -> None:
        """Register a replica. ``warm=False`` (callers hand over an
        already-warm replica) admits immediately; ``warm=True`` enters the
        ``warming`` state and lets the health loop admit once the bucket
        ladder is hydrated."""
        name = replica.name
        state = WARMING if warm else ADMITTED
        with self._lock:
            if name in self._table:
                raise ValueError(f"replica {name!r} already registered")
            ent = _ReplicaEntry(replica, weight, state, expected_rungs)
            self._table[name] = ent
            if state == ADMITTED:
                self._ring.add(name, weight)
        self.metrics.set_replica_state(name, state)
        telemetry.event("route/replica_added", replica=name, state=state)

    def scale_up(
        self,
        name: str,
        factory: Callable[[], Replica],
        weight: float = 1.0,
        expected_rungs: Optional[int] = None,
    ) -> threading.Thread:
        """Warm spin-up: run ``factory`` (which should build an engine
        pointed at the SHARED graftcache store — docs/COMPILE_CACHE.md) on
        a spawner thread; the replica is ``warming`` until its ladder
        reports hydrated and takes no traffic before admission. Returns the
        spawner thread (join it in tests/drills)."""
        with self._lock:
            if name in self._table:
                raise ValueError(f"replica {name!r} already registered")
            self._table[name] = _ReplicaEntry(
                None, weight, WARMING, expected_rungs
            )
        self.metrics.set_replica_state(name, WARMING)
        telemetry.event("route/scale_up", replica=name)
        thread = threading.Thread(
            target=self._spawn_replica,
            args=(name, factory),
            name="hydragnn-route-spawn",
            daemon=True,
        )
        thread.start()
        return thread

    def _spawn_replica(self, name: str, factory: Callable[[], Replica]) -> None:
        t0 = time.perf_counter()
        try:
            replica = factory()
        except Exception as e:  # noqa: BLE001 — spawn failure is a state, not a crash
            with self._lock:
                ent = self._table.get(name)
                if ent is not None:
                    ent.state = EJECTED
            self.metrics.set_replica_state(name, EJECTED)
            self.metrics.count("ejections_total")
            telemetry.event(
                "route/spawn_failed", replica=name, error=repr(e)
            )
            return
        stale = None
        with self._lock:
            ent = self._table.get(name)
            if ent is None:
                stale = replica  # removed while spawning — close it below
            else:
                ent.replica = replica
                ent.spawn_wall_s = time.perf_counter() - t0
        if stale is not None:
            stale.close()
            return
        telemetry.event(
            "route/spawned",
            replica=name,
            wall_s=round(time.perf_counter() - t0, 4),
        )

    # ------------------------------------------------------------ shadow arm
    def set_shadow(
        self,
        replica: Replica,
        fraction: float,
        tolerance: float,
        min_samples: int = 8,
    ) -> ShadowGate:
        """Arm shadow mode: mirror a sampled ``fraction`` of successful live
        calls to ``replica`` (a candidate-version replica NOT in the ring)
        and feed the tolerance-gated diff gate (lifecycle/shadow.py;
        ``hydragnn_swap_*`` metrics). Shadow answers are never returned to
        callers and never counted against SLO admission. The same knobs are
        statically checked as ``bad-lifecycle`` findings
        (analysis/contracts.py): fraction must be in (0, 1], tolerance
        positive."""
        fraction = float(fraction)
        if not (0.0 < fraction <= 1.0) or not math.isfinite(fraction):
            raise ValueError(
                f"shadow fraction must be in (0, 1], got {fraction!r}"
            )
        gate = ShadowGate(tolerance=tolerance, min_samples=min_samples)
        with self._lock:
            self._shadow = {
                "replica": replica,
                "fraction": fraction,
                "gate": gate,
            }
        self._start_shadow_worker()
        self.metrics.set_replica_state(replica.name, "shadow")
        telemetry.event(
            "swap/shadow_armed",
            replica=replica.name,
            fraction=fraction,
            tolerance=float(tolerance),
        )
        return gate

    def clear_shadow(self) -> None:
        """Disarm shadow mode. The gate record is RETAINED (``_last_shadow``)
        so ``shadow_report``/``shadow_prometheus`` keep exposing the last
        cycle's mirrored/dropped/compared evidence until the next arm —
        promotion consumed the verdict, but operators auditing it have not."""
        with self._lock:
            shadow = self._shadow
            self._shadow = None
            if shadow is not None:
                self._last_shadow = {
                    "replica_name": shadow["replica"].name,
                    "fraction": shadow["fraction"],
                    "gate": shadow["gate"],
                }
        if shadow is not None:
            self.metrics.set_replica_state(shadow["replica"].name, None)
            telemetry.event(
                "swap/shadow_cleared", replica=shadow["replica"].name
            )

    def shadow_report(self) -> Dict[str, Any]:
        """The shadow gate's snapshot + arm config ({configured: False}
        when no shadow is armed) — what LifecycleManager.promote gates on
        and the router /healthz exposes."""
        with self._lock:
            shadow = self._shadow
            last = self._last_shadow
        if shadow is None:
            out: Dict[str, Any] = {"configured": False, "green": False}
            if last is not None:
                lg = last["gate"].report()
                lg.update(
                    replica=last["replica_name"], fraction=last["fraction"]
                )
                out["last_gate"] = lg
            return out
        report = shadow["gate"].report()
        report.update(
            configured=True,
            replica=shadow["replica"].name,
            fraction=shadow["fraction"],
        )
        return report

    def shadow_prometheus(self) -> str:
        """``hydragnn_swap_*`` exposition — the armed gate's counters, or
        the last disarmed gate's (so mirrored/dropped/compared totals do not
        disappear from /metrics between gate cycles); '' only before the
        first arm."""
        with self._lock:
            shadow = self._shadow
            last = self._last_shadow
        if shadow is not None:
            return shadow["gate"].render_prometheus()
        return last["gate"].render_prometheus() if last else ""

    def _start_shadow_worker(self) -> None:
        if self._shadow_thread is not None and self._shadow_thread.is_alive():
            return
        self._shadow_ctx = telemetry.new_context()
        self._shadow_thread = threading.Thread(
            target=self._shadow_loop,
            name="hydragnn-route-shadow",
            daemon=True,
        )
        self._shadow_thread.start()

    def _maybe_shadow(self, samples, results, rid: str) -> None:
        """Caller-thread mirror decision: sampled, non-blocking, invisible
        to the caller. A full mirror queue drops (counted) — live latency
        is never a function of candidate health."""
        with self._lock:
            shadow = self._shadow
        if shadow is None:
            return
        if self._rng.random() >= shadow["fraction"]:
            return
        gate: ShadowGate = shadow["gate"]
        gate.count_mirrored()
        try:
            self._shadow_queue.put_nowait((shadow, samples, results, rid))
        except queue.Full:
            gate.count_dropped()
            telemetry.event("swap/shadow_dropped", request_id=rid)

    def _shadow_loop(self) -> None:
        telemetry.attach(self._shadow_ctx)
        while not self._stop.is_set():
            try:
                shadow, samples, live, rid = self._shadow_queue.get(
                    timeout=0.2
                )
            except queue.Empty:
                continue
            gate: ShadowGate = shadow["gate"]
            replica: Replica = shadow["replica"]
            try:
                with telemetry.span(
                    "swap/shadow_dispatch",
                    request_id=rid,
                    replica=replica.name,
                ):
                    mirrored, version = replica.predict_versioned(
                        samples,
                        timeout=self.default_timeout_s,
                        request_id=f"{rid}/shadow",
                    )
                verdict = compare_outputs(live, mirrored, gate.tolerance)
            except Exception as e:  # noqa: BLE001 — gate-scoped, never live
                gate.count_error(repr(e))
                telemetry.event(
                    "swap/shadow_error", request_id=rid, error=repr(e)
                )
                continue
            gate.record(verdict, candidate_version=version)
            telemetry.event(
                "swap/shadow_diff",
                request_id=rid,
                ok=bool(verdict["ok"]),
                fwd_err=verdict["fwd_err"],
                candidate_version=version,
            )

    def remove_replica(self, name: str) -> Optional[Replica]:
        """Drop a replica from the table entirely (the caller closes it)."""
        with self._lock:
            ent = self._table.pop(name, None)
            self._ring.remove(name)
        self.metrics.set_replica_state(name, None)
        return ent.replica if ent is not None else None

    def scale_down(self, name: str) -> bool:
        """Graceful scale-down (graftpilot's drain actuator): ``retiring``
        leaves the ring immediately (no NEW requests; in-flight dispatches
        finish) and the entry exits the table only through
        :meth:`reap_retired` once quiet. Unlike ``draining``, a retiring
        replica is never readmitted by the health loop — the autopilot
        decided the fleet is too big, not that the replica is sick.
        Returns False for an unknown or already-retiring name."""
        with self._lock:
            ent = self._table.get(name)
            if ent is None or ent.state == RETIRING:
                return False
            ent.state = RETIRING
            self._ring.remove(name)
        self.metrics.set_replica_state(name, RETIRING)
        telemetry.event("route/replica_retire", replica=name)
        return True

    def reap_retired(self) -> List[Replica]:
        """Pop retiring replicas whose in-flight count reached zero and
        return them — the CALLER closes them (an engine close joins worker
        threads; it must not run under the health or pilot loop's tick)."""
        popped: List[Tuple[str, Optional[Replica]]] = []
        with self._lock:
            quiet = [
                n
                for n, e in self._table.items()
                if e.state == RETIRING and e.inflight == 0
            ]
            for name in quiet:
                ent = self._table.pop(name)
                popped.append((name, ent.replica))
        out: List[Replica] = []
        for name, replica in popped:
            self.metrics.set_replica_state(name, None)
            self.metrics.count("retired_total")
            telemetry.event("route/replica_retired", replica=name)
            if replica is not None:
                out.append(replica)
        return out

    # ------------------------------------------------------- pilot actuators
    def set_degradation(
        self,
        shed_classes: Sequence[str] = (),
        deadline_scale: float = 1.0,
        queue_cap: Optional[int] = None,
    ) -> None:
        """Install the FULL brownout state for one ladder level
        (pilot/brownout.py): each level re-states everything, so the walk
        is idempotent and a crashed recovery cannot leave stale residue.
        ``shed_classes`` are refused outright at admission;
        ``deadline_scale`` in (0, 1] multiplies every class deadline in the
        admission estimate; ``queue_cap`` bounds the router-level in-flight
        count. Validation mirrors the static ``bad-pilot`` checks."""
        scale = float(deadline_scale)
        if not (0.0 < scale <= 1.0) or not math.isfinite(scale):
            raise ValueError(
                f"deadline_scale must be in (0, 1], got {deadline_scale!r}"
            )
        cap = None if queue_cap is None else int(queue_cap)
        if cap is not None and cap < 1:
            raise ValueError(f"queue_cap must be >= 1 or None, got {cap}")
        shed = {str(c) for c in shed_classes}
        unknown = shed - set(self.classes)
        if unknown:
            raise ValueError(
                f"cannot shed unknown admission classes {sorted(unknown)}; "
                f"configured: {sorted(self.classes)}"
            )
        with self._lock:
            self._deg_shed = shed
            self._deg_deadline_scale = scale
            self._deg_queue_cap = cap
        telemetry.event(
            "route/degradation",
            shed=sorted(shed),
            deadline_scale=scale,
            queue_cap=cap,
        )

    def set_bulkheads(self, bulkheads: Optional[Any]) -> None:
        """Attach (or detach, with None) the tenant bulkheads every
        tenant-tagged ``predict`` consults (pilot/tenants.py)."""
        with self._lock:
            self._bulkheads = bulkheads

    def control_snapshot(self) -> Dict[str, Any]:
        """The autopilot's ONE sensor read: queue depth, per-replica
        lifecycle state, per-class request/shed counters, rolling fleet
        p99, and the live degradation state — two internally-consistent
        locked copies (the routing table + degradation under this router's
        lock, every counter family in RouteMetrics.control_read's single
        locked pass) instead of the scattered ``metrics.snapshot()`` /
        ``/healthz`` / telemetry reads a control loop would otherwise tear
        (the PR-8 torn-counter-pair reasoning, now as a control input).

        ``fleet_p99_s`` is ROLLING: per class, the interpolated p99 of the
        latency observations recorded since the PREVIOUS control_snapshot
        call (bucket-count deltas), None for a window with no completions —
        a cumulative p99 would stay pinned high long after a wave passed
        and hold the brownout ladder down."""
        now = time.monotonic()
        with self._lock:
            replicas = {
                name: {
                    "state": ent.state,
                    "inflight": ent.inflight,
                    "fails": ent.fails,
                    "spawn_wall_s": ent.spawn_wall_s,
                    "queue_depth": int(
                        (ent.last_health or {}).get("queue_depth") or 0
                    ),
                }
                for name, ent in sorted(self._table.items())
            }
            inflight = self._inflight_total
            degradation = {
                "shed_classes": sorted(self._deg_shed),
                "deadline_scale": self._deg_deadline_scale,
                "queue_cap": self._deg_queue_cap,
            }
        m = self.metrics.control_read()
        with self._lock:
            prev = self._ctl_hist_seen
            self._ctl_hist_seen = {
                k: list(v["counts"]) for k, v in m["latency"].items()
            }
        p99: Dict[str, Optional[float]] = {}
        for k, v in m["latency"].items():
            base = prev.get(k, [0] * len(v["counts"]))
            delta = [c - p for c, p in zip(v["counts"], base)]
            if any(d < 0 for d in delta):
                delta = list(v["counts"])  # histogram replaced: full window
            p99[k] = LatencyHistogram.quantile_of(v["bounds"], delta, 0.99)
        counts: Dict[str, int] = {
            s: 0 for s in (WARMING, ADMITTED, DRAINING, EJECTED, RETIRING)
        }
        spawn_walls = []
        for rec in replicas.values():
            counts[rec["state"]] = counts.get(rec["state"], 0) + 1
            if rec["spawn_wall_s"] is not None:
                spawn_walls.append(rec["spawn_wall_s"])
        scale = degradation["deadline_scale"]
        return {
            "ts_monotonic": now,
            "queue_depth": inflight,
            "replicas": replicas,
            "counts": counts,
            "counters": m["counters"],
            "per_class": m["per_class"],
            "fleet_p99_s": p99,
            "deadlines_s": {
                name: ac.deadline_s * scale
                for name, ac in sorted(self.classes.items())
            },
            "max_spawn_wall_s": max(spawn_walls) if spawn_walls else None,
            "degradation": degradation,
        }

    def start_health_loop(self) -> None:
        """Launch the health-poll thread (idempotent)."""
        if self._health_thread is not None:
            return
        self._health_ctx = telemetry.new_context()
        self._health_thread = threading.Thread(
            target=self._health_loop,
            name="hydragnn-route-health",
            daemon=True,
        )
        self._health_thread.start()

    def close(self, close_replicas: bool = False, timeout: float = 5.0) -> None:
        """Stop the health loop (and optionally the replicas)."""
        self._stop.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout)
        if self._shadow_thread is not None:
            self._shadow_thread.join(timeout)
        if close_replicas:
            with self._lock:
                replicas = [
                    e.replica
                    for e in self._table.values()
                    if e.replica is not None
                ]
            for r in replicas:
                r.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ---------------------------------------------------------------- status
    def states(self) -> Dict[str, Dict[str, Any]]:
        """Locked snapshot of the replica-health map — the router /healthz
        payload's ``replicas`` field."""
        with self._lock:
            return {
                name: {
                    "state": ent.state,
                    "weight": ent.weight,
                    "inflight": ent.inflight,
                    "fails": ent.fails,
                    "spawn_wall_s": ent.spawn_wall_s,
                    "last_health": dict(ent.last_health)
                    if ent.last_health
                    else None,
                }
                for name, ent in sorted(self._table.items())
            }

    @property
    def default_class(self) -> str:
        """The admission class a caller that names none gets: ``fast``
        when configured (the stock tier), else the tightest-deadline class
        — so the single-engine request schema (no ``class`` field) keeps
        working against a custom-class fleet."""
        if "fast" in self.classes:
            return "fast"
        return min(self.classes.values(), key=lambda c: c.deadline_s).name

    def admitted_count(self) -> int:
        with self._lock:
            return sum(
                1 for e in self._table.values() if e.state == ADMITTED
            )

    def queue_depth(self) -> int:
        """Router-level in-flight count (the fleet 'queue depth' shed
        responses report)."""
        with self._lock:
            return self._inflight_total

    # ------------------------------------------------------------- dispatch
    def predict(
        self,
        samples: Sequence[Any],
        klass: Optional[str] = None,
        timeout: Optional[float] = None,
        request_id: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> RouteResult:
        """Route one prediction call. Admission against the class deadline,
        consistent-hash primary + bounded-load spill, retry on shed/down
        replicas while the deadline allows. ``klass=None`` takes
        :attr:`default_class`. ``tenant`` names the calling tenant's
        bulkhead namespace (pilot/tenants.py): the consistent-hash walk is
        keyed per tenant, the tenant's in-flight quota is charged for the
        call's duration, and each retry hop spends the tenant's retry
        budget. Raises :class:`RouterBusyError` (shed, retryable, jittered
        hint; :class:`TenantQuotaError` when the tenant's own bulkhead
        shed), :class:`NoReplicaAvailableError` (no serving replica,
        retryable), or propagates per-request errors (ValueError,
        TimeoutError)."""
        if klass is None:
            klass = self.default_class
        ac = self.classes.get(klass)
        if ac is None:
            raise ValueError(
                f"unknown admission class {klass!r}; configured: "
                f"{sorted(self.classes)}"
            )
        rid = request_id or telemetry.new_request_id()
        hop_timeout = (
            timeout if timeout is not None else self.default_timeout_s
        )
        t0 = time.perf_counter()
        deadline = t0 + ac.deadline_s
        self.metrics.count("requests_total")
        self.metrics.count_class(klass, "requests")
        with self._lock:
            bulkheads = self._bulkheads if tenant is not None else None
        if bulkheads is not None:
            try:
                bulkheads.acquire(
                    tenant, klass=klass, queue_depth=self.queue_depth()
                )
            except TenantQuotaError:
                self.metrics.count("shed_total")
                self.metrics.count_class(klass, "shed")
                telemetry.event(
                    "route/shed",
                    request_id=rid,
                    klass=klass,
                    reason="tenant_quota",
                    tenant=tenant,
                )
                raise
        try:
            return self._predict_admitted(
                samples, ac, klass, rid, t0, deadline, hop_timeout,
                tenant, bulkheads,
            )
        finally:
            if bulkheads is not None:
                bulkheads.release(tenant)

    def _predict_admitted(
        self, samples, ac, klass, rid, t0, deadline, hop_timeout,
        tenant, bulkheads,
    ) -> RouteResult:
        self._admit(ac, rid)
        # Per-tenant ring namespace: each tenant gets its own stable walk
        # over the SAME members, so one tenant's hot keys do not define
        # another tenant's primaries.
        ring_key = f"{tenant}/{rid}" if tenant is not None else rid

        hops: List[dict] = []
        tried: set = set()
        last_bp: Optional[ReplicaBackpressureError] = None
        for _hop in range(self.max_hops):
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            if hops and bulkheads is not None and not bulkheads.allow_retry(
                tenant
            ):
                # Retry budget spent: a tenant whose oversize graphs keep
                # bouncing off replicas must not consume the whole fleet's
                # hop capacity — fail over to the explicit shed below.
                telemetry.event(
                    "route/retry_budget_exhausted",
                    request_id=rid,
                    tenant=tenant,
                )
                break
            target = self._acquire_target(ring_key, tried)
            if target is None:
                break
            name, replica, spilled = target
            if spilled:
                self.metrics.count("spilled_total")
            if hops:
                # A retry is a SUBSEQUENT attempt actually starting — the
                # final failed attempt of a shed request is not a retry.
                self.metrics.count("retries_total")
            tsan.yield_point("route.dispatch.pre_send")
            t_hop = time.perf_counter()
            try:
                with telemetry.span(
                    "route/dispatch",
                    request_id=rid,
                    replica=name,
                    klass=klass,
                    hop=len(hops),
                ):
                    # Versioned dispatch when the backend supports it (both
                    # shipped backends do); plain Replica duck-types keep
                    # working with an untagged response.
                    versioned = getattr(replica, "predict_versioned", None)
                    if versioned is not None:
                        results, model_version = versioned(
                            samples,
                            timeout=min(remaining, hop_timeout),
                            request_id=rid,
                        )
                    else:
                        results = replica.predict(
                            samples,
                            timeout=min(remaining, hop_timeout),
                            request_id=rid,
                        )
                        model_version = None
            except ReplicaBackpressureError as e:
                self._release(name, ok=True)
                hops.append(self._hop(name, "backpressure", t_hop, spilled))
                self.metrics.count("hops_total")
                tried.add(name)
                last_bp = e
                telemetry.event(
                    "route/replica_shed", request_id=rid, replica=name
                )
                continue
            except ReplicaDownError as e:
                # Fast feedback: drain NOW (the health loop confirms the
                # ejection); the request retries on the next ring owner.
                self._release(name, ok=False)
                hops.append(self._hop(name, "down", t_hop, spilled))
                self.metrics.count("hops_total")
                tried.add(name)
                self.metrics.count("replica_down_dispatch_total")
                telemetry.event(
                    "route/replica_down",
                    request_id=rid,
                    replica=name,
                    error=repr(e),
                )
                continue
            except BaseException:
                # Per-request errors (validation, timeout): not the
                # replica's fault — release without marking it suspect.
                self._release(name, ok=True)
                hops.append(self._hop(name, "error", t_hop, spilled))
                self.metrics.count("hops_total")
                raise
            self._release(name, ok=True)
            hops.append(self._hop(name, "ok", t_hop, spilled))
            self.metrics.count("hops_total")
            e2e = time.perf_counter() - t0
            self.metrics.observe(klass, e2e)
            telemetry.event(
                "route/response",
                request_id=rid,
                replica=name,
                hops=len(hops),
                model_version=model_version,
                e2e_s=round(e2e, 6),
            )
            # Shadow mirror AFTER the live answer is final: the candidate
            # sees real traffic, the caller never sees the candidate.
            self._maybe_shadow(samples, results, rid)
            return RouteResult(
                results, rid, name, hops, klass, model_version=model_version
            )

        # Candidates exhausted (or deadline passed) without a result.
        depth = self.queue_depth()
        if last_bp is not None:
            self.metrics.count("shed_total")
            self.metrics.count_class(klass, "shed")
            hint = jittered(last_bp.retry_after_s, self._rng)
            telemetry.event(
                "route/shed", request_id=rid, klass=klass, reason="replicas_busy"
            )
            raise RouterBusyError(
                f"all candidate replicas shed within the {klass!r} deadline "
                f"({ac.deadline_s:g}s); retry in ~{hint:.2f}s",
                retry_after_s=hint,
                queue_depth=depth,
                replica_retry_after_s=last_bp.retry_after_s,
                klass=klass,
                hops=hops,
            )
        self.metrics.count("failed_total")
        hint = jittered(self.health_interval_s * 2.0, self._rng)
        telemetry.event(
            "route/no_replica", request_id=rid, klass=klass, hops=len(hops)
        )
        raise NoReplicaAvailableError(
            "no admitted replica could serve this request "
            f"(tried {sorted(tried) or 'none'}); retry in ~{hint:.2f}s",
            retry_after_s=hint,
            hops=hops,
        )

    @staticmethod
    def _hop(name: str, outcome: str, t_hop: float, spilled: bool) -> dict:
        return {
            "replica": name,
            "outcome": outcome,
            "ms": round((time.perf_counter() - t_hop) * 1000.0, 3),
            "spilled": spilled,
        }

    def _admit(self, ac: AdmissionClass, rid: str) -> None:
        """Deadline-based admission: estimated fleet wait (in-flight per
        admitted replica x observed per-request seconds) vs the class
        deadline. The generalization of the engine's single-queue 429.
        The brownout degradation state (set_degradation) is consulted here
        too: shed classes are refused outright, deadlines are tightened by
        the scale factor, and the queue cap bounds total in-flight."""
        with self._lock:
            admitted = sum(
                1 for e in self._table.values() if e.state == ADMITTED
            )
            inflight = self._inflight_total
            deg_shed = set(self._deg_shed)
            deg_scale = self._deg_deadline_scale
            deg_cap = self._deg_queue_cap
        if ac.name in deg_shed:
            self.metrics.count("shed_total")
            self.metrics.count_class(ac.name, "shed")
            self.metrics.count("brownout_shed_total")
            hint = jittered(self.health_interval_s * 4.0, self._rng)
            telemetry.event(
                "route/shed", request_id=rid, klass=ac.name, reason="brownout"
            )
            raise RouterBusyError(
                f"brownout: the {ac.name!r} class is temporarily shed "
                f"(degradation ladder); retry in ~{hint:.2f}s",
                retry_after_s=hint,
                queue_depth=inflight,
                klass=ac.name,
            )
        if admitted == 0:
            self.metrics.count("failed_total")
            hint = jittered(self.health_interval_s * 2.0, self._rng)
            telemetry.event(
                "route/no_replica", request_id=rid, klass=ac.name, hops=0
            )
            raise NoReplicaAvailableError(
                "no replica is admitted (all warming/draining/ejected); "
                f"retry in ~{hint:.2f}s",
                retry_after_s=hint,
            )
        if deg_cap is not None and inflight >= deg_cap:
            self.metrics.count("shed_total")
            self.metrics.count_class(ac.name, "shed")
            self.metrics.count("brownout_shed_total")
            hint = jittered(self.health_interval_s * 4.0, self._rng)
            telemetry.event(
                "route/shed", request_id=rid, klass=ac.name, reason="queue_cap"
            )
            raise RouterBusyError(
                f"brownout: router queue capped at {deg_cap} in-flight "
                f"({inflight} outstanding); retry in ~{hint:.2f}s",
                retry_after_s=hint,
                queue_depth=inflight,
                klass=ac.name,
            )
        hist = self.metrics.latency.get(ac.name)
        mean = hist.mean() if hist is not None else None
        per_req = mean if mean is not None else 0.05
        est_wait = (inflight / admitted) * per_req
        deadline_eff = ac.deadline_s * deg_scale
        if est_wait > deadline_eff:
            self.metrics.count("shed_total")
            self.metrics.count_class(ac.name, "shed")
            hint = jittered(est_wait, self._rng)
            telemetry.event(
                "route/shed", request_id=rid, klass=ac.name, reason="admission"
            )
            tightened = (
                f" (tightened x{deg_scale:g} by the brownout ladder)"
                if deg_scale < 1.0
                else ""
            )
            raise RouterBusyError(
                f"estimated fleet wait {est_wait:.2f}s exceeds the "
                f"{ac.name!r} deadline {deadline_eff:g}s{tightened}; retry "
                f"in ~{hint:.2f}s",
                retry_after_s=hint,
                queue_depth=inflight,
                klass=ac.name,
            )

    def _acquire_target(
        self, ring_key: str, tried: set
    ) -> Optional[Tuple[str, Replica, bool]]:
        """Pick the next candidate under the lock: ring owners in walk
        order (keyed per tenant when the request is tenant-tagged),
        skipping tried/non-admitted replicas, spilling past owners over
        the bounded-load limit; increments the in-flight counters."""
        with self._lock:
            admitted = sum(
                1 for e in self._table.values() if e.state == ADMITTED
            )
            if admitted == 0:
                return None
            cands = [
                n
                for n in self._ring.owners(ring_key)
                if n not in tried
                and self._table[n].state == ADMITTED
            ]
            if not cands:
                return None
            limit = math.ceil(
                self.load_factor * (self._inflight_total + 1) / admitted
            )
            chosen = None
            least, least_load = cands[0], None
            for n in cands:
                load = self._table[n].inflight
                if load < limit:
                    chosen = n
                    break
                if least_load is None or load < least_load:
                    least, least_load = n, load
            if chosen is None:
                # Every candidate is over the bounded-load limit: take the
                # least-loaded one rather than shedding a routable request.
                chosen = least
            spilled = chosen != cands[0]
            ent = self._table[chosen]
            ent.inflight += 1
            self._inflight_total += 1
            replica = ent.replica
        assert replica is not None  # ADMITTED implies a published replica
        return chosen, replica, spilled

    def _release(self, name: str, ok: bool) -> None:
        """Return an in-flight slot; a dispatch-observed failure drains the
        replica immediately (health loop confirms/ejects)."""
        drained = False
        with self._lock:
            ent = self._table.get(name)
            if ent is not None:
                ent.inflight = max(0, ent.inflight - 1)
                if not ok:
                    ent.fails += 1
                    if ent.state == ADMITTED:
                        ent.state = DRAINING
                        ent.healthy_polls = 0
                        self._ring.remove(name)
                        drained = True
            self._inflight_total = max(0, self._inflight_total - 1)
        if drained:
            self.metrics.set_replica_state(name, DRAINING)
            self.metrics.count("drains_total")
            telemetry.event(
                "route/replica_drain", replica=name, reason="dispatch_failure"
            )

    # ----------------------------------------------------------- health loop
    def _health_loop(self) -> None:
        telemetry.attach(self._health_ctx)
        while not self._stop.is_set():
            self.poll_health()
            self._stop.wait(self.health_interval_s)

    def poll_health(self) -> None:
        """One poll round over every registered replica (the health loop's
        body; callable directly in tests for deterministic stepping)."""
        with self._lock:
            targets = [
                (name, ent.replica)
                for name, ent in self._table.items()
                if ent.replica is not None
            ]
        for name, replica in targets:
            try:
                h: Optional[dict] = replica.health()
                ok = bool(h.get("ok")) if isinstance(h, dict) else False
            except Exception:  # noqa: BLE001 — any health failure == down
                h, ok = None, False
            self._apply_health(name, h, ok)
        if targets:
            self.metrics.count("health_checks_total", len(targets))

    def _apply_health(self, name: str, h: Optional[dict], ok: bool) -> None:
        """Apply one poll result to the state machine (transitions under
        the lock; metric/telemetry emission after release)."""
        events: List[Tuple[str, dict]] = []
        new_state: Optional[str] = None
        with self._lock:
            ent = self._table.get(name)
            if ent is None:
                return
            ent.last_health = h
            if ent.state == RETIRING:
                # Retiring replicas take no health transitions: not
                # ejectable (already leaving), never readmitted —
                # reap_retired() is the only exit from the table.
                return
            if not ok:
                ent.fails += 1
                # WARMING ejects too: a scale-up target whose health
                # endpoint keeps failing must not be polled forever while
                # permanently holding its name out of the gauge as
                # "warming" (re-registering it needs remove_replica).
                if (
                    ent.state in (ADMITTED, DRAINING, WARMING)
                    and ent.fails >= self.eject_after
                ):
                    ent.state = EJECTED
                    self._ring.remove(name)
                    new_state = EJECTED
                    events.append(("route/replica_eject", {"replica": name}))
            else:
                ent.fails = 0
                deg = sum(
                    int(h.get(k, 0) or 0)
                    for k in ("bad_batches", "nonfinite_outputs", "restarts")
                )
                if ent.state == EJECTED:
                    # Came back: re-verify hydration before readmission.
                    ent.state = WARMING
                    ent.deg_baseline = deg
                    new_state = WARMING
                elif ent.state == WARMING:
                    needed = (
                        ent.expected_rungs
                        if ent.expected_rungs is not None
                        else self.expected_rungs
                    ) or 1
                    if int(h.get("compiled_buckets", 0)) >= needed:
                        ent.state = ADMITTED
                        ent.deg_baseline = deg
                        self._ring.add(name, ent.weight)
                        new_state = ADMITTED
                        events.append(
                            (
                                "route/replica_admit",
                                {
                                    "replica": name,
                                    "compiled_buckets": int(
                                        h.get("compiled_buckets", 0)
                                    ),
                                    "hydrated_buckets": int(
                                        h.get("hydrated_buckets", 0) or 0
                                    ),
                                    "spawn_wall_s": ent.spawn_wall_s,
                                },
                            )
                        )
                elif ent.state == ADMITTED:
                    if ent.deg_baseline is None:
                        ent.deg_baseline = deg
                    elif deg > ent.deg_baseline:
                        ent.state = DRAINING
                        ent.healthy_polls = 0
                        ent.deg_baseline = deg
                        self._ring.remove(name)
                        new_state = DRAINING
                        events.append(
                            (
                                "route/replica_drain",
                                {"replica": name, "reason": "degraded"},
                            )
                        )
                    else:
                        ent.deg_baseline = deg
                elif ent.state == DRAINING:
                    if ent.deg_baseline is not None and deg > ent.deg_baseline:
                        ent.healthy_polls = 0
                    else:
                        ent.healthy_polls += 1
                    ent.deg_baseline = deg
                    if ent.healthy_polls >= self.readmit_polls:
                        ent.state = ADMITTED
                        self._ring.add(name, ent.weight)
                        new_state = ADMITTED
                        events.append(
                            ("route/replica_readmit", {"replica": name})
                        )
        if new_state is not None:
            self.metrics.set_replica_state(name, new_state)
        for ev_name, attrs in events:
            if ev_name == "route/replica_eject":
                self.metrics.count("ejections_total")
            elif ev_name == "route/replica_drain":
                self.metrics.count("drains_total")
            elif ev_name == "route/replica_readmit":
                self.metrics.count("readmissions_total")
            elif ev_name == "route/replica_admit":
                self.metrics.count("warm_admissions_total")
            telemetry.event(ev_name, **attrs)
