"""Router metrics: the ``hydragnn_route_*`` Prometheus family
(docs/OBSERVABILITY.md "Prometheus catalogue", docs/SERVING.md
"Multi-replica tier").

Same design as the engine's ``ServeMetrics``: host-side, one lock, seconds
credited into the shared ``Timer`` registry (``route_*`` names), fixed-bound
latency histograms per admission class. Observations arrive from every
router caller thread (main / HTTP handlers) plus the health-loop thread —
all fields are declared guarded and graftrace-checked.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence

from ..analysis import tsan
from ..serve.metrics import LatencyHistogram
from ..utils.time_utils import Timer


class RouteMetrics:
    """All counters/histograms of one ``Router``."""

    _COUNTERS = (
        "requests_total",
        "shed_total",
        "retries_total",
        "spilled_total",
        "failed_total",
        "hops_total",
        "replica_down_dispatch_total",
        "health_checks_total",
        "drains_total",
        "ejections_total",
        "readmissions_total",
        "warm_admissions_total",
        "brownout_shed_total",
        "retired_total",
    )

    def __init__(self, class_names: Sequence[str] = ("fast", "ensemble")):
        self._lock = tsan.instrument_lock(
            threading.Lock(), "RouteMetrics._lock"
        )
        self.requests_total = 0  # guarded-by: self._lock
        self.shed_total = 0  # guarded-by: self._lock
        self.retries_total = 0  # guarded-by: self._lock
        self.spilled_total = 0  # guarded-by: self._lock
        self.failed_total = 0  # guarded-by: self._lock
        self.hops_total = 0  # guarded-by: self._lock
        self.replica_down_dispatch_total = 0  # guarded-by: self._lock
        self.health_checks_total = 0  # guarded-by: self._lock
        self.drains_total = 0  # guarded-by: self._lock
        self.ejections_total = 0  # guarded-by: self._lock
        self.readmissions_total = 0  # guarded-by: self._lock
        self.warm_admissions_total = 0  # guarded-by: self._lock
        self.brownout_shed_total = 0  # guarded-by: self._lock
        self.retired_total = 0  # guarded-by: self._lock
        # Per admission class: request/shed counters + an e2e latency
        # histogram (the fleet-level p50/p95/p99 the load rig reports).
        self._per_class: Dict[str, Dict[str, int]] = {  # guarded-by: self._lock
            str(c): {"requests": 0, "shed": 0} for c in class_names
        }
        self.latency: Dict[str, LatencyHistogram] = {  # guarded-by: self._lock, dirty-reads(dict is immutable after construction; the leaf histograms carry their own lock)
            str(c): LatencyHistogram() for c in class_names
        }
        # Replica lifecycle states (admitted/warming/draining/ejected),
        # maintained by the Router's health loop — the _replica_state gauge.
        self._replica_states: Dict[str, str] = {}  # guarded-by: self._lock

    # ------------------------------------------------------------- recorders
    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + n)
            tsan.shared_access("RouteMetrics.counters")

    def count_class(self, klass: str, which: str, n: int = 1) -> None:
        with self._lock:
            entry = self._per_class.setdefault(
                klass, {"requests": 0, "shed": 0}
            )
            entry[which] = entry.get(which, 0) + n

    def observe(self, klass: str, seconds: float) -> None:
        hist = self.latency.get(klass)
        if hist is None:
            with self._lock:
                hist = self.latency.setdefault(klass, LatencyHistogram())
        hist.observe(seconds)
        Timer.credit("route_e2e", seconds)

    def set_replica_state(self, name: str, state: Optional[str]) -> None:
        """Record one replica's lifecycle state (None removes it)."""
        with self._lock:
            if state is None:
                self._replica_states.pop(name, None)
            else:
                self._replica_states[name] = str(state)

    def read_counters(self, *names: str) -> Dict[str, float]:
        """One locked copy of the named counters (cross-thread readers must
        not assemble their view field-by-field — same contract as
        ServeMetrics.read_counters)."""
        with self._lock:
            return {n: getattr(self, n) for n in names}

    def control_read(self) -> Dict:
        """The autopilot's sensor read (Router.control_snapshot's metrics
        half): EVERY counter plus the per-class request/shed table in ONE
        locked copy — a control loop diffing a torn counter pair would see
        phantom shed spikes (the PR-8 scrape bug as a control input) — and
        each class latency histogram's bounds + bucket counts so the caller
        can window quantiles by diffing successive snapshots."""
        with self._lock:
            counters = {n: getattr(self, n) for n in self._COUNTERS}
            per_class = {
                k: dict(v) for k, v in sorted(self._per_class.items())
            }
            hists = dict(self.latency)
        return {
            "counters": counters,
            "per_class": per_class,
            "latency": {
                k: {"bounds": h.bounds, "counts": h.counts_snapshot()}
                for k, h in sorted(hists.items())
            },
        }

    # -------------------------------------------------------------- reporters
    def snapshot(self) -> Dict:
        with self._lock:
            out: Dict = {n: getattr(self, n) for n in self._COUNTERS}
            out["per_class"] = {
                k: dict(v) for k, v in sorted(self._per_class.items())
            }
            out["replica_states"] = dict(sorted(self._replica_states.items()))
            classes = list(self.latency)
        out["latency_ms"] = {
            k: self.latency[k].snapshot() for k in classes
        }
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition — the router /metrics payload."""
        p = "hydragnn_route"
        snap = self.snapshot()
        lines = []
        for name in self._COUNTERS:
            lines.append(f"# TYPE {p}_{name} counter")
            lines.append(f"{p}_{name} {snap[name]}")
        lines.append(f"# TYPE {p}_class_requests_total counter")
        for klass, c in snap["per_class"].items():
            lines.append(
                f'{p}_class_requests_total{{class="{klass}"}} '
                f"{c['requests']}"
            )
        lines.append(f"# TYPE {p}_class_shed_total counter")
        for klass, c in snap["per_class"].items():
            lines.append(
                f'{p}_class_shed_total{{class="{klass}"}} {c["shed"]}'
            )
        # One gauge sample per replica, state as a label (value is always 1
        # for the current state — the standard state-set exposition).
        lines.append(f"# TYPE {p}_replica_state gauge")
        for name, state in snap["replica_states"].items():
            lines.append(
                f'{p}_replica_state{{replica="{name}",state="{state}"}} 1'
            )
        lines.append(f"# TYPE {p}_latency_seconds histogram")
        with self._lock:
            hists = dict(self.latency)
        for klass, hist in hists.items():
            lines.extend(
                hist.prometheus_lines(
                    f"{p}_latency_seconds", labels=f'class="{klass}"'
                )
            )
        return "\n".join(lines) + "\n"
