"""graftel exporters: JSONL event log + Chrome-trace (Perfetto-loadable)
JSON, plus the schema validators the tier-1 tests, ``bench.py --trace``, and
the CI smoke step share (docs/OBSERVABILITY.md "Exporter formats").

JSONL: line 1 is a header record (``kind: "header"``, schema tag, pid,
trace id); every following line is one span/event record exactly as graftel
recorded it. Chrome trace: the standard ``{"traceEvents": [...]}`` object —
complete ``"X"`` duration events in microseconds plus per-thread ``"M"``
thread_name metadata — which chrome://tracing and ui.perfetto.dev load
directly.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Tuple

from . import graftel

_RECORD_KINDS = ("span", "event")


def _records(records: Optional[List[dict]]) -> List[dict]:
    """Explicit records, else the collect buffer, else the ring — so a
    ring-only run can still be exported (bounded window, clearly enough for
    the short traced runs the exporters target)."""
    if records is not None:
        return records
    collected = graftel.collected_records()
    return collected if collected else graftel.snapshot_records()


def export_events_jsonl(
    path: str, records: Optional[List[dict]] = None
) -> int:
    """Write the JSONL event log; returns the number of data records."""
    recs = _records(records)
    header = {
        "kind": "header",
        "schema": graftel.SCHEMA_EVENTS,
        "ts_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "pid": os.getpid(),
        "records": len(recs),
    }
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        f.write(json.dumps(header) + "\n")
        for rec in recs:
            f.write(json.dumps(rec, default=str) + "\n")
    os.replace(tmp, path)
    return len(recs)


def _tid(thread_name: str, table: Dict[str, int]) -> int:
    tid = table.get(thread_name)
    if tid is None:
        tid = table[thread_name] = len(table) + 1
    return tid


def export_chrome_trace(
    path: str, records: Optional[List[dict]] = None
) -> int:
    """Write a Chrome-trace JSON of the spans/events; returns the number of
    trace events (excluding thread-name metadata)."""
    recs = _records(records)
    pid = os.getpid()
    tids: Dict[str, int] = {}
    events = []
    for rec in recs:
        args = dict(rec.get("attrs") or {})
        for k in ("request_id", "span_id", "parent_id"):
            if rec.get(k):
                args[k] = rec[k]
        base = {
            "name": rec.get("name", "?"),
            "pid": pid,
            "tid": _tid(rec.get("thread", "?"), tids),
            "ts": float(rec.get("ts", 0.0)) * 1e6,
            "args": args,
        }
        if rec.get("kind") == "span":
            base["ph"] = "X"
            base["dur"] = max(float(rec.get("dur_s", 0.0)) * 1e6, 0.01)
        else:
            base["ph"] = "i"
            base["s"] = "t"
        events.append(base)
    meta = [
        {
            "name": "thread_name",
            "ph": "M",
            "pid": pid,
            "tid": tid,
            "args": {"name": tname},
        }
        for tname, tid in tids.items()
    ]
    doc = {"traceEvents": meta + events, "displayTimeUnit": "ms"}
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, default=str)
    os.replace(tmp, path)
    return len(events)


# ------------------------------------------------------------------ validators
def validate_record(rec: dict) -> List[str]:
    """Schema errors of one span/event record ([] when valid)."""
    errors = []
    if not isinstance(rec, dict):
        return ["record is not an object"]
    kind = rec.get("kind")
    if kind not in _RECORD_KINDS:
        return [f"bad kind {kind!r}"]
    for key, typ in (
        ("name", str),
        ("ts", (int, float)),
        ("thread", str),
        ("trace_id", str),
        ("span_id", str),
    ):
        if not isinstance(rec.get(key), typ):
            errors.append(f"{kind} missing/invalid {key!r}")
    if kind == "span" and not isinstance(rec.get("dur_s"), (int, float)):
        errors.append("span missing/invalid 'dur_s'")
    return errors


def validate_events_jsonl(path: str) -> Tuple[int, List[str]]:
    """(record count, schema errors) of a JSONL event log. A valid log has a
    schema-tagged header line and >= 0 valid records; emptiness is the
    CALLER's check (the CI smoke asserts non-empty)."""
    errors: List[str] = []
    count = 0
    try:
        with open(path) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
    except OSError as e:
        return 0, [f"unreadable: {e}"]
    if not lines:
        return 0, ["empty file (no header line)"]
    try:
        header = json.loads(lines[0])
    except ValueError as e:
        return 0, [f"header line is not JSON: {e}"]
    if header.get("kind") != "header" or header.get("schema") != graftel.SCHEMA_EVENTS:
        errors.append(
            f"bad header (kind={header.get('kind')!r}, "
            f"schema={header.get('schema')!r})"
        )
    for i, line in enumerate(lines[1:], start=2):
        try:
            rec = json.loads(line)
        except ValueError as e:
            errors.append(f"line {i}: not JSON ({e})")
            continue
        errors.extend(f"line {i}: {e}" for e in validate_record(rec))
        count += 1
    return count, errors


def validate_flight(doc: dict) -> List[str]:
    """Schema errors of one flight-recorder dump document."""
    errors = []
    if not isinstance(doc, dict):
        return ["dump is not an object"]
    if doc.get("schema") != graftel.SCHEMA_FLIGHT:
        errors.append(f"bad schema tag {doc.get('schema')!r}")
    for key, typ in (
        ("trigger", str),
        ("ts_utc", str),
        ("pid", int),
        ("seq", int),
        ("records", list),
        ("counters", dict),
        ("gauges", dict),
    ):
        if not isinstance(doc.get(key), typ):
            errors.append(f"missing/invalid {key!r}")
    for i, rec in enumerate(doc.get("records") or []):
        errors.extend(f"records[{i}]: {e}" for e in validate_record(rec))
    return errors


def validate_flight_file(path: str) -> List[str]:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable: {e}"]
    return validate_flight(doc)


def validate_chrome_trace(path: str) -> List[str]:
    """Loads the Chrome-trace JSON back and checks the event structure —
    the "Perfetto export loads back" half of the tier-1 coverage."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"unreadable: {e}"]
    errors = []
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["missing traceEvents list"]
    for i, ev in enumerate(events):
        if not isinstance(ev, dict) or "ph" not in ev or "pid" not in ev:
            errors.append(f"traceEvents[{i}]: missing ph/pid")
            continue
        if ev["ph"] == "X" and not isinstance(ev.get("dur"), (int, float)):
            errors.append(f"traceEvents[{i}]: X event without dur")
        if ev["ph"] != "M" and not isinstance(ev.get("ts"), (int, float)):
            errors.append(f"traceEvents[{i}]: event without ts")
    return errors


def span_counts(records: Optional[List[dict]] = None) -> Dict[str, int]:
    """{record name: count} over the span/event stream — the per-layer span
    census ``bench.py --trace`` embeds in TRACE_rNN.json."""
    out: Dict[str, int] = {}
    for rec in _records(records):
        name = rec.get("name", "?")
        out[name] = out.get(name, 0) + 1
    return out
