"""graftel — unified structured tracing, flight recorder, and cross-layer
telemetry (docs/OBSERVABILITY.md).

One process-wide hub the five formerly-disconnected surfaces (``Timer``,
``FeedStats``, ``ServeMetrics``, ``FaultCounters``, ``supervisor.json``) now
emit into: spans/events with thread-aware context propagation across the
stack's seven host thread roots, serve request correlation ids carried
end-to-end, a bounded flight-recorder ring dumped on guard trips / engine
poisoning / checkpoint fallbacks / supervisor restarts, JSONL + Chrome-trace
exporters, a jax compile/annotation bridge, and a Prometheus rendering of
the shared metric registry (training gauges included).

CLI: ``python -m hydragnn_tpu.telemetry smoke`` runs a 2-epoch traced
synthetic train and schema-validates every exporter (the CI smoke step);
``... validate <path>`` checks an existing artifact.
"""

from __future__ import annotations

from .export import (
    export_chrome_trace,
    export_events_jsonl,
    span_counts,
    validate_chrome_trace,
    validate_events_jsonl,
    validate_flight,
    validate_flight_file,
)
from .graftel import (
    SCHEMA_EVENTS,
    SCHEMA_FLIGHT,
    Context,
    attach,
    clear_counters,
    collected_records,
    collecting,
    configure,
    configured_run_dir,
    counter,
    counter_value,
    counters_snapshot,
    current,
    detach,
    event,
    flight_dump,
    gauge,
    gauges_snapshot,
    install_jax_hooks,
    new_context,
    new_request_id,
    record_span,
    render_prometheus,
    reset,
    snapshot_records,
    span,
    timer_credit,
    timer_totals,
)

__all__ = [
    "SCHEMA_EVENTS",
    "SCHEMA_FLIGHT",
    "Context",
    "attach",
    "clear_counters",
    "collected_records",
    "collecting",
    "configure",
    "configured_run_dir",
    "counter",
    "counter_value",
    "counters_snapshot",
    "current",
    "detach",
    "event",
    "export_chrome_trace",
    "export_events_jsonl",
    "flight_dump",
    "gauge",
    "gauges_snapshot",
    "install_jax_hooks",
    "new_context",
    "new_request_id",
    "record_span",
    "render_prometheus",
    "reset",
    "snapshot_records",
    "span",
    "span_counts",
    "timer_credit",
    "timer_totals",
    "validate_chrome_trace",
    "validate_events_jsonl",
    "validate_flight",
    "validate_flight_file",
]
