"""graftel — process-wide structured tracing, flight recorder, and metric
registry for the whole train/serve stack (docs/OBSERVABILITY.md).

Before this module the stack had five disconnected telemetry surfaces
(``Timer``, ``FeedStats``, ``ServeMetrics``, ``FaultCounters``,
``supervisor.json``), none of which could answer "what was happening across
the stack when step K went bad / request R breached its deadline?". graftel
is the hub they all emit into:

* **Spans and events.** ``span(name, **attrs)`` is a context manager timing a
  wall-clock region; ``event(name, **attrs)`` records an instant. Both carry
  a :class:`Context` (trace id, span id, optional request correlation id) and
  the emitting thread's name. Same-thread nesting rides a thread-local
  context stack; CROSS-thread propagation is explicit — a producer captures
  ``current()`` (or a span's ``.ctx``) and the consumer thread calls
  ``attach(ctx)`` (the DeviceFeed pipeline and the serve dispatcher do this),
  because the stack's seven thread roots make thread-locals alone a dead end.

* **Flight recorder.** Every record also lands in a bounded ring
  (``deque(maxlen=...)``) that is ALWAYS on; ``flight_dump(trigger)`` writes
  the ring + counter/gauge snapshot to
  ``<run_dir>/flightrec_<pid>_<seq>_<trigger>.json``. Wired triggers:
  non-finite step-guard trips (faults/guard.py), engine poisoning
  (serve/engine.py), checkpoint-fallback loads (checkpoint/io.py),
  supervisor restarts (faults/supervisor.py), and elastic dirty-shrink
  transitions (parallel/elastic.py — the timeline that led into a worker
  death, next to the checkpoint the shrunk world resumed from).

* **Metric registry.** ``counter``/``gauge``/``timer_credit`` feed one locked
  registry; ``Timer`` and ``FaultCounters`` delegate their storage here, so
  ``print_timers``, ``bench.py``, and the serve ``/metrics`` exposition all
  read the same numbers. ``render_prometheus()`` exports the registry in
  Prometheus text format — including the per-epoch training gauges
  (``hydragnn_train_*``) the epoch loop publishes.

* **jax bridges.** ``install_jax_hooks()`` registers a monitoring listener
  that folds every XLA backend compile into the registry
  (``jax/compiles`` + ``jax/compile_s``) and the ring;
  ``configure(jax_annotations=True)`` makes every span also open a
  ``jax.profiler.TraceAnnotation`` so host spans line up with device ops in
  a captured Perfetto trace.

Zero-surprise defaults: the ring and registry are always live (host-side,
one uncontended lock acquisition per record — measured < 2% of a steady CPU
train epoch, ``bench.py --trace``); full span COLLECTION for the JSONL /
Chrome-trace exporters is opt-in (``configure(collect=True)``, the
``Telemetry`` config block, or ``HYDRAGNN_TRACE=1``). ``enabled=False``
silences span/event recording entirely while keeping the counter registry
(Timer/FaultCounters storage) functional.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from ..analysis import tsan

SCHEMA_EVENTS = "hydragnn-graftel-events/v1"
SCHEMA_FLIGHT = "hydragnn-flightrec/v1"

_RING_CAPACITY = 4096

_lock = tsan.instrument_lock(threading.Lock(), "graftel._lock")
# The record stream: ring is the always-on flight-recorder window; collected
# is the unbounded export buffer, a list only while collect mode is on.
_ring: "deque" = deque(maxlen=_RING_CAPACITY)  # guarded-by: _lock
_collected: Optional[List[dict]] = None  # guarded-by: _lock
# Metric registry (one store for Timer / FaultCounters / train gauges).
_counters: Dict[str, float] = {}  # guarded-by: _lock
_gauges: Dict[str, float] = {}  # guarded-by: _lock
_dump_seq = 0  # guarded-by: _lock
# Span-id source: itertools.count.__next__ is a single C call (GIL-atomic),
# so id allocation never touches the registry lock — spans stay cheap on the
# per-batch hot paths even while another thread holds _lock for a dump.
_id_counter = itertools.count(1)
# Config flags. Hot-path readers (span/event fast paths) read these
# unlocked; writers hold the lock.
_enabled = True  # guarded-by: _lock, dirty-reads(bool flag flipped only by configure(); a stale read records or skips one extra record, never corrupts state)
_run_dir: Optional[str] = None  # guarded-by: _lock, dirty-reads(rebound only by configure(); a dump racing a reconfigure writes to the old run dir, which is correct for the events it holds)
_jax_annotations = False  # guarded-by: _lock, dirty-reads(bool flag flipped only by configure(); a stale read annotates or skips one span)
_jax_hooks_installed = False  # guarded-by: _lock

# Per-process trace id — every record of this process shares it, so merged
# event logs from a supervised run's incarnations stay separable.
_TRACE_ID = uuid.uuid4().hex[:16]

_tls = threading.local()  # context stacks are thread-local (self-synced)


# ------------------------------------------------------------------ contexts
@dataclass(frozen=True)
class Context:
    """An explicit handoff token: (trace, parent span, request correlation).

    Producers capture one (``current()`` or ``span.ctx``) and hand it to the
    thread/callable that continues the work; the receiver either passes it as
    ``parent=`` or installs it as the thread's base with :func:`attach`."""

    trace_id: str
    span_id: str
    request_id: Optional[str] = None


def _new_span_id() -> str:
    return f"s{next(_id_counter):08x}"


def new_context(request_id: Optional[str] = None) -> Context:
    """Fresh root context (e.g. one per serve-pipeline incarnation)."""
    return Context(_TRACE_ID, _new_span_id(), request_id)


def new_request_id() -> str:
    """Serve correlation id: carried submit → pack bin → device batch →
    demux → response (+ echoed in the X-HydraGNN-Request-Id header)."""
    return "r-" + uuid.uuid4().hex[:12]


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current() -> Optional[Context]:
    """This thread's innermost context (None outside any span/attach)."""
    st = _stack()
    return st[-1] if st else None


def attach(ctx: Optional[Context]) -> None:
    """Install ``ctx`` as this thread's base context — the explicit
    cross-thread handoff (DeviceFeed stage threads, the serve dispatcher)."""
    if ctx is not None:
        _stack().append(ctx)


def detach() -> None:
    st = _stack()
    if st:
        st.pop()


# ------------------------------------------------------------------- records
def _record(rec: dict) -> None:
    with _lock:
        _ring.append(rec)
        if _collected is not None:
            _collected.append(rec)


class span:
    """Timed region. Plain class (not contextlib) — it sits in per-batch hot
    loops, so one small allocation per use, like pipeline.timed_consume."""

    __slots__ = ("name", "attrs", "ctx", "_parent", "_t0", "_wall0", "_jax", "_off")

    def __init__(
        self,
        name: str,
        parent: Optional[Context] = None,
        request_id: Optional[str] = None,
        **attrs: Any,
    ):
        self.name = name
        self.attrs = attrs
        self._parent = parent
        self.ctx = Context(
            _TRACE_ID,
            _new_span_id(),
            request_id
            if request_id is not None
            else (parent.request_id if parent is not None else None),
        )
        self._jax = None
        self._off = False

    def __enter__(self):
        # Disabled fast path: no stack/clock/annotation work — the .ctx is
        # still real (callers hand it to DeviceFeed regardless), but nothing
        # records, so the bench A/B's disabled arm is a near-zero baseline.
        if not _enabled:
            self._off = True
            return self
        parent = self._parent if self._parent is not None else current()
        if parent is not None and self.ctx.request_id is None and parent.request_id:
            self.ctx = Context(self.ctx.trace_id, self.ctx.span_id, parent.request_id)
        self._parent = parent
        _stack().append(self.ctx)
        if _jax_annotations:
            try:
                import jax

                self._jax = jax.profiler.TraceAnnotation(self.name)
                self._jax.__enter__()
            except Exception:
                self._jax = None
        self._wall0 = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._off:
            return
        dur = time.perf_counter() - self._t0
        if self._jax is not None:
            self._jax.__exit__(*exc)
        st = _stack()
        if st and st[-1] is self.ctx:
            st.pop()
        if not _enabled:
            return
        rec = {
            "kind": "span",
            "name": self.name,
            "ts": self._wall0,
            "dur_s": dur,
            "thread": threading.current_thread().name,
            "trace_id": self.ctx.trace_id,
            "span_id": self.ctx.span_id,
            "parent_id": self._parent.span_id if self._parent else None,
        }
        if self.ctx.request_id:
            rec["request_id"] = self.ctx.request_id
        if self.attrs:
            rec["attrs"] = self.attrs
        _record(rec)


def record_span(
    name: str,
    dur_s: float,
    parent: Optional[Context] = None,
    request_id: Optional[str] = None,
    **attrs: Any,
) -> None:
    """Retroactive span for a region timed elsewhere (FeedStats' H2D wire
    time is measured by its own perf_counter pair on the transfer thread)."""
    if not _enabled:
        return
    ctx = parent if parent is not None else current()
    rec = {
        "kind": "span",
        "name": name,
        "ts": time.time() - dur_s,
        "dur_s": float(dur_s),
        "thread": threading.current_thread().name,
        "trace_id": _TRACE_ID,
        "span_id": _new_span_id(),
        "parent_id": ctx.span_id if ctx else None,
    }
    rid = request_id or (ctx.request_id if ctx else None)
    if rid:
        rec["request_id"] = rid
    if attrs:
        rec["attrs"] = attrs
    _record(rec)


def event(name: str, request_id: Optional[str] = None, **attrs: Any) -> None:
    """Instant record (fault fired, request admitted, engine degraded...)."""
    if not _enabled:
        return
    ctx = current()
    rec = {
        "kind": "event",
        "name": name,
        "ts": time.time(),
        "thread": threading.current_thread().name,
        "trace_id": _TRACE_ID,
        "span_id": _new_span_id(),
        "parent_id": ctx.span_id if ctx else None,
    }
    rid = request_id or (ctx.request_id if ctx else None)
    if rid:
        rec["request_id"] = rid
    if attrs:
        rec["attrs"] = attrs
    _record(rec)


# ----------------------------------------------------------- metric registry
def counter(name: str, n: float = 1.0) -> None:
    with _lock:
        _counters[name] = _counters.get(name, 0.0) + n
        tsan.shared_access("graftel.registry")


def timer_credit(name: str, seconds: float) -> None:
    """The Timer storage op: accumulate seconds under ``timer/<name>``."""
    counter("timer/" + name, float(seconds))


def gauge(name: str, value: float) -> None:
    with _lock:
        _gauges[name] = float(value)
        tsan.shared_access("graftel.registry")


def counter_value(name: str) -> float:
    with _lock:
        return _counters.get(name, 0.0)


def counters_snapshot(prefix: str = "") -> Dict[str, float]:
    with _lock:
        return {
            k: v for k, v in _counters.items() if k.startswith(prefix)
        }


def gauges_snapshot() -> Dict[str, float]:
    with _lock:
        return dict(_gauges)


def timer_totals() -> Dict[str, float]:
    """{timer name: accumulated seconds} — the Timer.snapshot() payload."""
    pre = "timer/"
    with _lock:
        return {
            k[len(pre):]: v for k, v in _counters.items() if k.startswith(pre)
        }


def clear_counters(prefix: str) -> None:
    """Reset one delegated namespace (Timer.reset / FaultCounters.reset)."""
    with _lock:
        for k in [k for k in _counters if k.startswith(prefix)]:
            del _counters[k]


def snapshot_records() -> List[dict]:
    """Locked copy of the flight-recorder ring (newest last)."""
    with _lock:
        return list(_ring)


def collected_records() -> List[dict]:
    """Locked copy of the export buffer ([] when collect mode is off)."""
    with _lock:
        return list(_collected) if _collected is not None else []


# ----------------------------------------------------------------- lifecycle
def configure(
    run_dir: Optional[str] = None,
    collect: Optional[bool] = None,
    enabled: Optional[bool] = None,
    jax_annotations: Optional[bool] = None,
) -> None:
    """Process-wide setup. Omitted arguments keep their current value.
    ``run_dir`` is where flight-recorder dumps land (run_training points it
    at ``./logs/<name>``); ``collect=True`` buffers every record for the
    JSONL/Chrome exporters; ``enabled=False`` silences span/event recording
    (the counter registry stays live — Timer storage must keep working)."""
    global _run_dir, _collected, _enabled, _jax_annotations
    with _lock:
        if run_dir is not None:
            _run_dir = run_dir
        if enabled is not None:
            _enabled = bool(enabled)
        if jax_annotations is not None:
            _jax_annotations = bool(jax_annotations)
        if collect is not None:
            if collect and _collected is None:
                _collected = []
            elif not collect:
                _collected = None


def configured_run_dir() -> Optional[str]:
    with _lock:
        return _run_dir


def collecting() -> bool:
    with _lock:
        return _collected is not None


def reset(keep_config: bool = False) -> None:
    """Clear records + registry (tests). ``keep_config`` keeps run_dir /
    collect / enabled; the default restores module defaults."""
    global _collected, _run_dir, _enabled, _jax_annotations
    with _lock:
        _ring.clear()
        _counters.clear()
        _gauges.clear()
        if _collected is not None:
            _collected = []
        if not keep_config:
            _collected = None
            _run_dir = None
            _enabled = True
            _jax_annotations = False


# ------------------------------------------------------------ flight recorder
def flight_dump(
    trigger: str, run_dir: Optional[str] = None, extra: Optional[dict] = None
) -> Optional[str]:
    """Dump the ring + registry snapshot to
    ``<run_dir>/flightrec_<pid>_<seq>_<trigger>.json``; returns the path, or
    None when no run dir is known (telemetry never configured — a library
    user exercising the engine standalone). Never raises: a failing dump must
    not take down the run it is documenting."""
    global _dump_seq
    target = run_dir if run_dir is not None else _run_dir
    if not target:
        return None
    with _lock:
        _dump_seq += 1
        seq = _dump_seq
        records = list(_ring)
        counters = dict(_counters)
        gauges = dict(_gauges)
    doc = {
        "schema": SCHEMA_FLIGHT,
        "trigger": trigger,
        "ts_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "pid": os.getpid(),
        "trace_id": _TRACE_ID,
        "seq": seq,
        "records": records,
        "counters": counters,
        "gauges": gauges,
    }
    if extra:
        doc["extra"] = extra
    safe = "".join(c if c.isalnum() or c in "-_" else "_" for c in trigger)
    path = os.path.join(
        target, f"flightrec_{os.getpid()}_{seq:03d}_{safe}.json"
    )
    try:
        import json

        os.makedirs(target, exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, default=str)
        os.replace(tmp, path)
    except OSError:
        return None
    return path


# ------------------------------------------------------------------ jax hooks
def install_jax_hooks() -> None:
    """Fold XLA backend compiles into the registry + ring: one monitoring
    event fires per real compile (the recompile sentinel's mechanism,
    analysis/sentinel.py), so ``jax/compiles`` / ``jax/compile_s`` track
    compile count and seconds for ANY path — the training Prometheus compile
    gauge reads the per-epoch delta. Idempotent."""
    global _jax_hooks_installed
    with _lock:
        if _jax_hooks_installed:
            return
        _jax_hooks_installed = True
    import jax

    def _on_compile(name: str, duration: float, **kwargs) -> None:
        if name != "/jax/core/compile/backend_compile_duration":
            return
        counter("jax/compiles", 1.0)
        counter("jax/compile_s", float(duration))
        event("jax/compile", duration_s=round(float(duration), 4))

    jax.monitoring.register_event_duration_secs_listener(_on_compile)


# ------------------------------------------------------------------ prom text
def _prom_name(prefix: str, key: str) -> str:
    return prefix + "_" + "".join(
        c if c.isalnum() or c == "_" else "_" for c in key
    )


def render_prometheus(prefix: str = "hydragnn") -> str:
    """Registry → Prometheus text exposition: every counter as
    ``<prefix>_<name>_total``, every gauge as ``<prefix>_<name>`` — this is
    where the TRAINING path's per-epoch step/h2d/compile gauges surface
    (docs/OBSERVABILITY.md catalogue). The serve front end appends this to
    its engine-scoped /metrics payload."""
    with _lock:
        counters = dict(_counters)
        gauges = dict(_gauges)
    lines = []
    for key in sorted(counters):
        name = _prom_name(prefix, key) + "_total"
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name} {counters[key]}")
    for key in sorted(gauges):
        name = _prom_name(prefix, key)
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {gauges[key]}")
    return "\n".join(lines) + ("\n" if lines else "")
