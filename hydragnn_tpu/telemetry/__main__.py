"""graftel CLI: traced-train smoke + artifact validation.

``python -m hydragnn_tpu.telemetry smoke [--out DIR]``
    Run a 2-epoch traced synthetic train (CPU-safe, seconds), export the
    JSONL event log and the Chrome trace, round-trip a flight-recorder dump,
    and schema-validate all three. Exit 1 on any empty or invalid artifact —
    the CI smoke step (.github/workflows/static-analysis.yml).

``python -m hydragnn_tpu.telemetry validate <path>``
    Schema-validate an existing artifact (``*.jsonl`` event log,
    ``flightrec_*.json`` dump, or Chrome-trace JSON by sniffing).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

from . import (
    export_chrome_trace,
    export_events_jsonl,
    flight_dump,
    span_counts,
    validate_chrome_trace,
    validate_events_jsonl,
    validate_flight_file,
)
from . import configure as telemetry_configure


def _smoke_train(epochs: int = 2) -> None:
    """Tiny deterministic SAGE run through the REAL epoch driver — the spans
    the exporters must carry come from the production pipeline wiring."""
    import numpy as np

    from ..graphs.sample import GraphSample
    from ..models import create_model, init_model_variables
    from ..preprocess.dataloader import GraphDataLoader
    from ..train.train_validate_test import TrainingDriver
    from ..train.trainer import create_train_state
    from ..utils.optimizer import select_optimizer

    rng = np.random.default_rng(0)
    samples = []
    for _ in range(8):
        n = 6
        x = rng.normal(size=(n, 1)).astype(np.float32)
        senders = np.repeat(np.arange(n), 2)
        receivers = (senders + 1 + np.arange(senders.size) % (n - 1)) % n
        samples.append(
            GraphSample(
                x=x,
                pos=rng.random((n, 3)).astype(np.float32),
                y=np.array([x.sum()], np.float32),
                y_loc=np.array([[0, 1]], np.int64),
                edge_index=np.stack([senders, receivers]).astype(np.int64),
            )
        )
    loader = GraphDataLoader(samples, batch_size=4, shuffle=False)
    loader.set_head_spec(("graph",), (1,))
    heads = {
        "graph": {
            "num_sharedlayers": 1,
            "dim_sharedlayers": 4,
            "num_headlayers": 1,
            "dim_headlayers": [4],
        }
    }
    model = create_model("SAGE", 1, 8, (1,), ("graph",), heads, [1.0], 2)
    batch = next(iter(loader))
    variables = init_model_variables(model, batch)
    opt = select_optimizer("AdamW", 1e-3)
    state = create_train_state(model, variables, opt)
    driver = TrainingDriver(model, opt, state)
    for _ in range(epochs):
        driver.train_epoch(loader)
    driver.evaluate(loader)


def smoke(out_dir: str | None = None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    tmp_ctx = None
    if out_dir is None:
        tmp_ctx = tempfile.TemporaryDirectory(prefix="graftel_smoke_")
        out_dir = tmp_ctx.name
    os.makedirs(out_dir, exist_ok=True)
    telemetry_configure(run_dir=out_dir, collect=True)
    failures = []
    try:
        _smoke_train()

        jsonl_path = os.path.join(out_dir, "trace_events.jsonl")
        n_events = export_events_jsonl(jsonl_path)
        count, errors = validate_events_jsonl(jsonl_path)
        if count == 0:
            failures.append("JSONL event log is empty")
        failures.extend(f"jsonl: {e}" for e in errors)

        chrome_path = os.path.join(out_dir, "trace_chrome.json")
        export_chrome_trace(chrome_path)
        failures.extend(
            f"chrome: {e}" for e in validate_chrome_trace(chrome_path)
        )

        dump_path = flight_dump("smoke")
        if dump_path is None:
            failures.append("flight_dump returned no path")
        else:
            failures.extend(
                f"flight: {e}" for e in validate_flight_file(dump_path)
            )

        counts = span_counts()
        for required in ("train_epoch", "collate", "device_step"):
            if not counts.get(required):
                failures.append(f"no '{required}' spans in the trace")
        print(
            json.dumps(
                {
                    "ok": not failures,
                    "events": n_events,
                    "span_counts": counts,
                    "failures": failures,
                }
            )
        )
    finally:
        if tmp_ctx is not None:
            tmp_ctx.cleanup()
    return 1 if failures else 0


def validate(path: str) -> int:
    if path.endswith(".jsonl"):
        count, errors = validate_events_jsonl(path)
        ok = count > 0 and not errors
    else:
        with open(path) as f:
            head = f.read(4096)
        if '"traceEvents"' in head:
            errors = validate_chrome_trace(path)
        else:
            errors = validate_flight_file(path)
        ok = not errors
    print(json.dumps({"ok": ok, "path": path, "errors": errors}))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m hydragnn_tpu.telemetry")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sp = sub.add_parser("smoke", help="2-epoch traced train + validation")
    sp.add_argument("--out", default=None, help="artifact dir (default: tmp)")
    vp = sub.add_parser("validate", help="schema-validate one artifact")
    vp.add_argument("path")
    args = ap.parse_args(argv)
    if args.cmd == "smoke":
        return smoke(args.out)
    return validate(args.path)


if __name__ == "__main__":
    sys.exit(main())
