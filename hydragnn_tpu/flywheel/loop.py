"""graftloop — the continuous-learning flywheel's control loop
(docs/FLYWHEEL.md; ROADMAP item 4's "train WHILE serving" leg).

One :class:`Flywheel` closes two feedback loops over machinery that already
exists but was human-cranked:

**Weights loop** (checkpoint → candidate → shadow → promote/reject)::

    trainer save_model()                (checkpoint/io.py, sync or async)
      → post-save hook                  (observed here, writer thread)
      → registry.stage_candidate()      (digest-verified identity)
      → shadow engine loads candidate   (verified load, swap_weights)
      → router.set_shadow(...)          (sampled live traffic, diff gate)
      → GREEN  → manager.promote()      (auto-promotion, fleet-wide swap)
      → RED    → quarantine + flight dump (``flywheel_reject``) + clear
                 candidate — the poisoned fine-tune NEVER serves a request

**Data loop** (traffic histogram → drift → refit → ladder swap)::

    serve metrics size histograms       (per-tick deltas, all engines)
      → DriftDetector.observe/evaluate  (hysteresis — drift.py)
      → sustained drift → fit_ladder()  (graphs/packing.py, window traffic)
      → engine.swap_ladder(warm=True)   (rungs warmed through graftcache on
                                         THIS background thread, then one
                                         atomic publish per engine — zero
                                         recompiles for already-seen rungs)
      → detector.rebase(window)         (new ladder's source = new anchor)

Threading model: the post-save hook runs on the checkpoint writer thread
and only enqueues into a self-synchronizing queue; all decisions execute on
the single ``hydragnn-flywheel`` control thread (or a test/drill's direct
``tick()`` calls — the loop IS tick() in a timer). Cross-thread state is
``# guarded-by:``-annotated; counters live under one instrumented lock.

Refusal-first inheritance: every load rides the registry's verified chain
(a corrupt candidate is rejected and quarantined, the fleet untouched);
``manager.promote()`` re-checks the gate and unwinds half-applied fleet
swaps; a kill between weight publication and role persistence leaves a
consistent role table (the incarnation contract the kill drill pins).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from dataclasses import asdict, dataclass
from typing import Any, Dict, List, Optional

from ..analysis import tsan
from ..graphs.packing import fit_ladder
from ..lifecycle import (
    CandidateVerificationError,
    LifecycleError,
    LifecycleManager,
    ModelRegistry,
    ModelVersion,
    SwapGateError,
)
from ..telemetry import graftel as telemetry


@dataclass
class FlywheelConfig:
    """Knobs for both loops. The same fields ride the ``flywheel:`` config
    block ``contracts.check_config`` statically gates (``bad-flywheel``
    findings) — the runtime re-validates the load-bearing invariants in
    ``__post_init__`` so a hand-built config cannot skip the contract."""

    # Weights loop.
    shadow_fraction: float = 1.0
    shadow_tolerance: float = 1e-5
    shadow_min_samples: int = 8
    auto_promote: bool = True
    gate_window_s: float = 0.5  # min wall a candidate sits armed before verdict
    gate_patience_s: float = 60.0  # armed longer than this without quota → reject
    # Data loop.
    drift_high: float = 0.35
    drift_low: float = 0.15
    drift_window: int = 4
    drift_sustain: int = 3
    refit_interval_s: float = 1.0  # min seconds between drift evaluations
    max_rungs: int = 4
    # Control loop.
    tick_interval_s: float = 0.05
    quarantine_dir: str = "quarantine"

    def __post_init__(self) -> None:
        if self.auto_promote and not (
            isinstance(self.shadow_tolerance, (int, float))
            and self.shadow_tolerance > 0
        ):
            raise ValueError(
                "auto-promotion requires a positive shadow tolerance — an "
                "ungated automatic promotion would serve any candidate"
            )
        if not (0.0 < self.drift_low < self.drift_high < 1.0):
            raise ValueError(
                f"drift thresholds must satisfy 0 < low < high < 1, got "
                f"low={self.drift_low!r} high={self.drift_high!r}"
            )
        if self.refit_interval_s < self.gate_window_s:
            raise ValueError(
                f"refit_interval_s ({self.refit_interval_s}) must be >= "
                f"gate_window_s ({self.gate_window_s}): a ladder refit "
                "landing mid-gate-window would change the traffic the "
                "candidate is being judged on"
            )

    def to_json(self) -> Dict[str, Any]:
        return asdict(self)


class Flywheel:
    """Supervisor-mode control loop: one registry + manager + router +
    dedicated shadow engine, two closed feedback loops.

    Parameters
    ----------
    registry / manager / router:
        The graftswap trio (lifecycle/, route/). ``manager.engines`` is the
        live fleet the data loop reads histograms from and swaps ladders
        on; the router is where the shadow arm is armed.
    shadow_engine:
        A dedicated ``InferenceEngine`` NOT in the router's ring — the
        candidate's weights are loaded (verified) into it for the shadow
        arm. Reused across candidates; never serves live traffic.
    source_hist:
        The current ladder's source observations (a ``SizeHistogram`` or
        ``[(nodes, edges, weight)]`` rows) anchoring the drift detector.
    run_dir:
        The run directory (defaults to ``registry.run_dir``): quarantine
        copies and flight-recorder dumps land here.
    """

    def __init__(
        self,
        registry: ModelRegistry,
        manager: LifecycleManager,
        router: Any,
        shadow_engine: Any,
        source_hist: Any,
        config: Optional[FlywheelConfig] = None,
        run_dir: Optional[str] = None,
    ):
        from .drift import DriftDetector

        self.registry = registry
        self.manager = manager
        self.router = router
        self.shadow_engine = shadow_engine
        self.config = config or FlywheelConfig()
        self.run_dir = run_dir or registry.run_dir
        self.detector = DriftDetector(
            source_hist,
            high=self.config.drift_high,
            low=self.config.drift_low,
            window=self.config.drift_window,
            sustain=self.config.drift_sustain,
        )
        self._lock = tsan.instrument_lock(threading.Lock(), "Flywheel._lock")
        # Checkpoint paths observed by the post-save hook (writer thread) —
        # a self-synchronizing queue; the control thread drains + coalesces.
        self._pending: "queue.Queue[str]" = queue.Queue()
        # Armed-candidate record: {mv, gate, t_armed} while a shadow cycle
        # is in flight, else None. Written by the control thread, read by
        # report()/status threads.
        self._armed: Optional[Dict[str, Any]] = None  # guarded-by: self._lock
        # Per-engine cumulative size counts already fed to the detector
        # (engine id -> {(n, e): count}) — control thread only, but guarded
        # with the rest so report() can size it consistently.
        self._hist_seen: Dict[int, Dict[Any, int]] = {}  # guarded-by: self._lock
        self._counters: Dict[str, int] = {  # guarded-by: self._lock
            "checkpoints_observed": 0,
            "candidates_staged": 0,
            "stage_skipped": 0,
            "promotions": 0,
            "rejections": 0,
            "ladder_refits": 0,
            "ladder_swaps": 0,
        }
        self._last_reject: Optional[Dict[str, Any]] = None  # guarded-by: self._lock
        self._last_promote: Optional[Dict[str, Any]] = None  # guarded-by: self._lock
        self._last_drift_eval = 0.0  # control thread only  # guarded-by: self._lock, dirty-reads(written and read on the single control thread; the guard covers report())
        self._prior_hook: Optional[Any] = None
        self._attached = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ hook wiring
    def attach(self) -> "Flywheel":
        """Install the post-save observer, CHAINING any hook already
        registered (the TrainingDriver wires fault plans through the same
        module-global slot — both must keep firing)."""
        from ..checkpoint import io as ckpt_io

        if self._attached:
            return self
        self._prior_hook = ckpt_io._post_save_hook
        ckpt_io.set_post_save_hook(self._on_checkpoint_saved)
        self._attached = True
        return self

    def detach(self) -> None:
        from ..checkpoint import io as ckpt_io

        if self._attached:
            ckpt_io.set_post_save_hook(self._prior_hook)
            self._prior_hook = None
            self._attached = False

    def _on_checkpoint_saved(self, path_name: str) -> None:
        """Runs on the saver's thread (async writer or trainer) — observe
        and get out: fault hooks first (they may kill the process; that IS
        the drill), then enqueue for the control thread."""
        prior = self._prior_hook
        if prior is not None:
            prior(path_name)
        with self._lock:
            self._counters["checkpoints_observed"] += 1
        self._pending.put(path_name)
        telemetry.event(
            "flywheel/checkpoint_observed", file=os.path.basename(path_name)
        )

    # -------------------------------------------------------------- the loop
    def start(self) -> "Flywheel":
        """Run the control loop on a background thread (tick() on a timer).
        Tests and deterministic drills call :meth:`tick` directly instead."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="hydragnn-flywheel", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        self.detach()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the loop must outlive a bad tick
                telemetry.event("flywheel/tick_error", error=repr(e))
            self._stop.wait(self.config.tick_interval_s)

    def recover(self) -> Dict[str, Any]:
        """Restart-incarnation resume (the supervisor's incarnation
        contract): a candidate role that survived a kill is re-armed instead
        of forgotten. Judgement restarts from scratch — fresh gate, fresh
        shadow window — because the pre-kill comparisons died with the
        process; a half-promoted fleet was already handled by the registry's
        atomic role table (the kill drill pins this)."""
        with self._lock:
            armed = self._armed
        if armed is not None:
            return {"state": "armed", "candidate": armed["mv"].short}
        cand = self.registry.candidate
        if cand is None:
            return {"state": "idle"}
        telemetry.event("flywheel/recovered_candidate", version=cand.short)
        return self._stage_and_arm(cand.path)

    def tick(self) -> Dict[str, Any]:
        """One control-loop step: weights loop, then data loop. Idempotent
        when nothing changed; every decision lands in telemetry + counters."""
        weights = self._weights_step()
        data = self._data_step()
        return {"weights": weights, "data": data}

    # ---------------------------------------------------------- weights loop
    def _weights_step(self) -> Dict[str, Any]:
        with self._lock:
            armed = self._armed
        if armed is None:
            path = self._drain_pending()
            if path is None:
                return {"state": "idle"}
            return self._stage_and_arm(path)
        return self._judge(armed)

    def _drain_pending(self) -> Optional[str]:
        """Coalesce queued checkpoint paths to the NEWEST (each save
        overwrites ``<name>.pk`` — staging an older enqueue would just fail
        identity verification against the file's current bytes)."""
        path = None
        while True:
            try:
                path = self._pending.get_nowait()
            except queue.Empty:
                return path

    def _stage_and_arm(self, path: str) -> Dict[str, Any]:
        from ..route import InProcessReplica

        try:
            mv = self.registry.stage_candidate(path)
        except LifecycleError as e:
            # Same-as-live (a save with unchanged weights) or unverifiable:
            # nothing to gate. Not a rejection — no candidate existed.
            with self._lock:
                self._counters["stage_skipped"] += 1
            telemetry.event("flywheel/stage_skipped", reason=repr(e))
            return {"state": "idle", "staged": False}
        with self._lock:
            self._counters["candidates_staged"] += 1
        try:
            variables, _meta, loaded = self.registry.load_role(
                "candidate", self.shadow_engine.variables_template()
            )
            self.shadow_engine.swap_weights(variables, loaded.short)
        except Exception as e:  # noqa: BLE001 — any load/swap refusal (verification, fingerprint, engine state) rejects the candidate, never the loop
            return self._reject(mv, reason=f"shadow_load_failed: {e!r}")
        gate = self.router.set_shadow(
            InProcessReplica(f"shadow-{mv.short}", self.shadow_engine),
            fraction=self.config.shadow_fraction,
            tolerance=self.config.shadow_tolerance,
            min_samples=self.config.shadow_min_samples,
        )
        with self._lock:
            self._armed = {
                "mv": mv,
                "gate": gate,
                "t_armed": time.monotonic(),
            }
        telemetry.event(
            "flywheel/candidate_armed",
            version=mv.short,
            fraction=self.config.shadow_fraction,
        )
        return {"state": "armed", "candidate": mv.short}

    def _judge(self, armed: Dict[str, Any]) -> Dict[str, Any]:
        mv: ModelVersion = armed["mv"]
        report = armed["gate"].report()
        elapsed = time.monotonic() - armed["t_armed"]
        if report["failures"] > 0:
            # Red: failures never reset — this gate can never go green.
            return self._reject(mv, reason="gate_red", gate=report)
        if report["green"] and elapsed >= self.config.gate_window_s:
            return self._promote(mv, report)
        if elapsed > self.config.gate_patience_s:
            # Starved gate (drops/errors/no traffic): refusing is the safe
            # default — an unjudged candidate must not linger armed forever.
            return self._reject(mv, reason="gate_starved", gate=report)
        return {"state": "armed", "candidate": mv.short, "gate": report}

    def _promote(self, mv: ModelVersion, gate: Dict[str, Any]) -> Dict[str, Any]:
        try:
            result = self.manager.promote()
        except (SwapGateError, CandidateVerificationError, LifecycleError) as e:
            # promote() re-reads the live gate and re-verifies the load; a
            # refusal here is a rejection with the manager's own evidence.
            return self._reject(mv, reason=f"promote_refused: {e!r}", gate=gate)
        with self._lock:
            self._armed = None
            self._counters["promotions"] += 1
            self._last_promote = {
                "version": result["version"],
                "previous_version": result["previous_version"],
                "gate": gate,
            }
        telemetry.counter("flywheel/promotions")
        telemetry.event(
            "flywheel/promoted",
            version=result["version"],
            previous_version=result["previous_version"],
            compared=gate.get("compared"),
            diff_max=gate.get("diff_max"),
        )
        return {"state": "promoted", "result": result}

    def _reject(
        self,
        mv: ModelVersion,
        reason: str,
        gate: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Auto-rollback of the weights loop: disarm the shadow, quarantine
        a copy of the candidate's bytes for forensics, drop the candidate
        role, and dump the flight recorder under the ``flywheel_reject``
        trigger. The live fleet never saw the candidate — refusing IS the
        rollback; ``manager.rollback()`` stays an operator action for a
        promotion regretted later."""
        self.router.clear_shadow()
        quarantined = self._quarantine(mv)
        self.registry.clear_candidate(reason=reason)
        dump = telemetry.flight_dump(
            "flywheel_reject",
            run_dir=self.run_dir,
            extra={
                "candidate": mv.short,
                "reason": reason,
                "gate": gate,
                "quarantined": quarantined,
            },
        )
        with self._lock:
            self._armed = None
            self._counters["rejections"] += 1
            self._last_reject = {
                "candidate": mv.short,
                "reason": reason,
                "gate": gate,
                "quarantined": quarantined,
                "flight_dump": dump,
            }
        telemetry.counter("flywheel/rejections")
        telemetry.event(
            "flywheel/rejected", version=mv.short, reason=reason
        )
        return {"state": "rejected", "candidate": mv.short, "reason": reason}

    def _quarantine(self, mv: ModelVersion) -> Optional[str]:
        """Copy the rejected candidate's bytes aside (best-effort: the
        evidence should survive the trainer overwriting ``<name>.pk`` with
        its next save, but a vanished file must not mask the rejection)."""
        from ..checkpoint import io as ckpt_io

        qdir = os.path.join(self.run_dir, self.config.quarantine_dir)
        dst = os.path.join(qdir, f"{mv.short}.pk")
        try:
            os.makedirs(qdir, exist_ok=True)
            ckpt_io.atomic_copy_file(mv.path, dst)
        except OSError:
            return None
        return dst

    # ------------------------------------------------------------- data loop
    def _data_step(self) -> Dict[str, Any]:
        fed = self._pull_histograms()
        now = time.monotonic()
        with self._lock:
            due = now - self._last_drift_eval >= self.config.refit_interval_s
            if due:
                self._last_drift_eval = now
        if not due:
            return {"state": "sampling", "fed": fed}
        verdict = self.detector.evaluate()
        if verdict["transition"] == "entered":
            return self._refit(verdict)
        return {"state": "watching", "fed": fed, "drift": verdict}

    def _pull_histograms(self) -> int:
        """Feed the detector each engine's size-histogram DELTA since the
        last tick (cumulative counts minus what was already seen)."""
        total = 0
        for engine in self.manager.engines:
            metrics = getattr(engine, "metrics", None)
            if metrics is None:
                continue
            doc = metrics.histogram_json()  # one locked copy, engine-side
            current = {
                (int(n), int(e)): int(w)
                for n, e, w in doc.get("graph_sizes", ())
            }
            with self._lock:
                seen = self._hist_seen.setdefault(id(engine), {})
                delta = [
                    (n, e, c - seen.get((n, e), 0))
                    for (n, e), c in current.items()
                    if c - seen.get((n, e), 0) > 0
                ]
                self._hist_seen[id(engine)] = current
            total += self.detector.observe(delta)
        return total

    def _refit(self, verdict: Dict[str, Any]) -> Dict[str, Any]:
        """Sustained drift → fit a new ladder to the window's traffic and
        swap it across the fleet. Runs on the control thread — the warm
        (compile/hydrate of new rungs) is background work relative to
        serving; each engine's publish is one atomic reference rebind."""
        window = self.detector.window_histogram()
        new_ladder = fit_ladder(window, max_rungs=self.config.max_rungs)
        with self._lock:
            self._counters["ladder_refits"] += 1
        telemetry.counter("flywheel/ladder_refits")
        swaps: List[Dict[str, Any]] = []
        for engine in self.manager.engines:
            if not hasattr(engine, "swap_ladder"):
                continue
            swaps.append(engine.swap_ladder(new_ladder, warm=True))
        if swaps:
            with self._lock:
                self._counters["ladder_swaps"] += len(swaps)
            telemetry.counter("flywheel/ladder_swaps", len(swaps))
        self.detector.rebase(window)
        telemetry.event(
            "flywheel/ladder_refit",
            rungs=len(new_ladder),
            distance=verdict.get("distance"),
            engines=len(swaps),
            compiled=sum(s["compiled"] for s in swaps),
            hydrated=sum(s["hydrated"] for s in swaps),
        )
        return {
            "state": "refit",
            "ladder": [list(r) for r in new_ladder],
            "swaps": swaps,
            "drift": verdict,
        }

    # --------------------------------------------------------------- status
    def report(self) -> Dict[str, Any]:
        with self._lock:
            armed = self._armed
            out: Dict[str, Any] = {
                "attached": self._attached,
                "running": self._thread is not None
                and self._thread.is_alive(),
                "armed": None
                if armed is None
                else {"candidate": armed["mv"].short},
                "counters": dict(self._counters),
                "last_promote": self._last_promote,
                "last_reject": self._last_reject,
            }
        out["drift"] = self.detector.report()
        return out
